"""Namespace-label webhook (reference: pkg/webhook/namespacelabel.go).

Blocks namespaces from self-exempting with the
``admission.gatekeeper.sh/ignore`` label; namespaces whose NAME is on the
exemption lists (--exempt-namespace / -prefix / -suffix) may
(namespacelabel.go:28-30,63-66).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable

from gatekeeper_tpu.webhook.policy import parse_admission_review

IGNORE_LABEL = "admission.gatekeeper.sh/ignore"


@dataclass
class LabelResponse:
    allowed: bool
    message: str = ""
    code: int = 200
    uid: str = ""


class NamespaceLabelHandler:
    def __init__(self, exempt_namespaces: Iterable[str] = (),
                 exempt_prefixes: Iterable[str] = (),
                 exempt_suffixes: Iterable[str] = ()):
        self.exempt_namespaces = set(exempt_namespaces)
        self.exempt_prefixes = tuple(exempt_prefixes)
        self.exempt_suffixes = tuple(exempt_suffixes)

    def handle(self, review_body: dict) -> LabelResponse:
        req = parse_admission_review(review_body)
        if req.operation == "DELETE":
            return LabelResponse(allowed=True, uid=req.uid)
        kind = req.kind or {}
        if kind.get("group", "") or kind.get("kind", "") != "Namespace":
            return LabelResponse(allowed=True, uid=req.uid)
        obj = req.object or {}
        name = (obj.get("metadata") or {}).get("name", "")
        if (
            name in self.exempt_namespaces
            or any(name.startswith(p) for p in self.exempt_prefixes)
            or any(name.endswith(s) for s in self.exempt_suffixes)
        ):
            return LabelResponse(allowed=True, uid=req.uid)
        labels = (obj.get("metadata") or {}).get("labels") or {}
        if IGNORE_LABEL in labels:
            return LabelResponse(
                allowed=False,
                code=403,
                message=(
                    f"Only exempt namespace can have the {IGNORE_LABEL} label"
                ),
                uid=req.uid,
            )
        return LabelResponse(allowed=True, uid=req.uid)
