"""Namespace-label webhook (reference: pkg/webhook/namespacelabel.go).

Blocks unprivileged requests from self-exempting namespaces with the
``admission.gatekeeper.sh/ignore`` label; service accounts on the exemption
list may (namespacelabel.go:21-41).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable

from gatekeeper_tpu.webhook.policy import parse_admission_review

IGNORE_LABEL = "admission.gatekeeper.sh/ignore"


@dataclass
class LabelResponse:
    allowed: bool
    message: str = ""
    code: int = 200
    uid: str = ""


class NamespaceLabelHandler:
    def __init__(self, exempt_users: Iterable[str] = (),
                 exempt_prefixes: Iterable[str] = (),
                 exempt_suffixes: Iterable[str] = ()):
        self.exempt_users = set(exempt_users)
        self.exempt_prefixes = tuple(exempt_prefixes)
        self.exempt_suffixes = tuple(exempt_suffixes)

    def handle(self, review_body: dict) -> LabelResponse:
        req = parse_admission_review(review_body)
        if req.operation == "DELETE":
            return LabelResponse(allowed=True, uid=req.uid)
        username = (req.user_info or {}).get("username", "")
        if (
            username in self.exempt_users
            or any(username.startswith(p) for p in self.exempt_prefixes)
            or any(username.endswith(s) for s in self.exempt_suffixes)
        ):
            return LabelResponse(allowed=True, uid=req.uid)
        obj = req.object or {}
        labels = (obj.get("metadata") or {}).get("labels") or {}
        if IGNORE_LABEL in labels:
            return LabelResponse(
                allowed=False,
                code=403,
                message=(
                    f"only exempt users can add the {IGNORE_LABEL} label to "
                    "a namespace"
                ),
                uid=req.uid,
            )
        return LabelResponse(allowed=True, uid=req.uid)
