"""Prefix/suffix/contains glob (reference: pkg/wildcard/wildcard.go:17-42)."""

from __future__ import annotations


def matches(pattern: str, candidate: str) -> bool:
    if pattern.startswith("*") and pattern.endswith("*"):
        return pattern[1:-1] in candidate
    if pattern.startswith("*"):
        return candidate.endswith(pattern[1:])
    if pattern.endswith("*"):
        return candidate.startswith(pattern[:-1])
    return pattern == candidate


def matches_generate_name(pattern: str, candidate: str) -> bool:
    """generateName candidates only match contains/prefix globs
    (reference: wildcard.go:31-42)."""
    if pattern.startswith("*") and pattern.endswith("*"):
        return pattern[1:-1] in candidate
    if pattern.endswith("*"):
        return candidate.startswith(pattern[:-1])
    return False
