"""Constraint-scope match predicate.

Host-side exact implementation of the reference's 8 ANDed top-level matchers
(pkg/mutation/match/match.go:41-50): kinds, scope, namespaces,
excludedNamespaces, labelSelector, namespaceSelector, name, source.  The TPU
eval plane compiles the same semantics to boolean masks (see
gatekeeper_tpu.ir.masks); this module is the oracle those masks are
differential-tested against, and the fallback for odd inputs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from gatekeeper_tpu.match import wildcard
from gatekeeper_tpu.utils.unstructured import deep_get, gvk_of

WILDCARD = "*"

# Source types (reference: pkg/mutation/types/mutator.go SourceType).
SOURCE_ALL = "All"
SOURCE_ORIGINAL = "Original"
SOURCE_GENERATED = "Generated"
VALID_SOURCES = (SOURCE_ALL, SOURCE_ORIGINAL, SOURCE_GENERATED)


class MatchError(Exception):
    """Reference: ErrMatch (match.go:16)."""


@dataclass
class Matchable:
    """Object to match + its namespace metadata (match.go:24-28)."""

    obj: dict
    namespace: Optional[dict] = None  # the Namespace *object*
    source: str = ""


def is_namespace(obj: dict) -> bool:
    group, _, kind = gvk_of(obj)
    return kind == "Namespace" and group == ""


def label_selector_matches(selector: dict, labels: dict) -> bool:
    """k8s LabelSelector semantics: matchLabels AND matchExpressions."""
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key = expr.get("key", "")
        op = expr.get("operator", "")
        values = expr.get("values") or []
        if op == "In":
            if key not in labels or labels[key] not in values:
                return False
        elif op == "NotIn":
            if key in labels and labels[key] in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            raise MatchError(f"invalid labelSelector operator {op!r}")
    return True


def _obj_labels(obj: dict) -> dict:
    return deep_get(obj, ("metadata", "labels"), {}) or {}


def _obj_name(obj: dict) -> str:
    return deep_get(obj, ("metadata", "name"), "") or ""


def _obj_generate_name(obj: dict) -> str:
    return deep_get(obj, ("metadata", "generateName"), "") or ""


def _obj_namespace(obj: dict) -> str:
    return deep_get(obj, ("metadata", "namespace"), "") or ""


def matches(match: dict, target: Matchable) -> bool:
    """All 8 matchers must succeed (reference: match.go:32-65)."""
    if target.obj is None:
        raise MatchError("obj must be non-nil")
    return (
        _kinds_match(match, target)
        and _scope_match(match, target)
        and _namespaces_match(match, target)
        and _excluded_namespaces_match(match, target)
        and _label_selector_match(match, target)
        and _namespace_selector_match(match, target)
        and _names_match(match, target)
        and _source_match(match, target)
    )


def _kinds_match(match: dict, target: Matchable) -> bool:
    kinds = match.get("kinds") or []
    if not kinds:
        return True
    group, _, kind = gvk_of(target.obj)
    for kk in kinds:
        klist = kk.get("kinds") or []
        if klist and WILDCARD not in klist and kind not in klist:
            continue
        glist = kk.get("apiGroups") or []
        if not glist or WILDCARD in glist or group in glist:
            return True
    return False


def _scope_match(match: dict, target: Matchable) -> bool:
    scope = match.get("scope", "")
    has_namespace = _obj_namespace(target.obj) != "" or target.namespace is not None
    is_ns = is_namespace(target.obj)
    if scope == "Cluster":
        return is_ns or not has_namespace
    if scope == "Namespaced":
        return not is_ns and has_namespace
    # invalid scopes (typos) match everything, mirroring match.go:223-226
    return True


def _effective_namespace(target: Matchable) -> Optional[str]:
    """Namespace string used by namespaces/excludedNamespaces matchers
    (match.go:125-139): Namespace objects use their own name; otherwise the
    provided Namespace object's name, falling back to metadata.namespace."""
    if is_namespace(target.obj):
        return _obj_name(target.obj)
    if target.namespace is not None:
        return deep_get(target.namespace, ("metadata", "name"), "") or ""
    ns = _obj_namespace(target.obj)
    return ns if ns else None


def _namespaces_match(match: dict, target: Matchable) -> bool:
    patterns = match.get("namespaces") or []
    if not patterns:
        return True
    ns = _effective_namespace(target)
    if ns is None:
        return True  # cluster-scoped non-Namespace: can't disqualify
    return any(wildcard.matches(p, ns) for p in patterns)


def _excluded_namespaces_match(match: dict, target: Matchable) -> bool:
    patterns = match.get("excludedNamespaces") or []
    if not patterns:
        return True
    ns = _effective_namespace(target)
    if ns is None:
        return True
    return not any(wildcard.matches(p, ns) for p in patterns)


def _label_selector_match(match: dict, target: Matchable) -> bool:
    selector = match.get("labelSelector")
    if selector is None:
        return True
    return label_selector_matches(selector, _obj_labels(target.obj))


def _namespace_selector_match(match: dict, target: Matchable) -> bool:
    selector = match.get("namespaceSelector")
    if selector is None:
        return True
    is_ns = is_namespace(target.obj)
    if not is_ns and target.namespace is None and _obj_namespace(target.obj) == "":
        # Match all non-Namespace cluster-scoped objects (match.go:82-85).
        return True
    if is_ns:
        return label_selector_matches(selector, _obj_labels(target.obj))
    if target.namespace is None:
        raise MatchError(
            "namespace selector for namespace-scoped object but missing Namespace"
        )
    return label_selector_matches(
        selector, deep_get(target.namespace, ("metadata", "labels"), {}) or {}
    )


def _names_match(match: dict, target: Matchable) -> bool:
    name = match.get("name", "") or ""
    if name == "":
        return True
    return wildcard.matches(name, _obj_name(target.obj)) or (
        wildcard.matches_generate_name(name, _obj_generate_name(target.obj))
    )


def _source_match(match: dict, target: Matchable) -> bool:
    msrc = match.get("source", "") or ""
    tsrc = target.source
    if msrc == "":
        msrc = SOURCE_ALL
    elif msrc not in VALID_SOURCES:
        raise MatchError(f"invalid source field {msrc!r}")
    if tsrc == "" and msrc != SOURCE_ALL:
        raise MatchError("source field not specified for resource")
    if msrc == SOURCE_ALL:
        return True
    if tsrc not in VALID_SOURCES:
        raise MatchError(f"invalid source field {tsrc!r}")
    return msrc == tsrc
