"""Seeded adversarial corpus generator (ISSUE 17 tentpole).

Every differential lane in this repo was certified against hand-written
inputs.  This module generates the inputs nobody writes by hand — the
shapes a real apiserver feeds a webhook (PAPER.md's hostile-input
survey) — as deterministic, size-dialable scenario *families*:

====================  ==================================================
family                what it stresses
====================  ==================================================
``crd_heavy``         dozens of synthetic GVKs: vocab/group explosion,
                      ``backfill_gvk`` on unknown kinds, audit snapshot
                      group diversity
``megabyte_objects``  ~1MB single objects (size>=16) + 100-container
                      pods: ragged-column width, H2D volume, webhook
                      body limits
``deep_nesting``      256+-deep documents that MUST trip the raw C
                      lane's depth fallback (never crash, dict-lane
                      identical)
``selectors``         pathological label/namespace selectors across the
                      full 8-matcher surface (wildcards, matchExpressions,
                      unicode labels) — device masks vs the host oracle
``alias_mutators``    alias-heavy Assign/ModifySet registries over
                      overlapping list paths: solo-safety proofs,
                      device/multi/host lane routing
``vocab_churn``       unicode keys, near-collision strings, dup-key raw
                      JSON, per-round key churn: vocab growth + the
                      raw-vs-dict parser differential
``expansion``         generator resources (Deployment→Pod) for the
                      expansion stage riding the admit path
``extdata_hostile``   external-data keys that come back as errors,
                      absences, non-strings, unicode: batched-vs-perkey
                      failure-semantics parity
====================  ==================================================

Determinism contract: ``generate(family, seed, size)`` depends on
*nothing* but its arguments — the soak harness prints ``seed`` +
``family`` on any divergence and that pair is a one-command repro.

Also hosted here (ISSUE 17 satellite): the seeded object generator that
used to live in ``tests/fuzz_differential.py`` (``rand_obj`` /
``rand_value`` / ``IMAGES`` / ``VALUES``) so the manual fuzzer, the CI
entry (``tests/test_fuzz.py``) and the soak harness share ONE
generator.  This module stays import-light (no jax, no driver imports):
``fuzz_differential`` must be able to pin ``JAX_PLATFORMS`` before any
jax import, and the corpus is usable from tools without a device.
"""

from __future__ import annotations

import json
import random
import zlib
from dataclasses import dataclass, field

# --- the shared seeded object generator (ex tests/fuzz_differential.py) ---

IMAGES = ["openpolicyagent/opa:0.9.2", "nginx", "nginx:latest", "a/b:v1",
          "registry.corp:5000/x/y@sha256:ab", "", ":weird", "latest",
          "openpolicyagent/opa@sha256:" + "1" * 64]
VALUES = [True, False, 0, 1, -1, 2.5, "", "x", None, [], {},
          "user.agilebank.demo", "user"]


def rand_value(rng, depth=0):
    r = rng.random()
    if depth > 2 or r < 0.6:
        return rng.choice(VALUES)
    if r < 0.8:
        return [rand_value(rng, depth + 1) for _ in range(rng.randint(0, 3))]
    return {f"k{i}": rand_value(rng, depth + 1)
            for i in range(rng.randint(0, 3))}


def rand_obj(rng, i):
    kind = rng.choice(["Pod", "Deployment", "Service", "Namespace",
                       "Ingress", "RoleBinding"])
    group = {"Deployment": "apps", "Ingress": "networking.k8s.io",
             "RoleBinding": "rbac.authorization.k8s.io"}.get(kind, "")
    meta = {"name": f"o{i}"}
    if rng.random() < 0.7:
        meta["namespace"] = rng.choice(["default", "prod", "kube-system"])
    if rng.random() < 0.4:
        # stresses map key+value iteration (requiredannotations clause 2)
        meta["annotations"] = {
            k: rng.choice(["x", "", "a-b", 0, False, None, ["x"]])
            for k in rng.sample(["a8r.io/owner", "a-2", "owner"],
                                rng.randint(1, 2))}
    if rng.random() < 0.5:
        meta["labels"] = {
            k: rng.choice([str(rand_value(rng))[:20], False, None, 1])
            for k in rng.sample(["owner", "app", "team", "env"],
                                rng.randint(1, 3))}
    spec = {}
    if rng.random() < 0.8:
        containers = []
        for j in range(rng.randint(0, 4)):
            c = {}
            if rng.random() < 0.9:
                c["name"] = f"c{j}"
            if rng.random() < 0.9:
                c["image"] = rng.choice(IMAGES)
            if rng.random() < 0.4:
                c["resources"] = {"limits": {
                    k: rng.choice(["100m", "1", "2Gi", "64Mi", "bogus", 3])
                    for k in rng.sample(["cpu", "memory"],
                                        rng.randint(1, 2))}}
            if rng.random() < 0.3:
                c["ports"] = [{"hostPort": rng.choice(
                    [79, 80, 9000, 9001, "80"])}
                    for _ in range(rng.randint(0, 2))]
            if rng.random() < 0.3:
                # False-valued probes stress truthy-key semantics
                c[rng.choice(["readinessProbe", "livenessProbe"])] = \
                    rng.choice([{}, {"httpGet": {}}, False, None])
            if rng.random() < 0.4:
                sc = {}
                if rng.random() < 0.6:
                    sc["readOnlyRootFilesystem"] = rng.choice(
                        [True, False, "true", None])
                if rng.random() < 0.6:
                    sc["capabilities"] = {
                        k: rng.sample(["NET_BIND_SERVICE", "SYS_ADMIN",
                                       "NET_RAW", "ALL", "*"],
                                      rng.randint(0, 3))
                        for k in rng.sample(["add", "drop"],
                                            rng.randint(1, 2))}
                c["securityContext"] = sc
            containers.append(c)
        spec["containers"] = containers
    if kind == "Pod" and rng.random() < 0.4:
        spec["automountServiceAccountToken"] = rng.choice(
            [True, False, "false", None])
    if kind == "RoleBinding" and rng.random() < 0.8:
        return {"apiVersion": "rbac.authorization.k8s.io/v1",
                "kind": "RoleBinding", "metadata": meta,
                "subjects": [
                    {"kind": "User",
                     "name": rng.choice(["system:anonymous", "alice",
                                         "system:unauthenticated", 7])}
                    for _ in range(rng.randint(0, 2))]}
    for key in ("hostPID", "hostIPC", "hostNetwork"):
        if rng.random() < 0.15:
            spec[key] = rng.choice([True, False, "yes"])
    if kind == "Deployment" and rng.random() < 0.7:
        spec["replicas"] = rng.choice([0, 1, 3, 50, 51, "3"])
    if kind == "Service":
        spec["type"] = rng.choice(["ClusterIP", "NodePort", "LoadBalancer"])
        if rng.random() < 0.5:
            spec["externalIPs"] = [
                rng.choice(["203.0.113.0", "10.0.0.1", "", 8, None])
                for _ in range(rng.randint(1, 2))]
    if kind == "Pod" and rng.random() < 0.25:
        spec["securityContext"] = {"sysctls": rng.choice([
            [{"name": "kernel.msgmax", "value": "1"}],
            [{"name": "net.core.somaxconn"}],
            [{"name": "net.ipv4.tcp_syncookies", "value": "1"},
             {"name": "kernel.shm_rmid_forced"}],
            [{"name": 5}], [{}], "oops",
        ])}
    if rng.random() < 0.3:
        spec["volumes"] = [
            rng.choice([{"hostPath": {"path": p}},
                        {"hostPath": {}}, {"emptyDir": {}}, {}])
            for p in rng.sample(["/var/log/app", "/etc", "/var", ""],
                                rng.randint(1, 2))]
    if kind == "Ingress":
        if rng.random() < 0.4:
            spec["tls"] = rng.choice([[], [{"hosts": ["a.com"]}], "bad"])
        if rng.random() < 0.4:
            meta.setdefault("annotations", {})[
                "kubernetes.io/ingress.allow-http"] = rng.choice(
                ["false", "true", False, ""])
    if kind == "Ingress" and rng.random() < 0.8:
        spec["rules"] = [{"host": rng.choice(
            ["a.com", "b.com", ""])} for _ in range(rng.randint(0, 2))]
    if rng.random() < 0.1:
        spec["extra"] = rand_value(rng)
    av = f"{group}/v1" if group else "v1"
    return {"apiVersion": av, "kind": kind, "metadata": meta, "spec": spec}


# --- family bundles -------------------------------------------------------

FAMILIES = ("crd_heavy", "megabyte_objects", "deep_nesting", "selectors",
            "alias_mutators", "vocab_churn", "expansion", "extdata_hostile")

# near-collision key pool: visually/byte-wise adjacent strings that must
# stay DISTINCT vocab sids ("\u0430" is CYRILLIC a; "\u200b" is a
# zero-width space; "app " differs by a trailing space)
NEAR_COLLISIONS = ["app", "app ", "apP", "\u0430pp", "app\u200b",
                   "ap" + "p", "a\u0440p"]
UNICODE_KEYS = ["caf\u00e9", "\u043a\u043b\u044e\u0447", "\u952e",
                "na\u00efve", "\u2603", "k-" + "\U0001f600"]


@dataclass
class FamilyBundle:
    """One family's generated scenario: everything a harness arm needs.

    ``objects`` are plain dicts (admission/audit candidates);
    ``raw_docs`` are hostile JSON *bytes* for the raw flatten lane
    (dup keys, 256+ depth — shapes a Python dict cannot even express);
    the remaining fields carry family-specific fixtures (namespace
    objects for selector matching, mutator/expansion registries,
    constraint ``match`` specs, external-data keys).
    """

    family: str
    seed: int
    size: int
    objects: list = field(default_factory=list)
    raw_docs: list = field(default_factory=list)
    namespaces: dict = field(default_factory=dict)
    mutators: list = field(default_factory=list)
    match_specs: list = field(default_factory=list)
    expansion_templates: list = field(default_factory=list)
    extdata_keys: list = field(default_factory=list)
    notes: str = ""


def _rng(family: str, seed: int) -> random.Random:
    # crc32 of the family name keeps per-family streams independent for
    # one seed without Python's salted hash() (determinism contract)
    return random.Random(((seed & 0xFFFFFFFF) << 16)
                         ^ zlib.crc32(family.encode()))


def _ns(name: str, labels=None) -> dict:
    obj = {"apiVersion": "v1", "kind": "Namespace",
           "metadata": {"name": name}}
    if labels:
        obj["metadata"]["labels"] = dict(labels)
    return obj


def _dumps(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False
                      ).encode("utf-8")


# --- builders (one per family) --------------------------------------------

def _crd_heavy(rng, seed, size):
    b = FamilyBundle("crd_heavy", seed, size,
                     notes="synthetic GVK explosion: unknown groups/kinds")
    n_gvks = 8 + 8 * size
    for g in range(n_gvks):
        group = f"fuzz{g % 7}.example.com"
        version = rng.choice(["v1", "v1beta1", "v2alpha1"])
        kind = f"Widget{g}"
        for j in range(2):
            obj = {"apiVersion": f"{group}/{version}", "kind": kind,
                   "metadata": {"name": f"w{g}-{j}"},
                   "spec": rand_value(rng) if rng.random() < 0.7
                   else {"replicas": rng.randint(0, 5),
                         "items": [rand_value(rng)
                                   for _ in range(rng.randint(0, 3))]}}
            if rng.random() < 0.5:
                obj["metadata"]["namespace"] = rng.choice(
                    ["default", "prod", "crd-zoo"])
            b.objects.append(obj)
    b.namespaces["crd-zoo"] = _ns("crd-zoo", {"team": "platform"})
    # List items omit apiVersion/kind — the backfill_gvk shape
    b.raw_docs = [_dumps({"metadata": {"name": f"bare-{i}"},
                          "spec": {"x": i}}) for i in range(3)]
    return b


def _megabyte_objects(rng, seed, size):
    b = FamilyBundle(
        "megabyte_objects", seed, size,
        notes="single-object byte volume; size>=16 reaches ~1MB")
    target = 65536 * max(1, size)
    data, total, i = {}, 0, 0
    while total < target:
        chunk = rng.choice(["x", "ab", "data-", "\u00e9"]) * rng.randint(
            200, 400)
        data[f"blob-{i:04d}"] = chunk
        total += len(chunk) + 16
        i += 1
    b.objects.append({"apiVersion": "v1", "kind": "ConfigMap",
                      "metadata": {"name": "mega-cm",
                                   "namespace": "default"},
                      "data": data})
    # wide ragged columns: one pod with many containers
    n_containers = 24 * max(1, size)
    b.objects.append({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "mega-pod", "namespace": "default",
                     "annotations": {"huge": "y" * min(target // 4,
                                                       262144)}},
        "spec": {"containers": [
            {"name": f"c{j}", "image": rng.choice(IMAGES),
             "resources": {"limits": {"cpu": "100m", "memory": "64Mi"}}}
            for j in range(n_containers)]}})
    b.raw_docs = [_dumps(b.objects[0])]
    return b


def raw_deep_doc(depth: int, kind: str = "Pod",
                 name: str = "deep") -> bytes:
    """A valid JSON document nested ``depth`` dicts deep, built by byte
    concatenation (no Python recursion, no json.dumps recursion limit) —
    the >256 shape that must trip the raw C parser's depth fallback."""
    head = (b'{"apiVersion":"v1","kind":"' + kind.encode()
            + b'","metadata":{"name":"' + name.encode()
            + b'"},"spec":{"d":')
    return head + b'{"n":' * depth + b"1" + b"}" * depth + b"}}"


def raw_dup_key_doc(name: str = "dup") -> bytes:
    """Duplicate keys at several depths: JSON last-wins in both parsers
    (json.loads AND the native C lane) — the differential pins that."""
    return (b'{"apiVersion":"v1","kind":"Pod","metadata":{"name":"'
            + name.encode() + b'","labels":{"k":"first","k":"last"}},'
            b'"spec":{"x":1,"x":2,"c":{"a":1,"a":{"b":2}}}}')


def _deep_nesting(rng, seed, size):
    b = FamilyBundle(
        "deep_nesting", seed, size,
        notes=">256-deep docs live ONLY as raw bytes (raw-lane depth "
              "fallback); python objects stay shallow enough to walk")
    # python-object side: deep but walkable by every host lane
    for d in (8, 16, 24 + 4 * min(size, 6)):
        node = {"leaf": d}
        for _ in range(d):
            node = {"n": node} if rng.random() < 0.7 else {"n": [node]}
        b.objects.append({"apiVersion": "v1", "kind": "Pod",
                          "metadata": {"name": f"deep-{d}",
                                       "namespace": "default"},
                          "spec": {"d": node}})
    # raw side: straddle the C lane's 256-depth fallback boundary
    for d in (64, 255, 257, 300 + 16 * min(size, 30)):
        b.raw_docs.append(raw_deep_doc(d, name=f"deep-{d}"))
    return b


def _selectors(rng, seed, size):
    b = FamilyBundle(
        "selectors", seed, size,
        notes="pathological match specs over the full 8-matcher surface")
    teams = ["a", "b", "", "\u0442\u0435\u0441\u0442"]
    b.namespaces = {
        "default": _ns("default", {"team": "a", "env": "dev"}),
        "prod": _ns("prod", {"team": "b", "env": "prod"}),
        "kube-system": _ns("kube-system", {"team": "a"}),
        "edge-\u0442": _ns("edge-\u0442",
                           {"team": "\u0442\u0435\u0441\u0442",
                            UNICODE_KEYS[0]: "oui"}),
        "bare": _ns("bare"),
    }
    ns_names = sorted(b.namespaces)
    for i in range(12 + 8 * size):
        obj = rand_obj(rng, i)
        meta = obj["metadata"]
        if obj.get("kind") != "Namespace" and rng.random() < 0.9:
            meta["namespace"] = rng.choice(ns_names)
        labels = meta.setdefault("labels", {})
        if not isinstance(labels, dict):
            labels = meta["labels"] = {}
        labels["team"] = rng.choice(teams)
        if rng.random() < 0.5:
            labels[rng.choice(NEAR_COLLISIONS)] = rng.choice(
                ["on", "", "\u2603"])
        b.objects.append(obj)
    b.match_specs = [
        {"namespaces": ["kube-*", "prod"]},
        {"excludedNamespaces": ["*-system", "edge-*", "bare"]},
        {"labelSelector": {"matchExpressions": [
            {"key": "team", "operator": "In", "values": ["a", ""]},
            {"key": "missing", "operator": "DoesNotExist"}]}},
        {"namespaceSelector": {"matchLabels": {"team": "a"}}},
        {"namespaceSelector": {"matchExpressions": [
            {"key": "env", "operator": "NotIn", "values": ["prod"]},
            {"key": "team", "operator": "Exists"}]}},
        {"name": "o*", "scope": "Namespaced"},
        {"labelSelector": {"matchLabels": {NEAR_COLLISIONS[3]: "on"}}},
    ]
    for _ in range(size):
        b.match_specs.append({"labelSelector": {"matchExpressions": [
            {"key": rng.choice(NEAR_COLLISIONS + UNICODE_KEYS),
             "operator": rng.choice(["In", "NotIn"]),
             "values": rng.sample(["on", "", "\u2603", "x"], 2)}]},
            "namespaces": [rng.choice(["*", "def*", "prod"])]})
    return b


def _alias_mutators(rng, seed, size):
    b = FamilyBundle(
        "alias_mutators", seed, size,
        notes="overlapping keyed/wildcard list aliases: solo-safety "
              "proofs must route multi/host, never diverge")
    paths = [
        "spec.containers[name: *].imagePullPolicy",
        "spec.containers[name: c0].image",
        "spec.containers[name: c1].imagePullPolicy",
        "spec.initContainers[name: *].image",
        "spec.securityContext.runAsNonRoot",
        "metadata.labels.fuzz-owner",
        "metadata.annotations.fuzz-audit",
    ]
    for r in range(size):
        paths.append(f"metadata.labels.round-{r}")
        paths.append(f"spec.containers[name: c{r % 4}].env-{r}")
    values = ["Always", "IfNotPresent", "nginx:pinned", True, "team-x"]

    def value_for(loc):
        # keyed by the TERMINAL field, not the path: overlapping alias
        # writers (wildcard vs keyed list entries) agree on the value,
        # so the set stays alias-heavy yet CONVERGENT — non-convergence
        # is a deliberate admission error, not the lane stress we want
        field = loc.rsplit(".", 1)[-1]
        return values[zlib.crc32(field.encode()) % len(values)]

    seen = set()
    for i, loc in enumerate(paths):
        if loc in seen:
            continue
        seen.add(loc)
        doc = {
            "apiVersion": "mutations.gatekeeper.sh/v1",
            "kind": "Assign", "metadata": {"name": f"alias-{i}"},
            "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                                  "kinds": ["Pod"]}],
                     "location": loc,
                     "parameters": {"assign": {"value": value_for(loc)}}},
        }
        if loc.startswith("metadata."):
            doc["apiVersion"] = "mutations.gatekeeper.sh/v1beta1"
            doc["kind"] = "AssignMetadata"
            doc["spec"] = {"location": loc, "parameters": {
                "assign": {"value": str(value_for(loc))}}}
        elif rng.random() < 0.25:
            # assignIf gates are host-only: keeps the fallback lane hot
            doc["spec"]["parameters"]["assignIf"] = {
                "in": [None, "Default"]}
        b.mutators.append(doc)
    b.mutators.append({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "ModifySet", "metadata": {"name": "alias-topo"},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": ["Service"]}],
                 "location": "spec.topologyKeys",
                 "parameters": {"operation": "merge",
                                "values": {"fromList": ["zone", "rack"]}}},
    })
    for i in range(10 + 6 * size):
        containers = [{"name": f"c{j}", "image": rng.choice(IMAGES)}
                      for j in range(rng.randint(0, 5))]
        if rng.random() < 0.3 and containers:
            # duplicate container names: the alias proof's worst case
            containers.append(dict(containers[0]))
        obj = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": f"mp{i}", "namespace": "default"},
               "spec": {"containers": containers}}
        if rng.random() < 0.3:
            obj["spec"]["initContainers"] = [
                {"name": "c0", "image": rng.choice(IMAGES)}]
        if rng.random() < 0.2:
            obj["spec"]["containers"] = rng.choice(
                ["notalist", 5, [{"name": 3}]])
        b.objects.append(obj)
        if rng.random() < 0.25:
            b.objects.append({"apiVersion": "v1", "kind": "Service",
                              "metadata": {"name": f"ms{i}",
                                           "namespace": "default"},
                              "spec": {"topologyKeys": ["zone"]}})
    return b


def _vocab_churn(rng, seed, size):
    b = FamilyBundle(
        "vocab_churn", seed, size,
        notes="unicode/near-collision keys churning per round; dup-key "
              "raw docs pin parser last-wins parity")
    rounds = 2 + size
    for r in range(rounds):
        for i in range(6):
            labels = {f"{rng.choice(NEAR_COLLISIONS)}-{r}": "on",
                      rng.choice(UNICODE_KEYS): f"v{r}"}
            spec_map = {f"{k}-{r}": rand_value(rng)
                        for k in rng.sample(UNICODE_KEYS, 2)}
            spec_map["k" * 120 + str(i)] = i
            b.objects.append({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"vc-{r}-{i}",
                             "namespace": "default", "labels": labels},
                "spec": {"containers": [{"name": "c0",
                                         "image": rng.choice(IMAGES)}],
                         "churn": spec_map}})
    b.raw_docs = [
        raw_dup_key_doc("dup-a"),
        # unicode keys as raw utf-8 bytes (and escaped form of the same
        # key — distinct byte strings, identical parsed key)
        '{"apiVersion":"v1","kind":"Pod","metadata":{"name":"uni",'
        '"labels":{"caf\u00e9":"x","\\u0063\u0430f\u00e9":"y"}},'
        '"spec":{}}'.encode("utf-8"),
        _dumps({"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "nest-items"},
                # an inner "items" list must NOT confuse the List
                # splitter (split_list_items nested-items trap)
                "spec": {"items": [{"a": 1}, {"b": [2, 3]}]}}),
    ]
    return b


def _expansion(rng, seed, size):
    b = FamilyBundle(
        "expansion", seed, size,
        notes="generator resources: Deployment->Pod expansion on the "
              "admit path, resultants validated")
    b.expansion_templates = [{
        "apiVersion": "expansion.gatekeeper.sh/v1alpha1",
        "kind": "ExpansionTemplate",
        "metadata": {"name": "fuzz-expand-deployments"},
        "spec": {"applyTo": [{"groups": ["apps"], "versions": ["v1"],
                              "kinds": ["Deployment"]}],
                 "templateSource": "spec.template",
                 "generatedGVK": {"group": "", "version": "v1",
                                  "kind": "Pod"}},
    }]
    for i in range(4 + 2 * size):
        tpl_spec = {"containers": [
            {"name": f"c{j}", "image": rng.choice(IMAGES),
             **({"securityContext": {"privileged": True}}
                if rng.random() < 0.3 else {})}
            for j in range(rng.randint(1, 3))]}
        dep = {"apiVersion": "apps/v1", "kind": "Deployment",
               "metadata": {"name": f"gen-{i}", "namespace": "default"},
               "spec": {"replicas": rng.choice([1, 3]),
                        "template": {"metadata": {"labels":
                                                  {"app": f"gen-{i}"}},
                                     "spec": tpl_spec}}}
        if rng.random() < 0.2:
            del dep["spec"]["template"]  # templateSource missing: errors
        b.objects.append(dep)
    b.namespaces["default"] = _ns("default", {"team": "a"})
    return b


def _extdata_hostile(rng, seed, size):
    b = FamilyBundle(
        "extdata_hostile", seed, size,
        notes="provider keys answered with errors/absences/non-strings: "
              "batched-vs-perkey failure parity")
    cats = (["ok-{}", "err-{}", "absent-{}", "nonstring-{}",
             "\u043a\u043b\u044e\u0447-{}"])
    for i in range(3 + 2 * size):
        b.extdata_keys.append(cats[i % len(cats)].format(i))
    b.extdata_keys += ["", "k" * 200]
    for i, key in enumerate(b.extdata_keys):
        if not key:
            continue
        b.objects.append({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"xd{i}", "namespace": "default"},
            "spec": {"containers": [{"name": "c0", "image": key}]}})
    return b


_BUILDERS = {
    "crd_heavy": _crd_heavy,
    "megabyte_objects": _megabyte_objects,
    "deep_nesting": _deep_nesting,
    "selectors": _selectors,
    "alias_mutators": _alias_mutators,
    "vocab_churn": _vocab_churn,
    "expansion": _expansion,
    "extdata_hostile": _extdata_hostile,
}

assert tuple(_BUILDERS) == FAMILIES


def generate(family: str, seed: int = 0, size: int = 1) -> FamilyBundle:
    """Build one family's bundle; deterministic in (family, seed, size)."""
    if family not in _BUILDERS:
        raise ValueError(f"unknown corpus family {family!r}; "
                         f"known: {', '.join(FAMILIES)}")
    if size < 0:
        raise ValueError("size must be >= 0")
    return _BUILDERS[family](_rng(family, seed), seed, size)


def generate_all(seed: int = 0, size: int = 1,
                 families=None) -> list:
    fams = list(families) if families else list(FAMILIES)
    return [generate(f, seed=seed, size=size) for f in fams]


def admission_bodies(objects, seed: int = 0,
                     prefix: str = "fuzz") -> list:
    """AdmissionReview bodies for a bundle's objects (the loadtest
    shape: CREATE, a non-gatekeeper user, uid carrying the prefix so a
    diverging verdict names its family)."""
    bodies = []
    for i, obj in enumerate(objects):
        api = obj.get("apiVersion", "v1")
        group, _, version = api.rpartition("/")
        meta = obj.get("metadata") or {}
        bodies.append({
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": f"{prefix}-{seed}-{i:06d}",
                "kind": {"group": group, "version": version,
                         "kind": obj.get("kind", "")},
                "operation": "CREATE",
                "name": meta.get("name", "") or f"{prefix}-{i}",
                "namespace": meta.get("namespace", "") or "",
                "userInfo": {"username": "fuzz@soak"},
                "object": obj,
            },
        })
    return bodies


def corpus_stats(bundles) -> dict:
    """Per-family + total corpus shape (the SOAK_BENCH 'corpus' block)."""
    per = {}
    for b in bundles:
        per[b.family] = {
            "objects": len(b.objects),
            "raw_docs": len(b.raw_docs),
            "raw_bytes": sum(len(d) for d in b.raw_docs),
            "object_bytes": sum(len(_dumps(o)) for o in b.objects),
            "namespaces": len(b.namespaces),
            "mutators": len(b.mutators),
            "match_specs": len(b.match_specs),
            "expansion_templates": len(b.expansion_templates),
            "extdata_keys": len(b.extdata_keys),
        }
    tot = {k: sum(p[k] for p in per.values())
           for k in next(iter(per.values()))} if per else {}
    return {"families": per, "total": tot}
