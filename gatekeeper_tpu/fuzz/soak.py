"""Chaos trace-replay soak harness (ISSUE 17 tentpole).

Drives webhook ``/v1/admit``, ``/v1/mutate`` and the audit snapshot
pass SIMULTANEOUSLY over the adversarial corpus (:mod:`fuzz.corpus`),
under a seeded ``faults.py`` chaos plan, with EVERY differential lane
armed:

- **flatten**  — ``ShardedEvaluator(flatten_lane="differential")``
  (raw-vs-dict columns per audit chunk) plus a dedicated ``Flattener``
  differential arm over each family's hostile raw byte docs;
- **collect**  — ``collect="differential"`` (reduced vs masks fold);
- **mutate**   — ``MutationLane(differential=True)``: batched patches
  vs the per-object host reference on every ``/v1/mutate`` batch;
- **extdata**  — ``ExtDataLane(mode="differential")``: batched column
  joins vs the per-key transport reference, hostile keys included;
- **snapshot** — the snapshot-sourced audit vs a fresh relist sweep
  each round (canonical verdict compare) + ``audit_resync()`` at the
  end of the run;
- **resident** — ``residency="on"`` promotes the snapshot lane's
  columns to device-resident mirrors (single-device mesh), so the same
  snapshot-vs-relist compare exercises HBM-resident gather +
  scatter-patch ticks against the host reference under chaos churn.

Any lane divergence, lost verdict at drain, or handler crash fails the
run, and every failure record carries ``(seed, family)`` — ``python
tools/soak.py --seed N --families F`` replays the exact scenario.

Chaos-plan discipline: only *graceful-by-contract* fault modes are in
the default plan.  Sleeps go everywhere; the one error-mode fault sits
on ``mutation.batch`` (pinned: the whole batch routes to the
authoritative host walk — degradation, never loss).  Error/partial on
``externaldata.send`` is deliberately absent: the batched lane makes 1
transport call where the per-key reference makes N, so a count-gated
fault fires differently per lane and would report a FALSE divergence
(the lanes' shared failure semantics are pinned in tests/test_extdata
instead).

Sensitivity injections — the harness must demonstrably catch seeded
bugs: ``inject_bug="mutate_program"`` corrupts one batched patch per
burst (the corrupted-lowered-program analogue for the mutation
fragment); ``inject_bug="extdata_column"`` tampers a resident provider
column entry after warmup.  Both MUST surface as reported divergences.

1-core discipline (ROADMAP): the tier-1 smoke drives serially (one
request in flight); ``concurrent=True`` — the slow-marked soak and
multi-core hosts — drives admit and mutate from threads while the
audit loop runs in the caller's thread.
"""

from __future__ import annotations

import contextlib
import copy
import glob
import json
import os
import tempfile
import threading
import time
import urllib.request

from gatekeeper_tpu.fuzz import corpus as corpus_mod

TARGET = "admission.k8s.gatekeeper.sh"
XD_PROVIDER = "fuzz-xd"

# the hostile external-data template: batched keys, per-key errors
REGO_XD = """
package fuzzxd

violation[{"msg": msg}] {
  images := [img | img = input.review.object.spec.containers[_].image]
  response := external_data({"provider": "fuzz-xd", "keys": images})
  response_with_error(response)
  msg := sprintf("hostile extdata errors: %v", [response.errors])
}

response_with_error(response) {
  count(response.errors) > 0
}

response_with_error(response) {
  count(response.system_error) > 0
}
"""

CHAOS_FAULTS = [
    {"site": "webhook.request", "mode": "sleep", "delay_s": 0.002,
     "probability": 0.2},
    {"site": "webhook.review", "mode": "sleep", "delay_s": 0.002,
     "probability": 0.15},
    {"site": "externaldata.send", "mode": "sleep", "delay_s": 0.003,
     "probability": 0.25},
    {"site": "device.dispatch", "mode": "sleep", "delay_s": 0.002,
     "probability": 0.1},
    {"site": "mutation.batch", "mode": "error", "every": 5},
]


def default_chaos_plan(seed: int = 0):
    """The seeded default plan (see the module docstring for why these
    modes and no others)."""
    from gatekeeper_tpu.resilience.faults import FaultPlan

    return FaultPlan(list(CHAOS_FAULTS), seed=seed)


def _library_docs(keep: int = 3) -> list:
    """First ``keep`` shipped templates + their sample constraints as
    unstructured docs (the bench_replay idiom, inlined so the harness
    has no tools/ dependency)."""
    from gatekeeper_tpu.utils.synthetic import library_dir
    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    docs: list = []
    tpaths = sorted(
        glob.glob(os.path.join(library_dir(), "general", "*",
                               "template.yaml")) +
        glob.glob(os.path.join(library_dir(), "pod-security-policy", "*",
                               "template.yaml")))[:keep]
    for tpath in tpaths:
        docs.append(load_yaml_file(tpath)[0])
        cpath = os.path.join(os.path.dirname(tpath), "samples",
                             "constraint.yaml")
        if os.path.exists(cpath):
            docs.extend(load_yaml_file(cpath))
    return docs


class HostileTransport:
    """Deterministic provider double answering by KEY CONTENT — the
    same key gets the same answer whether it arrives in a bulk call or
    a per-key reference call, so the extdata differential sees zero
    false divergence regardless of batching:

    - ``err-*``       per-key error
    - ``absent-*``    no item in the response at all
    - ``nonstring-*`` a non-string JSON value
    - anything else   ``<key>#ok``
    """

    def __init__(self):
        self.calls = 0
        self.keys_sent = 0
        self._lock = threading.Lock()

    def __call__(self, provider, keys):
        with self._lock:
            self.calls += 1
            self.keys_sent += len(keys)
        items = []
        for k in keys:
            if "err-" in k:
                items.append({"key": k, "error": f"hostile: {k}"})
            elif "absent-" in k:
                continue
            elif "nonstring-" in k:
                items.append({"key": k, "value": 7})
            else:
                items.append({"key": k, "value": f"{k}#ok"})
        return {"response": {"items": items, "systemError": ""}}


class SoakHarness:
    """One full serving + audit stack over a corpus, every differential
    lane armed.  Build is explicit (``start``); ``stop`` drains."""

    def __init__(self, bundles, keep_templates: int = 3,
                 cache_dir: str = "", metrics=None,
                 residency: str = "off"):
        self.bundles = bundles
        self.keep_templates = keep_templates
        self.cache_dir = cache_dir
        self.metrics = metrics
        # "on" arms the device-resident snapshot lane on the snap-side
        # manager: every round's snapshot-vs-relist compare then runs
        # resident columns against the host reference under chaos
        self.residency_mode = residency
        self.residency = None
        self.divergences: list = []
        self.crashes: list = []
        self.sent = {"admit": 0, "mutate": 0}
        self.ok = {"admit": 0, "mutate": 0}
        self.current_family = ""
        self._tamper_extdata = False
        self._tampered = False
        self._built = False

    # --- failure recording -------------------------------------------------
    def _divergence(self, lane: str, detail: str) -> None:
        rec = {"lane": lane, "family": self.current_family,
               "detail": detail[:500]}
        self.divergences.append(rec)
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(M.FUZZ_SOAK_DIVERGENCE,
                                     {"lane": lane})

    # --- build -------------------------------------------------------------
    def _build(self) -> None:
        from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
        from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
        from gatekeeper_tpu.client.client import Client
        from gatekeeper_tpu.drivers.cel_driver import CELDriver
        from gatekeeper_tpu.drivers.generation import CompileCache
        from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
        from gatekeeper_tpu.expansion.system import ExpansionSystem
        from gatekeeper_tpu.extdata import ExtDataDivergence, ExtDataLane
        from gatekeeper_tpu.externaldata.providers import (Provider,
                                                           ProviderCache)
        from gatekeeper_tpu.gator import reader
        from gatekeeper_tpu.mutation.system import MutationSystem
        from gatekeeper_tpu.mutlane import (BatchedMutationHandler,
                                            MutationBatcher,
                                            MutationDifferentialError,
                                            MutationLane)
        from gatekeeper_tpu.parallel.sharded import (ShardedEvaluator,
                                                     make_mesh)
        from gatekeeper_tpu.snapshot import ClusterSnapshot, SnapshotConfig
        from gatekeeper_tpu.sync.source import FakeCluster
        from gatekeeper_tpu.target.target import K8sValidationTarget
        from gatekeeper_tpu.webhook.policy import ValidationHandler
        from gatekeeper_tpu.webhook.server import WebhookServer

        cel = CELDriver()
        kw = {}
        if self.cache_dir:
            kw["compile_cache"] = CompileCache(self.cache_dir)
        self.tpu = TpuDriver(batch_bucket=64, cel_driver=cel, **kw)
        self.client = Client(target=K8sValidationTarget(),
                             drivers=[self.tpu, cel],
                             enforcement_points=[WEBHOOK_EP, AUDIT_EP])

        # external data FIRST: the lane must be resident before the
        # extdata template lowers, or the generated program omits the
        # provider join entirely
        self.transport = HostileTransport()
        cache = ProviderCache(send_fn=self.transport)
        cache.upsert(Provider(name=XD_PROVIDER, url="https://fuzz",
                              ca_bundle="x"))
        self.xd_lane = ExtDataLane(cache, mode="differential",
                                   metrics=self.metrics)
        self.tpu.extdata_lane = self.xd_lane
        orig_resolve = self.xd_lane.resolve_keys

        def recording_resolve(provider, keys):
            try:
                return orig_resolve(provider, keys)
            except ExtDataDivergence as e:
                self._divergence("extdata", str(e))
                raise

        self.xd_lane.resolve_keys = recording_resolve

        docs = _library_docs(self.keep_templates)
        docs.append({
            "apiVersion": "templates.gatekeeper.sh/v1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": "k8sfuzzextdata"},
            "spec": {"crd": {"spec": {"names": {"kind": "K8sFuzzExtData"}}},
                     "targets": [{"target": TARGET, "rego": REGO_XD}]},
        })
        xd_con = {
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sFuzzExtData",
            "metadata": {"name": "fuzz-xd-errors"},
            "spec": {"match": {}, "parameters": {}},
        }
        if self.residency_mode != "off":
            # extdata-join groups keep host columns by design, and the
            # unscoped fuzz-xd constraint rides EVERY audit group — so
            # arming the resident lane scopes it to the webhook EP,
            # where its differential still fires on every /v1/admit
            xd_con["spec"]["enforcementAction"] = "scoped"
            xd_con["spec"]["scopedEnforcementActions"] = [
                {"action": "deny",
                 "enforcementPoints": [{"name": WEBHOOK_EP}]}]
        docs.append(xd_con)
        # pathological selector constraints ride a sample constraint's
        # template + parameters, with the hostile match spec swapped in
        base_con = next((d for d in docs if reader.is_constraint(d)), None)
        for b in self.bundles:
            for i, spec in enumerate(b.match_specs):
                if base_con is None:
                    break
                con = copy.deepcopy(base_con)
                con["metadata"] = {"name": f"fuzz-sel-{b.family}-{i}"}
                con.setdefault("spec", {})["match"] = copy.deepcopy(spec)
                if "namespaceSelector" in spec:
                    # audit reviews carry no Namespace context (the
                    # matcher would raise and drop whole audit chunks):
                    # scope these to the webhook EP, where the
                    # namespace_lookup fixture resolves them fully
                    con["spec"]["enforcementAction"] = "scoped"
                    con["spec"]["scopedEnforcementActions"] = [
                        {"action": "deny",
                         "enforcementPoints": [{"name": WEBHOOK_EP}]}]
                docs.append(con)
        for doc in docs:
            if reader.is_template(doc):
                self.client.add_template(doc)
        for doc in docs:
            if reader.is_constraint(doc):
                self.client.add_constraint(doc)
        if getattr(self.tpu, "gen_coord", None) is not None:
            self.tpu.gen_coord.constraints_fn = self.client.constraints

        # namespace fixtures: every namespace any corpus object can land
        # in gets a real Namespace object (namespaceSelector needs one)
        self.namespaces = {
            n: {"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": n, "labels": {"team": "a"}}}
            for n in ("default", "prod", "kube-system")}
        for b in self.bundles:
            self.namespaces.update(b.namespaces)

        # mutation: differential lane + microbatcher + handler
        self.mutation_system = MutationSystem()
        mutators = [m for b in self.bundles for m in b.mutators]
        if not mutators:
            mutators = [{
                "apiVersion": "mutations.gatekeeper.sh/v1",
                "kind": "Assign", "metadata": {"name": "soak-pull-policy"},
                "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                                      "kinds": ["Pod"]}],
                         "location": "spec.containers[name: *]."
                                     "imagePullPolicy",
                         "parameters": {"assign": {"value": "Always"}}},
            }]
        for m in mutators:
            self.mutation_system.upsert_unstructured(m)
        self.mut_lane = MutationLane(self.mutation_system,
                                     metrics=self.metrics,
                                     differential=True)
        orig_mutate = self.mut_lane.mutate_objects

        def recording_mutate(objects, namespaces=None, source="",
                             want_objects=False):
            try:
                return orig_mutate(objects, namespaces=namespaces,
                                   source=source,
                                   want_objects=want_objects)
            except MutationDifferentialError as e:
                self._divergence("mutate", str(e))
                raise

        self.mut_lane.mutate_objects = recording_mutate
        self.mut_batcher = MutationBatcher(self.mut_lane,
                                           metrics=self.metrics)
        mut_handler = BatchedMutationHandler(
            self.mutation_system, lane=self.mut_lane,
            namespace_lookup=self.namespaces.get,
            batcher=self.mut_batcher, metrics=self.metrics)

        # expansion: generator templates ride the admit path
        self.expansion = ExpansionSystem(
            mutation_system=self.mutation_system)
        for b in self.bundles:
            for t in b.expansion_templates:
                self.expansion.upsert_template(t)

        val_handler = ValidationHandler(
            self.client, expansion_system=self.expansion,
            namespace_lookup=self.namespaces.get, metrics=self.metrics)
        self.server = WebhookServer(validation_handler=val_handler,
                                    mutation_handler=mut_handler,
                                    port=0, metrics=self.metrics,
                                    mutation_batcher=self.mut_batcher)

        # audit: snapshot-sourced vs relist, flatten+collect differential
        self.cluster = FakeCluster()
        for ns_obj in self.namespaces.values():
            self.cluster.apply(copy.deepcopy(ns_obj))
        for b in self.bundles:
            for o in b.objects:
                self.cluster.apply(copy.deepcopy(o))
        # the resident lane is single-chip by design: arming it forces
        # a one-device mesh so DeviceResidency actually promotes
        mesh = (make_mesh(1) if self.residency_mode != "off"
                else make_mesh())
        self.evaluator = ShardedEvaluator(
            self.tpu, mesh, violations_limit=20,
            flatten_lane="differential", collect="differential",
            metrics=self.metrics)
        cfg = dict(exact_totals=False, chunk_size=64, pipeline="off")

        def lister():
            return iter(self.cluster.list())

        if self.residency_mode != "off":
            from gatekeeper_tpu.snapshot import DeviceResidency

            self.residency = DeviceResidency(
                self.evaluator, mode=self.residency_mode,
                metrics=self.metrics)
        self.snapshot = ClusterSnapshot(self.evaluator, SnapshotConfig())
        self.snap_mgr = AuditManager(
            self.client, lister=lister,
            config=AuditConfig(audit_source="snapshot", **cfg),
            evaluator=self.evaluator, snapshot=self.snapshot,
            residency=self.residency)
        self.relist_mgr = AuditManager(
            self.client, lister=lister, config=AuditConfig(**cfg),
            evaluator=self.evaluator)
        self._verdicts_differ = AuditManager._verdicts_differ_canonical
        self._built = True

    def start(self) -> "SoakHarness":
        from gatekeeper_tpu.extdata import lane as xd_mod

        if not self._built:
            self._build()
        # process-global: webhook handler threads, the mutation batcher
        # and the audit sweep must all resolve through the SAME lane
        xd_mod.install(self.xd_lane)
        self.mut_batcher.start()
        self.server.start()
        return self

    def stop(self, drain_timeout: float = 5.0) -> bool:
        """Drain + teardown; True when the server drained cleanly."""
        from gatekeeper_tpu.extdata import lane as xd_mod

        drain_ok = self.server.stop(drain_timeout=drain_timeout)
        self.mut_batcher.stop()
        xd_mod.uninstall()
        gc = getattr(self.tpu, "gen_coord", None)
        if gc is not None:
            gc.stop()
        return drain_ok

    # --- seeded-bug injections (sensitivity tests) -------------------------
    def inject_bug(self, which: str) -> None:
        if which == "mutate_program":
            # the corrupted-batched-program analogue: one emitted patch
            # op per burst flips to a wrong value — the differential's
            # host reference must flag the mismatch
            orig_impl = self.mut_lane._mutate_impl

            def corrupt(objects, namespaces, source, want_objects,
                        occ_out=None):
                outs = orig_impl(objects, namespaces, source,
                                 want_objects, occ_out=occ_out)
                for o in outs:
                    if o.patch:
                        o.patch[-1] = dict(o.patch[-1],
                                           value="~~soak-corrupted~~")
                        break
                return outs

            self.mut_lane._mutate_impl = corrupt
        elif which == "extdata_column":
            # tamper a resident provider column entry after warmup: the
            # per-key reference re-resolves from the transport and must
            # disagree with the poisoned batched column
            self._tamper_extdata = True
        else:
            raise ValueError(f"unknown inject_bug {which!r} "
                             "(mutate_program | extdata_column)")

    def _apply_extdata_tamper(self, prefer=()) -> bool:
        col = self.xd_lane.column(XD_PROVIDER)
        entries = getattr(col, "_entries", None)
        if not entries:
            return False
        # tamper a key the RE-DRIVE will actually query: with every
        # family armed, other families' objects populate the column
        # too, and poisoning one of their keys is a bug nobody asks
        # about again.  Prefer the bundle's own plain-value keys:
        # err-/absent- entries hold errors, not values, and EMPTY keys
        # are dropped before the join by both arms — poisoning one is
        # undetectable by design, not blindness.
        pool = [k for k in prefer
                if k and k in entries
                and not k.startswith(("err-", "absent-"))]
        key = sorted(pool)[0] if pool else sorted(entries)[0]
        landed_at = entries[key][0]
        entries[key] = (landed_at, "~~soak-tampered~~", None)
        self._tampered = True
        return True

    # --- drive -------------------------------------------------------------
    def _post(self, path: str, body: dict) -> dict | None:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.server.port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())
        except Exception:
            return None

    def _count_request(self, endpoint: str, resp) -> None:
        self.sent[endpoint] += 1
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(M.FUZZ_SOAK_REQUESTS,
                                     {"endpoint": endpoint})
        inner = (resp or {}).get("response") or {}
        if resp is None or "uid" not in inner:
            return  # lost: no verdict came back
        self.ok[endpoint] += 1
        code = (inner.get("status") or {}).get("code", 200)
        if endpoint == "admit" and code == 500:
            # fail-closed handler exception = a crash the soak must flag
            self.crashes.append({
                "family": self.current_family, "uid": inner.get("uid"),
                "message": (inner.get("status") or {}).get("message",
                                                           "")[:300]})

    def _drive_admit(self, bundle, seed: int) -> None:
        bodies = corpus_mod.admission_bodies(bundle.objects, seed=seed,
                                             prefix=bundle.family)
        for body in bodies:
            self._count_request("admit", self._post("/v1/admit", body))

    def _drive_mutate(self, bundle, seed: int) -> None:
        objs = [o for o in bundle.objects
                if o.get("kind") in ("Pod", "Service")]
        bodies = corpus_mod.admission_bodies(
            objs, seed=seed, prefix=f"mut-{bundle.family}")
        for body in bodies:
            self._count_request("mutate", self._post("/v1/mutate", body))

    def _flatten_arm(self, bundle) -> None:
        """Standalone flatten differential over the family's objects AND
        its hostile raw byte docs (dup keys, 256+ depth) — shapes the
        audit path's dict objects cannot express."""
        from gatekeeper_tpu.ops.flatten import Flattener, Schema, Vocab
        from gatekeeper_tpu.utils.rawjson import as_raw

        schema = Schema()
        for kind in self.tpu.lowered_kinds():
            schema.merge(self.tpu._programs[kind].program.schema)
        objs = ([as_raw(o) for o in bundle.objects]
                + [as_raw(d) for d in bundle.raw_docs])
        if not objs:
            return
        pad_n = max(8, 1 << (len(objs) - 1).bit_length())
        f = Flattener(schema, Vocab(), lane="differential")
        try:
            f.flatten(objs, pad_n=pad_n)
        except (RuntimeError, AssertionError) as e:
            self._divergence("flatten", str(e))

    def _audit_round(self, round_i: int) -> None:
        from gatekeeper_tpu.observability import tracing

        with tracing.span("soak.audit_tick", round=round_i):
            try:
                snap_run = self.snap_mgr.audit()
                relist_run = self.relist_mgr.audit()
            except (RuntimeError, AssertionError) as e:
                self._divergence("audit", str(e))
                return
            diff = self._verdicts_differ(
                snap_run.kept, snap_run.total_violations,
                relist_run.kept, relist_run.total_violations,
                self.snap_mgr.config.violations_limit)
            if diff is not None:
                self._divergence("snapshot", diff)

    def resync(self) -> None:
        """The end-of-run snapshot resync differential."""
        try:
            self.snap_mgr.audit_resync()
        except (RuntimeError, AssertionError) as e:
            self._divergence("snapshot", str(e))
            return
        diff = self.snap_mgr.last_resync_diff
        if diff is not None:
            self._divergence("snapshot", str(diff))

    def drive_round(self, round_i: int, seed: int = 0,
                    concurrent: bool = False) -> None:
        """One pass over every family: admit + mutate traffic and the
        audit differential.  Serial on the 1-core smoke; ``concurrent``
        posts admit/mutate from worker threads while the audit runs in
        this thread (the real SIMULTANEOUS shape)."""
        from gatekeeper_tpu.observability import tracing

        def families(fn):
            for b in self.bundles:
                self.current_family = b.family
                with tracing.span("soak.drive", family=b.family,
                                  round=round_i):
                    fn(b)
                    if (self._tamper_extdata and not self._tampered
                            and b.family == "extdata_hostile"):
                        if self._apply_extdata_tamper(
                                prefer=b.extdata_keys):
                            fn(b)  # resolve again: must now diverge

        if concurrent:
            threads = [
                threading.Thread(target=families, daemon=True,
                                 args=(lambda b: self._drive_admit(
                                     b, seed),)),
                threading.Thread(target=families, daemon=True,
                                 args=(lambda b: self._drive_mutate(
                                     b, seed),)),
            ]
            for t in threads:
                t.start()
            self._audit_round(round_i)
            for b in self.bundles:
                self._flatten_arm(b)
            for t in threads:
                t.join(timeout=600)
        else:
            def serial(b):
                self._drive_admit(b, seed)
                self._drive_mutate(b, seed)
                self._flatten_arm(b)

            families(serial)
            self._audit_round(round_i)


def run_soak(seed: int = 0, size: int = 1, families=None,
             duration_s: float = 0.0, rounds: int = 1,
             chaos: bool = True, chaos_seed=None,
             keep_templates: int = 3, inject_bug=None,
             concurrent: bool = False, cache_dir: str = "",
             metrics=None, quiet: bool = True,
             residency: str = "off") -> dict:
    """Run the soak; returns the report dict (``report["ok"]`` is the
    pass/fail).  ``duration_s`` > 0 loops rounds until the clock runs
    out; otherwise exactly ``rounds`` passes run.  Every failure path
    prints the one-command repro line."""
    from gatekeeper_tpu.metrics.registry import MetricsRegistry
    from gatekeeper_tpu.observability import tracing
    from gatekeeper_tpu.resilience.faults import inject

    bundles = corpus_mod.generate_all(seed=seed, size=size,
                                      families=families)
    fam_names = [b.family for b in bundles]
    metrics = metrics if metrics is not None else MetricsRegistry()
    from gatekeeper_tpu.metrics import registry as M

    for b in bundles:
        metrics.inc_counter(M.FUZZ_CASES, {"family": b.family},
                            value=float(len(b.objects)
                                        + len(b.raw_docs)))
    plan = (default_chaos_plan(seed if chaos_seed is None
                               else chaos_seed) if chaos else None)
    harness = SoakHarness(bundles, keep_templates=keep_templates,
                          cache_dir=cache_dir, metrics=metrics,
                          residency=residency)
    t0 = time.perf_counter()
    rounds_run = 0
    with tempfile.TemporaryDirectory(prefix="gtpu-soak-") as _tmp:
        if not cache_dir:
            harness.cache_dir = os.path.join(_tmp, "cc")
        ctx = inject(plan) if plan is not None else contextlib.nullcontext()
        with tracing.span("soak.run", seed=seed,
                          families=",".join(fam_names)), ctx:
            harness.start()
            try:
                if inject_bug:
                    harness.inject_bug(inject_bug)
                deadline = (time.monotonic() + duration_s
                            if duration_s > 0 else None)
                while True:
                    harness.drive_round(rounds_run, seed=seed,
                                        concurrent=concurrent)
                    rounds_run += 1
                    if deadline is not None:
                        if time.monotonic() >= deadline:
                            break
                    elif rounds_run >= rounds:
                        break
                harness.resync()
            finally:
                drain_ok = harness.stop()
    wall = time.perf_counter() - t0
    lost = ((harness.sent["admit"] - harness.ok["admit"])
            + (harness.sent["mutate"] - harness.ok["mutate"]))
    metrics.set_gauge(M.FUZZ_SOAK_SECONDS, wall)
    if lost:
        metrics.inc_counter(M.FUZZ_SOAK_LOST, value=float(lost))
    report = {
        "seed": seed,
        "size": size,
        "families": fam_names,
        "rounds": rounds_run,
        "chaos": bool(plan),
        "inject_bug": inject_bug or "",
        "requests": dict(harness.sent),
        "answered": dict(harness.ok),
        "lost_verdicts": lost,
        "drain_ok": drain_ok,
        "divergences": harness.divergences,
        "crashes": harness.crashes,
        "faults_fired": (_fault_counts(plan) if plan else {}),
        "extdata_transport_calls": harness.transport.calls,
        "residency": residency,
        "resident_uploads": (harness.residency.upload_count
                             if harness.residency else 0),
        "resident_patches": (harness.residency.patch_count
                             if harness.residency else 0),
        "corpus": corpus_mod.corpus_stats(bundles),
        "wall_s": round(wall, 3),
    }
    report["ok"] = (not harness.divergences and not harness.crashes
                    and lost == 0 and drain_ok)
    if not report["ok"] and not quiet:
        print(_repro_line(report))
    return report


def _fault_counts(plan) -> dict:
    out: dict = {}
    for site, _mode, _n in plan.events:
        out[site] = out.get(site, 0) + 1
    return out


def _repro_line(report: dict) -> str:
    fams = sorted({d.get("family") or f
                   for d in report["divergences"]
                   for f in [d.get("family")] if f} |
                  {c.get("family") for c in report["crashes"]
                   if c.get("family")}) or report["families"]
    return ("SOAK FAILURE — reproduce with: python tools/soak.py "
            f"--seed {report['seed']} --families {','.join(fams)}"
            + ("" if report["chaos"] else " --chaos off"))
