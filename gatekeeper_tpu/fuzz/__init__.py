"""Adversarial scenario fuzzing (PR 17).

``corpus``: the seeded adversarial corpus generator — scenario families
nobody writes by hand (CRD-heavy clusters, megabyte objects, 256+-deep
nesting, pathological selectors, alias-heavy mutators, hostile vocab,
expansion generators, hostile external-data keys), every family
deterministic per (seed, size).

``soak``: the chaos trace-replay soak harness — drives ``/v1/admit``,
``/v1/mutate`` and the audit snapshot tick simultaneously under a
seeded ``faults.py`` chaos plan with every differential lane armed;
any lane divergence, lost verdict at drain, or crash is a failure with
the reproducing seed + family attached.
"""

from gatekeeper_tpu.fuzz.corpus import (FAMILIES, FamilyBundle,
                                        admission_bodies, corpus_stats,
                                        generate, generate_all, rand_obj,
                                        rand_value)

__all__ = [
    "FAMILIES",
    "FamilyBundle",
    "admission_bodies",
    "corpus_stats",
    "generate",
    "generate_all",
    "rand_obj",
    "rand_value",
]
