"""Offline expander used by gator test/expand (reference: pkg/gator/expand).

Resolves namespaces from the supplied object set (with the reference's
quirks: a resource with no namespace gets an EMPTY Namespace object, an
unknown namespace named "default" gets a synthetic default —
expand.go:109-121) and expands generator resources through the expansion
system with mutators applied.
"""

from __future__ import annotations

from typing import Optional, Sequence

import copy

from gatekeeper_tpu.expansion.system import EXPANSION_GROUP, ExpansionSystem
from gatekeeper_tpu.expansion.system import Resultant  # noqa: F401 (re-export)
from gatekeeper_tpu.mutation.mutators import MUTATIONS_GROUP, MUTATOR_KINDS
from gatekeeper_tpu.utils.unstructured import gvk_of, name_of, namespace_of


class Expander:
    def __init__(self, objs: Sequence[dict]):
        self._namespaces: dict[str, dict] = {}
        mutators = []
        expansion_templates = []
        for obj in objs:
            group, _, kind = gvk_of(obj)
            if kind == "Namespace" and group == "":
                # deep copy: the reference's typed conversion detaches the
                # namespace map from caller objects (expand.go:201-208), so
                # base mutation must not leak into namespaceSelector matching
                self._namespaces[name_of(obj)] = copy.deepcopy(obj)
            elif kind == "ExpansionTemplate" and group == EXPANSION_GROUP:
                expansion_templates.append(obj)
            elif group == MUTATIONS_GROUP and kind in MUTATOR_KINDS:
                # unknown kinds in the mutations group are plain objects
                # (reference: isMutator filters the four kinds, expand.go)
                mutators.append(obj)
        self._system = None
        if expansion_templates:
            from gatekeeper_tpu.mutation.system import MutationSystem

            mut_system = MutationSystem()
            for m in mutators:
                mut_system.upsert_unstructured(m)
            self._system = ExpansionSystem(mutation_system=mut_system)
            for et in expansion_templates:
                self._system.upsert_template(et)

    def namespace_for(self, obj: dict) -> Optional[dict]:
        """Reference: NamespaceForResource (expand.go:109-121)."""
        ns = namespace_of(obj)
        if ns == "":
            return {}  # empty Namespace object, non-nil
        hit = self._namespaces.get(ns)
        if hit is not None:
            return hit
        if ns == "default":
            return {"apiVersion": "v1", "kind": "Namespace",
                    "metadata": {"name": "default"}}
        return None

    def expand(self, obj: dict) -> list:
        if self._system is None:
            return []
        ns = self.namespace_for(obj)
        # the base resource is mutated (in place, Source=Original) before
        # expansion — reference: Expander.Expand (expand.go:87-98)
        if self._system.mutation_system is not None:
            from gatekeeper_tpu.match.match import SOURCE_ORIGINAL

            self._system.mutation_system.mutate(
                obj, namespace=ns, source=SOURCE_ORIGINAL
            )
        return self._system.expand(obj, namespace=ns)
