"""Offline expander used by gator test (reference: pkg/gator/expand).

Resolves namespaces from the supplied object set and expands generator
resources through the expansion system.  (Expansion system itself lives in
gatekeeper_tpu.expansion.system.)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from gatekeeper_tpu.utils.unstructured import gvk_of, name_of, namespace_of


@dataclass
class Resultant:
    obj: dict
    template_name: str
    enforcement_action: str = ""


class Expander:
    def __init__(self, objs: Sequence[dict]):
        self._namespaces: dict[str, dict] = {}
        self._system = None
        expansion_templates = []
        mutators = []
        for obj in objs:
            group, _, kind = gvk_of(obj)
            if kind == "Namespace" and group == "":
                self._namespaces[name_of(obj)] = obj
            elif kind == "ExpansionTemplate" and group == "expansion.gatekeeper.sh":
                expansion_templates.append(obj)
            elif group == "mutations.gatekeeper.sh":
                mutators.append(obj)
        if expansion_templates:
            from gatekeeper_tpu.expansion.system import ExpansionSystem
            from gatekeeper_tpu.mutation.system import MutationSystem

            mut_system = MutationSystem()
            for m in mutators:
                mut_system.upsert_unstructured(m)
            self._system = ExpansionSystem(mutation_system=mut_system)
            for et in expansion_templates:
                self._system.upsert_template(et)

    def namespace_for(self, obj: dict) -> Optional[dict]:
        ns = namespace_of(obj)
        return self._namespaces.get(ns) if ns else None

    def expand(self, obj: dict) -> list[Resultant]:
        if self._system is None:
            return []
        ns = self.namespace_for(obj)
        return self._system.expand(obj, namespace=ns)
