"""Expansion result aggregation (reference: pkg/expansion/aggregate.go).

Resultant violations fold into the parent object's responses with an
``[Implied by <template>]`` message prefix; the expansion template may override
the enforcement action of resultant violations.
"""

from __future__ import annotations

from gatekeeper_tpu.client.types import Responses

CHILD_MSG_PREFIX = "[Implied by %s]"


def override_enforcement_action(action: str, responses: Responses) -> None:
    """Reference: aggregate.go:46 — apply template's enforcementAction
    override to every resultant result."""
    if not action:
        return
    for resp in responses.by_target.values():
        for result in resp.results:
            result.enforcement_action = action


def aggregate_responses(
    template_name: str, parent: Responses, child: Responses
) -> None:
    """Reference: aggregate.go:19-43 — merge child responses into parent with
    prefixed messages."""
    prefix = CHILD_MSG_PREFIX % template_name
    for target_name, child_resp in child.by_target.items():
        parent_resp = parent.by_target.get(target_name)
        if parent_resp is None:
            parent.by_target[target_name] = child_resp
            parent_resp = child_resp
            for result in child_resp.results:
                result.msg = f"{prefix} {result.msg}"
            continue
        for result in child_resp.results:
            result.msg = f"{prefix} {result.msg}"
            parent_resp.results.append(result)
        if child_resp.trace:
            parent_resp.trace = (
                (parent_resp.trace + "\n" + child_resp.trace)
                if parent_resp.trace
                else child_resp.trace
            )
    parent.stats_entries.extend(child.stats_entries)
