"""Expansion system: generator resources imply their children.

Reference: pkg/expansion/system.go — ExpansionTemplates map a generator GVK
(e.g. apps/v1 Deployment) to a source subtree (``spec.template``) and a
generated GVK (v1 Pod); Expand extracts the subtree, stamps GVK/namespace/
mock name/owner-ref, recursively expands resultants (depth cap 30) and runs
the mutation system over each with Source=Generated.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Optional

from gatekeeper_tpu.utils.unstructured import deep_get, gvk_of, name_of

MAX_RECURSION_DEPTH = 30  # reference: system.go:27-30

EXPANSION_GROUP = "expansion.gatekeeper.sh"


class ExpansionError(Exception):
    pass


@dataclass
class ExpansionTemplate:
    name: str
    apply_to: list
    template_source: str
    generated_gvk: dict  # {group, version, kind}
    enforcement_action: str = ""
    raw: dict = field(default_factory=dict)

    @staticmethod
    def from_unstructured(obj: dict) -> "ExpansionTemplate":
        group, _, kind = gvk_of(obj)
        if kind != "ExpansionTemplate" or group != EXPANSION_GROUP:
            raise ExpansionError(f"not an ExpansionTemplate: {group}/{kind}")
        name = name_of(obj)
        if not name:
            raise ExpansionError("ExpansionTemplate has no metadata.name")
        spec = obj.get("spec") or {}
        source = spec.get("templateSource", "") or ""
        if not source:
            raise ExpansionError(f"template {name}: no templateSource")
        gvk = spec.get("generatedGVK") or {}
        if not gvk.get("kind") or not gvk.get("version"):
            raise ExpansionError(f"template {name}: empty generatedGVK")
        return ExpansionTemplate(
            name=name,
            apply_to=spec.get("applyTo") or [],
            template_source=source,
            generated_gvk=gvk,
            enforcement_action=spec.get("enforcementAction", "") or "",
            raw=obj,
        )

    def applies_to(self, obj: dict) -> bool:
        group, version, kind = gvk_of(obj)
        for entry in self.apply_to:
            if (
                group in (entry.get("groups") or [])
                and version in (entry.get("versions") or [])
                and kind in (entry.get("kinds") or [])
            ):
                return True
        return False


@dataclass
class Resultant:
    obj: dict
    template_name: str
    enforcement_action: str = ""


class ExpansionSystem:
    def __init__(self, mutation_system=None):
        self._templates: dict[str, ExpansionTemplate] = {}
        self.mutation_system = mutation_system

    def upsert_template(self, obj_or_template) -> ExpansionTemplate:
        t = (obj_or_template if isinstance(obj_or_template, ExpansionTemplate)
             else ExpansionTemplate.from_unstructured(obj_or_template))
        self._templates[t.name] = t
        return t

    def remove_template(self, name: str) -> None:
        self._templates.pop(name, None)

    def templates(self) -> list:
        return [self._templates[k] for k in sorted(self._templates)]

    def get_conflicts(self) -> list:
        """Templates whose generated GVK is also a generator for another
        template of the same GVK chain are legal (recursive expansion);
        conflicting = two templates for the same generator with the same
        generated GVK (reference: GetConflicts system.go:81)."""
        seen: dict = {}
        conflicts = []
        for t in self.templates():
            for entry in t.apply_to:
                for g in entry.get("groups") or []:
                    for v in entry.get("versions") or []:
                        for k in entry.get("kinds") or []:
                            key = (g, v, k, t.generated_gvk.get("group", ""),
                                   t.generated_gvk.get("version", ""),
                                   t.generated_gvk.get("kind", ""))
                            if key in seen and seen[key] != t.name:
                                conflicts.append((seen[key], t.name))
                            seen[key] = t.name
        return conflicts

    # --- Expand (reference: system.go:137-210) ---------------------------
    def expand(self, base: dict, namespace: Optional[dict] = None,
               username: str = "", source: str = "") -> list:
        resultants: list[Resultant] = []
        self._expand_recursive(base, namespace, username, source,
                               resultants, 0)
        return resultants

    def _expand_recursive(self, base, namespace, username, source, out,
                          depth):
        if depth >= MAX_RECURSION_DEPTH:
            raise ExpansionError(
                f"maximum recursion depth of {MAX_RECURSION_DEPTH} reached"
            )
        res = self._expand_one(base, namespace, username)
        for r in res:
            self._expand_recursive(r.obj, namespace, username, source, out,
                                   depth + 1)
        out.extend(res)

    def _expand_one(self, base: dict, namespace, username) -> list:
        group, version, kind = gvk_of(base)
        if not kind or not version:
            raise ExpansionError(
                f"cannot expand resource {name_of(base)} with empty GVK"
            )
        out = []
        for t in self.templates():
            if not t.applies_to(base):
                continue
            out.append(Resultant(
                obj=self._expand_resource(base, namespace, t),
                template_name=t.name,
                enforcement_action=t.enforcement_action,
            ))
        if self.mutation_system is not None:
            from gatekeeper_tpu.match.match import SOURCE_GENERATED

            for r in out:
                self.mutation_system.mutate(
                    r.obj, namespace=namespace, source=SOURCE_GENERATED
                )
        return out

    @staticmethod
    def _expand_resource(obj: dict, namespace, template) -> dict:
        """Reference: expandResource (system.go:215-254)."""
        src_path = tuple(template.template_source.split("."))
        src = deep_get(obj, src_path)
        if not isinstance(src, dict):
            raise ExpansionError(
                f"could not find source field {template.template_source!r} "
                f"in resource {name_of(obj)}"
            )
        resource = copy.deepcopy(src)
        gvk = template.generated_gvk
        group, version, kind = (gvk.get("group", ""), gvk.get("version", ""),
                                gvk.get("kind", ""))
        resource["apiVersion"] = f"{group}/{version}" if group else version
        resource["kind"] = kind
        meta = resource.setdefault("metadata", {})
        if namespace is not None:
            ns_name = deep_get(namespace, ("metadata", "name"), "") or ""
            if ns_name:
                meta["namespace"] = ns_name
            else:
                meta.pop("namespace", None)
        else:
            parent_ns = deep_get(obj, ("metadata", "namespace"))
            if parent_ns:
                meta["namespace"] = parent_ns
        # mock name: "<generator name>-<kind>", lowercased (system.go:289-299)
        mock = name_of(obj)
        if kind:
            mock += "-"
        mock += kind
        meta["name"] = mock.lower()
        _ensure_owner_reference(resource, obj)
        return resource


def _ensure_owner_reference(resultant: dict, parent: dict) -> None:
    """Reference: ensureOwnerReference (system.go:257-286)."""
    api_version = parent.get("apiVersion", "")
    kind = parent.get("kind", "")
    name = name_of(parent)
    if not api_version or not kind or not name:
        return
    meta = resultant.setdefault("metadata", {})
    refs = meta.setdefault("ownerReferences", [])
    for ref in refs:
        if (ref.get("apiVersion") == api_version and ref.get("kind") == kind
                and ref.get("name") == name):
            return
    refs.append({"apiVersion": api_version, "kind": kind, "name": name,
                 "uid": ""})
