"""gatekeeper_tpu: a TPU-native policy-enforcement framework.

A ground-up re-design of the capabilities of open-policy-agent/gatekeeper
(reference: /root/reference) for TPU hardware:

- ConstraintTemplates (Rego / CEL source) are parsed and *partial-evaluated* at
  AddTemplate time and, where the policy falls in the vectorizable subset,
  lowered to a columnar predicate program executed as one batched JAX/XLA
  kernel (``vmap`` over an object batch x constraint axis).  Policies outside
  the subset fall back to an exact logic interpreter behind the same
  ``Driver.Query`` seam, so verdicts are always available and always exact.
- Constraint ``spec.match`` rules (kinds, namespaces, selectors, ...) become
  boolean masks over the flattened object batch (reference semantics:
  pkg/mutation/match/match.go).
- The audit sweep shards the object batch over a ``jax.sharding.Mesh``
  (data-parallel over chips via ICI, hosts via DCN) with a per-constraint
  device top-k reduction mirroring the reference's LimitQueue
  (pkg/audit/manager.go:161).

Layer map (mirrors SURVEY.md section 1):

==========  ==========================================================
L0          ``gatekeeper_tpu.drivers``       policy engines (tpu / rego / cel)
L1          ``gatekeeper_tpu.client``        constraint-framework client
L2          ``gatekeeper_tpu.target``        target handler + match
L3          ``gatekeeper_tpu.webhook``       admission webhooks
L4          ``gatekeeper_tpu.audit``         audit sweep
L5          ``gatekeeper_tpu.mutation`` / ``.expansion``
L6          ``gatekeeper_tpu.gator``         offline CLI
L7          ``gatekeeper_tpu.sync``          data-sync plane (inventory)
L9          ``gatekeeper_tpu.readiness``
L10         ``gatekeeper_tpu.metrics`` / ``.export``
==========  ==========================================================
"""

__version__ = "0.1.0"


def _honor_jax_platforms_env():
    """Pin jax to the platform named in JAX_PLATFORMS.

    Some accelerator plugins (e.g. the axon TPU plugin) prepend themselves to
    ``jax_platforms`` regardless of the env var; when the accelerator is
    unreachable that hangs every consumer on first device init.  Honoring the
    operator's explicit JAX_PLATFORMS here protects every entry point
    (webhook server, audit pod, gator CLI, library use).
    """
    import os

    want = os.environ.get("JAX_PLATFORMS", "")
    if not want:
        return
    try:
        import jax

        jax.config.update("jax_platforms", want)
    except Exception:
        pass  # backends already initialized or jax unavailable


_honor_jax_platforms_env()
