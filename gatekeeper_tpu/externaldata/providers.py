"""External data providers: cache, validation, batched resolution.

Reference: the framework's externaldata package + Provider CRD
(main.go:420-458); mutation placeholder resolution batches per-provider
calls with mTLS and a 5s timeout (mutation/system_external_data.go:21-221);
responses may be TTL-cached.  The transport is pluggable (``send_fn``) so
tests and offline runs need no network; the default transport posts the
ExternalData ProviderRequest JSON over HTTPS.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from gatekeeper_tpu.utils.unstructured import deep_get, gvk_of, name_of

PROVIDER_GROUP = "externaldata.gatekeeper.sh"


class ProviderError(Exception):
    pass


@dataclass
class Provider:
    name: str
    url: str
    timeout_s: float = 5.0
    ca_bundle: str = ""
    raw: dict = field(default_factory=dict)

    @staticmethod
    def from_unstructured(obj: dict) -> "Provider":
        group, _, kind = gvk_of(obj)
        if kind != "Provider" or group != PROVIDER_GROUP:
            raise ProviderError(f"not a Provider: {group}/{kind}")
        name = name_of(obj)
        spec = obj.get("spec") or {}
        url = spec.get("url", "")
        if not url:
            raise ProviderError(f"provider {name}: missing spec.url")
        if not url.startswith("https://"):
            # reference: provider URLs must use HTTPS (webhook validation of
            # Provider resources, policy.go:564-580)
            raise ProviderError(f"provider {name}: url must be https")
        if not spec.get("caBundle"):
            raise ProviderError(f"provider {name}: caBundle required")
        return Provider(
            name=name,
            url=url,
            timeout_s=float(spec.get("timeout", 5) or 5),
            ca_bundle=spec.get("caBundle", ""),
            raw=obj,
        )


def default_send(provider: Provider, keys: list) -> dict:
    """POST an ExternalData ProviderRequest (reference request shape)."""
    import base64
    import ssl
    import urllib.request

    body = json.dumps({
        "apiVersion": "externaldata.gatekeeper.sh/v1beta1",
        "kind": "ProviderRequest",
        "request": {"keys": keys},
    }).encode()
    # the provider's private CA (spec.caBundle, required by validation) must
    # anchor the TLS verification
    ctx = ssl.create_default_context(
        cadata=base64.b64decode(provider.ca_bundle).decode()
    )
    headers = {"Content-Type": "application/json"}
    # traceparent emit: the provider can join its own processing span to
    # the admission/audit trace that triggered this fetch
    from gatekeeper_tpu.observability import tracing

    tp = tracing.format_traceparent()
    if tp is not None:
        headers[tracing.TRACEPARENT_HEADER] = tp
    req = urllib.request.Request(
        provider.url, data=body, headers=headers)
    with urllib.request.urlopen(req, timeout=provider.timeout_s,
                                context=ctx) as resp:
        return json.loads(resp.read())


class ProviderCache:
    """Provider registry + response TTL cache + batched resolution.

    Resilience (resilience/policy.py): each provider gets a circuit
    breaker; transport failures retry with seeded-jitter exponential
    backoff bounded by the ambient request deadline.  When the breaker is
    open — or the transport keeps failing — keys present in the TTL cache
    are served STALE (the reference's external-data TTL-cache fallback)
    and counted in ``gatekeeper_resilience_stale_served_count``; keys
    with no cached value surface a per-key error that flows into the
    placeholder failure-policy semantics (Fail | Ignore | UseDefault)."""

    def __init__(self, send_fn: Optional[Callable] = None,
                 response_ttl_s: float = 180.0,
                 metrics=None,
                 retry=None,  # resilience.policy.RetryPolicy
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 30.0):
        self._providers: dict[str, Provider] = {}
        self._responses: dict[tuple, tuple] = {}  # (provider, key) -> (t, val)
        self.send_fn = send_fn or default_send
        self.response_ttl_s = response_ttl_s
        self.metrics = metrics
        self.breaker_threshold = breaker_threshold
        self.breaker_reset_s = breaker_reset_s
        if retry is None:
            from gatekeeper_tpu.resilience.policy import RetryPolicy

            retry = RetryPolicy(attempts=3, base_s=0.05, cap_s=1.0,
                                dependency="externaldata", metrics=metrics)
        self.retry = retry
        self._breakers: dict[str, Any] = {}
        self._lock = threading.Lock()
        # provider-change listeners (name) — the extdata lane registers
        # its column invalidation here so a Provider reconcile from
        # controller/manager.py drops the resident join columns
        self._listeners: list = []

    def add_listener(self, fn: Callable[[str], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def _notify(self, name: str) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(name)
            except Exception:
                pass  # invalidation must never break reconcile

    def _breaker(self, provider_name: str):
        from gatekeeper_tpu.resilience.policy import CircuitBreaker

        with self._lock:
            b = self._breakers.get(provider_name)
            if b is None:
                b = CircuitBreaker(
                    f"externaldata/{provider_name}",
                    failure_threshold=self.breaker_threshold,
                    reset_timeout_s=self.breaker_reset_s,
                    metrics=self.metrics)
                self._breakers[provider_name] = b
            return b

    def upsert(self, obj_or_provider) -> Provider:
        p = (obj_or_provider if isinstance(obj_or_provider, Provider)
             else Provider.from_unstructured(obj_or_provider))
        with self._lock:
            self._providers[p.name] = p
            self._drop_responses(p.name)
        self._notify(p.name)
        return p

    def remove(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)
            self._drop_responses(name)
        self._notify(name)

    def _drop_responses(self, name: str) -> None:
        """Reconcile invalidation (call under the lock): a Provider spec
        change (URL, CA, timeout) voids its cached answers — stale-serve
        fallbacks must not resurrect responses from the OLD endpoint."""
        for key in [k for k in self._responses if k[0] == name]:
            del self._responses[key]

    def get(self, name: str) -> Optional[Provider]:
        return self._providers.get(name)

    # --- resolution (reference: system_external_data.go) ----------------
    def _send(self, provider: Provider, keys: list) -> dict:
        """One transport round-trip through the chaos seam.  A partial
        fault truncates the item list (the provider 'answered' for only a
        fraction of the keys); the missing keys surface per-key 'key not
        returned' errors downstream."""
        from gatekeeper_tpu.observability import tracing
        from gatekeeper_tpu.resilience.faults import fault_point

        with tracing.span("externaldata.send", provider=provider.name,
                          n_keys=len(keys)):
            action = fault_point("externaldata.send",
                                 provider=provider.name, n_keys=len(keys))
            resp = self.send_fn(provider, keys)
            if action is not None and action.mode == "partial":
                items = deep_get(resp, ("response", "items"), []) or []
                keep = int(len(items) * action.spec.fraction)
                resp = {"response": {
                    "items": items[:keep],
                    "systemError": deep_get(resp,
                                            ("response", "systemError"),
                                            ""),
                }}
            system_error = deep_get(resp, ("response", "systemError"), "")
            if system_error:
                raise ProviderError(
                    f"provider {provider.name}: {system_error}")
            return resp

    def _serve_stale(self, provider_name: str, keys: list, out: dict,
                     reason: str) -> None:
        """Fill ``out`` for ``keys`` from expired TTL-cache entries
        (graceful degradation); keys never fetched get a per-key error
        that the placeholder failure policy interprets."""
        n_stale = 0
        with self._lock:
            for key in keys:
                hit = self._responses.get((provider_name, key))
                if hit is not None:
                    out[key] = hit[1]
                    n_stale += 1
                else:
                    out[key] = (None, f"provider {provider_name}: {reason}; "
                                      "no cached value")
        if n_stale and self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(
                M.RESILIENCE_STALE_SERVED,
                {"dependency": f"externaldata/{provider_name}"},
                value=float(n_stale))

    def fetch(self, provider_name: str, keys: list) -> dict:
        """Returns key -> (value, error-string-or-None); TTL-cached.
        Transport failures retry with jittered backoff (deadline-bounded);
        a tripped breaker — or exhausted retries — serves stale cache
        entries and per-key errors instead of raising."""
        provider = self._providers.get(provider_name)
        if provider is None:
            raise ProviderError(f"provider {provider_name!r} not found")
        now = time.monotonic()
        out: dict = {}
        missing = []
        with self._lock:
            for key in keys:
                hit = self._responses.get((provider_name, key))
                if hit and now - hit[0] < self.response_ttl_s:
                    out[key] = hit[1]
                else:
                    missing.append(key)
        if not missing:
            return out
        from gatekeeper_tpu.resilience import overload as _overload

        if _overload.current_brownout() >= 1 or \
                _overload.degradation_active(_overload.EXTDATA_STALE):
            # overload brownout (resilience/overload.py) — or a
            # breaching SLO objective holding the extdata_stale
            # degradation action: external-data joins are the expensive
            # optional work degraded BEFORE any admission is shed —
            # expired cache entries serve stale, keys never fetched
            # flow into the placeholder failure policy
            self._serve_stale(provider_name, missing, out,
                              "overload brownout")
            return out
        breaker = self._breaker(provider_name)
        if not breaker.allow():
            self._serve_stale(provider_name, missing, out,
                              "circuit breaker open")
            return out
        try:
            resp = self.retry.call(self._send, provider, missing)
        except Exception as e:
            breaker.record_failure()
            self._serve_stale(provider_name, missing, out, str(e))
            return out
        breaker.record_success()
        items = deep_get(resp, ("response", "items"), []) or []
        if not isinstance(items, list):
            items = []  # schema drift: every key degrades below
        got = {}
        for item in items:
            # response-schema hardening: a misbehaving provider may
            # return non-dict items or non-string keys/errors — skip or
            # coerce so the affected keys degrade to the per-key
            # "key not returned" error instead of crashing the batch
            if not isinstance(item, dict):
                continue
            key = item.get("key")
            if not isinstance(key, str):
                continue
            err = item.get("error")
            if err is not None and not isinstance(err, str):
                err = str(err)
            got[key] = (item.get("value"), err or None)
        with self._lock:
            for key in missing:
                value = got.get(key, (None, "key not returned"))
                self._responses[(provider_name, key)] = (now, value)
                out[key] = value
        return out

    def prefetch(self, pairs) -> None:
        """Concurrently warm the response cache for (provider, key) pairs
        (reference: async batch joins — the dispatcher overlaps provider
        RTTs instead of fetching serially).  Errors are swallowed here and
        surface through resolve()'s failure-policy semantics."""
        from concurrent.futures import ThreadPoolExecutor

        by_provider: dict = {}
        for provider, key in pairs:
            by_provider.setdefault(provider, set()).add(key)
        if len(by_provider) <= 1:
            for provider, keys in by_provider.items():
                try:
                    self.fetch(provider, sorted(keys, key=repr))
                except Exception:
                    pass
            return
        with ThreadPoolExecutor(max_workers=min(8, len(by_provider))) as ex:
            futures = [
                ex.submit(self.fetch, provider, sorted(keys, key=repr))
                for provider, keys in by_provider.items()
            ]
            for f in futures:
                try:
                    f.result()
                except Exception:
                    pass

    def resolve(self, placeholder) -> Any:
        """Resolve one mutation placeholder (failure policy semantics:
        Fail | Ignore | UseDefault)."""
        # ValueAtLocation: key = the pre-mutation value at the location;
        # Username: key = the admission username (caller sets original_value)
        key = placeholder.original_value
        try:
            result = self.fetch(placeholder.provider, [key])
            value, err = result[key]
            if err:
                raise ProviderError(err)
            return value
        except Exception as e:
            policy = placeholder.failure_policy
            if policy == "UseDefault":
                return placeholder.default
            if policy == "Ignore":
                return placeholder.original_value
            raise
