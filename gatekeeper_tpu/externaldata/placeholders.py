"""External-data placeholders for mutation values.

Reference: the framework's ExternalDataPlaceholder leaf — Assign mutators
with an externalData source insert placeholders during the mutation loop;
the system resolves them at convergence via batched provider calls
(pkg/mutation/system_external_data.go:21-221).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class ExternalDataPlaceholder:
    provider: str
    data_source: str = "ValueAtLocation"  # or "Username"
    default: Any = None
    failure_policy: str = "Fail"  # Fail | Ignore | UseDefault
    location: str = ""
    original_value: Any = None
