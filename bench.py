"""Benchmark: full shipped-library audit sweep rate on one chip.

Prints ONE JSON line:
  {"metric": "library audit reviews/sec/chip", "value": N,
   "unit": "reviews/s", "vs_baseline": R}

A "review" is one object evaluated against the full constraint set (the
reference's Client.Review unit, pkg/webhook/policy.go:664).  The workload is
BASELINE config #2: the ENTIRE shipped policy library (library/general — 21
Rego templates lowered to device verdict programs, incl. the referential
uniqueingresshost with device inventory-join tables, + 1 CEL template on the
interpreter lane) against a realistic mixed cluster
(gatekeeper_tpu/utils/synthetic.py: Pods/Services/Ingresses/Deployments/
Namespaces/RBAC bindings shaped per template).

The timed region is a full AuditManager.audit() run: host flattening, match
masks, pipelined chunked device sweeps, top-k extraction AND message
rendering of kept violations through the exact interpreter — the same path
a production audit pod executes (audit/manager.go:258-973 analog).

``vs_baseline`` is value / 100_000 — the BASELINE.json north-star target
(>=100k reviews/sec/chip); the reference publishes no absolute numbers
(BASELINE.md) so the target is the comparison point.

Component timings go to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

PROBE_ATTEMPTS = 3
PROBE_TIMEOUT_S = 75.0
PROBE_BACKOFF_S = (10.0, 30.0)

# --pipeline=auto|on|off|differential (default auto: staged host pipeline
# when the host has >1 effective core, serial eager-poll otherwise)
PIPELINE_MODE = "auto"
# --flatten-lane=auto|dict|raw|py|differential (sweep columnizer lane;
# auto = raw bytes through the threaded C columnizer when available)
FLATTEN_LANE = "auto"
# --collect=reduced|masks|differential (sweep collect lane; reduced
# folds totals/top-k/occupancy on device and ships O(kept) bytes, masks
# is the host-fold reference, differential runs both and asserts
# bit-identical)
COLLECT_LANE = "reduced"
# --flatten-workers=N (sweep ingest: fan each chunk's raw byte spans
# across N flatten worker processes; 0 = in-process).  Requested counts
# >1 on a 1-core host SKIP with a recorded reason (FLATTEN_BENCH
# convention: the numbers would measure process contention, not
# parallelism) and run workers=0 instead.
FLATTEN_WORKERS = 0
# --shard-chunks=K (audit scheduler: pack K consecutive same-group
# chunks into one mesh-wide dispatch, object axis sharded over 'data')
SHARD_CHUNKS = 0
# --trace out.json: span-trace the timed sweeps and export a Chrome
# trace-event file at exit (Perfetto-loadable device timeline)
TRACE_PATH = ""
# --resident[=N]: after the streaming sweep, run the device-resident
# snapshot tick lane over N rows (default min(n, 100k) — the snapshot
# holds full columns in host memory, unlike the O(chunk) stream) and
# record upload/clean-tick/dirty-sliver phases + h2d_bytes into the
# same SWEEP1M.json history entry
RESIDENT_LANE = 0


def _parse_pipeline_flag(argv: list) -> list:
    """Strip --pipeline[=mode], --flatten-lane[=lane], --chaos[=spec.json]
    and --trace[=path]
    from argv (the remaining args stay positional: N [chunk] |
    sweep [N [chunk]]).  --chaos installs the fault-injection plan
    process-wide so a bench run doubles as a deterministic chaos run (the
    resilience metrics and the run's incomplete/retried counters land in
    the JSON artifact); --trace installs the span tracer (seeded, full
    sampling) and writes the Chrome trace-event artifact — with --chaos
    the injected faults show up as instant events on the spans they hit."""
    global PIPELINE_MODE, TRACE_PATH, FLATTEN_LANE, COLLECT_LANE, \
        FLATTEN_WORKERS, SHARD_CHUNKS, RESIDENT_LANE
    out = []
    chaos = ""
    it = iter(argv)
    for a in it:
        if a == "--pipeline":
            PIPELINE_MODE = next(it, "auto")
        elif a.startswith("--pipeline="):
            PIPELINE_MODE = a.split("=", 1)[1]
        elif a == "--flatten-workers":
            FLATTEN_WORKERS = int(next(it, "0") or 0)
        elif a.startswith("--flatten-workers="):
            FLATTEN_WORKERS = int(a.split("=", 1)[1] or 0)
        elif a == "--shard-chunks":
            SHARD_CHUNKS = int(next(it, "0") or 0)
        elif a.startswith("--shard-chunks="):
            SHARD_CHUNKS = int(a.split("=", 1)[1] or 0)
        elif a == "--flatten-lane":
            FLATTEN_LANE = next(it, "auto")
        elif a.startswith("--flatten-lane="):
            FLATTEN_LANE = a.split("=", 1)[1]
        elif a == "--collect":
            COLLECT_LANE = next(it, "reduced")
        elif a.startswith("--collect="):
            COLLECT_LANE = a.split("=", 1)[1]
        elif a == "--resident":
            RESIDENT_LANE = -1
        elif a.startswith("--resident="):
            RESIDENT_LANE = int(a.split("=", 1)[1] or -1)
        elif a == "--chaos":
            chaos = next(it, "")
        elif a.startswith("--chaos="):
            chaos = a.split("=", 1)[1]
        elif a == "--trace":
            TRACE_PATH = next(it, "")
        elif a.startswith("--trace="):
            TRACE_PATH = a.split("=", 1)[1]
        else:
            out.append(a)
    if TRACE_PATH:
        from gatekeeper_tpu.observability import tracing

        tracing.install(tracing.Tracer(seed=0))
        log(f"span tracer active (export: {TRACE_PATH})")
    if chaos:
        from gatekeeper_tpu.resilience import faults

        faults.install(faults.load_chaos_spec(chaos))
        log(f"chaos harness active: {chaos}")
    return out


def export_trace() -> None:
    """Write the Chrome trace-event artifact (--trace), if tracing ran."""
    if not TRACE_PATH:
        return
    from gatekeeper_tpu.observability import (format_span_summary, tracing,
                                              write_chrome_trace)

    tracer = tracing.active_tracer()
    if tracer is None:
        return
    n = write_chrome_trace(TRACE_PATH, tracer)
    log(f"trace: {n} events ({tracer.kept} traces kept) -> {TRACE_PATH} "
        "(load in ui.perfetto.dev or chrome://tracing)")
    log(format_span_summary(tracer.traces()))


def bench_history_append(entry: dict, path: str = None) -> None:
    """Append this run to BENCH_TPU.json's history (VERDICT r4 weak #4:
    the perf record future rounds read first went stale because appends
    were manual).  The top-level headline only moves for real-TPU runs —
    the file is the per-chip TPU record; CPU-fallback runs append to
    history with their platform marked but never overwrite the headline."""
    path = path or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"metric": "library audit reviews/sec/chip",
               "unit": "reviews/s", "history": []}
    doc.setdefault("history", []).append(entry)
    if entry.get("platform") == "tpu":
        doc["value"] = entry["value"]
        doc["vs_baseline"] = round(entry["value"] / 100_000, 4)
        doc["platform"] = "tpu"
        if "legacy" in entry:
            doc["legacy_3template_reviews_per_s"] = entry["legacy"]
    try:
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
    except OSError as e:
        log(f"BENCH_TPU.json append failed: {e}")


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def effective_flatten_workers() -> tuple:
    """(workers, skip_reason): multi-worker flatten lanes SKIP with a
    recorded reason on 1-core hosts (the FLATTEN_BENCH convention —
    r05 showed 1T==8T at host_cpus=1, so the measurement would be
    process contention, not parallelism) and run workers=0 instead;
    the requested count still lands in the artifact so a multi-core
    re-run knows what was asked for."""
    n = os.cpu_count() or 1
    if FLATTEN_WORKERS > 1 and n < 2:
        return 0, (f"host_cpus={n}: {FLATTEN_WORKERS} flatten workers "
                   "would measure process contention, not parallelism "
                   "(FLATTEN_BENCH skip convention); ran workers=0")
    return FLATTEN_WORKERS, None


def _probe_accelerator_once(timeout_s: float) -> bool:
    """Device init in a subprocess with a timeout: a dead TPU tunnel hangs
    jax.devices() forever, which must not hang the benchmark harness."""
    import subprocess

    probe_src = (
        "import os, jax\n"
        "w = os.environ.get('JAX_PLATFORMS')\n"
        "w and jax.config.update('jax_platforms', w)\n"
        "jax.devices()\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe_src],
            timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        log(f"device init timed out after {timeout_s:.0f}s")
        return False
    if proc.returncode != 0:
        log("device init failed:\n" + (proc.stderr or "").strip()[-2000:])
        return False
    return True


def probe_accelerator() -> bool:
    """The axon tunnel flaps: retry with backoff before giving up
    (round-1 lesson — one eager probe cost the round its TPU artifact)."""
    for attempt in range(PROBE_ATTEMPTS):
        if _probe_accelerator_once(PROBE_TIMEOUT_S):
            return True
        if attempt < PROBE_ATTEMPTS - 1:
            delay = PROBE_BACKOFF_S[min(attempt, len(PROBE_BACKOFF_S) - 1)]
            log(f"probe {attempt + 1}/{PROBE_ATTEMPTS} failed; retrying in "
                f"{delay:.0f}s...")
            time.sleep(delay)
    return False


def build_client():
    from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.synthetic import load_library

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(),
                    drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    nt, nc = load_library(client)
    fb = tpu.fallback_kinds()
    assert not fb, f"library templates fell back to interpreter: {fb}"
    return client, tpu, nt, nc


def setup_platform_and_client():
    """Shared preamble for every bench lane: accelerator probe (with CPU
    fallback) + client/library build.  Returns (jax, client, tpu, nt, nc,
    cpu_fallback)."""
    import os

    cpu_fallback = False
    # always probe (honoring any env pin — the ambient pin may itself name a
    # dead accelerator); a cpu probe costs ~2s, a live TPU probe a few more
    if not probe_accelerator():
        was = os.environ.get("JAX_PLATFORMS", "<default>")
        log(f"accelerator unreachable (platform {was}); falling back to "
            "CPU — the reported number is NOT a TPU result")
        os.environ["JAX_PLATFORMS"] = "cpu"
        cpu_fallback = was != "cpu"
    import gatekeeper_tpu  # noqa: F401 — package hook pins JAX_PLATFORMS
    import jax

    if cpu_fallback:
        # the hook only pins from env; ensure the override sticks even if
        # another import already touched jax config
        jax.config.update("jax_platforms", "cpu")
    log(f"devices: {jax.devices()}")
    client, tpu, nt, nc = build_client()
    log(f"library loaded: {nt} templates ({len(tpu.lowered_kinds())} on the "
        f"device verdict path), {nc} constraints")
    return jax, client, tpu, nt, nc, cpu_fallback


def setup(n: int):
    """setup_platform_and_client + synthetic workload generation +
    referential inventory sync.  Returns (jax, client, tpu, nt, nc,
    objects, cpu_fallback, gen_s, inv_s)."""
    jax, client, tpu, nt, nc, cpu_fallback = setup_platform_and_client()
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects
    t0 = time.perf_counter()
    log(f"generating {n} synthetic cluster objects...")
    objects = make_cluster_objects(n)
    gen_s = time.perf_counter() - t0
    # referential inventory: uniqueingresshost joins over synced Ingresses
    t0 = time.perf_counter()
    n_ing = 0
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
            n_ing += 1
    inv_s = time.perf_counter() - t0
    # serialize the corpus once (still the generation phase, untimed by the
    # sweep): the audit flattens raw JSON through the threaded native lane
    # (native/flattenjsonmod.c) without materializing Python dicts
    from gatekeeper_tpu.utils.rawjson import as_raw

    t0 = time.perf_counter()
    objects = [as_raw(o) for o in objects]
    wrap_s = time.perf_counter() - t0
    gen_s += wrap_s
    log(f"generation {gen_s:.1f}s (incl. {wrap_s:.1f}s JSON serialize); "
        f"inventory: {n_ing} Ingresses synced for the referential join "
        f"({inv_s:.1f}s)")
    return jax, client, tpu, nt, nc, objects, cpu_fallback, gen_s, inv_s


def sweep_main(n: int = 1_000_000, chunk: int = 32_768,
               submit_window: int = 4):
    """BASELINE config #6: the N-object audit sweep, measured (not
    extrapolated), at O(chunk) host memory.  Writes SWEEP1M.json with
    elapsed + phase breakdown + peak RSS.

    The corpus spills to a JSONL file at generation time (the reference's
    disk list-cache, pkg/audit/manager.go:502-561: list pages spill to
    disk and review streams file-by-file); the warm pass and the timed
    sweep both STREAM it — no pass ever holds more than
    ``submit_window + 1`` chunks of objects, so peak RSS is bounded by
    vocab/table state + in-flight chunks instead of the whole corpus.

    Per-constraint violating-object counts come from the device count
    reduction (exact per (constraint, object) pair); kept top-20
    violations render through the exact engine — the production audit
    shape (pkg/audit/manager.go:258).
    """
    import json as _json
    import os
    import resource
    import tempfile

    jax, client, tpu, nt, nc, cpu_fallback = setup_platform_and_client()
    from gatekeeper_tpu.utils.synthetic import iter_cluster_objects

    # unique, safely-created spill (mkstemp): a fixed predictable path in
    # the shared tmp dir clobbers under concurrent runs and is a
    # pre-creation/symlink hazard on multi-user hosts
    spill_fd, spill = tempfile.mkstemp(
        prefix=f"sweep_corpus_{n}_", suffix=".jsonl")
    try:
        return _sweep_timed(jax, client, tpu, nt, nc, cpu_fallback, spill_fd,
                            spill, n, chunk, submit_window)
    finally:
        # unlink unconditionally: an interrupted run must not leak a
        # multi-GB uniquely-named spill per retry
        try:
            os.unlink(spill)
        except OSError:
            pass


def _sweep_timed(jax, client, tpu, nt, nc, cpu_fallback, spill_fd, spill,
                 n, chunk, submit_window):
    import json as _json
    import os
    import resource
    import time

    from gatekeeper_tpu.utils.synthetic import iter_cluster_objects

    t0 = time.perf_counter()
    n_ing = 0
    log(f"generating {n} objects to disk spill {spill} (streaming)...")
    with os.fdopen(spill_fd, "wb") as f:
        for o in iter_cluster_objects(n):
            if o.get("kind") == "Ingress":
                client.add_data(o)  # referential inventory sync
                n_ing += 1
            f.write(_json.dumps(o, separators=(",", ":")).encode())
            f.write(b"\n")
    gen_s = time.perf_counter() - t0
    log(f"generation+spill: {gen_s:.1f}s ({n_ing} Ingresses synced; "
        f"{os.path.getsize(spill) / 1e9:.2f}GB on disk)")

    from gatekeeper_tpu.utils.rawjson import RawJSON

    def lister():
        with open(spill, "rb") as f:
            for line in f:
                yield RawJSON(line.rstrip(b"\n"))

    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
    from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh

    workers, workers_skip = effective_flatten_workers()
    if workers_skip:
        log(f"flatten-workers lane skipped: {workers_skip}")
    evaluator = ShardedEvaluator(tpu, make_mesh(), violations_limit=20,
                                 flatten_lane=FLATTEN_LANE,
                                 collect=COLLECT_LANE,
                                 flatten_workers=workers)
    cfg = AuditConfig(violations_limit=20, chunk_size=chunk,
                      exact_totals=False, submit_window=submit_window,
                      pipeline=PIPELINE_MODE, shard_chunks=SHARD_CHUNKS)
    mgr = AuditManager(client, lister=lister, config=cfg,
                       evaluator=evaluator)
    # fetch-free warmup: interns every name (vocab reaches its final
    # bucket) and compiles all chunk shapes WITHOUT a single device->host
    # fetch, so the timed run's uploads still ride full tunnel bandwidth
    log("warmup (streaming vocab pass + per-group jit compile)...")
    t_w = time.perf_counter()
    # warm at the PACKED chunk size: shard_chunks coalesces K chunks
    # into one dispatch, so the timed sweep's pad buckets are K x chunk
    # wide — warming at the unpacked size would retrace mid-sweep
    evaluator.warm_pass(client.constraints(), lister(),
                        chunk * max(1, SHARD_CHUNKS),
                        return_bits=cfg.exact_totals)
    log(f"warmup: {time.perf_counter() - t_w:.1f}s")

    log(f"timed {n}-object sweep (chunk={chunk}, "
        f"window={submit_window})...")
    evaluator.perf_reset()
    mgr.perf = {}
    t0 = time.perf_counter()
    run = mgr.audit()
    elapsed = time.perf_counter() - t0
    phases = {k: round(v, 2) for k, v in evaluator.perf.items()}
    phases.update({k: round(v, 2) for k, v in mgr.perf.items()})
    phases["wire_mb"] = round(phases.pop("wire_bytes", 0.0) / 1e6, 1)
    # host-vs-device bytes per direction: wire_mb is H2D (packed columns
    # + tables + masks), d2h_kb is what collect fetched back — the
    # reduced lane's O(kept) contract shows up here
    phases["d2h_kb"] = round(phases.pop("d2h_bytes", 0.0) / 1e3, 2)
    # sum over constraints of violating-object counts: an object violating
    # k constraints contributes k (a violation count, not distinct objects)
    violations = sum(run.total_violations.values())
    kept = sum(len(v) for v in run.kept.values())
    rss_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    log(f"sweep: {elapsed:.2f}s for {n} objects x {nc} constraints "
        f"({violations} constraint violations, {kept} kept) "
        f"-> {n / elapsed:,.0f} reviews/s; peak RSS {rss_gb:.1f}GB")
    log(f"phases: {phases}")
    out = {
        "metric": "1M-object library audit sweep",
        "platform": jax.devices()[0].platform,
        "n_objects": n,
        "n_constraints": nc,
        "elapsed_s": round(elapsed, 2),
        "reviews_per_s": round(n / elapsed, 1),
        "violations": violations,
        "kept_rendered": kept,
        "generation_s": round(gen_s, 2),
        "peak_rss_gb": round(rss_gb, 2),
        "chunk_size": chunk,
        "submit_window": submit_window,
        "streaming": "disk JSONL spill; O(chunk) host memory",
        "phase_s": phases,
        "target": "<10s on v5e-4 (x4 chips: data-parallel chunks shard "
                  "across ICI; single-chip time / 4 is the honest "
                  "extrapolation only for the device phase — host flatten "
                  "stays serial unless hosts scale too)",
    }
    out["pipeline"] = {"mode": PIPELINE_MODE,
                       "schedule": ("pipelined"
                                    if mgr.perf.get("pipelined")
                                    else "serial")}
    out["flatten_lane"] = FLATTEN_LANE
    out["collect"] = COLLECT_LANE
    # self-describing ingest/dispatch geometry (run.flatten_workers etc.
    # come from the AuditRun annotation — the effective values, not the
    # requested flags)
    out["flatten_workers"] = run.flatten_workers
    out["shard_chunks"] = run.shard_chunks
    out["n_devices"] = run.n_devices
    if workers_skip:
        out["flatten_workers_requested"] = FLATTEN_WORKERS
        out["skipped_workers_reason"] = workers_skip
    worker_busy = phases.get("fl_worker_busy", 0.0)
    if worker_busy:
        # aggregate objects per worker-second across the timed sweep
        out["per_worker_objs_per_s"] = round(n / worker_busy, 1)
    if mgr.pipe_stats:
        out["pipeline"].update(mgr.pipe_stats)
    if cpu_fallback:
        out["cpu_fallback"] = True
    if RESIDENT_LANE:
        rows = RESIDENT_LANE if RESIDENT_LANE > 0 else min(n, 100_000)
        out["device_resident"] = _resident_lane(client, tpu, rows, chunk)
    sweep_history_append(out)
    export_trace()
    print(_json.dumps(out))


def _resident_lane(client, tpu, rows: int, chunk: int) -> dict:
    """The --resident sweep lane: HBM-resident snapshot columns ticked
    against watch churn.  Three timed phases — (1) full rebuild + first
    upload, (2) warm clean-rows tick (the zero-H2D pin: gather indices
    cached, no bytes cross the tunnel), (3) dirty-sliver tick (~1% rows
    churned; only the sliver's scatter-patch ships)."""
    import copy as _copy
    import time as _time

    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
    from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
    from gatekeeper_tpu.snapshot import (ClusterSnapshot, DeviceResidency,
                                         SnapshotConfig, WatchIngester,
                                         gvks_of)
    from gatekeeper_tpu.sync.source import FakeCluster
    from gatekeeper_tpu.utils.synthetic import iter_cluster_objects

    log(f"device-resident lane: {rows} snapshot rows...")
    # single-device mesh: the resident lane is single-chip by design
    ev = ShardedEvaluator(tpu, make_mesh(1), violations_limit=20)
    cluster = FakeCluster()
    churn_pool = []
    for o in iter_cluster_objects(rows):
        if len(churn_pool) < max(1, rows // 100):
            churn_pool.append(_copy.deepcopy(o))
        cluster.apply(o)
    residency = DeviceResidency(ev, mode="on")
    snap = ClusterSnapshot(ev, SnapshotConfig())
    mgr = AuditManager(
        client, lister=lambda: iter(cluster.list()),
        config=AuditConfig(violations_limit=20, chunk_size=chunk,
                           exact_totals=False, pipeline="off",
                           audit_source="snapshot"),
        evaluator=ev, snapshot=snap, residency=residency)
    ing = WatchIngester(snap, cluster, gvks_of(cluster.list())).start()
    try:
        phases = {}
        t0 = _time.perf_counter()
        mgr.audit()
        phases["rebuild_upload_s"] = round(_time.perf_counter() - t0, 3)
        mgr.audit_tick()  # prime the gather-index + param-table caches
        t0 = _time.perf_counter()
        mgr.audit_tick()
        phases["clean_tick_s"] = round(_time.perf_counter() - t0, 3)
        h2d_clean = int(mgr.perf.get("tick_h2d_bytes", 0))
        for o in churn_pool:
            o.setdefault("metadata", {}).setdefault(
                "labels", {})["bench-churn"] = "r1"
            cluster.apply(o)
        ing.pump()
        dirty = sum(len(v) for v in snap.dirty_rows().values())
        t0 = _time.perf_counter()
        mgr.audit_tick()
        phases["dirty_sliver_tick_s"] = round(_time.perf_counter() - t0, 3)
        h2d_dirty = int(mgr.perf.get("tick_h2d_bytes", 0))
    finally:
        ing.stop()
    lane = {
        "rows": rows,
        "resident_mb": round(residency.resident_bytes() / 1e6, 2),
        "uploads": residency.upload_count,
        "patches": residency.patch_count,
        "dirty_rows": dirty,
        "h2d_bytes_clean_tick": h2d_clean,
        "h2d_bytes_dirty_tick": h2d_dirty,
        "h2d_clean_ok": h2d_clean == 0,  # the acceptance pin
        "phase_s": phases,
    }
    log(f"device-resident lane: {lane}")
    if h2d_clean != 0:
        log(f"WARNING: warm clean-rows tick shipped {h2d_clean} bytes "
            "(expected 0)")
    return lane


def sweep_history_append(entry: dict) -> None:
    """SWEEP1M.json keeps a run history like BENCH_TPU.json: every run
    appends (with its collect/flatten lanes and both transfer-direction
    byte counts), the top-level headline only moves for real-TPU runs —
    CPU-fallback measurements on the bench host must not overwrite the
    per-chip record."""
    import json as _json
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "SWEEP1M.json")
    try:
        with open(path) as f:
            doc = _json.load(f)
    except (OSError, ValueError):
        doc = {}
    history = doc.pop("history", [])
    if doc and "metric" in doc:
        headline = doc
    else:
        headline = {}
    entry = dict(entry)
    entry["date"] = time.strftime("%Y-%m-%d")
    history.append(entry)
    if entry.get("platform") == "tpu" and not entry.get("cpu_fallback"):
        headline = {k: v for k, v in entry.items() if k != "date"}
    out_doc = dict(headline)
    out_doc["history"] = history
    try:
        with open(path, "w") as f:
            _json.dump(out_doc, f, indent=1)
            f.write("\n")
    except OSError as e:
        log(f"SWEEP1M.json append failed: {e}")


def legacy_lane(n: int = 100_000):
    """The round-1 comparison lane: 3 templates x 40 constraints raw
    device sweep over synthetic pods (no audit manager, no rendering).
    Kept so round-over-round perf is comparable after the primary lane
    hardened to the full library (VERDICT r2 weak #7)."""
    import __graft_entry__ as g
    from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh

    tpu = g._build_driver(
        [g._PRIV_TEMPLATE, g._REQ_LABELS_TEMPLATE, g._HOST_NS_TEMPLATE]
    )
    cons = g._constraints(n_labels=38)  # 40 constraints, as in round 1
    evaluator = ShardedEvaluator(tpu, make_mesh(), violations_limit=20)
    pods = g._make_pods(n)
    evaluator.sweep(cons, pods[:1024])  # compile small bucket
    evaluator.sweep(cons, pods)  # compile full bucket + warm vocab
    elapsed = None
    for _ in range(2):  # best of 2: tunnel throughput varies ±15%
        t0 = time.perf_counter()
        evaluator.sweep(cons, pods)
        dt = time.perf_counter() - t0
        elapsed = dt if elapsed is None else min(elapsed, dt)
    rate = n / elapsed
    log(f"legacy 3-template lane: {elapsed:.3f}s for {n} pods x "
        f"{len(cons)} constraints -> {rate:,.0f} reviews/s")
    return rate


def make_tenant_body(i: int, namespace: str) -> bytes:
    """A loadtest admission body re-homed into ``namespace`` (both the
    request and the object), so the QoS tenant key and the policy
    matchers see one coherent tenant."""
    from tools.loadtest_webhook import make_body

    doc = json.loads(make_body(i))
    doc["request"]["namespace"] = namespace
    obj = doc["request"].get("object") or {}
    obj.setdefault("metadata", {})["namespace"] = namespace
    return json.dumps(doc).encode()


def drive_tenant_mix(port: int, plan: list, bodies: dict,
                     timeout_s: float = 60.0) -> dict:
    """Offer a multi-tenant load mix against a running webhook and
    report per-tenant latency/shed stats.

    ``plan``: [{"name": tenant, "conc": N, "n": total requests}, ...] —
    every tenant's workers run concurrently (the contention IS the
    measurement); ``bodies``: {tenant: [request bytes, ...]}.  Returns
    {tenant: {requests, accepted, shed, shed_rate, p50_ms, p99_ms,
    mean_ms, errors}} — accepted-request latency only, sheds counted
    separately (the PR 5 burst-lane convention)."""
    import http.client
    import statistics
    import threading

    stats = {t["name"]: {"lat": [], "shed": 0, "errors": []}
             for t in plan}
    lock = threading.Lock()

    def worker(tenant: str, wid: int, conc: int, n: int):
        tb = bodies[tenant]
        st = stats[tenant]
        c = http.client.HTTPConnection("127.0.0.1", port,
                                       timeout=timeout_s)
        try:
            for i in range(max(1, n // conc)):
                body = tb[(wid + i * conc) % len(tb)]
                t0 = time.perf_counter()
                c.request("POST", "/v1/admit", body=body,
                          headers={"Content-Type": "application/json"})
                resp = json.loads(c.getresponse().read())
                dt = (time.perf_counter() - t0) * 1000
                r = resp["response"]
                shed = (r.get("status", {}).get("code") == 429
                        or any("overload" in w
                               for w in r.get("warnings", [])))
                with lock:
                    if shed:
                        st["shed"] += 1
                    else:
                        st["lat"].append(dt)
        except Exception as e:
            with lock:
                st["errors"].append(f"{wid}: {type(e).__name__}: {e}")
        finally:
            c.close()

    threads = [threading.Thread(target=worker,
                                args=(t["name"], w, t["conc"], t["n"]))
               for t in plan for w in range(t["conc"])]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out = {}
    for t in plan:
        st = stats[t["name"]]
        sv = sorted(st["lat"])

        def pct(p):
            return round(sv[min(len(sv) - 1,
                                int(p / 100 * len(sv)))], 2) if sv else 0.0

        total = len(sv) + st["shed"]
        out[t["name"]] = {
            "concurrency": t["conc"], "requests": total,
            "accepted": len(sv), "shed": st["shed"],
            "shed_rate": round(st["shed"] / total, 4) if total else 0.0,
            "p50_ms": pct(50), "p99_ms": pct(99),
            "mean_ms": (round(statistics.mean(sv), 2) if sv else 0.0),
            "errors": st["errors"],
        }
    return out


def burst_main(n_base: int = 240, conc_base: int = 2,
               burst_mult: int = 8):
    """``--burst``: offered-load step pattern against the real webhook
    stack with the overload limiter engaged — the overload-trajectory
    record (P50/P99/shed-rate per step), appended to WEBHOOK_LOAD.json's
    ``burst_history`` like FLATTEN_BENCH tracks the columnizer.

    Step 1 serves ``conc_base`` connections (the unloaded anchor); step 2
    offers ``burst_mult``x that.  The limiter is sized SMALL (the point is
    to exercise the shed path, not to absorb the burst), so the burst
    step reports how accepted-request latency holds while excess load is
    shed per failurePolicy."""
    import os
    import statistics
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import http.client

    from gatekeeper_tpu.metrics.registry import MetricsRegistry
    from gatekeeper_tpu.resilience import overload as _overload
    from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
    from gatekeeper_tpu.webhook.server import WebhookServer
    from tools.loadtest_webhook import make_body

    jax, client, tpu, nt, nc, _cpu_fallback = setup_platform_and_client()
    metrics = MetricsRegistry()
    # deliberately tight: in-flight capped at 4 with a 4-deep/50ms queue
    # so a burst_mult x step actually overflows into the shed path (a
    # production-sized limiter would absorb this workload's ~5ms reviews
    # without a single shed, recording nothing about the trajectory)
    ctl = _overload.OverloadController(_overload.OverloadConfig(
        min_inflight=1, max_inflight=4, initial_inflight=4,
        queue_depth=4, queue_timeout_s=0.05), metrics=metrics)
    _overload.install(ctl)
    batcher = Batcher(client, window_s=0.002, max_batch=64,
                      metrics=metrics).start()
    handler = ValidationHandler(client, batcher=batcher, metrics=metrics,
                                failure_policy="fail", overload=ctl)
    srv = WebhookServer(validation_handler=handler, port=0,
                        metrics=metrics, batcher=batcher).start()
    bodies = [make_body(i) for i in range(128)]

    def drive(n: int, conc: int) -> dict:
        lat_ms: list = []
        sheds = [0]
        errors: list = []
        lock = threading.Lock()

        def worker(wid: int):
            c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                           timeout=60)
            try:
                for i in range(n // conc):
                    body = bodies[(wid + i * conc) % len(bodies)]
                    t0 = time.perf_counter()
                    c.request("POST", "/v1/admit", body=body,
                              headers={"Content-Type": "application/json"})
                    resp = json.loads(c.getresponse().read())
                    dt = (time.perf_counter() - t0) * 1000
                    r = resp["response"]
                    shed = (r.get("status", {}).get("code") == 429
                            or any("overload" in w
                                   for w in r.get("warnings", [])))
                    with lock:
                        if shed:
                            sheds[0] += 1
                        else:
                            lat_ms.append(dt)
            except Exception as e:
                with lock:
                    errors.append(f"{wid}: {type(e).__name__}: {e}")
            finally:
                c.close()

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(conc)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        sv = sorted(lat_ms)

        def pct(p):
            return round(sv[min(len(sv) - 1, int(p / 100 * len(sv)))], 2) \
                if sv else 0.0

        total = len(lat_ms) + sheds[0]
        return {"concurrency": conc, "requests": total,
                "accepted": len(lat_ms), "shed": sheds[0],
                "shed_rate": round(sheds[0] / total, 4) if total else 0.0,
                "p50_ms": pct(50), "p99_ms": pct(99),
                "mean_ms": (round(statistics.mean(sv), 2) if sv else 0.0),
                "requests_per_s": round(total / elapsed, 1),
                "errors": errors}

    log("warmup...")
    drive(32, 1)
    log(f"step 1: unloaded anchor (conc={conc_base}, n={n_base})...")
    unloaded = drive(n_base, conc_base)
    log(f"  p50 {unloaded['p50_ms']}ms p99 {unloaded['p99_ms']}ms "
        f"shed {unloaded['shed']}")
    conc_burst = conc_base * burst_mult
    log(f"step 2: {burst_mult}x offered-load burst (conc={conc_burst})...")
    burst = drive(n_base * burst_mult, conc_burst)
    log(f"  p50 {burst['p50_ms']}ms p99 {burst['p99_ms']}ms "
        f"shed {burst['shed']} ({burst['shed_rate']:.1%})")

    # step 3: multi-tenant offered-load mix under QoS — tenant A bursts
    # at burst_mult x tenant B's load plus a system-lane trickle, the
    # isolation_ratio is B's accepted P99 under attack over B unloaded
    # (1.0 = perfect isolation; the tier-1 chaos test pins <= 2.0 with
    # a tight limiter)
    from gatekeeper_tpu.resilience.qos import QoSConfig

    # tight like steps 1-2: cap 1 slot per tenant and a short queue so
    # the attacker SHEDS instead of convoying the (1-core) host — the
    # isolation number then measures the scheduler, not CPU contention
    qos_ctl = _overload.OverloadController(_overload.OverloadConfig(
        min_inflight=1, max_inflight=4, initial_inflight=4,
        queue_depth=16, queue_timeout_s=0.25,
        qos=QoSConfig(tenant_inflight_cap=1, quantum=16384.0)),
        metrics=metrics)
    handler.overload = qos_ctl
    _overload.install(qos_ctl)
    tenant_bodies = {
        "tenant-a": [make_tenant_body(i, "tenant-a") for i in range(32)],
        "tenant-b": [make_tenant_body(i, "tenant-b") for i in range(32)],
        "kube-system": [make_tenant_body(i, "kube-system")
                        for i in range(8)],
    }
    log(f"step 3: multi-tenant mix (QoS on: tenant-a {burst_mult}x "
        f"tenant-b + system trickle)...")
    anchor = drive_tenant_mix(srv.port, [
        {"name": "tenant-b", "conc": conc_base, "n": n_base}],
        tenant_bodies)
    mix = drive_tenant_mix(srv.port, [
        {"name": "tenant-a", "conc": conc_base * burst_mult,
         "n": n_base * burst_mult},
        {"name": "tenant-b", "conc": conc_base, "n": n_base},
        {"name": "kube-system", "conc": 1, "n": max(8, n_base // 8)},
    ], tenant_bodies)
    b_unloaded_p99 = anchor["tenant-b"]["p99_ms"]
    isolation_ratio = (round(mix["tenant-b"]["p99_ms"] / b_unloaded_p99, 2)
                       if b_unloaded_p99 else None)
    for tn, st in sorted(mix.items()):
        log(f"  {tn}: p50 {st['p50_ms']}ms p99 {st['p99_ms']}ms "
            f"shed {st['shed']} ({st['shed_rate']:.1%})")
    log(f"  isolation_ratio (tenant-b p99 attacked/unloaded): "
        f"{isolation_ratio}")
    tenant_mix = {
        "qos": {"lanes": "system|break-glass|user",
                "tenant_inflight_cap": 1, "quantum": 16384,
                "queue_depth": 16, "queue_timeout_s": 0.25},
        "note": "1-core host: reviews are CPU-bound, so B's attacked "
                "P99 includes core contention the scheduler cannot "
                "remove; the pinned <=2x isolation bound is proven "
                "with controlled service times in tests/test_qos.py",
        "unloaded_b": anchor["tenant-b"],
        "mix": mix,
        "isolation_ratio": isolation_ratio,
        "sheds_by_tenant": {
            tn: st["shed"] for tn, st in sorted(mix.items())},
    }
    srv.stop(drain_timeout=5.0)
    _overload.uninstall()

    entry = {
        "date": time.strftime("%Y-%m-%d"),
        "host_cpus": os.cpu_count(),
        "limiter": {"max_inflight": 4, "initial": 4, "queue_depth": 4,
                    "queue_timeout_s": 0.05,
                    "final_limit": ctl.limiter.limit},
        "unloaded": unloaded,
        "burst": burst,
        "tenant_mix": tenant_mix,
        "p99_ratio": (round(burst["p99_ms"] / unloaded["p99_ms"], 2)
                      if unloaded["p99_ms"] else None),
        "note": f"offered-load step {conc_base}->{conc_burst} conns; "
                "accepted-request latency only (sheds excluded, counted "
                "in shed_rate); failurePolicy=fail (429 + Retry-After)",
    }
    root = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(root, "WEBHOOK_LOAD.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"metric": "webhook serving load"}
    doc.setdefault("burst_history", []).append(entry)
    with open(path, "w") as f:
        f.write(json.dumps(doc) + "\n")
    print(json.dumps({"metric": "webhook overload burst", **entry}))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 16_384
    jax, client, tpu, nt, nc, objects, cpu_fallback, _gen_s, _inv_s = \
        setup(n)
    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
    from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh

    workers, workers_skip = effective_flatten_workers()
    if workers_skip:
        log(f"flatten-workers lane skipped: {workers_skip}")
    evaluator = ShardedEvaluator(tpu, make_mesh(), violations_limit=20,
                                 flatten_lane=FLATTEN_LANE,
                                 collect=COLLECT_LANE,
                                 flatten_workers=workers)
    cfg = AuditConfig(violations_limit=20, chunk_size=chunk,
                      exact_totals=False, pipeline=PIPELINE_MODE,
                      shard_chunks=SHARD_CHUNKS)
    mgr = AuditManager(client, lister=lambda: iter(objects), config=cfg,
                       evaluator=evaluator)

    # fetch-free warmup (see sweep_main): vocab + jit compile without
    # poisoning the tunnel's upload bandwidth before the timed run
    log("warmup (vocab pass + per-bucket jit compile, fetch-free)...")
    t0 = time.perf_counter()
    evaluator.warm_pass(client.constraints(), objects,
                        chunk * max(1, SHARD_CHUNKS),
                        return_bits=cfg.exact_totals)
    log(f"warmup: {time.perf_counter() - t0:.1f}s")

    # methodology (VERDICT r4 weak #3): FIVE timed passes, MEDIAN reported
    # as the headline — a best-of-2 on a shared tunnel with ±15% session
    # variance is not a defensible steady-state number.  All pass times +
    # the IQR go into the JSON artifact; phases come from the median pass.
    n_passes = 5
    log(f"timed audit sweep (median of {n_passes} passes)...")
    pass_times = []
    pass_phases = []
    pass_pipes = []
    runs = []
    for p in range(n_passes):
        evaluator.perf_reset()
        mgr.perf = {}
        t0 = time.perf_counter()
        run = mgr.audit()
        dt = time.perf_counter() - t0
        log(f"  pass {p + 1}: {dt:.3f}s")
        pass_times.append(round(dt, 3))
        ph = {k: round(v, 3) for k, v in evaluator.perf.items()}
        ph.update({k: round(v, 3) for k, v in mgr.perf.items()})
        ph["wire_mb"] = round(ph.pop("wire_bytes", 0.0) / 1e6, 1)
        ph["d2h_kb"] = round(ph.pop("d2h_bytes", 0.0) / 1e3, 2)
        pass_phases.append(ph)
        pass_pipes.append(mgr.pipe_stats)
        runs.append(run)
    order = sorted(range(n_passes), key=lambda i: pass_times[i])
    med_i = order[n_passes // 2]
    elapsed = pass_times[med_i]
    phases = pass_phases[med_i]
    pipe_stats = pass_pipes[med_i]
    run = runs[med_i]
    iqr = round(pass_times[order[-(n_passes // 4 + 1)]]
                - pass_times[order[n_passes // 4]], 3)
    log(f"  median {elapsed:.3f}s, IQR {iqr:.3f}s")
    log(f"  phase breakdown (median pass): {phases}")
    violations = sum(run.total_violations.values())
    total_kept = sum(len(v) for v in run.kept.values())
    reviews_per_s = n / elapsed

    log(f"end-to-end: {elapsed:.3f}s for {n} objects x {nc} constraints "
        f"({violations} constraint violations, {total_kept} rendered "
        f"kept violations) -> {reviews_per_s:,.0f} reviews/s")
    log(f"constraint-evals/sec: {n * nc / elapsed:,.0f}")

    log("legacy 3-template lane (round-over-round comparison)...")
    legacy_rate = legacy_lane(n)

    out = {
        "metric": "library audit reviews/sec/chip",
        "value": round(reviews_per_s, 1),
        "unit": "reviews/s",
        "vs_baseline": round(reviews_per_s / 100_000, 4),
        "platform": jax.devices()[0].platform,
        "legacy_3template_reviews_per_s": round(legacy_rate, 1),
        "pass_times_s": pass_times,
        "pass_iqr_s": iqr,
        "methodology": f"median of {n_passes} passes (all listed); "
                       "phases from median pass",
        "phase_s": phases,
    }
    # staged-pipeline proof artifact: per-stage busy/occupancy + queue
    # high-water + device-idle proxy from the MEDIAN pass.  When the
    # schedule pipelined, stage_busy_sum_s > wall_s is the overlap
    # evidence (host stages ran concurrently with each other and the
    # device) — the BENCH acceptance signal for this round.
    out["pipeline"] = {"mode": PIPELINE_MODE,
                       "schedule": ("pipelined"
                                    if phases.get("pipelined")
                                    else "serial")}
    out["flatten_lane"] = FLATTEN_LANE
    out["collect"] = COLLECT_LANE
    out["flatten_workers"] = run.flatten_workers
    out["shard_chunks"] = run.shard_chunks
    out["n_devices"] = run.n_devices
    if workers_skip:
        out["flatten_workers_requested"] = FLATTEN_WORKERS
        out["skipped_workers_reason"] = workers_skip
    if pipe_stats:
        out["pipeline"].update(pipe_stats)
    if cpu_fallback:
        # metric name stays stable for consumers; the flag marks the result
        # as a CPU-fallback measurement (TPU unreachable)
        out["cpu_fallback"] = True
    bench_history_append({
        "note": f"auto-appended by bench.py (pipeline={PIPELINE_MODE}, "
                f"schedule={out['pipeline']['schedule']}, "
                f"flatten_lane={FLATTEN_LANE})",
        "value": out["value"],
        "legacy": out["legacy_3template_reviews_per_s"],
        "platform": out["platform"],
        "pass_iqr_s": iqr,
        "date": time.strftime("%Y-%m-%d"),
        "flatten_lane": FLATTEN_LANE,
        "collect": COLLECT_LANE,
        "host_cpus": os.cpu_count(),
        **({"cpu_fallback": True} if cpu_fallback else {}),
    })
    export_trace()
    print(json.dumps(out))


if __name__ == "__main__":
    sys.argv[1:] = _parse_pipeline_flag(sys.argv[1:])
    if "--burst" in sys.argv:
        sys.argv.remove("--burst")
        burst_main(int(sys.argv[1]) if len(sys.argv) > 1 else 240)
    elif len(sys.argv) > 1 and sys.argv[1] == "sweep":
        sweep_main(int(sys.argv[2]) if len(sys.argv) > 2 else 1_000_000,
                   int(sys.argv[3]) if len(sys.argv) > 3 else 32_768)
    elif len(sys.argv) > 1 and sys.argv[1] == "replay":
        # replay bench (record -> candidate replay: bit-identity +
        # zero-fresh-lowering pins): writes REPLAY_BENCH.json
        import importlib.util as _ilu

        _spec = _ilu.spec_from_file_location(
            "bench_replay",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "bench_replay.py"))
        _br = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(_br)
        sys.exit(_br.main(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "fleet":
        # fleet packing bench (K small clusters packed vs sequential):
        # one entry point beside sweep/burst; writes FLEET_BENCH.json
        import importlib.util as _ilu

        _spec = _ilu.spec_from_file_location(
            "bench_fleet",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "bench_fleet.py"))
        _bf = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(_bf)
        sys.exit(_bf.main(sys.argv[2:]))
    else:
        main()
