"""Benchmark: full-constraint-set audit sweep rate on one chip.

Prints ONE JSON line:
  {"metric": "audit admission reviews/sec/chip", "value": N,
   "unit": "reviews/s", "vs_baseline": R}

A "review" is one object evaluated against the full constraint set (the
reference's Client.Review unit, pkg/webhook/policy.go:664).  The workload is
BASELINE config #2-shaped: synthetic Pods with ragged container lists against
a policy library of lowerable templates (PSP subset + required-labels
variants).  End-to-end timing includes host flattening, match-mask
computation, the device verdict kernels, top-k extraction and message
rendering for kept violations — the full audit-sweep path
(gatekeeper_tpu.audit + parallel.sharded).

``vs_baseline`` is value / 100_000 — the BASELINE.json north-star target
(>=100k reviews/sec/chip); the reference publishes no absolute numbers
(BASELINE.md) so the target is the comparison point.

Device-only and component timings go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def build():
    import __graft_entry__ as g
    from gatekeeper_tpu.apis.constraints import Constraint
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh

    tpu = g._build_driver(
        [g._PRIV_TEMPLATE, g._REQ_LABELS_TEMPLATE, g._HOST_NS_TEMPLATE]
    )
    cons = g._constraints(n_labels=38)  # 40 constraints total
    assert len(tpu.fallback_kinds()) == 0, tpu.fallback_kinds()
    mesh = make_mesh()  # all local devices (1 chip under the driver)
    evaluator = ShardedEvaluator(tpu, mesh, violations_limit=20)
    return tpu, cons, evaluator


def _probe_accelerator(timeout_s: float = 90.0) -> bool:
    """Device init in a subprocess with a timeout: a dead TPU tunnel hangs
    jax.devices() forever, which must not hang the benchmark harness."""
    import subprocess

    probe_src = (
        "import os, jax\n"
        "w = os.environ.get('JAX_PLATFORMS')\n"
        "w and jax.config.update('jax_platforms', w)\n"
        "jax.devices()\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe_src],
            timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        log(f"device init timed out after {timeout_s:.0f}s")
        return False
    if proc.returncode != 0:
        log("device init failed:\n" + (proc.stderr or "").strip()[-2000:])
        return False
    return True


def main():
    import os

    cpu_fallback = False
    # always probe (honoring any env pin — the ambient pin may itself name a
    # dead accelerator); a cpu probe costs ~2s, a live TPU probe a few more
    if not _probe_accelerator():
        was = os.environ.get("JAX_PLATFORMS", "<default>")
        log(f"accelerator unreachable (platform {was}); falling back to "
            "CPU — the reported number is NOT a TPU result")
        os.environ["JAX_PLATFORMS"] = "cpu"
        cpu_fallback = was != "cpu"
    import gatekeeper_tpu  # noqa: F401 — package hook pins JAX_PLATFORMS
    import jax

    if cpu_fallback:
        # the hook only pins from env; ensure the override sticks even if
        # another import already touched jax config
        jax.config.update("jax_platforms", "cpu")

    import __graft_entry__ as g

    devices = jax.devices()
    log(f"devices: {devices}")
    tpu, cons, evaluator = build()

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    log(f"generating {n} synthetic pods...")
    pods = g._make_pods(n)

    # warmup: compile all shape buckets for the timed run
    log("warmup (jit compile)...")
    evaluator.sweep(cons, pods[:1024])
    warm = evaluator.sweep(cons, pods)  # compiles the full-size bucket
    del warm

    log("timed sweep...")
    t0 = time.perf_counter()
    swept = evaluator.sweep(cons, pods)
    total_violations = sum(int(c[3].sum()) for c in swept.values())
    t1 = time.perf_counter()
    elapsed = t1 - t0
    reviews_per_s = n / elapsed

    # component breakdown (device-only): rerun kernels on the resident batch
    log(
        f"end-to-end: {elapsed:.3f}s for {n} pods x {len(cons)} constraints "
        f"({total_violations} total violations) -> {reviews_per_s:,.0f} "
        "reviews/s"
    )
    log(
        f"constraint-evals/sec: {n * len(cons) / elapsed:,.0f}"
    )

    out = {
        "metric": "audit admission reviews/sec/chip",
        "value": round(reviews_per_s, 1),
        "unit": "reviews/s",
        "vs_baseline": round(reviews_per_s / 100_000, 4),
    }
    if cpu_fallback:
        # metric name stays stable for consumers; the flag marks the result
        # as a CPU-fallback measurement (TPU unreachable)
        out["cpu_fallback"] = True
    print(json.dumps(out))


if __name__ == "__main__":
    main()
