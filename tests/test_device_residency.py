"""Device-resident snapshot columns: the HBM-as-cluster-cache lane.

THE differential: an AuditManager ticking through the resident lane
(mode "on" — promoted even on the CPU host, where the device buffers
are just committed arrays) must be verdict-bit-identical to the
host-column reference manager across

1. the clean full tick (one upload, then index-gather-only dispatch);
2. the dirty-sliver tick (watch churn lands as device scatter-patch);
3. the post-evict tick (the ``device_residency_evict`` degradation
   demotes to host columns mid-flight, release re-promotes lazily);

plus the zero-H2D pin — a warm clean-rows tick reports
``tick_h2d_bytes == 0`` — the mask-mirror differential, and the
eviction/generation seams.

Wall-budget note: one module corpus (6-template slice, 100 objects)
behind a module-scoped compile cache dir, same shape as
test_snapshot_persist.py.
"""

from __future__ import annotations

import copy
import glob
import os

import numpy as np
import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.generation import CompileCache
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.resilience.overload import (DEVICE_RESIDENCY_EVICT,
                                                DegradationRegistry,
                                                activate_degradations)
from gatekeeper_tpu.snapshot import (ClusterSnapshot, DeviceResidency,
                                     SnapshotConfig, WatchIngester,
                                     gvks_of)
from gatekeeper_tpu.sync.source import FakeCluster
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import (library_dir, load_library,
                                            make_cluster_objects)
from gatekeeper_tpu.utils.unstructured import load_yaml_file

_KEEP = 6  # template-subset client: bounded compile wall (tier-1)


def _all_kinds():
    paths = sorted(
        glob.glob(os.path.join(library_dir(), "general", "*",
                               "template.yaml")) +
        glob.glob(os.path.join(library_dir(), "pod-security-policy", "*",
                               "template.yaml")))
    return [load_yaml_file(p)[0]["spec"]["crd"]["spec"]["names"]["kind"]
            for p in paths]


def _snap_manager(client, evaluator, lister, snapshot, residency=None):
    return AuditManager(
        client, lister=lister,
        config=AuditConfig(audit_source="snapshot", chunk_size=48,
                           exact_totals=False, pipeline="off"),
        evaluator=evaluator, snapshot=snapshot, residency=residency)


def _assert_identical(run_a, run_b, limit=20):
    diff = AuditManager._verdicts_differ_canonical(
        run_a.kept, run_a.total_violations,
        run_b.kept, run_b.total_violations, limit)
    assert diff is None, diff


def _churn_labels(cluster, objects, tag, idx):
    for j in idx:
        o = copy.deepcopy(objects[j])
        o.setdefault("metadata", {}).setdefault("labels", {})["churn"] = \
            tag
        cluster.apply(o)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    cache_dir = tmp_path_factory.mktemp("resid-cache")
    skip = tuple(_all_kinds()[_KEEP:])
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel,
                    compile_cache=CompileCache(str(cache_dir)))
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client, skip_kinds=skip)
    objects = make_cluster_objects(100, seed=7)
    cluster = FakeCluster()
    for o in objects:
        cluster.apply(copy.deepcopy(o))
    # single-device mesh: the resident lane is single-chip by design
    # (conftest forces 8 host devices for the multichip tests)
    evaluator = ShardedEvaluator(tpu, make_mesh(1), violations_limit=20)

    def lister():
        return iter(cluster.list())

    ctx = {"client": client, "tpu": tpu, "objects": objects,
           "cluster": cluster, "lister": lister, "evaluator": evaluator}
    yield ctx


def _paired_managers(corpus, residency):
    """Two snapshots over the same cluster: one resident, one host."""
    ev = corpus["evaluator"]
    snap_r = ClusterSnapshot(ev, SnapshotConfig())
    snap_h = ClusterSnapshot(ev, SnapshotConfig())
    mgr_r = _snap_manager(corpus["client"], ev, corpus["lister"], snap_r,
                          residency=residency)
    mgr_h = _snap_manager(corpus["client"], ev, corpus["lister"], snap_h)
    ing_r = WatchIngester(snap_r, corpus["cluster"],
                          gvks_of(corpus["cluster"].list())).start()
    ing_h = WatchIngester(snap_h, corpus["cluster"],
                          gvks_of(corpus["cluster"].list())).start()
    return snap_r, snap_h, mgr_r, mgr_h, ing_r, ing_h


# --- 1-3. THE differential: clean / dirty-sliver / post-evict ticks ---------

def test_resident_tick_differential_clean_dirty_evict(corpus):
    residency = DeviceResidency(corpus["evaluator"], mode="on")
    snap_r, snap_h, mgr_r, mgr_h, ing_r, ing_h = \
        _paired_managers(corpus, residency)
    try:
        # full rebuild both lanes (the resident lane's first upload)
        run_r = mgr_r.audit()
        run_h = mgr_h.audit()
        _assert_identical(run_r, run_h)
        assert residency.upload_count >= 1
        assert residency.resident_bytes() > 0

        # clean tick: nothing changed — dispatch is gather-index only,
        # and the SECOND clean tick's indices are cached: zero H2D
        tick_r0 = mgr_r.audit_tick()
        _assert_identical(tick_r0, mgr_h.audit_tick())
        tick_r1 = mgr_r.audit_tick()
        _assert_identical(tick_r1, mgr_h.audit_tick())
        assert mgr_r.perf["tick_h2d_bytes"] == 0, \
            "warm clean-rows resident tick uploaded bytes"

        # dirty-sliver tick: churn a handful of rows; the resident lane
        # scatter-patches exactly those and stays bit-identical
        patches0 = residency.patch_count
        _churn_labels(corpus["cluster"], corpus["objects"], "r1",
                      range(7))
        ing_r.pump()
        ing_h.pump()
        tick_r2 = mgr_r.audit_tick()
        tick_h2 = mgr_h.audit_tick()
        _assert_identical(tick_r2, tick_h2)
        assert residency.patch_count > patches0
        assert mgr_r.perf["tick_h2d_bytes"] > 0  # the sliver's bytes

        # a delete lands as a False mask column, not a re-upload
        gone = copy.deepcopy(corpus["objects"][3])
        corpus["cluster"].delete(gone)
        ing_r.pump()
        ing_h.pump()
        _assert_identical(mgr_r.audit_tick(), mgr_h.audit_tick())

        # post-evict tick: the SLO degradation demotes to host columns
        # (still bit-identical), release re-promotes lazily
        reg = DegradationRegistry()
        with activate_degradations(reg):
            reg.activate(DEVICE_RESIDENCY_EVICT, "test-objective")
            assert not residency.available()
            assert residency.evictions >= 1
            assert residency.resident_bytes() == 0
            _assert_identical(mgr_r.audit_tick(), mgr_h.audit_tick())
            reg.release(DEVICE_RESIDENCY_EVICT, "test-objective")
            uploads0 = residency.upload_count
            # re-promotion is lazy: the next tick that actually sweeps
            # a group re-uploads its mirror
            _churn_labels(corpus["cluster"], corpus["objects"], "r2",
                          range(2))
            ing_r.pump()
            ing_h.pump()
            _assert_identical(mgr_r.audit_tick(), mgr_h.audit_tick())
            assert residency.upload_count > uploads0  # re-promoted
    finally:
        ing_r.stop()
        ing_h.stop()


# --- 4. mask-mirror differential -------------------------------------------

def test_resident_mask_mirror_matches_host_masks(corpus):
    """The device mask's host mirror equals the masks the host dispatch
    path would compute per (constraint, row) — per-object purity is the
    scatter-patch lane's correctness argument."""
    from gatekeeper_tpu.ir import masks as masks_mod

    ev = corpus["evaluator"]
    residency = DeviceResidency(ev, mode="on")
    snap = ClusterSnapshot(ev, SnapshotConfig())
    mgr = _snap_manager(corpus["client"], ev, corpus["lister"], snap,
                        residency=residency)
    mgr.audit()
    assert residency._groups, "no group promoted"
    checked = 0
    for store in snap._groups.values():
        rg = residency.prepare(store)
        if rg is None:
            continue
        live = store.live_positions()
        batch = store.slice_rows(live, len(live))
        objs = [store.row_obj(p) for p in live]
        any_gen = any("generateName" in (o.get("metadata") or {})
                      for o in objs)
        ref_rows = [masks_mod.constraint_masks(
            rg.by_kind[kind], batch, ev.driver.vocab, objs,
            any_generate_name=any_gen) for kind in rg.kinds]
        ref = np.concatenate(ref_rows, axis=0)[:, : len(objs)]
        np.testing.assert_array_equal(rg.mask_host[:, live], ref)
        # device mirror == host mirror (committed arrays on CPU)
        np.testing.assert_array_equal(np.asarray(rg.mask_dev),
                                      rg.mask_host)
        # dead/pad columns are all-False
        dead = [p for p in range(store.cap) if p not in set(live)]
        assert not rg.mask_host[:, dead].any()
        checked += 1
    assert checked > 0


# --- 5. seams: auto-fallback, off mode, swap invalidation -------------------

def test_residency_auto_mode_declines_on_cpu_host(corpus):
    import jax

    residency = DeviceResidency(corpus["evaluator"], mode="auto")
    if jax.default_backend() == "cpu":
        assert not residency.available()
        snap = ClusterSnapshot(corpus["evaluator"], SnapshotConfig())
        mgr = _snap_manager(corpus["client"], corpus["evaluator"],
                            corpus["lister"], snap, residency=residency)
        mgr.audit()  # serves fine through the host path
        assert residency.upload_count == 0
    else:  # accelerator host: auto promotes
        assert residency.available()


def test_residency_off_mode_and_bad_mode(corpus):
    assert not DeviceResidency(corpus["evaluator"],
                               mode="off").available()
    with pytest.raises(ValueError):
        DeviceResidency(corpus["evaluator"], mode="bogus")


def test_generation_coordinator_invalidates_residency():
    from gatekeeper_tpu.drivers.generation import GenerationCoordinator

    class _Res:
        def __init__(self):
            self.calls = 0

        def invalidate(self):
            self.calls += 1

    import threading

    gc = GenerationCoordinator.__new__(GenerationCoordinator)
    gc._lock = threading.RLock()
    gc._residencies = []
    res = _Res()
    gc.attach_residency(res)
    assert gc._residencies == [res]
