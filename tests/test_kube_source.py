"""KubeCluster (apiserver ObjectSource) integration tests against the
in-process mock apiserver — the envtest-equivalent layer (SURVEY.md §4.2;
ref informer plane pkg/watch/manager.go:147-202, resync
pkg/cachemanager/cachemanager.go:410-540)."""

import threading
import time

import pytest

from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig
from gatekeeper_tpu.sync.mock_apiserver import MockApiServer
from gatekeeper_tpu.sync.source import ADDED, DELETED, MODIFIED

POD_GVK = ("", "v1", "Pod")
ING_GVK = ("networking.k8s.io", "v1", "Ingress")


def pod(name, ns="default", labels=None):
    meta = {"name": name, "namespace": ns}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
            "spec": {"containers": [{"name": "c", "image": "x"}]}}


@pytest.fixture()
def server():
    srv = MockApiServer().start()
    yield srv
    srv.stop()


@pytest.fixture()
def cluster(server):
    kc = KubeCluster(KubeConfig(server=server.url), page_limit=3,
                     watch_backoff_s=0.05, watch_timeout_s=20.0)
    yield kc
    kc.close()


def wait_for(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_paged_list_and_get(server, cluster):
    for i in range(8):  # 8 objects with page_limit 3 -> 3 pages
        server.put_object(pod(f"p{i}"))
    objs = cluster.list(POD_GVK)
    assert sorted(o["metadata"]["name"] for o in objs) == \
        sorted(f"p{i}" for i in range(8))
    assert all(o["kind"] == "Pod" and o["apiVersion"] == "v1"
               for o in objs)
    got = cluster.get(POD_GVK, "default", "p3")
    assert got["metadata"]["name"] == "p3"
    assert cluster.get(POD_GVK, "default", "nope") is None


def test_watch_replay_and_live_events(server, cluster):
    server.put_object(pod("existing"))
    events = []
    seen = threading.Event()

    def cb(ev):
        events.append(ev)
        seen.set()

    cancel = cluster.subscribe(POD_GVK, cb, replay=True)
    assert wait_for(lambda: any(
        e.type == ADDED and e.obj["metadata"]["name"] == "existing"
        for e in events))
    server.put_object(pod("live"))
    assert wait_for(lambda: any(
        e.type == ADDED and e.obj["metadata"]["name"] == "live"
        for e in events))
    server.put_object(pod("live", labels={"x": "y"}))
    assert wait_for(lambda: any(
        e.type == MODIFIED and e.obj["metadata"]["name"] == "live"
        for e in events))
    server.delete_object("Pod", "default", "live")
    assert wait_for(lambda: any(
        e.type == DELETED and e.obj["metadata"]["name"] == "live"
        for e in events))
    cancel()


def test_watch_410_resync_emits_deleted_diff(server, cluster):
    """On 410 Gone mid-stream the client relists; objects deleted during
    the outage surface as synthetic DELETED events (the reference's
    wipe-and-replay, cachemanager.go:527)."""
    server.put_object(pod("stay"))
    server.put_object(pod("goner"))
    events = []
    cluster.subscribe(POD_GVK, events.append, replay=True)
    assert wait_for(lambda: len(
        [e for e in events if e.type == ADDED]) >= 2)
    # delete behind the watcher's back while forcing the stream to die
    with server._lock:
        server._objects.pop(("Pod", "default", "goner"))
    server.break_watches("Pod")
    assert wait_for(lambda: any(
        e.type == DELETED and e.obj["metadata"]["name"] == "goner"
        for e in events), timeout=8.0)
    # the survivor is NOT re-announced as deleted
    assert not any(e.type == DELETED and
                   e.obj["metadata"]["name"] == "stay" for e in events)


def test_apply_create_conflict_update_delete(server, cluster):
    cluster.apply(pod("a"))
    assert server._objects[("Pod", "default", "a")]
    # second apply takes the read-modify-write path (409 -> PUT)
    cluster.apply(pod("a", labels={"v": "2"}))
    stored = server._objects[("Pod", "default", "a")]
    assert stored["metadata"]["labels"] == {"v": "2"}
    cluster.delete(pod("a"))
    assert ("Pod", "default", "a") not in server._objects
    cluster.delete(pod("a"))  # idempotent


def test_discovery_and_preferred_gvks(server, cluster):
    server.put_object({"apiVersion": "networking.k8s.io/v1",
                       "kind": "Ingress",
                       "metadata": {"name": "i", "namespace": "default"},
                       "spec": {"rules": [{"host": "a.com"}]}})
    objs = cluster.list(ING_GVK)
    assert objs[0]["metadata"]["name"] == "i"
    gvks = cluster.server_preferred_gvks()
    assert POD_GVK in gvks and ING_GVK in gvks


def test_controller_manager_runs_against_kube_cluster(server, cluster):
    """The reconciliation Manager pointed at the apiserver source: a
    ConstraintTemplate arriving through a real watch compiles into the
    client (the e2e shape of VERDICT r1 next-step #3)."""
    from gatekeeper_tpu.apis.constraints import WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.controller.manager import Manager
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    cel = CELDriver()
    tpu = TpuDriver(batch_bucket=8, cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, "audit.gatekeeper.sh"])
    mgr = Manager(client, cluster, operations=["webhook", "audit"]).start()
    t = load_yaml_file(
        "/root/reference/demo/basic/templates/"
        "k8srequiredlabels_template.yaml")[0]
    server.put_object(t)
    assert wait_for(
        lambda: client.get_template("K8sRequiredLabels") is not None)
    assert "K8sRequiredLabels" in tpu.lowered_kinds()

    # dynamic constraint kind: the Manager subscribed to it on template
    # arrival; installing the CRD resource + a constraint must make it
    # active for Review (watch retried until discovery resolved)
    server.add_resource("K8sRequiredLabels", "constraints.gatekeeper.sh",
                        "v1beta1", "k8srequiredlabels", False)
    server.put_object({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "need-owner"},
        "spec": {"parameters": {"labels": [{"key": "owner"}]}},
    })
    assert wait_for(lambda: client.get_constraint(
        "K8sRequiredLabels", "need-owner") is not None, timeout=8.0)


def test_kubeconfig_parsing(tmp_path):
    import base64 as b64

    kc_path = tmp_path / "config"
    kc_path.write_text("""
apiVersion: v1
kind: Config
current-context: ctx
contexts:
- name: ctx
  context: {cluster: c1, user: u1}
clusters:
- name: c1
  cluster:
    server: https://example:6443
    certificate-authority-data: %s
users:
- name: u1
  user:
    token: sekrit
""" % b64.b64encode(b"CA PEM").decode())
    cfg = KubeConfig.from_kubeconfig(str(kc_path))
    assert cfg.server == "https://example:6443"
    assert cfg.token == "sekrit"
    assert open(cfg.ca_file, "rb").read() == b"CA PEM"


def test_routing_cluster_over_live_target(server, cluster):
    """--management-manifests x --kubeconfig: the RoutingCluster keeps
    gatekeeper-internal state (status group, Secrets) on the management
    side while audit listing/discovery spans the live target."""
    from gatekeeper_tpu.sync.routing import RoutingCluster
    from gatekeeper_tpu.sync.source import FakeCluster

    mgmt = FakeCluster()
    routed = RoutingCluster(mgmt, cluster)
    server.put_object(pod("t1"))
    assert POD_GVK in routed.server_preferred_gvks()
    assert [o["metadata"]["name"] for o in routed.list_iter(POD_GVK)] == \
        ["t1"]
    status_obj = {
        "apiVersion": "status.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplatePodStatus",
        "metadata": {"name": "pod-x", "namespace": "gatekeeper-system"},
        "status": {"id": "pod-x"},
    }
    routed.apply(status_obj)  # routes to management, NOT the apiserver
    assert mgmt.get(("status.gatekeeper.sh", "v1beta1",
                     "ConstraintTemplatePodStatus"),
                    "gatekeeper-system", "pod-x") is not None
    assert ("ConstraintTemplatePodStatus" not in
            [k for (k, _ns, _n) in server._objects])
