"""Round-trip tests for the wire packing of sweep transfer columns.

The tunneled-TPU link sustains ~30MB/s, so pack_transfer_cols narrows
column dtypes (uint16/uint8/nibble with a +1 bias for the -1 sentinel),
dictionary-remaps low-cardinality wide-range columns, and elides
corpus-constant columns — all driven by corpus stats so the wire layout
is identical for every chunk of a run.  These tests pin the exactness
contract: unpack(pack(cols)) == cols bit-for-bit, for every wire kind
and for chunks that drift outside the corpus stats (which must fall
back to wider dtypes, never produce wrong values).
"""

import numpy as np
import jax
import pytest

from gatekeeper_tpu.parallel.sharded import (col_stats_update,
                                             pack_transfer_cols,
                                             unpack_transfer_cols)

N = 64


def _mk_cols(rng):
    return {
        # u2 sid + nibble kind + integral-float num
        "a": {"sid": rng.integers(-1, 40000, (N, 8)).astype(np.int32),
              "kind": rng.integers(-1, 7, (N, 8)).astype(np.int8),
              "num": rng.integers(0, 60000, (N, 8)).astype(np.float32)},
        # dictionary remap (4 distinct values, range >> u1) + odd-width
        # nibble candidate that must fall back to u1
        "b": {"sid": rng.choice(
                  np.array([-1, 5, 70000, 123456], np.int32), (N, 4)),
              "count": rng.integers(0, 8, N).astype(np.int32)},
        # corpus-constant: elided to a layout scalar
        "c": np.full((N, 8), -1, np.int32),
        # genuine floats: passthrough
        "d": {"num": rng.standard_normal((N, 2)).astype(np.float32)},
    }


def _roundtrip(cols, stats):
    bufs, layout = pack_transfer_cols(cols, N, stats=stats)
    out = jax.jit(lambda b: unpack_transfer_cols(b, layout, N))(
        {k: np.ascontiguousarray(v) for k, v in bufs.items()})
    return bufs, layout, out


def _assert_equal(out, cols, names):
    for key, sub in names:
        x = np.asarray(out[key][sub] if sub else out[key])
        y = np.asarray(cols[key][sub] if sub else cols[key])
        assert x.dtype == y.dtype, (key, sub, x.dtype, y.dtype)
        assert np.array_equal(x, y), (key, sub)


ALL = [("a", "sid"), ("a", "kind"), ("a", "num"),
       ("b", "sid"), ("b", "count"), ("c", None), ("d", "num")]


def test_roundtrip_all_wire_kinds():
    rng = np.random.default_rng(0)
    cols = _mk_cols(rng)
    stats = {}
    col_stats_update(stats, cols)
    bufs, layout, out = _roundtrip(cols, stats)
    _assert_equal(out, cols, ALL)
    kinds = {e[2] for e in layout}
    # the fixture must actually exercise every wire kind
    assert {"<u2", "|n1", "|u1", "const", "<f4"} <= kinds
    # elision really dropped the constant column from the buffers
    total = sum(b.nbytes for b in bufs.values())
    assert total < sum(
        np.asarray(v).nbytes
        for val in cols.values()
        for v in (val.values() if isinstance(val, dict) else [val]))


def test_drift_chunk_falls_back_wider_never_wrong():
    rng = np.random.default_rng(1)
    cols = _mk_cols(rng)
    stats = {}
    col_stats_update(stats, cols)
    drift = {k: ({s: v.copy() for s, v in val.items()}
                 if isinstance(val, dict) else val.copy())
             for k, val in cols.items()}
    drift["b"]["sid"][0, 0] = 999999   # outside the corpus dictionary
    drift["a"]["kind"][0, 0] = 100     # outside the nibble range
    drift["c"][0, 0] = 7               # breaks the constant
    drift["a"]["num"][0, 0] = 0.5      # corpus-integral f4 drifts fractional
    drift["d"]["num"][0, 0] = 0.5      # (already non-integral: no-op)
    _, _, out = _roundtrip(drift, stats)
    _assert_equal(out, drift, ALL)


def test_no_stats_passthrough():
    rng = np.random.default_rng(2)
    cols = _mk_cols(rng)
    _, layout, out = _roundtrip(cols, None)
    _assert_equal(out, cols, ALL)
    assert {e[2] for e in layout} == {"<i4", "|i1", "<f4"}


def test_multichunk_stats_union_keeps_layout_stable():
    rng = np.random.default_rng(3)
    chunks = [_mk_cols(rng) for _ in range(3)]
    stats = {}
    for ch in chunks:
        col_stats_update(stats, ch)
    layouts = []
    for ch in chunks:
        _, layout, out = _roundtrip(ch, stats)
        _assert_equal(out, ch, ALL)
        layouts.append(layout)
    # one wire layout across every chunk: no mid-run retrace
    assert layouts[0] == layouts[1] == layouts[2]
