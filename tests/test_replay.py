"""``gator replay``: the offline policy time machine.

1. Corpus ingest: capture-mode flight-recorder JSONL → replayable
   records, skip-and-count for malformed lines, a crashed recorder's
   torn tail, non-validate endpoints, shed/error decisions, no-body
   entries.
2. THE replay differential: an identical candidate replays the corpus
   with ZERO divergences, bit-identical decisions/messages/codes, and
   ZERO fresh lowerings (the shared on-disk compile cache answers every
   template).
3. The rollout preview: a candidate missing one deny-firing constraint
   attributes every ``newly_allowed`` divergence to exactly that
   constraint, with top offenders by namespace/kind.
4. ``gator replay`` CLI: exit codes (2 usage, 1 on non-bit-identical
   differential), JSON and table output.
5. Spill-at-rv replay: a ``--snapshot-spill`` directory replays its
   resident objects at the audit enforcement point against the spilled
   verdict store — differential bit-identity, constraint-drop diff,
   section integrity, and the TWO-WAY vocab prefix rule (snapshot ⊆
   current is a hit; a diverged overlap is a counted vocab miss).
6. ``bench.py replay --smoke`` rides tier-1 so REPLAY_BENCH.json's
   pins (bit-identity, zero-fresh-lowerings) cannot rot.
7. ``gator decisions`` + flight-recorder sink: truncated-tail vs
   malformed accounting, torn-tail sink repair on append.

Wall budget: one module-scoped corpus (5-template library slice, 90
recorded admissions) and one shared on-disk compile cache; every
candidate load after the first is all cache hits.
"""

from __future__ import annotations

import copy
import importlib.util
import json
import os
import shutil

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.gator import reader, replay_cmd
from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.ops.flatten import RowIdMap  # noqa: F401 (import check)
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.replay import core
from gatekeeper_tpu.snapshot import (ClusterSnapshot, SnapshotConfig,
                                     SnapshotSpill, templates_digest)
from gatekeeper_tpu.sync.source import FakeCluster
from gatekeeper_tpu.utils.synthetic import make_cluster_objects
from gatekeeper_tpu.utils.unstructured import name_of

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load_tool("bench_replay")


@pytest.fixture(scope="module")
def corpus(bench, tmp_path_factory):
    """A recorded corpus: the bench's serving stack (real
    ValidationHandler + capture-mode flight recorder) answers 90
    synthetic admissions over a 5-template library slice; the sink and
    the warm compile cache are shared module-wide."""
    cache_dir = str(tmp_path_factory.mktemp("replay-cc"))
    sink = os.path.join(str(tmp_path_factory.mktemp("replay-sink")),
                        "decisions.jsonl")
    docs = bench._library_docs()
    bodies = bench._admission_bodies(90)
    serve = bench._serve_and_record(docs, bodies, sink, cache_dir)
    records, counts = core.read_corpus(sink)
    return {"cache_dir": cache_dir, "sink": sink, "docs": docs,
            "serve": serve, "records": records, "counts": counts}


def _replay(corpus, docs, **kw):
    """One candidate replay lane over the module corpus (fresh runtime,
    warm disk cache), generation coordinator stopped on the way out."""
    runtime = core.load_candidate(
        docs, compile_cache_dir=corpus["cache_dir"],
        metrics=kw.pop("load_metrics", None))
    try:
        return core.replay_decisions(corpus["records"], runtime, **kw)
    finally:
        gc = getattr(runtime.driver, "gen_coord", None)
        if gc is not None:
            gc.stop()


def _dropped_deny_constraint(corpus):
    """The first (sorted) constraint the recorded corpus blames for a
    deny — the modified-candidate lanes drop it."""
    denied = set()
    for r in corpus["records"]:
        if r.get("decision") == "deny":
            denied.update(core.recorded_constraints(r.get("message", "")))
    assert denied, "corpus recorded no denies — fixture seed regressed"
    return sorted(denied)[0]


# --- 1. corpus ingest ------------------------------------------------------

def test_corpus_capture_complete(corpus):
    counts = corpus["counts"]
    assert counts["replayed"] == len(corpus["records"]) == 90
    assert counts["lines"] == 90  # every served admission recorded
    assert corpus["serve"]["denies"] > 0
    for r in corpus["records"]:
        assert isinstance(r["request"], dict)
        assert r["decision"] in ("allow", "deny")


def test_read_corpus_skip_and_count(tmp_path):
    good = {"endpoint": "validate", "decision": "allow", "uid": "g",
            "request": {"uid": "g"}}
    deny = {"endpoint": "validate", "decision": "deny", "uid": "d",
            "message": "[some-con] no", "request": {"uid": "d"}}
    path = tmp_path / "sink.jsonl"
    path.write_text(
        json.dumps(good) + "\n"
        + "{half a line\n"                                 # malformed
        + "42\n"                                           # not a record
        + json.dumps({"endpoint": "audit", "decision": "allow",
                      "request": {}}) + "\n"               # endpoint
        + json.dumps({"endpoint": "validate", "decision": "shed",
                      "request": {}}) + "\n"               # unreplayable
        + json.dumps({"endpoint": "validate",
                      "decision": "deny"}) + "\n"          # no body
        + json.dumps(deny) + "\n"
        + '{"endpoint": "validate", "deci')                # torn tail
    records, counts = core.read_corpus(str(path))
    assert [r["uid"] for r in records] == ["g", "d"]
    assert counts == {"lines": 8, "replayed": 2, "malformed": 2,
                      "endpoint": 1, "unreplayable_decision": 1,
                      "no_body": 1, "truncated_tail": 1}


def test_read_corpus_limit(corpus):
    records, counts = core.read_corpus(corpus["sink"], limit=10)
    assert len(records) == 10 and counts["replayed"] == 10


# --- 2. the identical-candidate differential -------------------------------

def test_identical_candidate_bit_identical_zero_lowerings(corpus):
    metrics = MetricsRegistry()
    report = _replay(corpus, corpus["docs"], differential=True,
                     metrics=metrics, skipped=corpus["counts"],
                     load_metrics=metrics)
    assert report["records"] == 90
    assert report["divergences_total"] == 0
    assert report["newly_denied"] == report["newly_allowed"] == 0
    assert report["message_changed"] == report["errors"] == 0
    assert report["by_constraint"] == {}
    diff = report["differential"]
    assert diff["bit_identical"] and diff["checked"] == 90
    assert diff["mismatches_total"] == 0
    # the recorded and candidate decision mixes agree exactly
    assert report["recorded"] == report["candidate"]
    # zero fresh lowerings: the serving pass populated the disk cache,
    # the candidate load answered every template from it
    cc = report["compile_cache"]
    assert cc["misses"] == 0 and cc["hits"] > 0
    assert report["lowering"]["templates"] == 5
    # metrics: replayed outcome counted, no divergence series touched
    assert metrics.get_counter(M.REPLAY_RECORDS,
                               {"outcome": "replayed"}) == 90
    assert metrics.counter_total(M.REPLAY_DIVERGENCE) == 0
    assert metrics.get_gauge(M.REPLAY_SECONDS) is not None


# --- 3. the rollout preview (modified candidate) ---------------------------

def test_modified_candidate_attributes_newly_allowed(corpus):
    drop = _dropped_deny_constraint(corpus)
    docs = [d for d in corpus["docs"]
            if not (reader.is_constraint(d) and name_of(d) == drop)]
    metrics = MetricsRegistry()
    report = _replay(corpus, docs, metrics=metrics)
    assert report["newly_allowed"] > 0
    assert report["newly_denied"] == 0
    per = report["by_constraint"][drop]
    assert per["newly_allowed"] > 0 and per["newly_denied"] == 0
    for d in report["divergences"]:
        assert d["kind"] == "newly_allowed"
        assert drop in d["constraints_removed"]
    # the offender axes name where the divergences landed
    assert sum(c for _n, c in report["top_offenders"]["namespace"]) == \
        report["divergences_total"]
    assert sum(c for _n, c in report["top_offenders"]["kind"]) == \
        report["divergences_total"]
    assert "differential" not in report  # candidate mode only
    assert metrics.get_counter(M.REPLAY_DIVERGENCE,
                               {"kind": "newly_allowed"}) == \
        report["newly_allowed"]


# --- 4. the CLI ------------------------------------------------------------

def _docs_file(tmp_path, docs, name="candidate.json"):
    p = tmp_path / name
    p.write_text(json.dumps(docs, default=str))
    return str(p)


def test_replay_cli_differential_json(corpus, tmp_path, capsys):
    cand = _docs_file(tmp_path, corpus["docs"])
    rc = replay_cmd.run_cli([
        "-f", corpus["sink"], "--candidate", cand, "--differential",
        "--compile-cache", corpus["cache_dir"], "-o", "json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["differential"]["bit_identical"]
    assert report["records"] == 90
    assert report["compile_cache"]["misses"] == 0


def test_replay_cli_mismatch_exits_1(corpus, tmp_path, capsys):
    drop = _dropped_deny_constraint(corpus)
    cand = _docs_file(tmp_path, [
        d for d in corpus["docs"]
        if not (reader.is_constraint(d) and name_of(d) == drop)])
    rc = replay_cmd.run_cli([
        "-f", corpus["sink"], "--candidate", cand, "--differential",
        "--compile-cache", corpus["cache_dir"]])
    assert rc == 1
    out = capsys.readouterr().out
    assert "MISMATCHES" in out
    assert drop in out  # per-constraint attribution in the table


def test_replay_cli_usage_errors(corpus, tmp_path, capsys):
    cand = _docs_file(tmp_path, corpus["docs"])
    # exactly one corpus source required
    assert replay_cmd.run_cli(["--candidate", cand]) == 2
    assert replay_cmd.run_cli([
        "-f", corpus["sink"], "--from-spill", "x",
        "--candidate", cand]) == 2
    # candidate required
    assert replay_cmd.run_cli(["-f", corpus["sink"]]) == 2
    # unreadable candidate / empty doc set are reported, not tracebacks
    assert replay_cmd.run_cli([
        "-f", corpus["sink"], "--candidate",
        str(tmp_path / "nope.yaml")]) == 1
    empty = _docs_file(tmp_path, [], name="empty.json")
    assert replay_cmd.run_cli([
        "-f", corpus["sink"], "--candidate", empty]) == 1
    capsys.readouterr()


# --- 5. spill-at-rv replay -------------------------------------------------

@pytest.fixture(scope="module")
def spilled(corpus, tmp_path_factory):
    """A --snapshot-spill directory: the candidate docs' library audits
    60 synthetic objects through the snapshot path, then spills."""
    root = str(tmp_path_factory.mktemp("replay-spill"))
    runtime = core.load_candidate(corpus["docs"],
                                  compile_cache_dir=corpus["cache_dir"])
    evaluator = ShardedEvaluator(runtime.driver, make_mesh(),
                                 violations_limit=20)
    cluster = FakeCluster()
    for o in make_cluster_objects(60, seed=23):
        cluster.apply(copy.deepcopy(o))
    snap = ClusterSnapshot(evaluator, SnapshotConfig())
    mgr = AuditManager(
        runtime.client, lister=lambda: iter(cluster.list()),
        config=AuditConfig(audit_source="snapshot", chunk_size=64,
                           exact_totals=False, pipeline="off"),
        evaluator=evaluator, snapshot=snap)
    run = mgr.audit()
    spill = SnapshotSpill(root)
    wrote = spill.save(snap, templates=templates_digest(runtime.client))
    assert wrote["ok"] and wrote["rows"] == 60
    return {"root": root, "run": run,
            "tdig": templates_digest(runtime.client)}


def test_spill_replay_differential_bit_identical(corpus, spilled):
    spill = core.read_spill(spilled["root"])
    assert spill["rows"] == 60 and len(spill["objects"]) == 60
    assert spill["verdicts"], "spill recorded no violating rows"
    runtime = core.load_candidate(corpus["docs"],
                                  compile_cache_dir=corpus["cache_dir"])
    report = core.replay_spill(spill, runtime, differential=True)
    assert report["divergences_total"] == 0
    assert report["by_constraint"] == {}
    assert report["differential"]["bit_identical"]
    assert report["compile_cache"]["misses"] == 0


def test_spill_replay_modified_candidate_newly_clean(corpus, spilled):
    spill = core.read_spill(spilled["root"])
    drop = sorted(n for n, rows in spill["verdicts"].items() if rows)[0]
    docs = [d for d in corpus["docs"]
            if not (reader.is_constraint(d) and name_of(d) == drop)]
    runtime = core.load_candidate(docs,
                                  compile_cache_dir=corpus["cache_dir"])
    report = core.replay_spill(spill, runtime)
    per = report["by_constraint"][drop]
    assert per["newly_clean"] == len(spill["verdicts"][drop])
    assert per["newly_violating"] == 0
    assert all(d["constraint"] == drop and d["kind"] == "newly_clean"
               for d in report["divergences"])


def test_read_spill_rejects_corrupt_section(spilled, tmp_path):
    d = str(tmp_path / "spill-copy")
    shutil.copytree(spilled["root"], d)
    rows_p = os.path.join(d, "snapshot.rows.pkl")
    with open(rows_p, "r+b") as f:
        f.seek(os.path.getsize(rows_p) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(ValueError, match="sha256"):
        core.read_spill(d)


def test_spill_vocab_two_way_prefix_rule(corpus, spilled):
    """The fleet-mode vocab gate on ``SnapshotSpill.load``: current ⊆
    snapshot replays the tail; snapshot ⊆ current (a sibling cluster
    grew the shared vocab past the spill) is ALSO a hit with nothing to
    replay; a diverged overlap is a counted (non-deleting) miss."""
    from gatekeeper_tpu.snapshot.persist import MISS_VOCAB

    runtime = core.load_candidate(corpus["docs"],
                                  compile_cache_dir=corpus["cache_dir"])
    ev = ShardedEvaluator(runtime.driver, make_mesh(),
                          violations_limit=20)
    cons = [c for c in runtime.client.constraints()
            if c.actions_for(AUDIT_EP)]
    vocab = runtime.driver.vocab

    # restart shape: boot vocab is a prefix of the spilled table
    snap_a = ClusterSnapshot(ev, SnapshotConfig())
    assert SnapshotSpill(spilled["root"]).load(
        snap_a, cons, templates=spilled["tdig"]) is not None
    spilled_len = len(vocab._to_str)  # tail replayed: cur == snapshot

    # sibling-churn shape: the shared vocab grew PAST the spill
    for i in range(5):
        vocab.intern(f"sibling-churn-{i}")
    snap_b = ClusterSnapshot(ev, SnapshotConfig())
    sp = SnapshotSpill(spilled["root"])
    assert sp.load(snap_b, cons, templates=spilled["tdig"]) is not None
    assert sp.miss_reasons == {}
    assert len(vocab._to_str) == spilled_len + 5  # nothing re-interned

    # adversarial churn: a conflicting sid inside the overlap — the
    # spill itself is fine (files stay), but it must never load here
    vocab._to_str[spilled_len - 1] = "conflicting-intern"
    snap_c = ClusterSnapshot(ev, SnapshotConfig())
    sp2 = SnapshotSpill(spilled["root"])
    assert sp2.load(snap_c, cons, templates=spilled["tdig"]) is None
    assert sp2.miss_reasons == {MISS_VOCAB: 1}
    assert snap_c.stale  # untouched on a miss
    assert os.path.exists(os.path.join(spilled["root"], "snapshot.json"))


def test_replay_cli_from_spill(corpus, spilled, tmp_path, capsys):
    cand = _docs_file(tmp_path, corpus["docs"])
    rc = replay_cmd.run_cli([
        "--from-spill", spilled["root"], "--candidate", cand,
        "--differential", "--compile-cache", corpus["cache_dir"],
        "-o", "json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["source"] == "spill" and report["rows"] == 60
    assert report["differential"]["bit_identical"]


# --- 6. the bench smoke (REPLAY_BENCH.json cannot rot) ---------------------

def test_bench_replay_smoke(corpus, bench):
    rec = bench.run_bench(n_requests=60, write=False,
                          cache_dir=corpus["cache_dir"])
    assert rec["headline"]["bit_identical"]
    assert rec["headline"]["zero_fresh_lowerings"]
    assert rec["identical"]["divergences_total"] == 0
    assert rec["corpus"]["records"] == 60
    mod = rec["modified"]
    assert "skipped" in mod or mod["newly_allowed"] > 0


# --- 7. gator decisions + sink hardening -----------------------------------

def test_decisions_cmd_truncated_vs_malformed(tmp_path, capsys):
    from gatekeeper_tpu.gator import decisions_cmd

    path = tmp_path / "sink.jsonl"
    path.write_text(
        json.dumps({"ts": 1.0, "endpoint": "validate",
                    "decision": "allow", "uid": "u1"}) + "\n"
        + "{corrupt mid-file\n"
        + "17\n"
        + '{"ts": 2.0, "endpoint": "validate", "decis')  # torn tail
    doc = decisions_cmd.read_decisions(str(path))
    assert [e["uid"] for e in doc["decisions"]] == ["u1"]
    assert doc["malformed"] == 2
    assert doc["truncated"] == 1
    rc = decisions_cmd.run_cli(["-f", str(path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "2 malformed" in out and "1 truncated" in out


def test_flightrec_sink_torn_tail_repaired_on_append(tmp_path):
    """A crashed recorder leaves a torn final line; the next recorder
    appending to the same sink must not fuse its first record onto it."""
    from gatekeeper_tpu.observability import flightrec

    path = tmp_path / "sink.jsonl"
    path.write_text('{"endpoint": "validate", "decision": "al')  # torn
    rec = flightrec.FlightRecorder(capacity=8, sink_path=str(path),
                                   capture=True)
    rec.record("validate", "allow", uid="after-crash",
               request={"uid": "after-crash"})
    rec.close()
    records, counts = core.read_corpus(str(path))
    assert counts["malformed"] == 1  # the torn line, confined
    assert counts.get("truncated_tail", 0) == 0
    assert [r["uid"] for r in records] == ["after-crash"]
    assert records[0]["request"] == {"uid": "after-crash"}


# --- 4. namespace-selector replay fidelity ---------------------------------

NS_SEL_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8snssel"},
    "spec": {"crd": {"spec": {"names": {"kind": "K8sNsSel"}}},
             "targets": [{
                 "target": "admission.k8s.gatekeeper.sh",
                 "rego": """
package k8snssel

violation[{"msg": msg}] {
  input.review.object.kind == "Pod"
  msg := "pod in selected namespace"
}
"""}]},
}
NS_SEL_CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sNsSel",
    "metadata": {"name": "deny-team-a-pods"},
    "spec": {"match": {
        "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
        "namespaceSelector": {"matchLabels": {"team": "a"}}}},
}
NS_AUDIT_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8snsspill"},
    "spec": {"crd": {"spec": {"names": {"kind": "K8sNsSpill"}}},
             "targets": [{
                 "target": "admission.k8s.gatekeeper.sh",
                 "rego": """
package k8snsspill

violation[{"msg": msg}] {
  input.review.object.kind == "Pod"
  msg := "audited"
}
"""}]},
}
NS_AUDIT_CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sNsSpill",
    "metadata": {"name": "ns-spill-audit"},
    "spec": {"match": {
        "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]}},
}


def _ns_doc(name, team):
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": name, "labels": {"team": team}}}


def _ns_pod(i, ns):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"p{i}", "namespace": ns}, "spec": {}}


@pytest.fixture(scope="module")
def ns_corpus(tmp_path_factory):
    """Recorded decisions whose verdicts depended on the RECORDED
    cluster's Namespace labels (alpha: team=a denied), plus a snapshot
    spill of that cluster — the namespace source of record."""
    from gatekeeper_tpu.observability import flightrec
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    sink = os.path.join(str(tmp_path_factory.mktemp("ns-sink")),
                        "decisions.jsonl")
    runtime = core.load_candidate([NS_SEL_TEMPLATE, NS_SEL_CONSTRAINT])
    ns_live = {"alpha": _ns_doc("alpha", "a"),
               "beta": _ns_doc("beta", "b")}
    handler = ValidationHandler(runtime.client,
                                namespace_lookup=ns_live.get)
    bodies = []
    for i, ns in enumerate(["alpha", "beta"] * 6):
        bodies.append({"apiVersion": "admission.k8s.io/v1",
                       "kind": "AdmissionReview",
                       "request": {"uid": f"ns-{i:04d}",
                                   "kind": {"group": "", "version": "v1",
                                            "kind": "Pod"},
                                   "operation": "CREATE",
                                   "name": f"p{i}", "namespace": ns,
                                   "userInfo": {"username": "t@ns"},
                                   "object": _ns_pod(i, ns)}})
    rec = flightrec.FlightRecorder(capacity=64, sink_path=sink,
                                   capture=True)
    denies = 0
    with flightrec.activate(rec):
        for b in bodies:
            resp = handler.handle(b)
            denies += 0 if resp.allowed else 1
    rec.close()
    gc = getattr(runtime.driver, "gen_coord", None)
    if gc is not None:
        gc.stop()
    records, _counts = core.read_corpus(sink)
    assert denies == 6 and len(records) == 12
    # spill the recorded cluster (Namespaces included) as rows
    root = str(tmp_path_factory.mktemp("ns-spill"))
    audit_rt = core.load_candidate([NS_AUDIT_TEMPLATE,
                                    NS_AUDIT_CONSTRAINT])
    evaluator = ShardedEvaluator(audit_rt.driver, make_mesh(),
                                 violations_limit=20)
    cluster = FakeCluster()
    for o in list(ns_live.values()) + [_ns_pod(i, "alpha")
                                       for i in (90, 91)]:
        cluster.apply(copy.deepcopy(o))
    snap = ClusterSnapshot(evaluator, SnapshotConfig())
    mgr = AuditManager(
        audit_rt.client, lister=lambda: iter(cluster.list()),
        config=AuditConfig(audit_source="snapshot", chunk_size=64,
                           exact_totals=False, pipeline="off"),
        evaluator=evaluator, snapshot=snap)
    mgr.audit()
    wrote = SnapshotSpill(root).save(
        snap, templates=templates_digest(audit_rt.client))
    assert wrote["ok"]
    gc = getattr(audit_rt.driver, "gen_coord", None)
    if gc is not None:
        gc.stop()
    return {"records": records, "sink": sink, "root": root}


def test_namespaces_from_spill_extracts_recorded_fixtures(ns_corpus):
    ns = core.namespaces_from_spill(core.read_spill(ns_corpus["root"]))
    assert set(ns) == {"alpha", "beta"}
    assert ns["alpha"]["metadata"]["labels"] == {"team": "a"}


def test_namespace_selector_replay_pins_recorded_labels(ns_corpus):
    """Stale candidate Namespace fixtures flip namespace-selector
    verdicts (looks like a library change, is corpus skew); sourcing
    fixtures from the recorded spill restores bit-identity."""
    stale = [NS_SEL_TEMPLATE, NS_SEL_CONSTRAINT,
             _ns_doc("alpha", "b"), _ns_doc("beta", "b")]

    def run(**kw):
        rt = core.load_candidate(stale, **kw)
        try:
            return core.replay_decisions(ns_corpus["records"], rt,
                                         differential=True)
        finally:
            gc = getattr(rt.driver, "gen_coord", None)
            if gc is not None:
                gc.stop()

    skewed = run()
    assert not skewed["differential"]["bit_identical"]
    assert skewed["newly_allowed"] == 6  # every alpha deny flipped
    fixed = run(namespaces=core.namespaces_from_spill(
        core.read_spill(ns_corpus["root"])))
    assert fixed["differential"]["bit_identical"]
    assert fixed["newly_allowed"] == 0


def test_replay_cli_namespaces_from_spill_flag(ns_corpus, tmp_path,
                                               capsys):
    """--namespaces-from-spill: opt-in; without it the stale-fixture
    skew exits 1, with it the same corpus is bit-identical (exit 0)."""
    f = _docs_file(tmp_path, [NS_SEL_TEMPLATE, NS_SEL_CONSTRAINT,
                              _ns_doc("alpha", "b"),
                              _ns_doc("beta", "b")], "ns-cand.json")
    base = ["-f", ns_corpus["sink"], "--candidate", f,
            "--differential", "-o", "json"]
    assert replay_cmd.run_cli(base) == 1
    capsys.readouterr()
    rc = replay_cmd.run_cli(base + ["--namespaces-from-spill",
                                    ns_corpus["root"]])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["differential"]["bit_identical"]
