"""Batched expansion stage (ISSUE 7): edge cases the recursive path
never pinned, asserted IDENTICAL between `mutlane.ExpansionStage` /
the audit generator stage and the recursive `expansion/system.py`:

- depth-cap (30) enforcement voids the base with the reference's exact
  error message;
- owner-ref + mock-name stamping and namespace resolution (real ns,
  parent ns, empty-ns pop) byte-for-byte;
- nested generator recursion (Deployment → ReplicaSet → Pod) in the
  reference's depth-first output order;
- `enforcementAction` override + `[Implied by <template>]` prefix on
  generated resultants in the audit sweep;
- the audit generator stage differential: a relist sweep with the
  batched stage equals the same sweep with a recursive-reference stage
  bit-identically over the library corpus, and snapshot-mode generated
  verdicts (O(churn), per parent gid) equal a fresh relist after churn;
- `gator expand --lane differential` (batched CLI lane vs host walk).
"""

import copy

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.rego_driver import RegoDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.expansion.aggregate import CHILD_MSG_PREFIX
from gatekeeper_tpu.expansion.system import (MAX_RECURSION_DEPTH,
                                             ExpansionError,
                                             ExpansionSystem)
from gatekeeper_tpu.mutation.system import MutationSystem
from gatekeeper_tpu.mutlane import ExpansionStage
from gatekeeper_tpu.mutlane.expand_stage import ExpandResult
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.snapshot import (ClusterSnapshot, SnapshotConfig,
                                     WatchIngester, gvks_of)
from gatekeeper_tpu.sync.source import FakeCluster
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects


def _template(name, from_kind, to_kind, source="spec.template",
              group_from="apps", group_to="", enforcement=""):
    return {
        "apiVersion": "expansion.gatekeeper.sh/v1alpha1",
        "kind": "ExpansionTemplate", "metadata": {"name": name},
        "spec": {"applyTo": [{"groups": [group_from], "versions": ["v1"],
                              "kinds": [from_kind]}],
                 "templateSource": source,
                 "generatedGVK": {"group": group_to, "version": "v1",
                                  "kind": to_kind},
                 **({"enforcementAction": enforcement}
                    if enforcement else {})},
    }


def _assign(name, location, value):
    return {
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign", "metadata": {"name": name},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": ["Pod", "ReplicaSet"]}],
                 "location": location,
                 "parameters": {"assign": {"value": value}}},
    }


def _deployment(name, ns="", priv=False):
    spec = {"containers": [{"name": "app"}]}
    if priv:
        spec["containers"][0]["securityContext"] = {"privileged": True}
    d = {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": name},
         "spec": {"template": {"metadata": {"labels": {"app": name}},
                               "spec": spec}}}
    if ns:
        d["metadata"]["namespace"] = ns
    return d


def _ref_expand_batch(es, bases, namespaces=None):
    """The recursive reference wrapped in the stage's result shape."""
    out = []
    for i, base in enumerate(bases):
        ns = namespaces[i] if namespaces else None
        try:
            out.append(ExpandResult(
                es.expand(copy.deepcopy(base), namespace=ns)))
        except ExpansionError as e:
            out.append(ExpandResult([], error=str(e)))
    return out


def _assert_results_identical(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert (g.error is None) == (w.error is None), (g.error, w.error)
        if g.error is not None:
            assert g.error == w.error
            continue
        assert [r.obj for r in g.resultants] == \
            [r.obj for r in w.resultants]
        assert [(r.template_name, r.enforcement_action)
                for r in g.resultants] == \
            [(r.template_name, r.enforcement_action)
             for r in w.resultants]


# --- stage vs recursive reference: structural edge cases -------------------

def test_mixed_batch_identical_to_reference():
    """Generators, non-generators, error bases, and namespaces in one
    batch: per-base resultants + errors equal the recursive walk."""
    system = MutationSystem()
    system.upsert_unstructured(_assign("nonroot",
                                       "spec.securityContext.runAsNonRoot",
                                       True))
    es = ExpansionSystem(mutation_system=system)
    es.upsert_template(_template("expand-deployments", "Deployment",
                                 "Pod", enforcement="warn"))
    ns_obj = {"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": "prod"}}
    bases = [
        _deployment("web", ns="prod"),
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "plain"}, "spec": {}},  # not a generator
        # templateSource missing → the reference errors the base
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": "broken"}, "spec": {}},
        _deployment("bare"),  # no namespace anywhere
    ]
    namespaces = [ns_obj, None, ns_obj, None]
    got = ExpansionStage(es).expand_batch(copy.deepcopy(bases),
                                          namespaces)
    want = _ref_expand_batch(es, bases, namespaces)
    _assert_results_identical(got, want)
    assert got[1].resultants == []  # non-generator expands to nothing
    assert got[2].error and "could not find source field" in got[2].error
    # enforcementAction override rides every resultant
    assert got[0].resultants[0].enforcement_action == "warn"


def test_owner_ref_mock_name_and_namespace_stamping():
    """The stamped resultant, pinned literally AND against the
    reference: mock name `<base>-<kind>` lowercased, owner-ref with
    empty uid, namespace from the Namespace object / parent fallback /
    empty-ns pop."""
    es = ExpansionSystem()
    es.upsert_template(_template("expand-deployments", "Deployment",
                                 "Pod"))
    base = _deployment("WEB", ns="shadowed")
    ns_obj = {"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": "real-ns"}}
    stage = ExpansionStage(es)

    got = stage.expand_batch([copy.deepcopy(base)], [ns_obj])[0]
    want = _ref_expand_batch(es, [base], [ns_obj])[0]
    _assert_results_identical([got], [want])
    meta = got.resultants[0].obj["metadata"]
    assert meta["name"] == "web-pod"  # lowercased mock name
    assert meta["namespace"] == "real-ns"  # ns object wins
    assert meta["ownerReferences"] == [{
        "apiVersion": "apps/v1", "kind": "Deployment", "name": "WEB",
        "uid": ""}]

    # no Namespace object: the parent's namespace carries over
    got = stage.expand_batch([copy.deepcopy(base)], [None])[0]
    want = _ref_expand_batch(es, [base], [None])[0]
    _assert_results_identical([got], [want])
    assert got.resultants[0].obj["metadata"]["namespace"] == "shadowed"

    # EMPTY Namespace object (gator's cluster-scoped quirk): the
    # namespace key is POPPED off the resultant
    got = stage.expand_batch([copy.deepcopy(base)], [{}])[0]
    want = _ref_expand_batch(es, [base], [{}])[0]
    _assert_results_identical([got], [want])
    assert "namespace" not in got.resultants[0].obj["metadata"]


def _nest(levels):
    """A base whose spec.template nests ``levels`` deep, so a
    self-recursive template expands ``levels`` generations."""
    node = {"spec": {"leaf": True}}
    for _ in range(levels):
        node = {"spec": {"template": node}}
    return {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "recur"}, **node}


def test_depth_cap_enforced_identically():
    """A self-recursive template (generated GVK re-enters its own
    applyTo) past the cap voids the base with the reference's exact
    message; below the cap both walks agree — here on the identical
    missing-source error when the nesting bottoms out."""
    es = ExpansionSystem()
    # apps/v1 Deployment → apps/v1 Deployment: every resultant is
    # itself a generator, recursion runs until the cap
    es.upsert_template(_template("self", "Deployment", "Deployment",
                                 group_to="apps"))

    deep = _nest(MAX_RECURSION_DEPTH + 4)
    got = ExpansionStage(es).expand_batch([copy.deepcopy(deep)])[0]
    want = _ref_expand_batch(es, [deep])[0]
    assert want.error == (f"maximum recursion depth of "
                          f"{MAX_RECURSION_DEPTH} reached")
    _assert_results_identical([got], [want])

    # below the cap the chain bottoms out on a generation with no
    # spec.template: BOTH walks void the base with the same
    # missing-source error (recursion error semantics, not just depth)
    shallow = _nest(5)
    got = ExpansionStage(es).expand_batch([copy.deepcopy(shallow)])[0]
    want = _ref_expand_batch(es, [shallow])[0]
    assert want.error and "could not find source field" in want.error
    _assert_results_identical([got], [want])


def test_depth_cap_generated_gvk_needs_matching_group():
    """The chain above only recurses because the generated GVK
    re-enters the template's applyTo — with group "" the resultant is a
    v1 Deployment, does NOT re-match apps/v1, and a 40-deep nest stays
    one generation (no cap, no error)."""
    es = ExpansionSystem()
    es.upsert_template(_template("once", "Deployment", "Deployment"))
    one = ExpansionStage(es).expand_batch([_nest(40)])[0]
    ref = _ref_expand_batch(es, [_nest(40)])[0]
    _assert_results_identical([one], [ref])
    assert one.error is None
    assert len(one.resultants) == 1


def test_nested_generator_recursion_order():
    """Deployment → ReplicaSet → Pod through two templates: resultants
    arrive in the reference's depth-first output order (the child's
    subtree before the children list), with mutation applied per level
    BEFORE the next level expands."""
    system = MutationSystem()
    # this mutator rewrites the subtree the NESTED generator extracts:
    # level ordering is observable, not cosmetic
    system.upsert_unstructured(_assign("stamp", "spec.stamped", True))
    es = ExpansionSystem(mutation_system=system)
    es.upsert_template(_template("deploy-rs", "Deployment", "ReplicaSet"))
    es.upsert_template(_template("rs-pod", "ReplicaSet", "Pod",
                                 group_from="", enforcement="dryrun"))
    base = {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "d"},
            "spec": {"template": {
                "metadata": {"labels": {"tier": "rs"}},
                "spec": {"template": {
                    "metadata": {"labels": {"tier": "pod"}},
                    "spec": {"containers": [{"name": "c"}]}}}}}}
    got = ExpansionStage(es).expand_batch([copy.deepcopy(base)])[0]
    want = _ref_expand_batch(es, [base])[0]
    _assert_results_identical([got], [want])
    kinds = [r.obj["kind"] for r in got.resultants]
    assert kinds == ["Pod", "ReplicaSet"]  # subtree first, then child
    # the Pod was extracted from the MUTATED ReplicaSet and then
    # mutated itself
    assert got.resultants[0].obj["spec"]["stamped"] is True
    assert got.resultants[1].obj["spec"]["stamped"] is True
    assert got.resultants[0].obj["metadata"]["name"] == "web-replicaset-pod"
    assert [r.enforcement_action for r in got.resultants] == ["dryrun", ""]


# --- the audit generator stage --------------------------------------------

PRIV_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8snoprivileged"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sNoPrivileged"}}},
        "targets": [{
            "target": "admission.k8s.io",
            "rego": """
package k8snoprivileged

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  c.securityContext.privileged
  msg := sprintf("privileged container %v", [c.name])
}
""",
        }],
    },
}

PRIV_CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sNoPrivileged", "metadata": {"name": "no-priv"},
    "spec": {"match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]}},
}


def test_audit_expand_generated_prefix_and_override():
    """`--audit-expand`: a Deployment whose pod template is privileged
    produces a violation on the IMPLIED Pod — `[Implied by <template>]`
    prefix, the template's enforcementAction override, counted in
    totals — while the expand-off sweep sees nothing."""
    client = Client(target=K8sValidationTarget(), drivers=[RegoDriver()],
                    enforcement_points=[AUDIT_EP])
    client.add_template(PRIV_TEMPLATE)
    client.add_constraint(PRIV_CONSTRAINT)
    es = ExpansionSystem(mutation_system=MutationSystem())
    es.upsert_template(_template("expand-deployments", "Deployment",
                                 "Pod", enforcement="warn"))
    objects = [_deployment("web", ns="prod", priv=True),
               {"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "prod"}}]

    def run(expand):
        return AuditManager(
            client, lister=lambda: iter(copy.deepcopy(objects)),
            config=AuditConfig(chunk_size=16, pipeline="off",
                               expand_generated=expand),
            expansion_system=es,
        ).audit()

    off = run(False)
    assert sum(off.total_violations.values()) == 0

    on = run(True)
    key = ("K8sNoPrivileged", "no-priv")
    assert on.total_violations[key] == 1
    v = on.kept[key][0]
    assert v.message.startswith(CHILD_MSG_PREFIX % "expand-deployments")
    assert "privileged container app" in v.message
    assert v.enforcement_action == "warn"  # the template's override
    assert v.kind == "Pod" and v.name == "web-pod"
    assert v.namespace == "prod"


class _RefStage:
    """Recursive-reference drop-in for the batched ExpansionStage."""

    def __init__(self, es):
        self.es = es

    def expand_batch(self, bases, namespaces=None, source=""):
        return _ref_expand_batch(self.es, bases, namespaces)


@pytest.fixture(scope="module")
def library_corpus():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client)
    objects = make_cluster_objects(140, seed=43)
    evaluator = ShardedEvaluator(tpu, make_mesh(), violations_limit=20)
    return client, objects, evaluator


def _expansion_system():
    system = MutationSystem()
    system.upsert_unstructured(_assign(
        "nonroot", "spec.securityContext.runAsNonRoot", True))
    es = ExpansionSystem(mutation_system=system)
    es.upsert_template(_template("expand-deployments", "Deployment",
                                 "Pod", enforcement="warn"))
    return es


def _mgr(client, evaluator, objects, es, **cfg_kw):
    cfg_kw.setdefault("chunk_size", 64)
    cfg_kw.setdefault("exact_totals", False)
    cfg_kw.setdefault("pipeline", "off")
    cfg_kw.setdefault("expand_generated", True)
    lister = (objects if callable(objects)
              else (lambda: iter(copy.deepcopy(objects))))
    return AuditManager(client, lister=lister,
                        config=AuditConfig(**cfg_kw),
                        evaluator=evaluator, expansion_system=es)


def test_audit_generator_stage_differential_library(library_corpus):
    """THE audit-stage differential: the relist sweep with the batched
    expansion stage equals the same sweep with the recursive-reference
    stage bit-identically over the library corpus (device grid for
    lowered kinds, driver lane for the rest, Generated mutation
    applied) — and the generated rows really contribute verdicts."""
    client, objects, evaluator = library_corpus
    es = _expansion_system()

    batched = _mgr(client, evaluator, objects, es).audit()

    ref_mgr = _mgr(client, evaluator, objects, es)
    ref_mgr._expansion_stage = _RefStage(es)
    reference = ref_mgr.audit()

    diff = AuditManager._verdicts_differ_canonical(
        batched.kept, batched.total_violations,
        reference.kept, reference.total_violations, 20)
    assert diff is None, diff

    plain = _mgr(client, evaluator, objects, es,
                 expand_generated=False).audit()
    assert sum(batched.total_violations.values()) > \
        sum(plain.total_violations.values()), \
        "the generator stage added no verdicts — vacuous differential"
    # implied-Pod violations carry the prefix + override
    gen = [v for vs in batched.kept.values() for v in vs
           if v.message.startswith("[Implied by")]
    assert gen and all(v.enforcement_action == "warn" for v in gen)


def test_snapshot_generated_verdicts_track_churn(library_corpus):
    """Snapshot mode: generated verdicts live per parent gid and ride
    the dirty set — full pass, post-churn tick (modified/deleted/added
    generators), and the built-in resync differential all equal a fresh
    relist with the same expansion stage."""
    client, objects, evaluator = library_corpus
    es = _expansion_system()
    cluster = FakeCluster()
    for o in objects:
        cluster.apply(copy.deepcopy(o))

    def lister():
        return iter(cluster.list())

    snapshot = ClusterSnapshot(evaluator, SnapshotConfig())
    snap_mgr = AuditManager(
        client, lister=lister,
        config=AuditConfig(audit_source="snapshot", chunk_size=64,
                           exact_totals=False, pipeline="off",
                           expand_generated=True, resync_every=0),
        evaluator=evaluator, snapshot=snapshot, expansion_system=es)
    relist_mgr = _mgr(client, evaluator, lister, es)

    def assert_identical(snap_run):
        relist_run = relist_mgr.audit()
        diff = AuditManager._verdicts_differ_canonical(
            snap_run.kept, snap_run.total_violations,
            relist_run.kept, relist_run.total_violations, 20)
        assert diff is None, diff

    ingester = WatchIngester(snapshot, cluster,
                             gvks_of(cluster.list())).start()
    try:
        first = snap_mgr.audit()  # full pass builds generated verdicts
        assert_identical(first)
        assert any(v.message.startswith("[Implied by")
                   for vs in first.kept.values() for v in vs)

        # churn: a generator's pod template changes (its generated
        # verdicts must recompute), one generator disappears, a fresh
        # one appears
        deps = [o for o in cluster.list()
                if o.get("kind") == "Deployment"]
        assert len(deps) >= 2, "corpus must contain generators"
        mod = copy.deepcopy(deps[0])
        tmpl = mod["spec"].setdefault("template", {})
        tmpl.setdefault("spec", {})["hostPID"] = True
        tmpl.setdefault("metadata", {}).setdefault(
            "labels", {})["churn"] = "1"
        cluster.apply(mod)
        cluster.delete(deps[1])
        cluster.apply(_deployment("fresh-gen", ns="default", priv=True))
        ingester.pump()
        assert snapshot.dirty_count() > 0
        assert_identical(snap_mgr.audit_tick())  # O(churn) tick

        # the built-in resync differential (reference sweep expands too)
        resync_run = snap_mgr.audit_resync()
        assert snap_mgr.last_resync_diff is None, snap_mgr.last_resync_diff
        assert not resync_run.incomplete
    finally:
        ingester.stop()


# --- gator expand CLI lanes -----------------------------------------------

def test_gator_expand_differential_lane(tmp_path, capsys):
    import json

    import yaml

    from gatekeeper_tpu.gator.expand_cmd import run_cli

    docs = [
        _template("expand-deployments", "Deployment", "Pod",
                  enforcement="warn"),
        _assign("nonroot", "spec.securityContext.runAsNonRoot", True),
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": "prod"}},
        _deployment("web", ns="prod", priv=True),
        _deployment("bare"),
    ]
    path = tmp_path / "input.yaml"
    path.write_text(yaml.safe_dump_all(docs))
    assert run_cli(["-f", str(path), "--lane", "differential",
                    "--format", "json"]) == 0
    out = capsys.readouterr()
    assert "differential: batched lane identical" in out.err
    got = json.loads(out.out)
    # the host walk, run independently, produced the same documents
    assert run_cli(["-f", str(path), "--lane", "host",
                    "--format", "json"]) == 0
    want = json.loads(capsys.readouterr().out)
    assert got == want
    assert any(o.get("metadata", {}).get("name") == "web-pod"
               for o in got)
