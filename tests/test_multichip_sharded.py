"""Simulated multi-chip sharded-chunk sweep parity (ISSUE 14).

Promotes the MULTICHIP dryrun to a real ``audit`` pass: a subprocess
pinned to a 4-device virtual CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=4``) runs the full
library corpus through the sharded-chunk scheduler
(``AuditConfig.shard_chunks``) on a 4-way data mesh AND on a 1-device
mesh, and the verdicts — totals, kept violations, rendered messages —
must be bit-identical.  Slow lane: the subprocess pays a full library
compile; tier-1 keeps the in-process 1-device scheduler-path test in
tests/test_flatten_lanes.py.
"""

import json
import os
import subprocess
import sys

import pytest

_SCRIPT = r"""
import json, sys

from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects

import jax
assert len(jax.devices()) == 4, jax.devices()

cel = CELDriver()
tpu = TpuDriver(cel_driver=cel)
client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                enforcement_points=[WEBHOOK_EP, AUDIT_EP])
load_library(client)
objects = make_cluster_objects(600, seed=11)
for o in objects:
    if o.get("kind") == "Ingress":
        client.add_data(o)


def signature(run):
    return (
        sorted((list(k), v) for k, v in run.total_violations.items()),
        sorted((list(k), [(v.message, v.kind, v.name, v.namespace,
                           v.enforcement_action) for v in vs])
               for k, vs in run.kept.items()),
    )


def audit(n_devices, shard_chunks):
    mgr = AuditManager(
        client, lister=lambda: iter(objects),
        config=AuditConfig(chunk_size=64, exact_totals=False,
                           pipeline="off", shard_chunks=shard_chunks),
        evaluator=ShardedEvaluator(tpu, make_mesh(n_devices),
                                   violations_limit=20),
    )
    return mgr.audit()

single = audit(1, 0)
sharded = audit(4, 4)
print(json.dumps({
    "violations": sum(single.total_violations.values()),
    "identical": signature(single) == signature(sharded),
    "n_devices": sharded.n_devices,
    "shard_chunks": sharded.shard_chunks,
}))
"""


@pytest.mark.slow
def test_sharded_chunk_sweep_4dev_parity_subprocess():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    proc = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["violations"] > 0
    assert out["n_devices"] == 4 and out["shard_chunks"] == 4
    assert out["identical"], out
