"""Test configuration: force an 8-device virtual CPU mesh.

Tests must run without TPU hardware; multi-chip sharding paths are exercised
on a virtual CPU mesh (the driver separately dry-runs the multichip path via
``__graft_entry__.dryrun_multichip``).

Note: this environment's axon TPU plugin prepends itself to
``jax_platforms`` regardless of the JAX_PLATFORMS env var, so the env var
alone is NOT enough — the config must be updated explicitly before any
backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}"
)

import pytest  # noqa: E402

REFERENCE = "/root/reference"


@pytest.fixture(scope="session")
def reference_dir():
    return REFERENCE
