"""Test configuration: force an 8-device virtual CPU mesh.

Tests must run without TPU hardware; multi-chip sharding paths are exercised on
a virtual CPU mesh (the driver separately dry-runs the multichip path via
``__graft_entry__.dryrun_multichip``).  Env must be set before jax imports.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

REFERENCE = "/root/reference"


@pytest.fixture(scope="session")
def reference_dir():
    return REFERENCE
