"""Watch-driven incremental audit acceptance tests (ISSUE 6).

1. Row-stable global ids (``ops.flatten.RowIdMap``) — unit-tested
   independently of the snapshot.
2. Mock-apiserver watch bookmarks + forced 410-Gone compaction hook, so
   relist recovery is testable without a real apiserver.
3. ``fault_point("kube.watch")`` chaos: injected 410 exercises the
   relist-recovery path, repeated stream errors exercise the watch
   circuit breaker — events flow again after the faults clear.
4. The churn differential: seeded adds/modifies/deletes over the library
   corpus where incremental snapshot verdicts are asserted bit-identical
   to a fresh relist after every burst, the resync differential proves
   column-level identity, compaction preserves row ids, and a chaos run
   with ``kube.watch`` faults active stays identical end-to-end.
5. The webhook's warm namespace cache reads resident snapshot rows.
6. A ``tools/bench_snapshot.py`` smoke invocation, so the bench script
   cannot rot.
"""

import copy
import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.ops.flatten import RowIdMap
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.snapshot import (ClusterSnapshot, SnapshotConfig,
                                     WatchIngester, gvks_of)
from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig
from gatekeeper_tpu.sync.mock_apiserver import MockApiServer
from gatekeeper_tpu.sync.source import ADDED, DELETED, FakeCluster
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import (iter_cluster_objects,
                                            load_library,
                                            make_cluster_objects)

ROOT = os.path.join(os.path.dirname(__file__), "..")
POD_GVK = ("", "v1", "Pod")


def pod(name, ns="default", labels=None):
    meta = {"name": name, "namespace": ns}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
            "spec": {"containers": [{"name": "c", "image": "x"}]}}


def wait_for(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


# --- 1. RowIdMap ----------------------------------------------------------

def test_rowid_map_stable_and_monotone():
    m = RowIdMap()
    a, created_a = m.assign("uid-a")
    b, created_b = m.assign("uid-b")
    assert (a, created_a) == (0, True)
    assert (b, created_b) == (1, True)
    # re-assign of a known uid is a lookup, not a new id
    assert m.assign("uid-a") == (0, False)
    assert m.get("uid-b") == 1
    assert "uid-a" in m and "uid-zzz" not in m
    assert m.uids() == ["uid-a", "uid-b"]
    assert len(m) == 2 and m.high_water == 2


def test_rowid_map_forget_retires_ids_forever():
    m = RowIdMap()
    m.assign("x")
    m.assign("y")
    assert m.forget("x") == 0
    assert m.forget("x") is None  # idempotent
    assert "x" not in m and len(m) == 1
    # a re-created object is a NEW row: fresh id, never a reissue
    nx, created = m.assign("x")
    assert created and nx == 2
    assert m.high_water == 3


# --- 2. mock apiserver: bookmarks + compaction hook ----------------------

@pytest.fixture()
def server():
    srv = MockApiServer().start()
    yield srv
    srv.stop()


@pytest.fixture()
def kube(server):
    kc = KubeCluster(KubeConfig(server=server.url), page_limit=50,
                     watch_backoff_s=0.05, watch_timeout_s=20.0,
                     watch_breaker_threshold=2,
                     watch_breaker_reset_s=0.1)
    yield kc
    kc.close()


def test_mock_watch_stream_replays_cache_then_bookmarks(server):
    server.put_object(pod("p0"))
    resp = urllib.request.urlopen(
        f"{server.url}/api/v1/pods?watch=1&resourceVersion=0", timeout=5)
    try:
        lines = iter(resp)
        first = json.loads(next(lines))
        second = json.loads(next(lines))
    finally:
        resp.close()
    # watch-cache replay: the event missed since rv=0 streams first...
    assert first["type"] == "ADDED"
    assert first["object"]["metadata"]["name"] == "p0"
    # ...then the sync BOOKMARK carrying the post-replay rv
    assert second["type"] == "BOOKMARK"
    assert int(second["object"]["metadata"]["resourceVersion"]) >= 1


def test_mock_compaction_hook_answers_410_for_old_rv(server):
    for i in range(3):
        server.put_object(pod(f"p{i}"))
    server.compact()  # compaction floor = current rv
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"{server.url}/api/v1/pods?watch=1&resourceVersion=1",
            timeout=5)
    assert ei.value.code == 410
    # a watch from at/after the floor is fine (only history compacted)
    resp = urllib.request.urlopen(
        f"{server.url}/api/v1/pods?watch=1&resourceVersion=999999",
        timeout=5)
    resp.close()


def test_compact_plus_break_forces_relist_recovery(server, kube):
    """compact() + break_watches() = the apiserver compacted past our
    resume rv: the client relists and surfaces the outage-window churn
    (a DELETED diff for the vanished object)."""
    server.put_object(pod("stay"))
    server.put_object(pod("goner"))
    events = []
    kube.subscribe(POD_GVK, events.append, replay=True)
    assert wait_for(lambda: len(
        [e for e in events if e.type == ADDED]) >= 2)
    with server._lock:
        server._objects.pop(("Pod", "default", "goner"))
    server.compact()
    server.break_watches("Pod")
    assert wait_for(lambda: any(
        e.type == DELETED and e.obj["metadata"]["name"] == "goner"
        for e in events))
    server.put_object(pod("after"))  # the recovered stream is live
    assert wait_for(lambda: any(
        e.type == ADDED and e.obj["metadata"]["name"] == "after"
        for e in events))


# --- 3. kube.watch chaos: injected 410 + breaker --------------------------

def test_kube_watch_fault_410_replays_through_relist(server, kube):
    server.put_object(pod("a"))
    events = []
    plan = FaultPlan([{"site": "kube.watch", "mode": "error",
                       "status": 410, "times": 1}])
    with inject(plan):
        kube.subscribe(POD_GVK, events.append, replay=True)
        assert wait_for(lambda: any(
            e.type == ADDED and e.obj["metadata"]["name"] == "a"
            for e in events))
        assert wait_for(lambda: plan.fired("kube.watch") >= 1)
        server.put_object(pod("post-410"))
        assert wait_for(lambda: any(
            e.obj["metadata"]["name"] == "post-410" for e in events))
    # the injected 410 is an ANSWER, not a failure: breaker stays closed
    assert kube._watch_breaker.allow()


def test_kube_watch_fault_errors_trip_breaker_then_recover(server, kube):
    server.put_object(pod("b"))
    events = []
    plan = FaultPlan([{"site": "kube.watch", "mode": "error",
                       "status": 500, "times": 3}])
    with inject(plan):
        kube.subscribe(POD_GVK, events.append, replay=True)
        assert wait_for(lambda: plan.fired("kube.watch") >= 3,
                        timeout=15.0)
    # threshold 2 < 3 consecutive failures: the breaker opened and paced
    # the reconnects; once faults clear the stream heals and events flow
    server.put_object(pod("healed"))
    assert wait_for(lambda: any(
        e.obj["metadata"]["name"] == "healed" for e in events),
        timeout=15.0)


# --- 4. the churn differential --------------------------------------------

def _library_client():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client)
    return client, tpu


@pytest.fixture(scope="module")
def corpus():
    client, tpu = _library_client()
    objects = make_cluster_objects(150, seed=13)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
    evaluator = ShardedEvaluator(tpu, make_mesh(), violations_limit=20)
    return client, tpu, objects, evaluator


def _fake_cluster(objects):
    cluster = FakeCluster()
    for o in objects:
        cluster.apply(copy.deepcopy(o))
    return cluster


def _managers(client, evaluator, cluster, snap_cfg=None, **cfg_kw):
    cfg_kw.setdefault("exact_totals", False)
    cfg_kw.setdefault("chunk_size", 64)
    cfg_kw.setdefault("pipeline", "off")

    def lister():
        return iter(cluster.list())

    snapshot = ClusterSnapshot(evaluator, snap_cfg or SnapshotConfig())
    snap_mgr = AuditManager(
        client, lister=lister,
        config=AuditConfig(audit_source="snapshot", **cfg_kw),
        evaluator=evaluator, snapshot=snapshot)
    relist_mgr = AuditManager(
        client, lister=lister, config=AuditConfig(**cfg_kw),
        evaluator=evaluator)
    return snapshot, snap_mgr, relist_mgr


def _assert_identical(snap_run, relist_run, limit=20):
    assert snap_run.total_objects == relist_run.total_objects
    diff = AuditManager._verdicts_differ_canonical(
        snap_run.kept, snap_run.total_violations,
        relist_run.kept, relist_run.total_violations, limit)
    assert diff is None, diff


def _churn(cluster, objects, fresh_iter, round_i, n_events, seed_names):
    """One seeded burst: ~1/3 modify, ~1/3 add, ~1/3 delete."""
    for j in range(n_events):
        which = j % 3
        k = round_i * n_events + j
        if which == 0:
            o = copy.deepcopy(objects[k % len(objects)])
            o.setdefault("metadata", {}).setdefault(
                "labels", {})["churn"] = f"r{round_i}-{j}"
            cluster.apply(o)
        elif which == 1:
            o = next(fresh_iter)
            o["metadata"]["name"] += f"-churn-{round_i}-{j}"
            cluster.apply(o)
        else:
            name = seed_names[k % len(seed_names)]
            victim = next((ob for ob in cluster.list()
                           if ob["metadata"].get("name") == name), None)
            if victim is not None:
                cluster.delete(victim)


def test_snapshot_full_pass_identical_to_relist(corpus):
    client, _tpu, objects, evaluator = corpus
    cluster = _fake_cluster(objects)
    snapshot, snap_mgr, relist_mgr = _managers(client, evaluator, cluster)
    snap_run = snap_mgr.audit()  # builds the snapshot, evaluates all rows
    relist_run = relist_mgr.audit()
    assert sum(relist_run.total_violations.values()) > 0  # non-vacuous
    _assert_identical(snap_run, relist_run)
    assert snapshot.stats()["rows"] == len(cluster.list())
    # a second full pass re-evaluates resident columns: still identical
    _assert_identical(snap_mgr.audit(), relist_run)


def test_snapshot_full_pass_identical_exact_totals(corpus):
    """The exact-totals lane (render every hit at fold time) agrees with
    a fresh relist in the same mode."""
    client, _tpu, objects, evaluator = corpus
    cluster = _fake_cluster(objects[:90])
    _snap, snap_mgr, relist_mgr = _managers(
        client, evaluator, cluster, exact_totals=True)
    _assert_identical(snap_mgr.audit(), relist_mgr.audit())


def test_churn_differential_bit_identical_every_burst(corpus):
    """THE acceptance criterion: seeded adds/modifies/deletes, and after
    every burst the incremental tick's verdicts equal a fresh relist
    sweep; the tick evaluates only the dirty rows (O(churn)); the resync
    differential proves per-row column identity at the end."""
    client, _tpu, objects, evaluator = corpus
    cluster = _fake_cluster(objects)
    snapshot, snap_mgr, relist_mgr = _managers(client, evaluator, cluster)
    ingester = WatchIngester(snapshot, cluster,
                            gvks_of(cluster.list())).start()
    try:
        snap_mgr.audit()  # initial build + full evaluation
        names = [o["metadata"]["name"] for o in objects]
        fresh = iter_cluster_objects(200, seed=77)
        for round_i in range(4):
            _churn(cluster, objects, fresh, round_i, 15, names)
            ingester.pump()
            dirty = snapshot.dirty_count()
            assert 0 < dirty < snapshot.live_count()  # O(churn), not O(n)
            evaluated0 = snap_mgr.perf.get("snapshot_rows_evaluated", 0)
            tick_run = snap_mgr.audit_tick()
            evaluated = snap_mgr.perf["snapshot_rows_evaluated"] \
                - evaluated0
            assert evaluated <= dirty
            relist_run = relist_mgr.audit()
            _assert_identical(tick_run, relist_run)
        assert snapshot.resync_differential(
            lambda: iter(cluster.list())) is None
        resync_run = snap_mgr.audit_resync()
        assert snap_mgr.last_resync_diff is None
        assert not resync_run.incomplete
    finally:
        ingester.stop()


def test_compaction_preserves_row_ids_and_verdicts(corpus):
    """A delete-heavy churn pushes tombstones past the threshold: the
    stores compact (positions move, ids do not) and the next tick +
    resync are still bit-identical to a fresh relist."""
    client, _tpu, objects, evaluator = corpus
    cluster = _fake_cluster(objects[:100])
    snapshot, snap_mgr, relist_mgr = _managers(
        client, evaluator, cluster,
        snap_cfg=SnapshotConfig(compact_tombstone_fraction=0.15,
                                compact_min_rows=8))
    ingester = WatchIngester(snapshot, cluster,
                            gvks_of(cluster.list())).start()
    try:
        snap_mgr.audit()
        ids_before = {k: snapshot.ids.get(k)
                      for k in snapshot.ids.uids()}
        # delete a third of the cluster
        victims = cluster.list()[::3]
        for v in victims:
            cluster.delete(v)
        ingester.pump()
        # compaction fired somewhere: no store is left over-threshold
        for store in snapshot._groups.values():
            assert not store.needs_compaction(snapshot.config)
        # surviving keys keep their EXACT pre-compaction ids
        for key in snapshot.ids.uids():
            assert snapshot.ids.get(key) == ids_before[key]
        tick_run = snap_mgr.audit_tick()
        _assert_identical(tick_run, relist_mgr.audit())
        assert snapshot.resync_differential(
            lambda: iter(cluster.list())) is None
    finally:
        ingester.stop()


def test_resync_divergence_invalidates_and_rebuilds(corpus):
    """A corrupted resident row makes the resync differential report a
    difference: the run is marked incomplete, the snapshot invalidated,
    and the next resync (post-rebuild) is clean again."""
    client, _tpu, objects, evaluator = corpus
    cluster = _fake_cluster(objects[:60])
    snapshot, snap_mgr, _relist = _managers(client, evaluator, cluster)
    snap_mgr.audit()
    store = next(s for s in snapshot.routed_stores() if s.n_rows)
    store.batch.kind_sid[0] += 1  # flip one identity column value
    run = snap_mgr.audit_resync()
    assert snap_mgr.last_resync_diff is not None
    assert run.incomplete and snapshot.stale
    run2 = snap_mgr.audit_resync()  # rebuilds first, then proves identity
    assert snap_mgr.last_resync_diff is None
    assert not run2.incomplete and not snapshot.stale


def test_chaos_churn_over_kube_watch_faults(corpus, server):
    """The chaos acceptance run: the snapshot is fed by a REAL KubeCluster
    watch against the mock apiserver while ``kube.watch`` faults (an
    injected 410 and transient stream errors) plus a forced server-side
    compaction break the stream mid-churn — the incremental verdicts
    still match a fresh relist bit-identically."""
    client, _tpu, objects, evaluator = corpus
    corpus_objs = [copy.deepcopy(o) for o in objects[:80]]
    for o in corpus_objs:
        server.put_object(o)
    kube = KubeCluster(KubeConfig(server=server.url), page_limit=200,
                       watch_backoff_s=0.05, watch_timeout_s=20.0,
                       watch_breaker_threshold=3,
                       watch_breaker_reset_s=0.1)
    gvks = gvks_of(corpus_objs)

    def lister():
        return iter(o for gvk in gvks for o in kube.list(gvk))

    snapshot = ClusterSnapshot(evaluator, SnapshotConfig())
    cfg = dict(exact_totals=False, chunk_size=64, pipeline="off")
    snap_mgr = AuditManager(
        client, lister=lister,
        config=AuditConfig(audit_source="snapshot", **cfg),
        evaluator=evaluator, snapshot=snapshot)
    relist_mgr = AuditManager(client, lister=lister,
                              config=AuditConfig(**cfg),
                              evaluator=evaluator)
    plan = FaultPlan([
        {"site": "kube.watch", "mode": "error", "status": 410,
         "after": len(gvks), "every": 7, "times": 2},
        {"site": "kube.watch", "mode": "error", "status": 500,
         "after": len(gvks) + 3, "every": 11, "times": 2},
    ])
    ingester = None
    try:
        with inject(plan):
            ingester = WatchIngester(snapshot, kube, gvks).start()
            snap_mgr.audit()
            # churn behind the watch: modify + add + delete
            for j, o in enumerate(corpus_objs[:12]):
                o2 = copy.deepcopy(o)
                o2.setdefault("metadata", {}).setdefault(
                    "labels", {})["churn"] = f"c{j}"
                server.put_object(o2)
            extra = [o for o in iter_cluster_objects(6, seed=5)]
            for j, o in enumerate(extra):
                o["metadata"]["name"] += f"-chaos-{j}"
                server.put_object(o)
            for o in corpus_objs[60:66]:
                server.delete_object(o["kind"],
                                     o["metadata"].get("namespace", ""),
                                     o["metadata"]["name"])
            server.compact()
            for kind in sorted({o["kind"] for o in corpus_objs[:20]}):
                server.break_watches(kind)
            expected = sum(len(kube.list(g)) for g in gvks)

            def caught_up():
                ingester.pump()
                return (snapshot.live_count() == expected
                        and snapshot.pending_count() == 0)

            assert wait_for(caught_up, timeout=30.0)
            tick_run = snap_mgr.audit_tick()
            _assert_identical(tick_run, relist_mgr.audit())
            assert snapshot.resync_differential(lister) is None
        assert plan.fired("kube.watch") >= 2  # the chaos actually bit
    finally:
        if ingester is not None:
            ingester.stop()
        kube.close()


# --- 4c. rotated resync (ISSUE 10 satellite) -------------------------------

def test_resync_rotation_partitions_keyspace_and_stays_clean(corpus):
    """``--snapshot-resync-rotate K``: the K key-hash slices partition
    the keyspace exactly (every key in one slice, no slice empty at
    this corpus size), each rotated resync proves only its slice, and a
    clean snapshot passes a full rotation."""
    from gatekeeper_tpu.snapshot.store import obj_key, resync_slice

    client, _tpu, objects, evaluator = corpus
    cluster = _fake_cluster(objects[:90])
    snapshot, snap_mgr, _relist = _managers(client, evaluator, cluster,
                                            resync_rotate=4)
    snap_mgr.audit()
    keys = [obj_key(o) for o in cluster.list()]
    per_slice = [sum(1 for k in keys if resync_slice(k, p, 4))
                 for p in range(4)]
    assert sum(per_slice) == len(keys)  # a partition, not a sample
    assert all(n > 0 for n in per_slice)
    for _ in range(4):  # one full rotation: every slice proves clean
        run = snap_mgr.audit_resync()
        assert snap_mgr.last_resync_diff is None
        assert not run.incomplete
        assert snap_mgr.perf["resync_scope"] == 0.25


def test_resync_rotation_catches_divergence_within_k_intervals(corpus):
    """Corrupt ONE resident row: the rotated resync flags it no later
    than the pass whose slice holds the row (within K intervals),
    invalidates the snapshot, and the post-rebuild rotation is clean."""
    client, _tpu, objects, evaluator = corpus
    cluster = _fake_cluster(objects[:60])
    snapshot, snap_mgr, _relist = _managers(client, evaluator, cluster,
                                            resync_rotate=3)
    snap_mgr.audit()
    store = next(s for s in snapshot.routed_stores() if s.n_rows)
    store.batch.kind_sid[0] += 1  # flip one identity column value
    caught_at = None
    for i in range(3):
        snap_mgr.audit_resync()
        if snap_mgr.last_resync_diff is not None:
            caught_at = i
            break
    assert caught_at is not None, \
        "a full rotation must visit the corrupted row's slice"
    assert snapshot.stale  # invalidated: the next sweep rebuilds
    snap_mgr.audit()  # rebuild
    for _ in range(3):  # post-rebuild rotation proves clean again
        run = snap_mgr.audit_resync()
        assert snap_mgr.last_resync_diff is None
        assert not run.incomplete


# --- 5. webhook warm cache -------------------------------------------------

def test_webhook_namespace_lookup_served_from_snapshot(corpus):
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    client, _tpu, _objects, evaluator = corpus
    ns_obj = {"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": "prod",
                           "labels": {"env": "production"}}}
    cluster = FakeCluster()
    cluster.apply(ns_obj)
    snapshot = ClusterSnapshot(evaluator, SnapshotConfig())
    snapshot.set_constraints([c for c in client.constraints()
                              if c.actions_for(AUDIT_EP)])
    snapshot.rebuild(lambda: iter(cluster.list()))
    calls = []

    def fallback(name):
        calls.append(name)
        return None

    handler = ValidationHandler(client, namespace_lookup=fallback,
                                snapshot=snapshot)
    got = handler._lookup_namespace("prod")
    assert got["metadata"]["labels"] == {"env": "production"}
    assert calls == []  # warm hit: the apiserver-backed source never ran
    # unknown namespace falls through to the source
    assert handler._lookup_namespace("nope") is None
    assert calls == ["nope"]
    # a STALE snapshot never answers (rebuild pending): fall through
    snapshot.invalidate()
    handler._lookup_namespace("prod")
    assert calls == ["nope", "prod"]


# --- 6. bench smoke --------------------------------------------------------

@pytest.mark.slow  # tier-1 wall budget (PR 16): 40s bench smoke; the
# snapshot contracts it exercises are pinned by the tests above.
def test_bench_snapshot_smoke():
    spec = importlib.util.spec_from_file_location(
        "bench_snapshot", os.path.join(ROOT, "tools", "bench_snapshot.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_bench(n_objects=100, churn_fraction=0.05, ticks=1,
                        chunk_size=64, write=False, spill=True)
    assert rec["resync_ok"] is True
    assert rec["snapshot_rows"] > 0
    assert rec["tick_s_median"] > 0
    assert rec["tick_dirty_rows"][0] <= rec["snapshot_rows"]
    for key in ("relist_sweep_s", "snapshot_full_s",
                "tick_vs_relist_speedup", "full_vs_relist_speedup"):
        assert key in rec
    # the cold-start lane's tier-1 pin: loading resident columns from
    # disk must beat rebuilding them from a relist by 2x even on a tiny
    # corpus (at 20k objects the measured gap is far wider)
    assert rec["spill_boot_vs_relist"] < 0.5, rec["spill_boot_vs_relist"]
    assert rec["spill_bytes"] > 0
