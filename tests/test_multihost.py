"""Two-process multi-host (DCN-shaped) mesh test: the sharded evaluation
plane spans processes via jax.distributed + Gloo CPU collectives
(tests/multihost_worker.py; reference scale-out: sharded audit pods)."""

import os
import socket
import subprocess
import sys

REPO = os.path.join(os.path.dirname(__file__), "..")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_global_mesh_sweep():
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    worker = os.path.join(REPO, "tests", "multihost_worker.py")
    procs = [
        subprocess.Popen([sys.executable, worker, str(i), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, cwd=REPO, env=env)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, err[-2000:]
        outs.append(out)
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("MH_RESULT"):
                _tag, pid, ndev, total = line.split()
                results[int(pid)] = (int(ndev), int(total))
    assert set(results) == {0, 1}, outs
    # both processes saw the 8-device global mesh and agree on the verdict
    assert results[0] == results[1]
    assert results[0][0] == 8
    assert results[0][1] > 0
