"""Worker for the two-process multi-host test (not a pytest module).

Usage: python tests/multihost_worker.py <process_id> <coordinator_port>

Joins a 2-process JAX runtime (4 virtual CPU devices each -> one global
8-device mesh), runs a full ShardedEvaluator sweep over the global mesh,
and prints one line: MH_RESULT <pid> <n_global_devices> <total_violations>.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

pid = int(sys.argv[1])
port = sys.argv[2]

from gatekeeper_tpu.parallel.distributed import (  # noqa: E402
    init_distributed,
    process_info,
)

init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                 local_device_count=4)

import __graft_entry__ as g  # noqa: E402
from gatekeeper_tpu.parallel.sharded import (  # noqa: E402
    ShardedEvaluator,
    make_mesh,
)

_, nproc, local, global_ = process_info()
assert nproc == 2 and local == 4 and global_ == 8, (nproc, local, global_)

tpu = g._build_driver([g._PRIV_TEMPLATE, g._REQ_LABELS_TEMPLATE,
                       g._HOST_NS_TEMPLATE])
cons = g._constraints(n_labels=4)
mesh = make_mesh()  # all GLOBAL devices: the mesh spans both processes
assert mesh.shape["data"] * mesh.shape.get("model", 1) == 8, dict(mesh.shape)
evaluator = ShardedEvaluator(tpu, mesh, violations_limit=5)
# every process feeds the same full batch; the 'data' axis shards globally
pods = g._make_pods(64)
swept = evaluator.sweep(cons, pods)
total = sum(int(c[3].sum()) for c in swept.values())
print(f"MH_RESULT {pid} {global_} {total}", flush=True)
