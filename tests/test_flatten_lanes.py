"""Flatten-lane acceptance tests (ISSUE 4).

1. Three-way lane differential over the full shipped-library union
   schema: py (oracle) vs dict-walking C vs raw c-json produce
   bit-identical columns AND an identical vocabulary.
2. Verdict differential: the audit sweep run with
   ``flatten_lane=raw|dict|py|differential`` yields bit-identical
   totals and kept violations over the library corpus.
3. Raw-bytes ingest: KubeCluster.list_iter yields unparsed RawJSON
   objects split straight out of List page bytes, routable by
   peek_kind, content-identical to the parsed lane.
"""

import json

import numpy as np
import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.ops import native
from gatekeeper_tpu.ops.flatten import (Flattener, Schema, Vocab,
                                         diff_batches)
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.rawjson import (RawJSON, as_raw, backfill_gvk,
                                          peek_kind, split_list_items)
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects

jmod = native.load_json()


def _library_client():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client)
    return client, tpu


@pytest.fixture(scope="module")
def corpus():
    client, tpu = _library_client()
    objects = make_cluster_objects(160, seed=21)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
    return client, tpu, objects


def _union_schema(tpu):
    schema = Schema()
    for kind in tpu.lowered_kinds():
        schema.merge(tpu._programs[kind].program.schema)
    return schema


# --- 1. three-way column differential ---------------------------------

@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_three_way_lane_differential_library_schema(corpus):
    """raw c-json FIRST (creates every interning), then dict-walking C,
    then pure python, all over ONE vocab: every column bit-identical,
    and neither oracle lane interns a single new string — the raw
    kernel's vocabulary is exactly the oracle's."""
    client, tpu, objects = corpus
    schema = _union_schema(tpu)
    vocab = Vocab()

    f_raw = Flattener(schema, vocab, lane="raw")
    b_raw = f_raw.flatten([as_raw(o) for o in objects], pad_n=192)
    assert f_raw.lane_used == "raw"
    vocab_after_raw = len(vocab)

    f_dict = Flattener(schema, vocab, lane="dict")
    b_dict = f_dict.flatten(objects, pad_n=192)
    assert f_dict.lane_used == "dict"

    f_py = Flattener(schema, vocab, lane="py")
    b_py = f_py.flatten(objects, pad_n=192)
    assert f_py.lane_used == "py"

    assert diff_batches(schema, b_raw, b_dict) is None
    assert diff_batches(schema, b_raw, b_py) is None
    # identical vocab: the oracle lanes only ever looked strings up
    assert len(vocab) == vocab_after_raw


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_differential_lane_runs_and_agrees(corpus):
    client, tpu, objects = corpus
    schema = _union_schema(tpu)
    f = Flattener(schema, Vocab(), lane="differential")
    batch = f.flatten([as_raw(o) for o in objects], pad_n=192)
    assert f.lane_used == "differential:raw"
    assert batch.n == 192


def test_differential_lane_catches_divergence():
    """A poisoned batch comparison must raise, not pass silently."""
    schema = _union_schema(_library_client()[1])
    f = Flattener(schema, Vocab(), lane="differential")
    objects = make_cluster_objects(8, seed=3)
    real_diff = diff_batches

    import gatekeeper_tpu.ops.flatten as fl_mod

    orig = fl_mod.diff_batches
    fl_mod.diff_batches = lambda *a: "synthetic divergence"
    try:
        with pytest.raises(RuntimeError, match="synthetic divergence"):
            f.flatten([as_raw(o) for o in objects], pad_n=8)
    finally:
        fl_mod.diff_batches = orig
    assert real_diff is orig


# --- 2. verdict differential across sweep lanes -----------------------

def _audit_with_lane(client, tpu, objects, lane, metrics=None):
    mgr = AuditManager(
        client, lister=lambda: iter(objects),
        config=AuditConfig(chunk_size=64, exact_totals=False,
                           pipeline="off"),
        evaluator=ShardedEvaluator(tpu, make_mesh(), violations_limit=20,
                                   flatten_lane=lane, metrics=metrics),
        metrics=metrics,
    )
    return mgr.audit()


def _signature(run):
    return (
        {k: v for k, v in run.total_violations.items()},
        {k: [(v.message, v.kind, v.name, v.namespace,
              v.enforcement_action) for v in vs]
         for k, vs in run.kept.items()},
    )


def test_sweep_verdicts_identical_across_lanes(corpus):
    """The acceptance differential: raw / dict / py / differential
    sweep lanes produce bit-identical verdicts over the library
    corpus.  The raw lanes see RawJSON input (the lister contract);
    materialization inside the oracle lanes is the lanes' own
    business."""
    client, tpu, objects = corpus
    lanes = ["dict", "py", "differential"]
    if jmod is not None:
        lanes.insert(0, "raw")
    metrics = MetricsRegistry()
    base = None
    for lane in lanes:
        raws = [as_raw(o) for o in objects]
        run = _audit_with_lane(client, tpu, raws, lane, metrics=metrics)
        sig = _signature(run)
        assert sum(sig[0].values()) > 0, "corpus produced no violations"
        if base is None:
            base = sig
        else:
            assert sig == base, f"lane {lane} diverged"
    # the lane counter observed every lane it ran
    for lane in lanes:
        label = {"lane": lane if lane != "differential"
                 else ("differential:raw" if jmod is not None
                       else "differential:dict")}
        assert metrics.get_counter(M.FLATTEN_LANE, label) > 0, label
    assert metrics.get_gauge(M.FLATTEN_OBJECTS_PER_SECOND) > 0


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_sweep_auto_lane_takes_raw_on_rawjson_input(corpus):
    client, tpu, objects = corpus
    metrics = MetricsRegistry()
    run = _audit_with_lane(client, tpu, [as_raw(o) for o in objects],
                           "auto", metrics=metrics)
    assert sum(run.total_violations.values()) > 0
    assert metrics.get_counter(M.FLATTEN_LANE, {"lane": "raw"}) > 0
    assert metrics.get_counter(M.FLATTEN_LANE, {"lane": "dict"}) == 0


# --- 3. raw-bytes list ingest -----------------------------------------

def test_split_list_items_roundtrip():
    page_doc = {
        "apiVersion": "v1", "kind": "PodList",
        "metadata": {"resourceVersion": "42", "continue": "tok"},
        "items": [
            {"metadata": {"name": "a", "labels": {"x": "1"}},
             "spec": {"containers": [{"name": "c,{}[]\""}]}},
            {"metadata": {"name": "b"}, "note": 'tricky "items": ['},
            {},
        ],
    }
    for dumps_kw in ({"separators": (",", ":")}, {"indent": 2}):
        page = json.dumps(page_doc, **dumps_kw).encode()
        spans, envelope = split_list_items(page)
        assert [json.loads(s) for s in spans] == page_doc["items"]
        assert envelope["metadata"]["continue"] == "tok"
        assert envelope["kind"] == "PodList"
        assert envelope["items"] == []


def test_split_list_items_rejects_non_lists():
    with pytest.raises(ValueError):
        split_list_items(b'{"kind":"Pod","metadata":{"name":"x"}}')
    with pytest.raises(ValueError):
        split_list_items(b'{"items":[1,2,"three"]}')


def test_backfill_gvk_setdefault_semantics():
    # absent keys take the defaults
    r = json.loads(backfill_gvk(b'{"metadata":{"name":"x"}}', "v1", "Pod"))
    assert r["apiVersion"] == "v1" and r["kind"] == "Pod"
    assert r["metadata"]["name"] == "x"
    # present keys win (JSON duplicate keys are last-wins)
    r = json.loads(backfill_gvk(
        b'{"apiVersion":"apps/v1","kind":"Deployment"}', "v1", "Pod"))
    assert r["apiVersion"] == "apps/v1" and r["kind"] == "Deployment"
    # empty object stays valid
    assert json.loads(backfill_gvk(b"{}", "v1", "Pod")) == {
        "apiVersion": "v1", "kind": "Pod"}
    # the native parser agrees on the spliced bytes
    if jmod is not None:
        raw = RawJSON(backfill_gvk(b'{"metadata":{"name":"x"}}',
                                   "v1", "Pod"))
        assert peek_kind(raw) == "Pod"
        assert not raw._loaded


def test_kube_list_iter_yields_unparsed_rawjson():
    from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig
    from gatekeeper_tpu.sync.mock_apiserver import MockApiServer

    srv = MockApiServer().start()
    try:
        for i in range(8):
            srv.put_object({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"p{i}", "namespace": "default"},
                "spec": {"containers": [{"name": "c", "image": "x"}]},
            })
        kc = KubeCluster(KubeConfig(server=srv.url), page_limit=3)
        try:
            objs = list(kc.list_iter(("", "v1", "Pod")))
            assert len(objs) == 8
            assert all(isinstance(o, RawJSON) for o in objs)
            # kind routing never parses
            assert all(peek_kind(o) == "Pod" for o in objs)
            assert all(not o._loaded for o in objs)
            # content identical to the parsed lane (materializes now)
            parsed = {o["metadata"]["name"]: o for o in kc.list(
                ("", "v1", "Pod"))}
            for o in objs:
                assert dict(o) == parsed[o["metadata"]["name"]]
            # the parsed-lane opt-out still yields plain dicts
            kc.raw_list = False
            objs2 = list(kc.list_iter(("", "v1", "Pod")))
            assert len(objs2) == 8
            assert not any(isinstance(o, RawJSON) for o in objs2)
        finally:
            kc.close()
    finally:
        srv.stop()


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_kube_raw_ingest_flattens_identically(corpus):
    """End to end: bytes listed from the apiserver, split, routed and
    columnized raw match the dict lane bit for bit."""
    from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig
    from gatekeeper_tpu.sync.mock_apiserver import MockApiServer

    client, tpu, objects = corpus
    pods = [o for o in objects if o.get("kind") == "Pod"][:24]
    srv = MockApiServer().start()
    try:
        for o in pods:
            srv.put_object(o)
        kc = KubeCluster(KubeConfig(server=srv.url), page_limit=5)
        try:
            raws = list(kc.list_iter(("", "v1", "Pod")))
            assert raws and all(not r._loaded for r in raws)
            schema = _union_schema(tpu)
            vocab = Vocab()
            f = Flattener(schema, vocab, lane="raw")
            b_raw = f.flatten(raws, pad_n=32)
            assert f.lane_used == "raw"
            kc.raw_list = False
            dicts = list(kc.list_iter(("", "v1", "Pod")))
            b_dict = Flattener(schema, vocab, lane="dict").flatten(
                dicts, pad_n=32)
            assert diff_batches(schema, b_raw, b_dict) is None
        finally:
            kc.close()
    finally:
        srv.stop()
