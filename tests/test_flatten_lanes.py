"""Flatten-lane acceptance tests (ISSUE 4).

1. Three-way lane differential over the full shipped-library union
   schema: py (oracle) vs dict-walking C vs raw c-json produce
   bit-identical columns AND an identical vocabulary.
2. Verdict differential: the audit sweep run with
   ``flatten_lane=raw|dict|py|differential`` yields bit-identical
   totals and kept violations over the library corpus.
3. Raw-bytes ingest: KubeCluster.list_iter yields unparsed RawJSON
   objects split straight out of List page bytes, routable by
   peek_kind, content-identical to the parsed lane.
"""

import json

import numpy as np
import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.ops import native
from gatekeeper_tpu.ops.flatten import (Flattener, Schema, Vocab,
                                         diff_batches)
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.rawjson import (RawJSON, as_raw, backfill_gvk,
                                          peek_kind, split_list_items)
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects

jmod = native.load_json()


def _library_client():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client)
    return client, tpu


@pytest.fixture(scope="module")
def corpus():
    client, tpu = _library_client()
    objects = make_cluster_objects(160, seed=21)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
    return client, tpu, objects


def _union_schema(tpu):
    schema = Schema()
    for kind in tpu.lowered_kinds():
        schema.merge(tpu._programs[kind].program.schema)
    return schema


# --- 1. three-way column differential ---------------------------------

@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_three_way_lane_differential_library_schema(corpus):
    """raw c-json FIRST (creates every interning), then dict-walking C,
    then pure python, all over ONE vocab: every column bit-identical,
    and neither oracle lane interns a single new string — the raw
    kernel's vocabulary is exactly the oracle's."""
    client, tpu, objects = corpus
    schema = _union_schema(tpu)
    vocab = Vocab()

    f_raw = Flattener(schema, vocab, lane="raw")
    b_raw = f_raw.flatten([as_raw(o) for o in objects], pad_n=192)
    assert f_raw.lane_used == "raw"
    vocab_after_raw = len(vocab)

    f_dict = Flattener(schema, vocab, lane="dict")
    b_dict = f_dict.flatten(objects, pad_n=192)
    assert f_dict.lane_used == "dict"

    f_py = Flattener(schema, vocab, lane="py")
    b_py = f_py.flatten(objects, pad_n=192)
    assert f_py.lane_used == "py"

    assert diff_batches(schema, b_raw, b_dict) is None
    assert diff_batches(schema, b_raw, b_py) is None
    # identical vocab: the oracle lanes only ever looked strings up
    assert len(vocab) == vocab_after_raw


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_differential_lane_runs_and_agrees(corpus):
    client, tpu, objects = corpus
    schema = _union_schema(tpu)
    f = Flattener(schema, Vocab(), lane="differential")
    batch = f.flatten([as_raw(o) for o in objects], pad_n=192)
    assert f.lane_used == "differential:raw"
    assert batch.n == 192


def test_differential_lane_catches_divergence():
    """A poisoned batch comparison must raise, not pass silently."""
    schema = _union_schema(_library_client()[1])
    f = Flattener(schema, Vocab(), lane="differential")
    objects = make_cluster_objects(8, seed=3)
    real_diff = diff_batches

    import gatekeeper_tpu.ops.flatten as fl_mod

    orig = fl_mod.diff_batches
    fl_mod.diff_batches = lambda *a: "synthetic divergence"
    try:
        with pytest.raises(RuntimeError, match="synthetic divergence"):
            f.flatten([as_raw(o) for o in objects], pad_n=8)
    finally:
        fl_mod.diff_batches = orig
    assert real_diff is orig


# --- 2. verdict differential across sweep lanes -----------------------

def _audit_with_lane(client, tpu, objects, lane, metrics=None):
    mgr = AuditManager(
        client, lister=lambda: iter(objects),
        config=AuditConfig(chunk_size=64, exact_totals=False,
                           pipeline="off"),
        evaluator=ShardedEvaluator(tpu, make_mesh(), violations_limit=20,
                                   flatten_lane=lane, metrics=metrics),
        metrics=metrics,
    )
    return mgr.audit()


def _signature(run):
    return (
        {k: v for k, v in run.total_violations.items()},
        {k: [(v.message, v.kind, v.name, v.namespace,
              v.enforcement_action) for v in vs]
         for k, vs in run.kept.items()},
    )


def test_sweep_verdicts_identical_across_lanes(corpus):
    """The acceptance differential: raw / dict / py / differential
    sweep lanes produce bit-identical verdicts over the library
    corpus.  The raw lanes see RawJSON input (the lister contract);
    materialization inside the oracle lanes is the lanes' own
    business."""
    client, tpu, objects = corpus
    lanes = ["dict", "py", "differential"]
    if jmod is not None:
        lanes.insert(0, "raw")
    metrics = MetricsRegistry()
    base = None
    for lane in lanes:
        raws = [as_raw(o) for o in objects]
        run = _audit_with_lane(client, tpu, raws, lane, metrics=metrics)
        sig = _signature(run)
        assert sum(sig[0].values()) > 0, "corpus produced no violations"
        if base is None:
            base = sig
        else:
            assert sig == base, f"lane {lane} diverged"
    # the lane counter observed every lane it ran
    for lane in lanes:
        label = {"lane": lane if lane != "differential"
                 else ("differential:raw" if jmod is not None
                       else "differential:dict")}
        assert metrics.get_counter(M.FLATTEN_LANE, label) > 0, label
    assert metrics.get_gauge(M.FLATTEN_OBJECTS_PER_SECOND) > 0


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_sweep_auto_lane_takes_raw_on_rawjson_input(corpus):
    client, tpu, objects = corpus
    metrics = MetricsRegistry()
    run = _audit_with_lane(client, tpu, [as_raw(o) for o in objects],
                           "auto", metrics=metrics)
    assert sum(run.total_violations.values()) > 0
    assert metrics.get_counter(M.FLATTEN_LANE, {"lane": "raw"}) > 0
    assert metrics.get_counter(M.FLATTEN_LANE, {"lane": "dict"}) == 0


# --- 3. raw-bytes list ingest -----------------------------------------

def test_split_list_items_roundtrip():
    page_doc = {
        "apiVersion": "v1", "kind": "PodList",
        "metadata": {"resourceVersion": "42", "continue": "tok"},
        "items": [
            {"metadata": {"name": "a", "labels": {"x": "1"}},
             "spec": {"containers": [{"name": "c,{}[]\""}]}},
            {"metadata": {"name": "b"}, "note": 'tricky "items": ['},
            {},
        ],
    }
    for dumps_kw in ({"separators": (",", ":")}, {"indent": 2}):
        page = json.dumps(page_doc, **dumps_kw).encode()
        spans, envelope = split_list_items(page)
        assert [json.loads(s) for s in spans] == page_doc["items"]
        assert envelope["metadata"]["continue"] == "tok"
        assert envelope["kind"] == "PodList"
        assert envelope["items"] == []


def test_split_list_items_rejects_non_lists():
    with pytest.raises(ValueError):
        split_list_items(b'{"kind":"Pod","metadata":{"name":"x"}}')
    with pytest.raises(ValueError):
        split_list_items(b'{"items":[1,2,"three"]}')


def test_backfill_gvk_setdefault_semantics():
    # absent keys take the defaults
    r = json.loads(backfill_gvk(b'{"metadata":{"name":"x"}}', "v1", "Pod"))
    assert r["apiVersion"] == "v1" and r["kind"] == "Pod"
    assert r["metadata"]["name"] == "x"
    # present keys win (JSON duplicate keys are last-wins)
    r = json.loads(backfill_gvk(
        b'{"apiVersion":"apps/v1","kind":"Deployment"}', "v1", "Pod"))
    assert r["apiVersion"] == "apps/v1" and r["kind"] == "Deployment"
    # empty object stays valid
    assert json.loads(backfill_gvk(b"{}", "v1", "Pod")) == {
        "apiVersion": "v1", "kind": "Pod"}
    # the native parser agrees on the spliced bytes
    if jmod is not None:
        raw = RawJSON(backfill_gvk(b'{"metadata":{"name":"x"}}',
                                   "v1", "Pod"))
        assert peek_kind(raw) == "Pod"
        assert not raw._loaded


def test_kube_list_iter_yields_unparsed_rawjson():
    from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig
    from gatekeeper_tpu.sync.mock_apiserver import MockApiServer

    srv = MockApiServer().start()
    try:
        for i in range(8):
            srv.put_object({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"p{i}", "namespace": "default"},
                "spec": {"containers": [{"name": "c", "image": "x"}]},
            })
        kc = KubeCluster(KubeConfig(server=srv.url), page_limit=3)
        try:
            objs = list(kc.list_iter(("", "v1", "Pod")))
            assert len(objs) == 8
            assert all(isinstance(o, RawJSON) for o in objs)
            # kind routing never parses
            assert all(peek_kind(o) == "Pod" for o in objs)
            assert all(not o._loaded for o in objs)
            # content identical to the parsed lane (materializes now)
            parsed = {o["metadata"]["name"]: o for o in kc.list(
                ("", "v1", "Pod"))}
            for o in objs:
                assert dict(o) == parsed[o["metadata"]["name"]]
            # the parsed-lane opt-out still yields plain dicts
            kc.raw_list = False
            objs2 = list(kc.list_iter(("", "v1", "Pod")))
            assert len(objs2) == 8
            assert not any(isinstance(o, RawJSON) for o in objs2)
        finally:
            kc.close()
    finally:
        srv.stop()


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_kube_raw_ingest_flattens_identically(corpus):
    """End to end: bytes listed from the apiserver, split, routed and
    columnized raw match the dict lane bit for bit."""
    from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig
    from gatekeeper_tpu.sync.mock_apiserver import MockApiServer

    client, tpu, objects = corpus
    pods = [o for o in objects if o.get("kind") == "Pod"][:24]
    srv = MockApiServer().start()
    try:
        for o in pods:
            srv.put_object(o)
        kc = KubeCluster(KubeConfig(server=srv.url), page_limit=5)
        try:
            raws = list(kc.list_iter(("", "v1", "Pod")))
            assert raws and all(not r._loaded for r in raws)
            schema = _union_schema(tpu)
            vocab = Vocab()
            f = Flattener(schema, vocab, lane="raw")
            b_raw = f.flatten(raws, pad_n=32)
            assert f.lane_used == "raw"
            kc.raw_list = False
            dicts = list(kc.list_iter(("", "v1", "Pod")))
            b_dict = Flattener(schema, vocab, lane="dict").flatten(
                dicts, pad_n=32)
            assert diff_batches(schema, b_raw, b_dict) is None
        finally:
            kc.close()
    finally:
        srv.stop()


# --- 4. host-parallel flatten workers (ISSUE 14) ----------------------

@pytest.fixture(scope="module", autouse=True)
def _flatten_pools_teardown():
    yield
    from gatekeeper_tpu.ops.flatten import shutdown_flatten_pools

    shutdown_flatten_pools()


def test_flatten_worker_spans_match_native_partition():
    from gatekeeper_tpu.ops.flatten import flatten_worker_spans

    # the native clamp: tiny batches stay single-context
    assert flatten_worker_spans(100, 4) == [(0, 100)]
    assert flatten_worker_spans(0, 4) == []
    # ceil-block contiguous ranges, empty tails dropped
    assert flatten_worker_spans(300, 2) == [(0, 150), (150, 300)]
    assert flatten_worker_spans(260, 4) == [(0, 87), (87, 174), (174, 260)]
    # spans cover every item exactly once, in order
    for n, w in ((1000, 8), (513, 4), (129, 2)):
        spans = flatten_worker_spans(n, w)
        assert spans[0][0] == 0 and spans[-1][1] == n
        assert all(a[1] == b[0] for a, b in zip(spans, spans[1:]))


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_flatten_workers_bit_identical_columns_and_vocab(corpus):
    """The tentpole differential: the worker pool's columns AND vocab
    string table (order included) equal the in-process lane's at the
    matching thread partition; workers=0 stays literally the current
    path."""
    client, tpu, objects = corpus
    schema = _union_schema(tpu)
    n = len(objects)

    v_ref = Vocab()
    f_ref = Flattener(schema, v_ref, lane="raw")
    f_ref.nthreads = 2  # the worker partition the pool will use
    b_ref = f_ref.flatten([as_raw(o) for o in objects], pad_n=192)
    assert f_ref.lane_used == "raw"
    assert f_ref.last_workers_used == 0

    v_w = Vocab()
    f_w = Flattener(schema, v_w, lane="raw", workers=2)
    b_w = f_w.flatten([as_raw(o) for o in objects], pad_n=192)
    assert f_w.lane_used == "raw+workers"
    assert f_w.last_workers_used == 2
    assert f_w.perf.get("worker_busy", 0.0) > 0

    assert diff_batches(schema, b_ref, b_w) is None
    assert v_ref._to_str == v_w._to_str  # intern ORDER, not just content


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_flatten_workers_differential_lane(corpus):
    """lane='differential' + workers asserts the worker pool against
    the in-process raw-vs-dict differential per batch — columns and
    vocab order — and reports the composed lane."""
    client, tpu, objects = corpus
    schema = _union_schema(tpu)
    f = Flattener(schema, Vocab(), lane="differential", workers=2)
    batch = f.flatten([as_raw(o) for o in objects], pad_n=192)
    assert f.lane_used == "differential:raw+workers"
    assert batch.n == 192


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_flatten_workers_parse_reject_falls_back_like_in_process(corpus):
    """A worker-side C parse reject must take the same dict-lane
    fallback as the in-process call — same columns, same vocab, and
    the shared vocab untouched by the failed worker pass."""
    client, tpu, objects = corpus
    schema = _union_schema(tpu)
    # deep nesting: the C parser rejects (>256 levels), json.loads accepts
    deep = RawJSON(b'{"kind":"Pod","metadata":{"name":"deep"},"x":'
                   + b'[' * 300 + b'1' + b']' * 300 + b'}')
    mk = lambda: [as_raw(o) for o in objects] + [deep]

    v_w = Vocab()
    f_w = Flattener(schema, v_w, lane="raw", workers=2)
    b_w = f_w.flatten(mk(), pad_n=192)
    assert f_w.lane_used == "dict"

    v_ref = Vocab()
    f_ref = Flattener(schema, v_ref, lane="raw")
    b_ref = f_ref.flatten(mk(), pad_n=192)
    assert f_ref.lane_used == "dict"
    assert diff_batches(schema, b_w, b_ref) is None
    assert v_w._to_str == v_ref._to_str


@pytest.fixture(scope="module")
def big_corpus():
    """A Pod-heavy corpus whose routed chunks exceed the native 128-row
    fan-out clamp, so sweep chunks actually engage the pool."""
    client, tpu = _library_client()
    objects = make_cluster_objects(400, seed=7)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
    return client, tpu, objects


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_sweep_verdicts_identical_with_flatten_workers(big_corpus):
    """The acceptance differential at sweep level: --flatten-workers N
    produces bit-identical totals + kept violations to the in-process
    lane over the library corpus, and the worker metrics surface."""
    client, tpu, objects = big_corpus

    def run_audit(workers, metrics=None):
        mgr = AuditManager(
            client, lister=lambda: iter([as_raw(o) for o in objects]),
            config=AuditConfig(chunk_size=256, exact_totals=False,
                               pipeline="off"),
            evaluator=ShardedEvaluator(tpu, make_mesh(),
                                       violations_limit=20,
                                       flatten_lane="auto",
                                       metrics=metrics,
                                       flatten_workers=workers),
            metrics=metrics,
        )
        return mgr.audit()

    base = run_audit(0)
    metrics = MetricsRegistry()
    withw = run_audit(2, metrics=metrics)
    assert _signature(base) == _signature(withw)
    assert sum(base.total_violations.values()) > 0
    # the run is self-describing
    assert base.flatten_workers == 0 and withw.flatten_workers == 2
    assert withw.n_devices == 8  # conftest's virtual mesh
    # some chunk engaged the pool and the metrics surfaced it
    assert metrics.get_counter(M.FLATTEN_LANE, {"lane": "raw+workers"}) > 0
    assert metrics.get_gauge(M.FLATTEN_WORKER_COUNT) == 2
    assert metrics.get_gauge(M.FLATTEN_WORKER_OBJECTS_PER_SECOND) > 0


# --- 5. data-parallel chunk sharding (ISSUE 14) -----------------------

def test_shard_chunks_verdicts_identical(corpus):
    """Packing K consecutive chunks into one mesh-wide dispatch must
    not change a single verdict — totals, kept order, messages — on
    the multi-device virtual mesh AND on a 1-device mesh (the tier-1
    scheduler-path pin; full 4-device parity runs in the slow lane)."""
    client, tpu, objects = corpus

    def run_audit(shard_chunks, n_devices=None):
        mgr = AuditManager(
            client, lister=lambda: iter(objects),
            config=AuditConfig(chunk_size=24, exact_totals=False,
                               pipeline="off", shard_chunks=shard_chunks),
            evaluator=ShardedEvaluator(tpu, make_mesh(n_devices),
                                       violations_limit=20),
        )
        return mgr.audit()

    base = run_audit(0)
    assert sum(base.total_violations.values()) > 0
    sharded = run_audit(3)
    assert _signature(base) == _signature(sharded)
    assert sharded.shard_chunks == 3 and sharded.n_devices == 8
    # 1-device scheduler path: coalescing alone, no mesh to shard over
    one_dev = run_audit(3, n_devices=1)
    assert _signature(base) == _signature(one_dev)
    assert one_dev.n_devices == 1


def test_shard_chunks_coalesces_same_group_only():
    """The packer may only merge chunks of the SAME constraint group,
    flushing partial tails at end of stream."""
    from gatekeeper_tpu.apis.constraints import Constraint
    from gatekeeper_tpu.audit.manager import AuditManager as AM

    mgr = AM.__new__(AM)  # no client needed for the source wrapper
    mgr.config = AuditConfig(shard_chunks=2)
    ca = Constraint(kind="A", name="a", match={}, parameters={},
                    enforcement_action="deny")
    cb = Constraint(kind="B", name="b", match={}, parameters={},
                    enforcement_action="deny")

    def impl(constraints, kind_filter, use_router, counter):
        yield [1, 2], [ca]
        yield [3], [cb]
        yield [4, 5], [ca]
        yield [6], [ca]
    mgr._chunk_source_impl = impl
    out = list(mgr._chunk_source(None, None, False, [0]))
    assert out == [([1, 2, 4, 5], [ca]), ([3], [cb]), ([6], [ca])]


# --- 6. hostile raw-JSON semantics (the fuzz corpus's weapons) ---------

@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_hostile_deep_docs_fall_back_never_crash(corpus):
    """256+-deep documents overflow the C parser's depth budget: the
    raw lane must FALL BACK to the dict walk (reported via lane_used),
    never crash, and the differential lane stays green on the fallback
    route."""
    from gatekeeper_tpu.fuzz.corpus import raw_deep_doc

    _, tpu, _ = corpus
    schema = _union_schema(tpu)
    docs = [raw_deep_doc(d, name=f"deep{d}") for d in (257, 300, 512)]
    f = Flattener(schema, Vocab(), lane="raw")
    f.flatten([RawJSON(d) for d in docs], pad_n=8)
    assert f.lane_used == "dict"
    f2 = Flattener(schema, Vocab(), lane="differential")
    f2.flatten([RawJSON(d) for d in docs], pad_n=8)
    assert f2.lane_used == "differential:dict"


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_hostile_dup_key_docs_raw_lane_last_wins(corpus):
    """Duplicate-key docs do NOT trip the raw lane.  (ISSUE 17 guessed
    they would; the pinned truth is stronger: the C parser's last-wins
    is bit-identical to json.loads, so the differential passes WITH the
    raw kernel still engaged — no fallback, no divergence.)"""
    from gatekeeper_tpu.fuzz.corpus import raw_dup_key_doc

    _, tpu, _ = corpus
    schema = _union_schema(tpu)
    doc = raw_dup_key_doc()
    f = Flattener(schema, Vocab(), lane="differential")
    f.flatten([RawJSON(doc)], pad_n=8)
    assert f.lane_used == "differential:raw"
    # the lazy parse view agrees with json.loads last-wins
    assert RawJSON(doc)["metadata"]["labels"]["k"] == "last"
    assert json.loads(doc)["spec"]["x"] == 2
    assert RawJSON(doc)["spec"]["c"]["a"] == {"b": 2}


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_hostile_unicode_and_near_collision_keys(corpus):
    """Unicode keys (escaped \\uXXXX in one doc, literal UTF-8 in the
    next) and near-collision strings intern to identical columns across
    raw and dict lanes."""
    from gatekeeper_tpu.fuzz.corpus import NEAR_COLLISIONS, UNICODE_KEYS

    _, tpu, _ = corpus
    schema = _union_schema(tpu)
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": f"u{i}", "namespace": "default",
                          "labels": {k: "v", "app": k}},
             "spec": {}}
            for i, k in enumerate(UNICODE_KEYS + NEAR_COLLISIONS)]
    vocab = Vocab()
    f_raw = Flattener(schema, vocab, lane="raw")
    # as_raw() dumps with ensure_ascii (escaped); the second batch uses
    # literal UTF-8 bytes of the SAME objects — both must match dict
    b_raw = f_raw.flatten([as_raw(o) for o in objs], pad_n=16)
    assert f_raw.lane_used == "raw"
    f_dict = Flattener(schema, vocab, lane="dict")
    b_dict = f_dict.flatten(objs, pad_n=16)
    assert diff_batches(schema, b_raw, b_dict) is None
    f_utf8 = Flattener(schema, Vocab(), lane="differential")
    f_utf8.flatten([RawJSON(json.dumps(o, ensure_ascii=False).encode())
                    for o in objs], pad_n=16)
    assert f_utf8.lane_used == "differential:raw"


def test_split_list_items_survives_unicode_and_nested_items_trap():
    """A List page whose ITEMS contain their own "items" arrays, brace
    strings and unicode keys still splits span-exact."""
    from gatekeeper_tpu.fuzz.corpus import UNICODE_KEYS

    inner = {"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "trap",
                          "labels": {UNICODE_KEYS[0]: "v"}},
             "spec": {"items": [{"items": [1, 2]}], "k": '}],"items":['}}
    page_doc = {"apiVersion": "v1", "kind": "PodList",
                "metadata": {"resourceVersion": "9"},
                "items": [inner,
                          {"apiVersion": "v1", "kind": "Pod",
                           "metadata": {"name": "pлain"}}]}
    for kw in ({"ensure_ascii": False}, {"separators": (",", ":")}):
        page = json.dumps(page_doc, **kw).encode()
        spans, envelope = split_list_items(page)
        assert [json.loads(s) for s in spans] == page_doc["items"]
        assert envelope["kind"] == "PodList"


def test_backfill_gvk_survives_unicode_and_dup_keys():
    """backfill_gvk splices bytes blind: unicode payloads stay intact
    and its prepend-plus-last-wins contract composes with docs that
    ALREADY contain duplicate keys."""
    from gatekeeper_tpu.fuzz.corpus import raw_dup_key_doc

    raw = json.dumps({"metadata": {"name": "ки"},
                      "spec": {"☃": 1}},
                     ensure_ascii=False).encode()
    r = json.loads(backfill_gvk(raw, "fuzz.example.com/v1", "Widget"))
    assert r["apiVersion"] == "fuzz.example.com/v1"
    assert r["kind"] == "Widget"
    assert r["metadata"]["name"] == "ки"
    # a dup-key doc keeps ITS OWN gvk (present keys win) and its
    # last-wins fields survive the splice
    r2 = json.loads(backfill_gvk(raw_dup_key_doc(), "v2", "Other"))
    assert r2["apiVersion"] == "v1" and r2["kind"] == "Pod"
    assert r2["metadata"]["labels"]["k"] == "last"
