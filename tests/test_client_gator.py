"""Client + gator test end-to-end over the reference demo fixtures
(BASELINE config #1: K8sRequiredLabels + demo/basic constraints)."""

import glob

import pytest

from gatekeeper_tpu.client.client import Client, ClientError
from gatekeeper_tpu.drivers.rego_driver import RegoDriver
from gatekeeper_tpu.gator.test import test as gator_test
from gatekeeper_tpu.target.review import (
    AdmissionRequest,
    AugmentedUnstructured,
    RequestObjectError,
)
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.unstructured import load_yaml_file

DEMO = "/root/reference/demo/basic"


def demo_objects():
    objs = []
    for path in [
        f"{DEMO}/templates/k8srequiredlabels_template.yaml",
        f"{DEMO}/templates/k8suniquelabel_template.yaml",
        *sorted(glob.glob(f"{DEMO}/constraints/*.yaml")),
        f"{DEMO}/bad/bad_ns.yaml",
        f"{DEMO}/good/good_ns.yaml",
    ]:
        objs.extend(load_yaml_file(path))
    return objs


def test_gator_test_demo_basic():
    responses = gator_test(demo_objects())
    results = responses.results()
    # bad-ns violates both the deny and the dryrun required-labels constraints
    msgs = {(r.constraint["metadata"]["name"], r.enforcement_action)
            for r in results}
    assert msgs == {
        ("ns-must-have-gk", "deny"),
        ("ns-must-have-gk-dryrun", "dryrun"),
    }
    for r in results:
        assert r.msg == 'you must provide labels: {"gatekeeper"}'
        assert r.violating_object["metadata"]["name"] == "bad-ns"


def _client():
    return Client(target=K8sValidationTarget(), drivers=[RegoDriver()],
                  enforcement_points=["gator.gatekeeper.sh"])


def test_client_review_with_admission_request():
    c = _client()
    objs = demo_objects()
    c.add_template(objs[0])  # k8srequiredlabels only
    for o in objs[2:5]:  # the three demo constraints; K8sUniqueLabel has no
        try:  # template here and must be rejected
            c.add_constraint(o)
        except ClientError:
            pass
    req = AdmissionRequest(
        kind={"group": "", "version": "v1", "kind": "Namespace"},
        name="test-ns",
        operation="CREATE",
        object={"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "test-ns"}},
    )
    resp = c.review(req, enforcement_point="gator.gatekeeper.sh")
    results = resp.results()
    assert len(results) == 2  # deny + dryrun constraints
    assert all("gatekeeper" in r.msg for r in results)


def test_delete_requires_old_object():
    c = _client()
    req = AdmissionRequest(
        kind={"group": "", "version": "v1", "kind": "Pod"},
        operation="DELETE",
        object={"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "x"}},
    )
    with pytest.raises(RequestObjectError):
        c.review(req)


def test_delete_copies_old_object():
    c = _client()
    objs = demo_objects()
    c.add_template(objs[0])
    c.add_constraint(objs[3])  # ns-must-have-gk (deny)
    req = AdmissionRequest(
        kind={"group": "", "version": "v1", "kind": "Namespace"},
        name="del-ns",
        operation="DELETE",
        old_object={"apiVersion": "v1", "kind": "Namespace",
                    "metadata": {"name": "del-ns"}},
    )
    resp = c.review(req, enforcement_point="gator.gatekeeper.sh")
    assert len(resp.results()) >= 1  # evaluated against oldObject copy


def test_constraint_without_template_rejected():
    c = _client()
    with pytest.raises(ClientError):
        c.add_constraint(
            {
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": "K8sNoTemplate",
                "metadata": {"name": "x"},
                "spec": {},
            }
        )


def test_inventory_data_flow():
    """Referential policy: unique label across cluster namespaces."""
    c = _client()
    objs = demo_objects()
    c.add_template(objs[1])  # k8suniquelabel
    c.add_constraint(objs[2])  # all_ns_gatekeeper_label_unique
    other = {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": "other", "labels": {"gatekeeper": "dup"}}}
    c.add_data(other)
    mine = {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "mine", "labels": {"gatekeeper": "dup"}}}
    resp = c.review(AugmentedUnstructured(object=mine),
                    enforcement_point="gator.gatekeeper.sh")
    assert len(resp.results()) == 1
    assert "duplicate value" in resp.results()[0].msg
    # remove the conflicting object -> no violation
    c.remove_data(other)
    resp = c.review(AugmentedUnstructured(object=mine),
                    enforcement_point="gator.gatekeeper.sh")
    assert resp.results() == []
