"""CI entry for the differential fuzzer (VERDICT r1 #8: the
interpreter-vs-lowered property harness must run in every pytest pass,
not only when invoked manually).  tests/fuzz_differential.py keeps the
larger manual mode (`python tests/fuzz_differential.py 400 0 1 2 3 4`)."""

from tests.fuzz_differential import build_fuzz_driver, run_fuzz


def test_fuzz_differential_seeded():
    tpu, cons = build_fuzz_driver()
    assert run_fuzz(120, [0, 1], quiet=True, tpu=tpu,
                    constraints=cons) == 0


def test_fuzz_harness_catches_seeded_bug():
    """Sensitivity check: corrupting one lowered program must surface as
    divergences — proof the harness would catch a real lowering bug."""
    from gatekeeper_tpu.ir import nodes as N

    tpu, cons = build_fuzz_driver()
    prog = tpu._programs["K8sNoPrivileged"]
    orig = prog.program.expr
    try:
        prog.program.expr = N.Not(orig)  # seeded bug: inverted verdicts
        prog._fn = None
        import jax

        prog._fn = jax.jit(prog._build())
        assert run_fuzz(60, [7], quiet=True, tpu=tpu,
                        constraints=cons) > 0
    finally:
        prog.program.expr = orig
        import jax

        prog._fn = jax.jit(prog._build())
