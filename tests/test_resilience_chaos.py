"""Chaos acceptance tests (ISSUE 2 criteria).

1. Differential: with injection disabled (or an installed-but-empty
   plan), the resilience layer is verdict-bit-identical to the plain
   path over the shipped library corpus.
2. Under injected faults — provider hang, stage-worker crash, transient
   device/apiserver errors — the webhook answers within its deadline
   budget per failurePolicy, the audit sweep completes with
   retried/partial chunks marked ``incomplete``, and the
   ``gatekeeper_resilience_*`` metrics record every breaker transition
   and retry.
"""

import threading
import time

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects


def _library_client():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client)
    return client, tpu


def _mgr(client, tpu, objects, metrics=None, **cfg_kw):
    cfg_kw.setdefault("exact_totals", False)
    cfg = AuditConfig(chunk_size=64, **cfg_kw)
    return AuditManager(
        client, lister=lambda: iter(objects), config=cfg,
        evaluator=ShardedEvaluator(tpu, make_mesh(), violations_limit=20),
        metrics=metrics,
    )


def _kept_signature(run):
    return {
        k: [(v.message, v.kind, v.name, v.namespace, v.enforcement_action)
            for v in vs]
        for k, vs in run.kept.items()
    }


@pytest.fixture(scope="module")
def corpus():
    client, tpu = _library_client()
    objects = make_cluster_objects(180, seed=13)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
    return client, tpu, objects


@pytest.fixture(scope="module")
def baseline_run(corpus):
    client, tpu, objects = corpus
    return _mgr(client, tpu, objects, pipeline="off").audit()


# --- 1. chaos differential: empty plan is bit-identical -------------------

@pytest.mark.slow  # tier-1 wall budget (PR 16): 28s; the resilience-
# knobs differential below pins the same seam-is-free contract.
def test_differential_empty_plan_bit_identical(corpus, baseline_run):
    """An INSTALLED chaos plan with no firing spec must not perturb a
    single verdict, total, kept message, or the incomplete flag — the
    seam itself is free."""
    client, tpu, objects = corpus
    plan = FaultPlan([{"site": "never.matches.anything", "mode": "error"}])
    with inject(plan):
        run_serial = _mgr(client, tpu, objects, pipeline="off").audit()
        run_pipe = _mgr(client, tpu, objects, pipeline="on").audit()
    assert plan.fired() == 0
    for run in (run_serial, run_pipe):
        assert not run.incomplete
        assert run.failed_chunks == 0
        assert run.total_objects == baseline_run.total_objects
        assert run.total_violations == baseline_run.total_violations
        assert _kept_signature(run) == _kept_signature(baseline_run)
    assert sum(baseline_run.total_violations.values()) > 0  # non-vacuous


def test_differential_resilience_knobs_bit_identical(corpus, baseline_run):
    """Retry budgets armed (chunk_retries high) but nothing failing:
    output identical to the plain pass."""
    client, tpu, objects = corpus
    run = _mgr(client, tpu, objects, pipeline="off", chunk_retries=3,
               pipeline_stage_retries=3).audit()
    assert run.total_violations == baseline_run.total_violations
    assert _kept_signature(run) == _kept_signature(baseline_run)
    assert run.retried_chunks == 0 and not run.incomplete


# --- 2. injected faults ----------------------------------------------------

def test_stage_worker_crash_restarts_and_output_identical(
        corpus, baseline_run):
    """A flatten worker crashing twice mid-sweep: the stage restarts,
    re-runs the chunk, and the pass finishes bit-identical with the
    retries recorded in metrics."""
    client, tpu, objects = corpus
    reg = MetricsRegistry()
    plan = FaultPlan([{"site": "pipeline.stage.flatten", "mode": "error",
                       "times": 2}])
    with inject(plan):
        mgr = _mgr(client, tpu, objects, metrics=reg, pipeline="on",
                   pipeline_stage_retries=2)
        run = mgr.audit()
    assert plan.fired() == 2
    assert not run.incomplete
    assert run.retried_chunks >= 2
    assert run.total_violations == baseline_run.total_violations
    assert _kept_signature(run) == _kept_signature(baseline_run)
    assert reg.get_counter(M.RESILIENCE_RETRIES,
                           {"dependency": "audit_pipeline"}) >= 2
    assert mgr.pipe_stats["stages"]["flatten"]["retries"] >= 2


def test_pipeline_persistent_crash_degrades_to_serial(corpus, baseline_run):
    """A stage that keeps dying past its restart budget: the sweep
    degrades to the serial schedule mid-pass and still produces the full
    result (chunks re-list from the source, nothing lost)."""
    client, tpu, objects = corpus
    reg = MetricsRegistry()
    plan = FaultPlan([{"site": "pipeline.stage.dispatch", "mode": "error"}])
    with inject(plan):
        mgr = _mgr(client, tpu, objects, metrics=reg, pipeline="on",
                   pipeline_stage_retries=1)
        run = mgr.audit()
    assert mgr.perf.get("degraded_to_serial") == 1.0
    assert mgr.perf["pipelined"] == 0.0
    assert reg.get_counter(M.RESILIENCE_DEGRADED,
                           {"component": "audit", "to": "serial"}) == 1
    assert not run.incomplete  # the serial rerun covered every chunk
    assert run.total_violations == baseline_run.total_violations
    assert _kept_signature(run) == _kept_signature(baseline_run)


def test_transient_device_errors_retried_serial(corpus, baseline_run):
    """Each chunk's first dispatch fails (transient device error): the
    chunk retries and the pass completes identically, retries counted."""
    client, tpu, objects = corpus
    reg = MetricsRegistry()
    # every=2 starting at call 0: dispatch calls alternate fail/succeed —
    # with chunk_retries=1 every chunk survives exactly one retry
    plan = FaultPlan([{"site": "device.dispatch", "mode": "error",
                       "every": 2}])
    with inject(plan):
        mgr = _mgr(client, tpu, objects, metrics=reg, pipeline="off",
                   chunk_retries=1)
        run = mgr.audit()
    assert not run.incomplete
    assert run.retried_chunks >= 1
    assert run.total_violations == baseline_run.total_violations
    assert _kept_signature(run) == _kept_signature(baseline_run)
    assert reg.get_counter(M.RESILIENCE_RETRIES,
                           {"dependency": "audit_chunk"}) >= 1


def test_audit_partial_results_marked_incomplete(corpus, baseline_run):
    """Chunks that fail past their retry budget are DROPPED, not fatal:
    the pass finishes with partial results, the explicit incomplete
    marker, failed-chunk metrics, and the status writeback carries the
    marker."""
    client, tpu, objects = corpus
    reg = MetricsRegistry()
    # after the first dispatch, everything fails — including retries
    plan = FaultPlan([{"site": "device.dispatch", "mode": "error",
                       "after": 1}])
    statuses = {}
    with inject(plan):
        mgr = _mgr(client, tpu, objects, metrics=reg, pipeline="off",
                   chunk_retries=1)
        mgr.status_writer = \
            lambda con, status: statuses.setdefault(con.name, status)
        run = mgr.audit()
    assert run.incomplete
    assert run.failed_chunks >= 1
    assert run.retried_chunks >= 1
    assert reg.counter_total(M.RESILIENCE_CHUNKS_FAILED) >= 1
    assert reg.get_gauge("audit_last_run_incomplete") == 1.0
    # partial: strictly fewer violations than the complete pass
    assert sum(run.total_violations.values()) < \
        sum(baseline_run.total_violations.values())
    assert statuses and all(s.get("incomplete") is True
                            for s in statuses.values())
    # a complete pass never writes the marker
    assert all("incomplete" not in s
               for s in (_status_of(baseline_run),))


def _status_of(run):
    """Status dict shape check helper for the complete-run case."""
    return {"auditTimestamp": run.timestamp} if not run.incomplete else \
        {"incomplete": True}


def test_lister_dying_midsweep_marks_incomplete(corpus):
    client, tpu, objects = corpus

    def dying_lister():
        yield from objects[:100]
        raise RuntimeError("apiserver watch storm")

    mgr = AuditManager(
        client, lister=dying_lister,
        config=AuditConfig(chunk_size=64, exact_totals=False,
                           pipeline="off"),
        evaluator=ShardedEvaluator(tpu, make_mesh(), violations_limit=20),
    )
    run = mgr.audit()
    assert run.incomplete
    assert run.total_objects <= 100  # partial listing still folded


# --- webhook deadline budget under injected hang --------------------------

def test_webhook_full_stack_deadline_under_provider_hang():
    """End-to-end through the HTTP server: an injected review-path hang
    (standing in for a hung external dependency) answers within the
    deadline budget per failurePolicy, and the accept-lane metrics
    record the convoy."""
    import http.client
    import json as _json

    from gatekeeper_tpu.webhook.policy import ValidationHandler
    from gatekeeper_tpu.webhook.server import WebhookServer

    class _EmptyResponses:
        stats_entries: list = []

        def results(self):
            return []

    class _StubClient:
        drivers: list = []

        def review(self, augmented, **kw):
            return _EmptyResponses()

    reg = MetricsRegistry()
    plan = FaultPlan([{"site": "webhook.review", "mode": "hang",
                       "delay_s": 2.0}])
    handler = ValidationHandler(_StubClient(), metrics=reg,
                                deadline_budget_s=0.2,
                                failure_policy="ignore")
    srv = WebhookServer(validation_handler=handler, port=0,
                        metrics=reg).start()
    body = _json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": "u-hang", "operation": "CREATE",
                    "kind": {"group": "", "version": "v1", "kind": "Pod"},
                    "userInfo": {"username": "load"},
                    "object": {"apiVersion": "v1", "kind": "Pod",
                               "metadata": {"name": "x",
                                            "namespace": "default"},
                               "spec": {}}},
    }).encode()

    results = []

    def post():
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        t0 = time.perf_counter()
        c.request("POST", "/v1/admit", body,
                  {"Content-Type": "application/json"})
        doc = _json.loads(c.getresponse().read())
        results.append((time.perf_counter() - t0, doc))
        c.close()

    try:
        with inject(plan):
            threads = [threading.Thread(target=post) for _ in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
    finally:
        srv.stop()
    assert len(results) == 3
    for elapsed, doc in results:
        assert elapsed < 1.5  # answered by the budget, not the 2s hang
        assert doc["response"]["allowed"] is True  # failurePolicy=Ignore
        assert any("deadline budget" in w
                   for w in doc["response"].get("warnings", []))
    assert reg.get_counter(M.RESILIENCE_DEADLINE_EXCEEDED,
                           {"component": "webhook",
                            "policy": "ignore"}) == 3
    # accept-lane convoy instrumentation: 3 concurrent handlers were
    # in flight together at some point
    assert reg.get_gauge(M.WEBHOOK_INFLIGHT_HIGHWATER) >= 2
    assert reg.get_gauge(M.WEBHOOK_INFLIGHT) == 0  # drained


def test_batcher_queue_wait_metrics_show_device_lane_convoy():
    """The multiworker2 root-cause instrumentation (VERDICT r4 weak #5):
    with a slow device lane, concurrent reviews convoy in the batcher —
    the queue-wait summary and batch-size distribution make that
    observable per worker, distinguishing device-lane convoying (this
    metric) from an accept-queue convoy (the server inflight gauge)."""
    from gatekeeper_tpu.target.review import AugmentedUnstructured
    from gatekeeper_tpu.webhook.policy import Batcher

    class _SlowResponses:
        stats_entries: list = []

        def results(self):
            return []

    class _SlowClient:
        drivers: list = []

        def review(self, augmented, **kw):
            time.sleep(0.05)  # the device-lane holdup
            return _SlowResponses()

    reg = MetricsRegistry()
    b = Batcher(_SlowClient(), metrics=reg, small_batch=64).start()
    try:
        aug = AugmentedUnstructured(object={"apiVersion": "v1",
                                            "kind": "Pod",
                                            "metadata": {"name": "x"}})
        threads = [threading.Thread(target=lambda: b.review(aug))
                   for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
    finally:
        b.stop()
    rendered = reg.render()
    assert "webhook_batch_queue_wait_seconds_count" in rendered
    assert "webhook_batch_size_count" in rendered
    # 6 requests against a 50ms serial lane: the later ones waited
    waits = reg.get_histogram(M.WEBHOOK_QUEUE_WAIT)
    assert waits["count"] == 6
    assert waits["max"] > 0.04
