"""Per-tenant, per-priority admission QoS (ISSUE 10).

Acceptance pins:
- priority lanes preempt: system/break-glass traffic dequeues ahead of
  user lanes and sheds last;
- weighted-fair (deficit-round-robin) dequeue holds tenant weights in
  COST units under skewed object sizes;
- per-tenant inflight caps and queue-cost budgets hold;
- tenant-aware displacement sheds the heaviest tenant first, never the
  mid-burst arrival by default;
- identical (config, seed, arrival order) replays the exact
  dequeue/shed trajectory;
- multi-tenant isolation chaos: tenant A at 8x offered load plus an
  injected ``webhook.overload`` fault must not move tenant B's accepted
  P99 beyond 2x unloaded, and drain answers every accepted uid across
  all lanes;
- ``--qos off`` (the compat default) is bit-identical to the PR 5
  single-FIFO path over the library corpus — pinned in
  ``tests/test_overload.py::test_qos_off_bit_identical_to_pr5_fifo_
  over_library`` (it shares that module's library fixture instead of
  building a second client).
"""

import http.client
import json
import os
import sys
import threading
import time

import pytest

from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.observability import costattr, flightrec
from gatekeeper_tpu.resilience import overload as ovl
from gatekeeper_tpu.resilience import qos
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.webhook.policy import ValidationHandler
from gatekeeper_tpu.webhook.server import WebhookServer

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


class _EmptyResponses:
    stats_entries: list = []

    def results(self):
        return []


class _TenantTrackingClient:
    """Review stub recording per-namespace review concurrency (the
    inflight-cap witness) with a configurable service time."""

    drivers: list = []

    def __init__(self, service_s: float = 0.0):
        self.service_s = service_s
        self.reviews = 0
        self.max_conc: dict = {}
        self._cur: dict = {}
        self._lock = threading.Lock()

    def constraints(self):
        return []

    def review(self, augmented, **kw):
        ns = augmented.admission_request.namespace or "_cluster"
        with self._lock:
            self.reviews += 1
            self._cur[ns] = self._cur.get(ns, 0) + 1
            if self._cur[ns] > self.max_conc.get(ns, 0):
                self.max_conc[ns] = self._cur[ns]
        try:
            if self.service_s:
                time.sleep(self.service_s)
            return _EmptyResponses()
        finally:
            with self._lock:
                self._cur[ns] -= 1


def _body(uid="u1", namespace="team-a", username="load", kind="Pod",
          nbytes=0):
    obj = {"apiVersion": "v1", "kind": kind,
           "metadata": {"name": "x", "namespace": namespace}}
    if nbytes:
        obj["data"] = "x" * nbytes
    return {
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": uid, "operation": "CREATE",
                    "kind": {"group": "", "version": "v1", "kind": kind},
                    "namespace": namespace,
                    "userInfo": {"username": username},
                    "object": obj},
    }


def _lv(cfg, name):
    return next(lv for lv in cfg.levels if lv.name == name)


# --- config parsing / routing ---------------------------------------------

def test_qos_config_parse_and_classify(tmp_path):
    doc = {
        "tenantKey": "namespace",
        "priorityLevels": [
            {"name": "system", "matchNamespaces": ["kube-system"],
             "matchUserPrefixes": ["system:node:"]},
            {"name": "break-glass",
             "matchNamespacePrefixes": ["break-glass"]},
            {"name": "user"},
        ],
        "tenantWeights": {"team-a": 4},
        "defaultTenantWeight": 1,
        "tenantInflightCap": 8,
        "tenantQueueCost": 64e6,
        "quantum": 4096,
    }
    p = tmp_path / "qos.json"
    p.write_text(json.dumps(doc))
    cfg = qos.load_qos_config(str(p))
    assert [lv.name for lv in cfg.levels] == ["system", "break-glass",
                                              "user"]
    assert cfg.classify("kube-system", "").name == "system"
    assert cfg.classify("anything", "system:node:n1").name == "system"
    assert cfg.classify("break-glass-ops", "").name == "break-glass"
    assert cfg.classify("team-a", "alice").name == "user"
    assert cfg.weight("team-a") == 4 and cfg.weight("team-b") == 1
    assert cfg.tenant_inflight_cap == 8
    # tenant keys
    req = {"namespace": "team-a", "userInfo": {"username": "alice"}}
    assert qos.tenant_of_request(req) == "team-a"
    assert qos.tenant_of_request(req, "serviceaccount") == "alice"
    assert qos.tenant_of_request({}, "namespace") == qos.CLUSTER_TENANT
    with pytest.raises(ValueError):
        qos.parse_qos_config({"tenantKey": "nope"})
    # --qos off (the compat default) yields no config at all
    assert qos.qos_from_args("off", str(p)) is None
    assert qos.qos_from_args("on", str(p)).tenant_inflight_cap == 8


# --- the DRR queue (deterministic, driven directly) -----------------------

def test_drr_weights_hold_under_skewed_object_sizes():
    """Tenant A posts 16x bigger objects than B at equal weight: served
    COST stays ~equal (request counts skew instead) — the fairness unit
    is cost, not request slots.  With weight 2, B earns ~2x the cost
    share."""
    for w_b, want_ratio in ((1.0, 1.0), (2.0, 2.0)):
        cfg = qos.QoSConfig(quantum=1000.0,
                            tenant_weights={"b": w_b})
        q = qos.QoSQueue(cfg)
        lv = _lv(cfg, "user")
        seq = 0
        for i in range(64):
            q.enqueue(qos.Ticket(seq, "a", lv, 16000.0), 1000, 1e18)
            seq += 1
        for i in range(1024):
            q.enqueue(qos.Ticket(seq, "b", lv, 1000.0), 1000, 1e18)
            seq += 1
        served = {"a": 0.0, "b": 0.0}
        for _ in range(200):
            t = q.pick_next(lambda tn: 0)
            if t is None:
                break
            served[t.tenant] += t.cost
        assert served["a"] > 0 and served["b"] > 0
        ratio = served["b"] / served["a"]
        assert want_ratio / 1.6 <= ratio <= want_ratio * 1.6, \
            f"weight {w_b}: served cost ratio {ratio:.2f}"


def test_priority_lane_strictly_preempts_user_lane():
    cfg = qos.QoSConfig()
    q = qos.QoSQueue(cfg)
    user, system = _lv(cfg, "user"), _lv(cfg, "system")
    q.enqueue(qos.Ticket(0, "team-a", user, 10.0), 1000, 1e18)
    q.enqueue(qos.Ticket(1, "team-b", user, 10.0), 1000, 1e18)
    q.enqueue(qos.Ticket(2, "kube-system", system, 10.0), 1000, 1e18)
    order = [q.pick_next(lambda tn: 0).tenant for _ in range(3)]
    assert order[0] == "kube-system"  # arrived last, dequeues first
    assert set(order[1:]) == {"team-a", "team-b"}


def test_displacement_sheds_heaviest_tenant_first_system_last():
    cfg = qos.QoSConfig()
    heavy = {"whale": 1e9, "minnow": 1.0, "kube-system": 5e9}
    q = qos.QoSQueue(cfg, heaviness=lambda tn: heavy.get(tn, 0.0))
    user, system = _lv(cfg, "user"), _lv(cfg, "system")
    whale_tickets = [qos.Ticket(i, "whale", user, 10.0)
                     for i in range(3)]
    for t in whale_tickets:
        assert q.enqueue(t, 4, 1e18) == (True, None, "")
    sys_t = qos.Ticket(3, "kube-system", system, 10.0)
    assert q.enqueue(sys_t, 4, 1e18) == (True, None, "")
    # queue full (depth 4): a light user tenant displaces the WHALE's
    # newest ticket, not the system lane, not itself
    minnow = qos.Ticket(4, "minnow", user, 10.0)
    admitted, victim, reason = q.enqueue(minnow, 4, 1e18)
    assert admitted and victim is whale_tickets[-1]
    assert victim.shed == "displaced"
    # another whale arrival cannot displace anyone (it IS the heaviest)
    whale_new = qos.Ticket(5, "whale", user, 10.0)
    admitted, victim, reason = q.enqueue(whale_new, 4, 1e18)
    assert not admitted and victim is None and reason == "queue_full"
    # drain everything queued, then fill with system-only traffic: a
    # user arrival must NOT displace system tickets (system sheds last)
    while q.pick_next(lambda tn: 0) is not None:
        pass
    q.enqueue(qos.Ticket(6, "kube-system", system, 10.0), 1000, 1e18)
    q.enqueue(qos.Ticket(7, "kube-system", system, 10.0), 1000, 1e18)
    q.enqueue(qos.Ticket(8, "kube-system", system, 10.0), 1000, 1e18)
    late_user = qos.Ticket(9, "minnow", user, 10.0)
    admitted, victim, reason = q.enqueue(late_user, 3, 1e18)
    assert not admitted and victim is None and reason == "queue_full"
    # ...while a SYSTEM arrival displaces nothing either (same level,
    # not lighter than the heaviest system tenant = itself)
    late_sys = qos.Ticket(10, "kube-system", system, 10.0)
    admitted, victim, _ = q.enqueue(late_sys, 3, 1e18)
    assert not admitted and victim is None


def test_tenant_queue_cost_budget_sheds_only_the_offender():
    cfg = qos.QoSConfig(tenant_queue_cost=100.0)
    q = qos.QoSQueue(cfg)
    user = _lv(cfg, "user")
    assert q.enqueue(qos.Ticket(0, "a", user, 60.0), 1000, 1e18)[0]
    # a's second ticket would exceed ITS budget: shed with the tenant
    # reason, global bounds untouched
    admitted, victim, reason = q.enqueue(qos.Ticket(1, "a", user, 60.0),
                                         0, 0)
    assert not admitted and reason == "tenant_queue_cost"
    # tenant b is unaffected
    assert q.enqueue(qos.Ticket(2, "b", user, 60.0), 1000, 1e18)[0]


def test_pick_next_skips_tenants_at_inflight_cap():
    cfg = qos.QoSConfig(tenant_inflight_cap=1)
    q = qos.QoSQueue(cfg)
    user = _lv(cfg, "user")
    q.enqueue(qos.Ticket(0, "a", user, 10.0), 1000, 1e18)
    q.enqueue(qos.Ticket(1, "b", user, 10.0), 1000, 1e18)
    inflight = {"a": 1}
    t = q.pick_next(lambda tn: inflight.get(tn, 0))
    assert t.tenant == "b"  # a is at cap: skipped, not starved-forever
    # b now at cap too; a still capped: nothing serviceable
    inflight["b"] = 1
    assert q.pick_next(lambda tn: inflight.get(tn, 0)) is None
    # a releases: its queued ticket is served
    inflight["a"] = 0
    assert q.pick_next(lambda tn: inflight.get(tn, 0)).tenant == "a"


def test_seeded_trajectory_replays_exactly():
    """Identical (config, arrival order, release order) => identical
    grant/shed trajectory, twice over — the deterministic-replay pin."""

    def run():
        cfg = qos.QoSConfig(tenant_inflight_cap=2, quantum=512.0,
                            tenant_weights={"team-b": 2})
        ctl = ovl.OverloadController(ovl.OverloadConfig(
            min_inflight=2, max_inflight=2, initial_inflight=2,
            queue_depth=4, queue_timeout_s=5.0, qos=cfg))
        user = _lv(cfg, "user")
        system = _lv(cfg, "system")
        script = [("team-a", user, 4096.0), ("team-a", user, 4096.0),
                  ("team-a", user, 8192.0), ("team-b", user, 512.0),
                  ("kube-system", system, 1024.0),
                  ("team-b", user, 512.0), ("team-a", user, 2048.0)]
        holders: list = []
        # sequential script: each admit runs on its own thread but the
        # ARRIVAL order is serialized by events, and releases happen in
        # scripted order — the trajectory is then a pure function of the
        # config + script
        entered = []

        def one(i, tenant, lv, cost):
            gate = threading.Event()
            holders.append(gate)
            try:
                with ctl.admit(cost, tenant=tenant, priority=lv):
                    entered.append(i)
                    gate.wait(10)
            except ovl.Shed:
                pass

        threads = []
        for i, (tenant, lv, cost) in enumerate(script):
            t = threading.Thread(target=one, args=(i, tenant, lv, cost))
            threads.append(t)
            t.start()
            time.sleep(0.03)  # serialize arrivals
        for gate in list(holders):  # release in arrival order
            gate.set()
            time.sleep(0.03)
        for t in threads:
            t.join(10)
        return list(ctl.trajectory)

    t1, t2 = run(), run()
    assert t1 == t2
    assert any(e[0] == "grant" for e in t1)


# --- controller-level caps + sheds ----------------------------------------

def test_controller_tenant_inflight_cap_holds_under_burst():
    reg = MetricsRegistry()
    cfg = qos.QoSConfig(tenant_inflight_cap=1)
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=4, max_inflight=4, initial_inflight=4,
        queue_depth=16, queue_timeout_s=2.0, qos=cfg), metrics=reg)
    client = _TenantTrackingClient(service_s=0.05)
    h = ValidationHandler(client, failure_policy="fail", overload=ctl)
    threads = [threading.Thread(
        target=lambda i=i: h.handle(_body(uid=f"a{i}",
                                          namespace="team-a")))
        for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    # 4 limiter slots but ONE tenant: never more than cap=1 in review
    assert client.max_conc.get("team-a", 0) == 1
    assert client.reviews == 6  # capped, queued, all served (no sheds)
    assert ctl.shed_count == 0


def test_shed_metric_carries_tenant_and_priority_labels():
    reg = MetricsRegistry()
    cfg = qos.QoSConfig()
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=1, max_inflight=1, initial_inflight=1,
        queue_depth=0, queue_timeout_s=0.05, qos=cfg), metrics=reg)
    h = ValidationHandler(_TenantTrackingClient(service_s=0.3),
                          failure_policy="fail", overload=ctl)
    held = threading.Event()
    t = threading.Thread(target=lambda: (
        held.set(), h.handle(_body(uid="h", namespace="team-a"))))
    t.start()
    held.wait(2)
    time.sleep(0.05)  # the holder is inside its review
    resp = h.handle(_body(uid="x", namespace="team-b"))
    t.join(5)
    assert resp.code == 429
    assert reg.get_counter(M.OVERLOAD_SHED,
                           {"reason": "queue_full", "tenant": "team-b",
                            "priority": "user"}) == 1


# --- the isolation chaos test ---------------------------------------------

def test_multitenant_isolation_tenant_a_burst_does_not_move_b_p99():
    """THE acceptance pin: tenant A at 8x offered load through a tight
    limiter, plus injected ``webhook.overload`` chaos sheds, must not
    move tenant B's accepted P99 beyond 2x its unloaded P99; the system
    lane sheds last (here: not at all); per-tenant caps hold; excess
    shed cost lands on the attacker."""
    service_s = 0.04
    reg = MetricsRegistry()
    cfg = qos.QoSConfig(tenant_inflight_cap=1, quantum=16384.0)
    # 3 slots, cap 1: each of the three tenants can hold at most one —
    # the attacker's 8x concurrency buys it queueing + sheds, not slots
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=3, max_inflight=3, initial_inflight=3,
        queue_depth=6, queue_timeout_s=0.3, qos=cfg), metrics=reg)
    client = _TenantTrackingClient(service_s=service_s)
    h = ValidationHandler(client, failure_policy="fail", overload=ctl)

    # unloaded anchor: sequential tenant-B requests, no contention
    unloaded = []
    for i in range(6):
        t0 = time.perf_counter()
        r = h.handle(_body(uid=f"warm{i}", namespace="tenant-b"))
        assert r.allowed
        unloaded.append(time.perf_counter() - t0)
    unloaded_p99 = sorted(unloaded)[-1]

    plan = FaultPlan([{"site": "webhook.overload", "mode": "error",
                       "after": 10, "every": 9, "times": 3}])
    results: dict = {"tenant-a": [], "tenant-b": [], "kube-system": []}
    sheds: dict = {"tenant-a": 0, "tenant-b": 0, "kube-system": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def closed_loop(ns, n):
        for i in range(n):
            if stop.is_set():
                break
            t0 = time.perf_counter()
            resp = h.handle(_body(uid=f"{ns}-{i}", namespace=ns))
            dt = time.perf_counter() - t0
            with lock:
                if resp.code == 429:
                    sheds[ns] += 1
                else:
                    results[ns].append(dt)

    with inject(plan):
        threads = [threading.Thread(target=closed_loop,
                                    args=("tenant-a", 10))
                   for _ in range(8)]  # 8x offered load
        threads.append(threading.Thread(target=closed_loop,
                                        args=("tenant-b", 12)))
        threads.append(threading.Thread(target=closed_loop,
                                        args=("kube-system", 6)))
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
    assert plan.fired("webhook.overload") >= 1  # the chaos actually bit

    assert results["tenant-b"], "tenant B must have accepted requests"
    b_p99 = sorted(results["tenant-b"])[-1]
    assert b_p99 <= 2.0 * unloaded_p99, \
        f"tenant-B P99 {b_p99 * 1e3:.1f}ms vs unloaded " \
        f"{unloaded_p99 * 1e3:.1f}ms: isolation broken"
    # the attacker absorbed the shedding; system lane shed nothing
    # beyond chaos' indiscriminate injections
    assert sheds["tenant-a"] > 0, "an 8x burst through a tight " \
                                  "limiter must shed the attacker"
    queue_sheds_sys = reg.get_counter(
        M.OVERLOAD_SHED, {"reason": "queue_timeout",
                          "tenant": "kube-system", "priority": "system"})
    queue_full_sys = reg.get_counter(
        M.OVERLOAD_SHED, {"reason": "queue_full",
                          "tenant": "kube-system", "priority": "system"})
    assert queue_sheds_sys == 0 and queue_full_sys == 0
    # per-tenant inflight cap held the whole run
    assert client.max_conc.get("tenant-a", 0) <= 1


# --- drain across lanes ----------------------------------------------------

def test_drain_answers_every_accepted_uid_across_all_lanes():
    """Zero-loss drain with QoS on: begin_drain + stop() mid-burst with
    tickets queued across three lanes — every request the server
    accepted is answered with its own uid (grants, sheds and queued
    waiters alike)."""
    reg = MetricsRegistry()
    cfg = qos.QoSConfig(tenant_inflight_cap=2)
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=2, max_inflight=2, initial_inflight=2,
        queue_depth=16, queue_timeout_s=5.0, qos=cfg), metrics=reg)
    client = _TenantTrackingClient(service_s=0.06)
    handler = ValidationHandler(client, failure_policy="fail",
                                overload=ctl, metrics=reg)
    accepted: list = []
    accept_lock = threading.Lock()
    inner = handler.handle

    def tracking(body, cost_hint=0):
        with accept_lock:
            accepted.append(body["request"]["uid"])
        return inner(body, cost_hint=cost_hint)

    handler.handle = tracking
    srv = WebhookServer(validation_handler=handler, port=0,
                        metrics=reg).start()
    answered: dict = {}
    failures: list = []
    lock = threading.Lock()
    namespaces = ["tenant-a", "tenant-b", "kube-system",
                  "break-glass-ops"]

    def post(i):
        uid = f"qos-burst-{i}"
        try:
            c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                           timeout=20)
            c.request("POST", "/v1/admit", json.dumps(
                _body(uid=uid, namespace=namespaces[i % 4])).encode(),
                {"Content-Type": "application/json"})
            doc = json.loads(c.getresponse().read())
            with lock:
                answered[uid] = doc["response"]
            c.close()
        except Exception as e:
            with lock:
                failures.append((uid, e))

    threads = [threading.Thread(target=post, args=(i,))
               for i in range(16)]
    for t in threads:
        t.start()
    time.sleep(0.1)  # burst in flight: slots busy, lanes queued
    drained = srv.stop(drain_timeout=15)
    for t in threads:
        t.join(20)
    assert drained
    accepted_set = set(accepted)
    assert accepted_set, "the burst must have been accepted"
    lost = accepted_set - set(answered)
    assert lost == set(), f"accepted but never answered: {sorted(lost)}"
    for uid in accepted_set:
        assert answered[uid]["uid"] == uid
    assert {u for u, _ in failures} & accepted_set == set()


# --- observability plumbing ------------------------------------------------

def test_flightrec_and_costattr_carry_tenant_axis():
    reg = MetricsRegistry()
    cfg = qos.QoSConfig()
    ctl = ovl.OverloadController(ovl.OverloadConfig(qos=cfg), metrics=reg)
    rec = flightrec.FlightRecorder(capacity=64)
    attr = costattr.CostAttribution(metrics=reg)
    h = ValidationHandler(_TenantTrackingClient(), overload=ctl)
    with flightrec.activate(rec), costattr.activate(attr):
        h.handle(_body(uid="t1", namespace="team-a"))
        h.handle(_body(uid="t2", namespace="team-b"))
        h.handle(_body(uid="t3", namespace="team-a"))
    e = rec.by_uid("t1")[0]
    assert e["tenant"] == "team-a" and e["priority"] == "user"
    # the ?tenant= filter composes like the others
    snap = rec.snapshot(tenant="team-a")
    assert snap["matched"] == 2
    assert all(x["tenant"] == "team-a" for x in snap["decisions"])
    # cost grid: per-tenant admission seconds + the heaviness roll-up
    totals = attr.tenant_totals("webhook")
    assert set(totals) == {"team-a", "team-b"}
    assert totals["team-a"] > 0
    snap = attr.snapshot()
    assert {t["tenant"] for t in snap["tenants"]} == {"team-a", "team-b"}
    # the metric rides {tenant, enforcement_point, phase=admission}
    assert reg.get_counter(M.CONSTRAINT_EVAL,
                           {"tenant": "team-a",
                            "enforcement_point": "webhook",
                            "phase": "admission"}) > 0
    # tenant cells never pollute the per-template closure population
    assert attr.total_seconds("webhook") == 0.0


def test_debug_overload_lane_view_and_decisions_tenant_filter():
    reg = MetricsRegistry()
    cfg = qos.QoSConfig(tenant_inflight_cap=3)
    ctl = ovl.OverloadController(ovl.OverloadConfig(qos=cfg), metrics=reg)
    rec = flightrec.FlightRecorder(capacity=64)
    h = ValidationHandler(_TenantTrackingClient(), overload=ctl)
    srv = WebhookServer(validation_handler=h, port=0, metrics=reg).start()
    try:
        with ovl.activate(ctl), flightrec.activate(rec):
            h.handle(_body(uid="d1", namespace="team-a"))
            h.handle(_body(uid="d2", namespace="team-b"))
            c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                           timeout=5)
            c.request("GET", "/debug/overload")
            doc = json.loads(c.getresponse().read())
            assert doc["mode"] == "qos"
            assert [ln["priority"] for ln in doc["qos"]["lanes"]] == \
                ["system", "break-glass", "user"]
            assert doc["qos"]["tenant_inflight_cap"] == 3
            assert doc["qos"]["trajectory_len"] >= 2
            c.request("GET", "/debug/decisions?tenant=team-b")
            doc = json.loads(c.getresponse().read())
            assert doc["matched"] == 1
            assert doc["decisions"][0]["uid"] == "d2"
            c.close()
    finally:
        srv.stop(drain_timeout=3)


def test_gator_decisions_reader_matches_debug_semantics(tmp_path):
    """The offline reader over the JSONL sink: uid/since/until/decision/
    tenant filters behave exactly like /debug/decisions (half-open
    range, compose), most recent first, malformed lines survive."""
    from gatekeeper_tpu.gator import decisions_cmd

    sink = tmp_path / "decisions.jsonl"
    rec = flightrec.FlightRecorder(capacity=64, sink_path=str(sink),
                                   wall=iter(range(100)).__next__)
    rec.record("validate", "allow", uid="u0", tenant="team-a")
    rec.record("validate", "shed", uid="u1", tenant="team-b",
               reason="queue_full")
    rec.record("validate", "shed", uid="u2", tenant="team-a",
               reason="displaced")
    rec.record("mutate", "deny", uid="u3", tenant="team-a")
    rec.close()
    with open(sink, "a") as f:
        f.write("corrupt line\n")
    doc = decisions_cmd.read_decisions(str(sink), kinds={"shed"},
                                       tenant="team-a")
    assert doc["matched"] == 1 and doc["decisions"][0]["uid"] == "u2"
    assert doc["malformed"] == 1
    # half-open [since, until): ts 1 included, ts 3 excluded
    doc = decisions_cmd.read_decisions(str(sink), since=1, until=3)
    assert [e["uid"] for e in doc["decisions"]] == ["u2", "u1"]
    doc = decisions_cmd.read_decisions(str(sink), uid="u1")
    assert doc["matched"] == 1
    assert doc["decisions"][0]["reason"] == "queue_full"
    # the CLI wrapper end-to-end (in-process)
    rc = decisions_cmd.run_cli(["-f", str(sink), "--decision", "shed",
                                "--tenant", "team-a", "-o", "json"])
    assert rc == 0
    assert decisions_cmd.run_cli(["-f", str(sink), "--since", "bogus"]) \
        == 2


# --- bench harness smoke ---------------------------------------------------

def test_bench_tenant_mix_smoke_toy_scale():
    """The ``bench.py --burst`` multi-tenant mix driver at toy scale:
    per-tenant stats + a computable isolation_ratio against a live
    server with QoS on (the full-library run happens in the bench lane,
    not tier-1)."""
    import bench

    reg = MetricsRegistry()
    cfg = qos.QoSConfig(tenant_inflight_cap=2)
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=2, max_inflight=2, initial_inflight=2,
        queue_depth=8, queue_timeout_s=0.2, qos=cfg), metrics=reg)
    h = ValidationHandler(_TenantTrackingClient(service_s=0.01),
                          failure_policy="fail", overload=ctl)
    srv = WebhookServer(validation_handler=h, port=0, metrics=reg).start()
    try:
        bodies = {
            ns: [json.dumps(_body(uid=f"{ns}-{i}",
                                  namespace=ns)).encode()
                 for i in range(8)]
            for ns in ("tenant-a", "tenant-b", "kube-system")}
        anchor = bench.drive_tenant_mix(srv.port, [
            {"name": "tenant-b", "conc": 1, "n": 6}], bodies)
        mix = bench.drive_tenant_mix(srv.port, [
            {"name": "tenant-a", "conc": 6, "n": 24},
            {"name": "tenant-b", "conc": 1, "n": 6},
            {"name": "kube-system", "conc": 1, "n": 4},
        ], bodies)
        assert set(mix) == {"tenant-a", "tenant-b", "kube-system"}
        for st in mix.values():
            assert st["requests"] == st["accepted"] + st["shed"]
            assert not st["errors"]
        assert anchor["tenant-b"]["p99_ms"] > 0
        assert mix["tenant-b"]["accepted"] > 0  # B survived the mix
    finally:
        srv.stop(drain_timeout=3)


# --- PR 11 QoS hardening: SA-triple normalization + AIMD-derived cap ------

def test_serviceaccount_tenant_normalization():
    """The serviceaccount tenant key must not trust userInfo.username
    verbatim: only a well-formed system:serviceaccount:<ns>:<name>
    triple normalizes; malformed/spoof-shaped identities fold into the
    cluster tenant instead of minting themselves a fair-share queue."""
    def t(username):
        return qos.tenant_of_request(
            {"namespace": "x", "userInfo": {"username": username}},
            qos.TENANT_SERVICEACCOUNT)

    assert t("system:serviceaccount:team-a:bot") == \
        "system:serviceaccount:team-a:bot"
    # extra segments, empty parts, whitespace, case games: NOT an SA
    assert t("system:serviceaccount:team-a:bot:extra") == \
        qos.CLUSTER_TENANT
    assert t("system:serviceaccount::bot") == qos.CLUSTER_TENANT
    assert t("system:serviceaccount:team-a:") == qos.CLUSTER_TENANT
    assert t("system:serviceaccount: team-a :bot") == qos.CLUSTER_TENANT
    assert t("System:ServiceAccount:team-a:bot") == qos.CLUSTER_TENANT
    # non-SA identities keep their username; empty folds to cluster
    assert t("alice") == "alice"
    assert t("") == qos.CLUSTER_TENANT
    # the unit normalizer agrees
    assert qos.normalize_serviceaccount(
        "system:serviceaccount:a:b") == "system:serviceaccount:a:b"
    assert qos.normalize_serviceaccount("system:serviceaccount:a") is None


def test_tenant_cap_derives_from_live_aimd_limit():
    """tenantInflightCap scales with the limiter's LIVE limit: a cap
    chosen as a fraction of healthy capacity keeps that fraction when
    AIMD collapses, so one tenant can never own every remaining slot
    (the PR 10 isolation guarantee surviving limit collapse)."""
    cfg = qos.QoSConfig(tenant_inflight_cap=4)
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=1, max_inflight=8, initial_inflight=8,
        queue_depth=16, queue_timeout_s=2.0, qos=cfg))
    assert ctl._tenant_cap() == 4  # healthy: the configured cap
    with ctl.limiter._lock:
        ctl.limiter._limit = 2.0  # AIMD collapse
    assert ctl._tenant_cap() == 1  # ceil(4 * 2/8) = 1: a slot stays free
    with ctl.limiter._lock:
        ctl.limiter._limit = 4.0
    assert ctl._tenant_cap() == 2
    # snapshot surfaces the cap in force
    assert ctl._queue_qos.snapshot()["tenant_inflight_cap"] == 2
    # cap 0 stays unbounded at any limit
    cfg0 = qos.QoSConfig()
    ctl0 = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=1, max_inflight=8, initial_inflight=2, qos=cfg0))
    assert ctl0._tenant_cap() == 0


def test_collapsed_limit_tenant_cannot_hoard_slots():
    """Behavioral pin: static cap 4, limit collapsed to 2 — tenant A's
    burst must never hold more than the DERIVED cap (1) in review, so
    a victim tenant still gets the other slot."""
    cfg = qos.QoSConfig(tenant_inflight_cap=4)
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=1, max_inflight=8, initial_inflight=8,
        queue_depth=32, queue_timeout_s=2.0, qos=cfg))
    with ctl.limiter._lock:
        ctl.limiter._limit = 2.0
    client = _TenantTrackingClient(service_s=0.05)
    h = ValidationHandler(client, failure_policy="fail", overload=ctl)
    threads = [threading.Thread(
        target=lambda i=i, ns=ns: h.handle(
            _body(uid=f"{ns}-{i}", namespace=ns)))
        for ns in ("team-a", "team-b") for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert client.max_conc.get("team-a", 0) <= 1
    assert client.max_conc.get("team-b", 0) <= 1
    assert client.reviews == 8
    assert ctl.shed_count == 0


# --- demand-aware assuredConcurrencyShares (PR 12) -------------------------

def _shares_cfg():
    return qos.parse_qos_config({
        "priorityLevels": [
            {"name": "system", "matchNamespaces": ["kube-system"],
             "assuredConcurrencyShares": 1},
            {"name": "user", "assuredConcurrencyShares": 3},
        ]})


def test_shares_parse_and_snapshot():
    cfg = _shares_cfg()
    assert _lv(cfg, "system").shares == 1
    assert _lv(cfg, "user").shares == 3
    q = qos.QoSQueue(cfg)
    assert q.assured_cap(_lv(cfg, "system"), 8) == 2   # ceil(8*1/4)
    assert q.assured_cap(_lv(cfg, "user"), 8) == 6
    snap = q.snapshot()
    assert {l["priority"]: l["shares"] for l in snap["lanes"]} == \
        {"system": 1, "user": 3}


def test_shares_bound_a_system_lane_flood():
    """A pathological system-lane flood is bounded: with user demand
    queued, the system lane cannot take slots past its assured
    concurrency — user traffic keeps its share instead of starving
    under strict priority."""
    cfg = _shares_cfg()
    q = qos.QoSQueue(cfg)
    system, user = _lv(cfg, "system"), _lv(cfg, "user")
    seq = 0
    for i in range(32):  # the flood
        q.enqueue(qos.Ticket(seq, "kube-system", system, 10.0), 1000, 1e18)
        seq += 1
    for i in range(8):
        q.enqueue(qos.Ticket(seq, "team-a", user, 10.0), 1000, 1e18)
        seq += 1
    limit = 8
    lane_inflight = {"system": 0, "user": 0}
    granted = []
    for _ in range(limit):  # fill every limiter slot
        t = q.pick_next(lambda tn: 0,
                        lane_inflight_of=lambda nm: lane_inflight[nm],
                        limit=limit)
        assert t is not None
        lane_inflight[t.level.name] += 1
        granted.append(t.level.name)
    # system bounded at ceil(8 * 1/4) = 2; user holds its 6
    assert lane_inflight == {"system": 2, "user": 6}, granted


def test_shares_work_conserving_without_lower_demand():
    """With NO lower-priority demand the cap does not idle slots: the
    system lane takes everything (the second work-conserving pass)."""
    cfg = _shares_cfg()
    q = qos.QoSQueue(cfg)
    system = _lv(cfg, "system")
    for i in range(8):
        q.enqueue(qos.Ticket(i, "kube-system", system, 10.0), 1000, 1e18)
    lane_inflight = {"system": 0, "user": 0}
    for _ in range(8):
        t = q.pick_next(lambda tn: 0,
                        lane_inflight_of=lambda nm: lane_inflight[nm],
                        limit=8)
        assert t is not None
        lane_inflight[t.level.name] += 1
    assert lane_inflight["system"] == 8  # nothing below wanted the slots


def test_shares_unset_keeps_strict_priority_bit_identical():
    """All-zero shares (the default): pick_next with the new arguments
    decides exactly what the legacy call decides."""
    def fill(q, cfg):
        user, system = _lv(cfg, "user"), _lv(cfg, "system")
        seq = 0
        for tn, lv in (("team-a", user), ("kube-system", system),
                       ("team-b", user), ("kube-system", system)):
            q.enqueue(qos.Ticket(seq, tn, lv, 10.0), 1000, 1e18)
            seq += 1

    cfg = qos.QoSConfig()
    q1, q2 = qos.QoSQueue(cfg), qos.QoSQueue(cfg)
    fill(q1, cfg)
    fill(q2, cfg)
    legacy = [q1.pick_next(lambda tn: 0).tenant for _ in range(4)]
    shares = [q2.pick_next(lambda tn: 0,
                           lane_inflight_of=lambda nm: 0,
                           limit=8).tenant for _ in range(4)]
    assert legacy == shares
