"""Batched external-data join lane (PR 11, gatekeeper_tpu/extdata/).

THE pins: the device join is bit-identical to the exact interpreter on
every (object, constraint) pair; the batched lane resolves the same
values the per-key reference resolves; warm columns make ZERO transport
calls; Provider reconcile invalidates residency."""

import threading

import pytest

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.extdata import ExtDataDivergence, ExtDataLane, activate
from gatekeeper_tpu.extdata.column import ProviderColumn
from gatekeeper_tpu.externaldata.providers import Provider, ProviderCache
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget

TARGET = "admission.k8s.gatekeeper.sh"

# the canonical validation-side external-data template (key batching +
# response_with_error, the reference docs' shape)
RULES_ERRORS = """
package k8sextdata

violation[{"msg": msg}] {
  images := [img | img = input.review.object.spec.containers[_].image]
  response := external_data({"provider": "trusted", "keys": images})
  response_with_error(response)
  msg := sprintf("invalid images: %v", [response.errors])
}

response_with_error(response) {
  count(response.errors) > 0
}

response_with_error(response) {
  count(response.system_error) > 0
}
"""

# value-comparison shape: per-container single-key request, responses
# pair iteration, resolved value vs the original feature
RULES_DIGEST = """
package k8sdigest

violation[{"msg": msg}] {
  container := input.review.object.spec.containers[_]
  resp := external_data({"provider": "digest", "keys": [container.image]})
  item := resp.responses[_]
  item[1] != container.image
  msg := sprintf("image %v is not pinned to its digest", [container.image])
}
"""


def tmpl(kind, rego):
    return ConstraintTemplate.from_unstructured({
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {"crd": {"spec": {"names": {"kind": kind}}},
                 "targets": [{"target": TARGET, "rego": rego}]},
    })


class CountingTransport:
    """send_fn double: answers deterministically, counts round-trips."""

    def __init__(self):
        self.calls = 0
        self.keys_sent = 0
        self.lock = threading.Lock()

    def __call__(self, provider, keys):
        with self.lock:
            self.calls += 1
            self.keys_sent += len(keys)
        items = []
        for k in keys:
            if provider.name == "trusted":
                if "bad" in k:
                    items.append({"key": k, "error": f"untrusted: {k}"})
                else:
                    items.append({"key": k, "value": k})
            else:  # digest provider pins unpinned images
                if "@sha256:" in k:
                    items.append({"key": k, "value": k})
                else:
                    items.append({"key": k, "value": k + "@sha256:abc"})
        return {"response": {"items": items, "systemError": ""}}


def make_lane(mode="batched", **kw):
    transport = CountingTransport()
    cache = ProviderCache(send_fn=transport)
    cache.upsert(Provider(name="trusted", url="https://t", ca_bundle="x"))
    cache.upsert(Provider(name="digest", url="https://d", ca_bundle="x"))
    lane = ExtDataLane(cache, mode=mode, **kw)
    return lane, cache, transport


def make_driver(lane):
    tpu = TpuDriver(batch_bucket=8)
    tpu.extdata_lane = lane
    tpu.add_template(tmpl("K8sExtData", RULES_ERRORS))
    tpu.add_template(tmpl("K8sDigest", RULES_DIGEST))
    cons = [
        Constraint(kind="K8sExtData", name="trusted-images", match={},
                   parameters={}, enforcement_action="deny"),
        Constraint(kind="K8sDigest", name="pinned", match={},
                   parameters={}, enforcement_action="deny"),
    ]
    for c in cons:
        tpu.add_constraint(c)
    return tpu, cons


def corpus():
    """Pods covering every join outcome: ok keys, error keys, pinned and
    unpinned digests, duplicate keys, empty container lists, absent and
    non-string image fields."""
    pods = [
        {"kind": "Pod", "metadata": {"name": "ok"},
         "spec": {"containers": [{"name": "c", "image": "nginx"}]}},
        {"kind": "Pod", "metadata": {"name": "mixed"},
         "spec": {"containers": [{"name": "c", "image": "bad/x"},
                                 {"name": "d", "image": "repo/y"}]}},
        {"kind": "Pod", "metadata": {"name": "dup"},
         "spec": {"containers": [{"name": "c", "image": "bad/x"},
                                 {"name": "d", "image": "bad/x"}]}},
        {"kind": "Pod", "metadata": {"name": "pinned"},
         "spec": {"containers": [
             {"name": "c", "image": "repo/y@sha256:abc"}]}},
        {"kind": "Pod", "metadata": {"name": "empty"},
         "spec": {"containers": []}},
        {"kind": "Pod", "metadata": {"name": "noimage"},
         "spec": {"containers": [{"name": "c"}]}},
        {"kind": "Pod", "metadata": {"name": "numimage"},
         "spec": {"containers": [{"name": "c", "image": 42}]}},
    ]
    for i in range(40):
        img = f"bad/i{i % 5}" if i % 3 == 0 else f"ok/i{i % 7}"
        pods.append({"kind": "Pod", "metadata": {"name": f"p{i}"},
                     "spec": {"containers": [{"name": "c", "image": img}]}})
    return pods


def reviews_of(pods):
    target = K8sValidationTarget()
    return target, [target.handle_review(AugmentedUnstructured(object=p))
                    for p in pods]


def result_key(r):
    return ((r.constraint or {}).get("kind"), r.msg)


# --- ProviderColumn unit --------------------------------------------------

def test_provider_column_ttl_land_invalidate():
    clock = [0.0]
    col = ProviderColumn("p", ttl_s=10.0, clock=lambda: clock[0])
    assert col.missing(["a", "b", "a"]) == ["a", "b"]
    col.land({"a": ("v", None), "b": (None, "boom")})
    v0 = col.version
    assert col.missing(["a", "b"]) == []
    assert col.get("a") == ("v", None)
    assert col.get("b") == (None, "boom")
    clock[0] = 11.0  # TTL expiry: keys refetch, last values stay readable
    assert col.missing(["a", "b"]) == ["a", "b"]
    assert col.get("a") == ("v", None)
    col.invalidate()
    assert col.version > v0
    assert col.get("a") is None
    assert len(col) == 0


def test_lane_dedupes_and_chunks_bulk_calls():
    lane, _cache, transport = make_lane(max_keys_per_call=3)
    keys = [f"k{i}" for i in range(8)] * 4  # heavy duplication
    lane.ensure("trusted", keys)
    # 8 unique keys at <=3 per call = 3 transport sends, 8 keys total
    assert transport.calls == 3
    assert transport.keys_sent == 8
    lane.ensure("trusted", keys)  # warm: zero new transport
    assert transport.calls == 3
    res = lane.resolve_keys("trusted", ["k1", "bad/z"])
    assert res["k1"] == ("k1", None)
    assert res["bad/z"][1].startswith("untrusted")
    assert transport.calls == 4  # only the one missing key went out


def test_provider_reconcile_invalidates_column():
    lane, cache, transport = make_lane()
    lane.ensure("trusted", ["a", "b"])
    assert len(lane.column("trusted")) == 2
    # reconcile (spec change) through the cache -> listener invalidates
    cache.upsert(Provider(name="trusted", url="https://t2", ca_bundle="x"))
    assert len(lane.column("trusted")) == 0
    lane.ensure("trusted", ["a"])
    assert transport.calls == 2  # refetched after invalidation


def test_unknown_provider_errors_per_key():
    lane, _cache, _t = make_lane()
    res = lane.resolve_keys("nosuch", ["a"])
    assert res["a"][0] is None and "nosuch" in res["a"][1]


def test_builtin_without_lane_errors_every_key():
    from gatekeeper_tpu.extdata.lane import builtin_fetch

    resp = builtin_fetch({"provider": "p", "keys": ["a", 7]})
    assert resp["responses"] == []
    assert len(resp["errors"]) == 2
    assert resp["system_error"] == ""


# --- lowering coverage ----------------------------------------------------

def test_extdata_templates_lower():
    lane, _c, _t = make_lane()
    tpu, _cons = make_driver(lane)
    assert {"K8sExtData", "K8sDigest"} <= set(tpu.lowered_kinds()), \
        tpu.fallback_kinds()


def test_extdata_without_lane_falls_back_to_interp():
    tpu = TpuDriver(batch_bucket=8)
    tpu.add_template(tmpl("K8sExtData", RULES_ERRORS))
    assert "K8sExtData" in tpu.lowered_kinds()
    # the program exists, but with no lane the kind is not device-ready
    assert not tpu.extdata_ready("K8sExtData")
    lane, _c, _t = make_lane(mode="perkey")
    tpu.extdata_lane = lane
    assert not tpu.extdata_ready("K8sExtData")  # perkey: interp lane
    lane.mode = "batched"
    assert tpu.extdata_ready("K8sExtData")


# --- THE verdict differential --------------------------------------------

@pytest.mark.parametrize("mode", ["batched", "differential"])
def test_query_batch_matches_interpreter(mode):
    lane, _cache, transport = make_lane(mode=mode)
    tpu, cons = make_driver(lane)
    target, reviews = reviews_of(corpus())
    with activate(lane):
        got = tpu.query_batch(TARGET, cons, reviews)
        for oi, review in enumerate(reviews):
            expected = []
            for con in cons:
                if not target.to_matcher(con.match).match(review):
                    continue
                expected.extend(
                    tpu._interp.query(TARGET, [con], review).results)
            assert sorted(map(result_key, got[oi].results)) == \
                sorted(map(result_key, expected)), f"pod {oi}"
    assert transport.calls > 0


def test_warm_columns_make_zero_transport_calls():
    lane, _cache, transport = make_lane()
    tpu, cons = make_driver(lane)
    _target, reviews = reviews_of(corpus())
    with activate(lane):
        tpu.query_batch(TARGET, cons, reviews)
        cold = transport.calls
        tpu.query_batch(TARGET, cons, reviews)
        tpu.query_batch(TARGET, cons, reviews)
    assert transport.calls == cold


def test_batched_and_perkey_lanes_bit_identical():
    """The acceptance pin: identical verdicts AND resolved values across
    lanes, with a validation-side and a mutation-side consumer."""
    pods = corpus()
    out = {}
    for mode in ("batched", "perkey"):
        lane, _cache, _t = make_lane(mode=mode)
        tpu, cons = make_driver(lane)
        _target, reviews = reviews_of(pods)
        with activate(lane):
            got = tpu.query_batch(TARGET, cons, reviews)
        out[mode] = [sorted(map(result_key, r.results)) for r in got]
        # resolved values: every key the corpus references
        keys = sorted({c.get("image") for p in pods
                       for c in p["spec"]["containers"]
                       if isinstance(c.get("image"), str)})
        with activate(lane):
            out[mode + ":vals"] = lane.resolve_keys("digest", keys)
    assert out["batched"] == out["perkey"]
    assert out["batched:vals"] == out["perkey:vals"]


def test_differential_mode_catches_tampered_column():
    lane, _cache, _t = make_lane(mode="differential")
    tpu, cons = make_driver(lane)
    _target, reviews = reviews_of(corpus()[:4])
    with activate(lane):
        tpu.query_batch(TARGET, cons, reviews)  # clean pass
        # tamper a resolved value behind the per-key reference's back
        col = lane.column("digest")
        key = next(iter(col.snapshot()))
        col.land({key: ("tampered", None)})
        with pytest.raises(ExtDataDivergence):
            tpu.query_batch(TARGET, cons, reviews)


# --- audit sweep ----------------------------------------------------------

def test_sweep_exact_totals_and_lane_parity():
    from gatekeeper_tpu.parallel.sharded import (ShardedEvaluator,
                                                 make_mesh,
                                                 violation_rows)

    pods = []
    want_bad = set()
    for i in range(120):
        bad = i % 3 == 0
        if bad:
            want_bad.add(i)
        img = f"bad/i{i % 7}" if bad else f"ok/i{i % 11}"
        pods.append({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": f"p{i}", "uid": f"u{i}"},
                     "spec": {"containers": [{"name": "c", "image": img}]}})
    lane, _cache, transport = make_lane()
    tpu = TpuDriver(batch_bucket=8)
    tpu.extdata_lane = lane
    tpu.add_template(tmpl("K8sExtData", RULES_ERRORS))
    con = Constraint(kind="K8sExtData", name="x", match={}, parameters={},
                     enforcement_action="deny")
    tpu.add_constraint(con)
    ev = ShardedEvaluator(tpu, make_mesh())
    with activate(lane):
        out = ev.sweep([con], pods, return_bits=True)
        _cons, _idx, _valid, counts, bits = out["K8sExtData"]
        assert counts[0] == len(want_bad)
        rows = set(violation_rows(bits, 0, len(pods)).tolist())
        assert rows == want_bad
        # the whole chunk cost ONE bulk transport call (18 unique keys)
        assert transport.calls == 1
        # perkey lane: the kind leaves the device set; the caller's
        # interpreter fallback is the reference (sweep returns {})
        lane.mode = "perkey"
        assert ev.sweep([con], pods) == {}
        lane.mode = "batched"


# --- mutation-side consumer ----------------------------------------------

MUTATOR = {
    "apiVersion": "mutations.gatekeeper.sh/v1",
    "kind": "Assign",
    "metadata": {"name": "pin-image"},
    "spec": {
        "applyTo": [{"groups": [""], "versions": ["v1"], "kinds": ["Pod"]}],
        "location": "spec.containers[name:*].image",
        "parameters": {"assign": {
            "externalData": {"provider": "digest",
                             "dataSource": "ValueAtLocation",
                             "failurePolicy": "Fail"}}},
    },
}


def mutate_pod():
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "m"},
            "spec": {"containers": [{"name": "a", "image": "repo/a"},
                                    {"name": "b", "image": "repo/b"},
                                    {"name": "c", "image": "repo/a"}]}}


def test_mutation_placeholders_batch_resolve_identical():
    from gatekeeper_tpu.mutation.system import MutationSystem

    results = {}
    calls = {}
    for mode in ("batched", "perkey"):
        lane, cache, transport = make_lane(mode=mode)
        sys_ = MutationSystem(provider_cache=cache)
        sys_.upsert_unstructured(MUTATOR)
        obj = mutate_pod()
        with activate(lane):
            changed = sys_.mutate(obj)
        assert changed
        results[mode] = obj
        calls[mode] = transport.calls
    assert results["batched"] == results["perkey"]
    imgs = [c["image"] for c in results["batched"]["spec"]["containers"]]
    assert imgs == ["repo/a@sha256:abc", "repo/b@sha256:abc",
                    "repo/a@sha256:abc"]
    # batched: ONE bulk call for the deduped {repo/a, repo/b}.  (The
    # perkey reference ALSO coalesces here — PR 2's prefetch already
    # batched the mutation convergence pass — so the contrast this pin
    # guards is resolve identity, not mutation-path call counts.)
    assert calls["batched"] == 1
    assert calls["perkey"] >= 1


# --- gator generate-vap (satellite) --------------------------------------

def test_gator_generate_vap_library_cel_template(capsys):
    from gatekeeper_tpu.gator.generate_vap_cmd import run_cli

    rc = run_cli(["-f", "library/general/containerlimitscel"])
    assert rc == 0
    out = capsys.readouterr().out
    import yaml as _yaml

    docs = list(_yaml.safe_load_all(out))
    kinds = [d["kind"] for d in docs]
    assert "ValidatingAdmissionPolicy" in kinds
    assert "ValidatingAdmissionPolicyBinding" in kinds
    vap = docs[kinds.index("ValidatingAdmissionPolicy")]
    assert vap["spec"]["paramKind"]["kind"] == "K8sContainerLimitsCEL"
    assert vap["spec"]["validations"]
    names = [v["name"] for v in vap["spec"]["variables"]]
    assert "params" in names and "anyObject" in names
    vapb = docs[kinds.index("ValidatingAdmissionPolicyBinding")]
    assert vapb["spec"]["policyName"] == vap["metadata"]["name"]


def test_gator_generate_vap_skips_rego_templates(capsys):
    from gatekeeper_tpu.gator import reader  # noqa: F401
    from gatekeeper_tpu.gator.generate_vap_cmd import generate

    docs, skipped = generate([
        {"apiVersion": "templates.gatekeeper.sh/v1",
         "kind": "ConstraintTemplate",
         "metadata": {"name": "regoonly"},
         "spec": {"crd": {"spec": {"names": {"kind": "RegoOnly"}}},
                  "targets": [{"target": TARGET,
                               "rego": RULES_ERRORS}]}}])
    assert docs == []
    assert skipped and skipped[0][0] == "RegoOnly"


# --- idiom boundary: variants lower or fall back, never diverge ----------

VARIANTS = {
    # exact counts are dedupe-sensitive: interpreter lane
    "K8sExact": ("fallback", """
package a
violation[{"msg": "x"}] {
  images := [img | img = input.review.object.spec.containers[_].image]
  resp := external_data({"provider": "trusted", "keys": images})
  count(resp.errors) == 2
}
"""),
    # responses pair key slot: only the value slot lowers
    "K8sKeySlot": ("fallback", """
package b
violation[{"msg": "x"}] {
  c := input.review.object.spec.containers[_]
  resp := external_data({"provider": "trusted", "keys": [c.image]})
  item := resp.responses[_]
  item[0] == "nginx"
}
"""),
    # non-constant provider name: interpreter lane
    "K8sDynProv": ("fallback", """
package c
violation[{"msg": "x"}] {
  p := input.parameters.provider
  resp := external_data({"provider": p, "keys": ["k"]})
  count(resp.errors) > 0
}
"""),
    # error strings are host-rendered: iterating them stays exact-engine
    "K8sErrIter": ("fallback", """
package f
violation[{"msg": msg}] {
  images := [img | img = input.review.object.spec.containers[_].image]
  resp := external_data({"provider": "trusted", "keys": images})
  e := resp.errors[_]
  msg := sprintf("%v", [e])
}
"""),
    # negated helper over the errors count: ¬∃ closes on device
    "K8sNegated": ("lowered", """
package d
violation[{"msg": "x"}] {
  images := [img | img = input.review.object.spec.containers[_].image]
  resp := external_data({"provider": "trusted", "keys": images})
  not clean(resp)
}
clean(resp) { count(resp.errors) == 0 }
"""),
    # responses emptiness
    "K8sNoResp": ("lowered", """
package e
violation[{"msg": "x"}] {
  images := [img | img = input.review.object.spec.containers[_].image]
  resp := external_data({"provider": "trusted", "keys": images})
  count(resp.responses) == 0
}
"""),
    # resolved-value string predicate
    "K8sPrefix": ("lowered", """
package g
violation[{"msg": "x"}] {
  c := input.review.object.spec.containers[_]
  resp := external_data({"provider": "digest", "keys": [c.image]})
  item := resp.responses[_]
  not startswith(item[1], "repo/")
}
"""),
}


def test_idiom_variants_route_and_agree():
    """Each variant either lowers or cleanly falls back (LowerError is
    the ONLY acceptable compile failure), and EVERY variant's verdicts
    match the interpreter over the full corpus either way."""
    lane, _cache, _t = make_lane()
    tpu = TpuDriver(batch_bucket=8)
    tpu.extdata_lane = lane
    cons = []
    for kind, (_want, rules) in VARIANTS.items():
        tpu.add_template(tmpl(kind, rules))
        con = Constraint(kind=kind, name=kind.lower(), match={},
                         parameters={}, enforcement_action="deny")
        tpu.add_constraint(con)
        cons.append(con)
    lowered = set(tpu.lowered_kinds())
    for kind, (want, _rules) in VARIANTS.items():
        assert (kind in lowered) == (want == "lowered"), \
            (kind, want, tpu.fallback_kinds().get(kind))
    target, reviews = reviews_of(corpus())
    with activate(lane):
        got = tpu.query_batch(TARGET, cons, reviews)
        for oi, review in enumerate(reviews):
            expected = []
            for con in cons:
                if not target.to_matcher(con.match).match(review):
                    continue
                expected.extend(
                    tpu._interp.query(TARGET, [con], review).results)
            assert sorted(map(result_key, got[oi].results)) == \
                sorted(map(result_key, expected)), f"pod {oi}"


# --- per-provider fan-out (PR 12) ------------------------------------------

def test_ensure_many_parity_with_serial():
    """ensure_many (thread-pool fan-out) lands exactly what serial
    ensures land: same values, same per-key errors, same bulk-call
    count."""
    keys_t = ["nginx", "bad/x", "repo/y"]
    keys_d = ["img@sha256:abc", "plain"]
    lane_s, _c1, tr_s = make_lane(fanout=1)
    n_s = lane_s.ensure_many({"trusted": keys_t, "digest": keys_d})
    lane_f, _c2, tr_f = make_lane(fanout=4)
    n_f = lane_f.ensure_many({"trusted": keys_t, "digest": keys_d})
    assert n_s == n_f == len(keys_t) + len(keys_d)
    assert tr_s.calls == tr_f.calls == 2  # one bulk call per provider
    for prov, keys in (("trusted", keys_t), ("digest", keys_d)):
        assert lane_s.resolve_keys(prov, keys) == \
            lane_f.resolve_keys(prov, keys)
    # warm re-ensure: zero transport either way
    assert lane_f.ensure_many({"trusted": keys_t, "digest": keys_d}) == 0
    assert tr_f.calls == 2


def test_ensure_many_actually_overlaps_providers():
    """Two cold providers' bulk fetches overlap in wall time: each
    fetch blocks on a barrier only released when BOTH are in flight —
    completing at all proves the fan-out is concurrent."""
    barrier = threading.Barrier(2, timeout=10.0)

    def blocking_transport(provider, keys):
        barrier.wait()  # serial execution would deadlock here
        return {"response": {
            "items": [{"key": k, "value": k} for k in keys],
            "systemError": ""}}

    cache = ProviderCache(send_fn=blocking_transport)
    cache.upsert(Provider(name="p1", url="https://1", ca_bundle="x"))
    cache.upsert(Provider(name="p2", url="https://2", ca_bundle="x"))
    lane = ExtDataLane(cache, fanout=4)
    n = lane.ensure_many({"p1": ["a", "b"], "p2": ["c"]})
    assert n == 3
    assert lane.resolve_keys("p1", ["a"]) == {"a": ("a", None)}
    assert lane.resolve_keys("p2", ["c"]) == {"c": ("c", None)}


def test_ensure_many_failure_semantics_unchanged():
    """A provider whose transport raises degrades per key exactly as
    the serial path: the OTHER provider's keys land clean."""
    def flaky_transport(provider, keys):
        if provider.name == "p1":
            raise RuntimeError("transport down")
        return {"response": {
            "items": [{"key": k, "value": k} for k in keys],
            "systemError": ""}}

    for fanout in (1, 4):
        cache = ProviderCache(send_fn=flaky_transport)
        cache.upsert(Provider(name="p1", url="https://1", ca_bundle="x"))
        cache.upsert(Provider(name="p2", url="https://2", ca_bundle="x"))
        lane = ExtDataLane(cache, fanout=fanout)
        lane.ensure_many({"p1": ["a"], "p2": ["b"]})
        ra = lane.resolve_keys("p1", ["a"])["a"]
        assert ra[0] is None and ra[1]  # per-key error, not an exception
        assert lane.resolve_keys("p2", ["b"]) == {"b": ("b", None)}
