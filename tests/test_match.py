"""Match predicate semantics (reference: pkg/mutation/match/match_test.go
table-driven cases, condensed)."""

import pytest

from gatekeeper_tpu.match.match import Matchable, MatchError, matches
from gatekeeper_tpu.match import wildcard


def pod(name="p", ns="default", labels=None):
    meta = {"name": name, "namespace": ns}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta}


def namespace(name, labels=None):
    meta = {"name": name}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Namespace", "metadata": meta}


def test_empty_match_matches_everything():
    assert matches({}, Matchable(pod()))


def test_kinds_with_wildcards():
    m = {"kinds": [{"apiGroups": ["*"], "kinds": ["Pod"]}]}
    assert matches(m, Matchable(pod()))
    assert not matches(m, Matchable(namespace("x")))
    m2 = {"kinds": [{"apiGroups": ["apps"], "kinds": ["*"]}]}
    assert not matches(m2, Matchable(pod()))  # pod group is ""
    m3 = {"kinds": [{"apiGroups": [""], "kinds": ["Deployment"]},
                    {"apiGroups": [""], "kinds": ["Pod"]}]}
    assert matches(m3, Matchable(pod()))


def test_namespaces_globs():
    m = {"namespaces": ["kube-*"]}
    assert matches(m, Matchable(pod(ns="kube-system")))
    assert not matches(m, Matchable(pod(ns="default")))
    # namespace objects match on their own name (match.go:160-161)
    assert matches(m, Matchable(namespace("kube-public")))
    # cluster-scoped non-namespace objects can't be disqualified
    crd = {"apiVersion": "apiextensions.k8s.io/v1", "kind": "CustomResourceDefinition",
           "metadata": {"name": "x"}}
    assert matches(m, Matchable(crd))


def test_excluded_namespaces():
    m = {"excludedNamespaces": ["*-system"]}
    assert not matches(m, Matchable(pod(ns="kube-system")))
    assert matches(m, Matchable(pod(ns="default")))


def test_label_selector():
    m = {"labelSelector": {"matchLabels": {"app": "web"}}}
    assert matches(m, Matchable(pod(labels={"app": "web"})))
    assert not matches(m, Matchable(pod(labels={"app": "db"})))
    assert not matches(m, Matchable(pod()))
    m2 = {"labelSelector": {"matchExpressions": [
        {"key": "env", "operator": "In", "values": ["prod", "stage"]}]}}
    assert matches(m2, Matchable(pod(labels={"env": "prod"})))
    assert not matches(m2, Matchable(pod(labels={"env": "dev"})))
    m3 = {"labelSelector": {"matchExpressions": [
        {"key": "env", "operator": "DoesNotExist"}]}}
    assert matches(m3, Matchable(pod()))
    assert not matches(m3, Matchable(pod(labels={"env": "prod"})))


def test_namespace_selector():
    m = {"namespaceSelector": {"matchLabels": {"team": "a"}}}
    ns_obj = namespace("default", labels={"team": "a"})
    assert matches(m, Matchable(pod(), namespace=ns_obj))
    # namespace objects: selector applies to their own labels (match.go:92-93)
    assert matches(m, Matchable(namespace("x", labels={"team": "a"})))
    assert not matches(m, Matchable(namespace("x")))
    # cluster-scoped non-namespace: matches all (match.go:82-85)
    crd = {"apiVersion": "apiextensions.k8s.io/v1", "kind": "CustomResourceDefinition",
           "metadata": {"name": "x"}}
    assert matches(m, Matchable(crd))
    # namespaced object with no ns data: error (match.go:96-98)
    with pytest.raises(MatchError):
        matches(m, Matchable(pod()))


def test_scope():
    assert matches({"scope": "Cluster"}, Matchable(namespace("x")))
    assert not matches({"scope": "Cluster"}, Matchable(pod()))
    assert matches({"scope": "Namespaced"}, Matchable(pod()))
    assert not matches({"scope": "Namespaced"}, Matchable(namespace("x")))
    # invalid scope matches everything (match.go:223-226)
    assert matches({"scope": "cluster"}, Matchable(pod()))


def test_name_and_generate_name():
    m = {"name": "web-*"}
    assert matches(m, Matchable(pod(name="web-1")))
    assert not matches(m, Matchable(pod(name="db-1")))
    gen = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"generateName": "web-", "namespace": "default"}}
    assert matches(m, Matchable(gen))


def test_source():
    m = {"source": "Generated"}
    assert matches(m, Matchable(pod(), source="Generated"))
    assert not matches(m, Matchable(pod(), source="Original"))
    assert matches({"source": "All"}, Matchable(pod(), source="Original"))
    assert matches({}, Matchable(pod(), source=""))
    with pytest.raises(MatchError):
        matches({"source": "Generated"}, Matchable(pod(), source=""))


def test_wildcard_globs():
    assert wildcard.matches("*", "anything")
    assert wildcard.matches("*sys*", "kube-system")
    assert not wildcard.matches("kube", "kube-system")
    assert not wildcard.matches_generate_name("*-system", "kube-")
