"""Sync plane, readiness, metrics, export, external data, and the
reconciliation manager — the control-plane equivalents of SURVEY.md §2.5-2.7."""

import json
import os

import pytest

from gatekeeper_tpu.apis.constraints import WEBHOOK_EP
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.controller.manager import Manager
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.export.system import ExportSystem
from gatekeeper_tpu.externaldata.placeholders import ExternalDataPlaceholder
from gatekeeper_tpu.externaldata.providers import Provider, ProviderCache, ProviderError
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.readiness.tracker import Tracker
from gatekeeper_tpu.sync.aggregator import GVKAggregator
from gatekeeper_tpu.sync.source import FakeCluster
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.unstructured import load_yaml_file

LIB = os.path.join(os.path.dirname(__file__), "..", "library", "general")


def test_aggregator_reverse_index():
    agg = GVKAggregator()
    agg.upsert(("config", "config"), [("", "v1", "Pod"), ("", "v1", "Secret")])
    agg.upsert(("syncset", "s1"), [("", "v1", "Pod")])
    assert agg.gvks() == {("", "v1", "Pod"), ("", "v1", "Secret")}
    agg.remove(("config", "config"))
    assert agg.gvks() == {("", "v1", "Pod")}  # still wanted by s1
    agg.remove(("syncset", "s1"))
    assert agg.gvks() == set()


def ns(name, labels=None):
    meta = {"name": name}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Namespace", "metadata": meta}


def make_manager(metrics=None, **kw):
    client = Client(target=K8sValidationTarget(), drivers=[TpuDriver()],
                    enforcement_points=[WEBHOOK_EP, "audit.gatekeeper.sh",
                                        "gator.gatekeeper.sh"])
    cluster = FakeCluster()
    mgr = Manager(client, cluster, metrics=metrics, **kw).start()
    return client, cluster, mgr


def test_manager_reconciles_referential_policy_via_sync():
    """The full sync loop: Config -> watch -> inventory -> referential
    verdicts (the reference's data-sync plane, SURVEY.md §3.4)."""
    client, cluster, mgr = make_manager()
    cluster.apply(load_yaml_file(
        os.path.join(LIB, "uniqueingresshost", "template.yaml"))[0])
    cluster.apply(load_yaml_file(
        os.path.join(LIB, "uniqueingresshost", "samples",
                     "constraint.yaml"))[0])
    cluster.apply({
        "apiVersion": "config.gatekeeper.sh/v1alpha1",
        "kind": "Config",
        "metadata": {"name": "config", "namespace": "gatekeeper-system"},
        "spec": {"sync": {"syncOnly": [
            {"group": "networking.k8s.io", "version": "v1",
             "kind": "Ingress"}]}},
    })
    existing = load_yaml_file(os.path.join(
        LIB, "uniqueingresshost", "samples", "example_inventory.yaml"))[0]
    cluster.apply(existing)  # synced into data.inventory via the watch
    conflicting = load_yaml_file(os.path.join(
        LIB, "uniqueingresshost", "samples", "example_disallowed.yaml"))[0]
    resp = client.review(AugmentedUnstructured(object=conflicting),
                         enforcement_point=WEBHOOK_EP)
    assert len(resp.results()) == 1
    assert "conflicts" in resp.results()[0].msg
    # deleting the synced object clears the inventory -> no violation
    cluster.delete(existing)
    resp = client.review(AugmentedUnstructured(object=conflicting),
                         enforcement_point=WEBHOOK_EP)
    assert resp.results() == []


def test_manager_template_error_cancels_readiness():
    client, cluster, mgr = make_manager()
    bad = load_yaml_file("/root/reference/demo/basic/bad/bad_template.yaml")[0]
    cluster.apply(bad)
    mgr.tracker.all_populated()
    assert mgr.tracker.satisfied()  # cancelled, not wedged
    assert "lowercase" in mgr.template_error(
        (bad.get("metadata") or {}).get("name"))
    # the error travels via this pod's *PodStatus CR, folded into the
    # parent's .status.byPod by the status controller
    name = (bad.get("metadata") or {}).get("name")
    stored = cluster.get(
        ("templates.gatekeeper.sh", "v1", "ConstraintTemplate"), "", name)
    assert stored["status"]["byPod"][0]["errors"]
    assert stored["status"]["byPod"][0]["id"] == mgr.pod_name


def test_manager_excluder_wipe_and_replay():
    client, cluster, mgr = make_manager()
    cluster.apply({
        "apiVersion": "config.gatekeeper.sh/v1alpha1",
        "kind": "Config", "metadata": {"name": "config"},
        "spec": {"sync": {"syncOnly": [
            {"group": "", "version": "v1", "kind": "Namespace"}]}},
    })
    cluster.apply(ns("keep-me"))
    cluster.apply(ns("kube-system"))
    inv = mgr.client.drivers[0]._interp._data.get("inventory", {})
    assert "keep-me" in json.dumps(inv)
    assert "kube-system" in json.dumps(inv)
    # excluder change wipes and replays without the excluded namespace
    cluster.apply({
        "apiVersion": "config.gatekeeper.sh/v1alpha1",
        "kind": "Config", "metadata": {"name": "config"},
        "spec": {
            "sync": {"syncOnly": [
                {"group": "", "version": "v1", "kind": "Namespace"}]},
            "match": [{"processes": ["sync"],
                       "excludedNamespaces": ["kube-*"]}],
        },
    })
    inv = mgr.client.drivers[0]._interp._data.get("inventory", {})
    blob = json.dumps(inv)
    assert "keep-me" in blob
    # namespaces are cluster-scoped objects named kube-system; exclusion
    # keys on metadata.namespace, so cluster-scoped objects stay — verify a
    # namespaced object is dropped instead
    cluster.apply({"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": "x"}})
    pod_gvk_config = {
        "apiVersion": "config.gatekeeper.sh/v1alpha1",
        "kind": "Config", "metadata": {"name": "config"},
        "spec": {
            "sync": {"syncOnly": [
                {"group": "", "version": "v1", "kind": "Namespace"},
                {"group": "", "version": "v1", "kind": "Pod"}]},
            "match": [{"processes": ["sync"],
                       "excludedNamespaces": ["kube-*"]}],
        },
    }
    cluster.apply(pod_gvk_config)
    cluster.apply({"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": "p1", "namespace": "kube-system"}})
    cluster.apply({"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": "p2", "namespace": "default"}})
    blob = json.dumps(
        mgr.client.drivers[0]._interp._data.get("inventory", {}))
    assert "p2" in blob and '"p1"' not in blob


def test_readiness_tracker():
    t = Tracker()
    t.expect("templates", "a")
    t.expect("templates", "b")
    t.all_populated()
    assert not t.satisfied()
    t.observe("templates", "a")
    t.try_cancel("templates", "b")
    assert t.satisfied()


def test_readiness_try_cancel_retry_budget():
    """TryCancelExpect circuit breaker (object_tracker.go:158-188): a
    retryable failure only cancels once the per-object budget is spent."""
    t = Tracker(retries=2)
    t.expect("templates", "bad")
    t.all_populated()
    assert not t.try_cancel("templates", "bad")  # 2 -> 1
    assert not t.try_cancel("templates", "bad")  # 1 -> 0
    assert not t.satisfied()
    assert t.stats()["templates"]["retrying"] == 1
    assert t.try_cancel("templates", "bad")  # budget spent: cancelled
    assert t.satisfied()
    # -1 retries forever: the expectation survives any number of tries
    t2 = Tracker(retries=-1)
    t2.expect("templates", "bad")
    t2.all_populated()
    for _ in range(10):
        assert not t2.try_cancel("templates", "bad")
    assert not t2.satisfied()
    # an observation resets the budget (reference deletes the objData)
    t3 = Tracker(retries=1)
    t3.expect("templates", "flaky")
    t3.all_populated()
    assert not t3.try_cancel("templates", "flaky")  # budget 1 -> 0
    t3.observe("templates", "flaky")
    assert t3.satisfied()


def test_readiness_all_satisfied_breaker_latches():
    """Once satisfied, the tracker latches and frees tracking state
    (object_tracker.go:65,336-345): late arrivals cannot flip a serving
    pod back to not-ready."""
    t = Tracker()
    t.expect("templates", "a")
    t.all_populated()
    t.observe("templates", "a")
    assert t.satisfied()
    snap = t.stats()["templates"]
    assert snap["satisfied"] and snap["expected"] == 1
    # post-trip expectations are no-ops; satisfied stays latched
    t.expect("templates", "late-poisoned")
    assert t.satisfied()
    assert t.stats()["templates"] == snap


def test_poisoned_template_trips_breaker_serving_goes_ready():
    """One poisoned template exhausts its retry budget and trips its
    breaker; readiness goes green for everything else (VERDICT r2 #7).
    Expectations are seeded from the boot snapshot, so both templates
    exist before the manager starts."""
    good = load_yaml_file(os.path.join(
        LIB, "requiredlabels", "template.yaml"))[0]
    bad = load_yaml_file(
        "/root/reference/demo/basic/bad/bad_template.yaml")[0]

    def boot(retries):
        client = Client(target=K8sValidationTarget(),
                        drivers=[TpuDriver()],
                        enforcement_points=[WEBHOOK_EP,
                                            "audit.gatekeeper.sh"])
        cluster = FakeCluster()
        cluster.apply(good)
        cluster.apply(bad)
        mgr = Manager(client, cluster, readiness_retries=retries).start()
        mgr.tracker.all_populated()
        return cluster, mgr

    # retries=-1: the poisoned template may never be disregarded — the
    # pod (correctly) wedges not-ready until a human intervenes
    _, wedged = boot(-1)
    assert not wedged.tracker.satisfied()
    assert wedged.tracker.stats()["templates"]["cancelled"] == 0

    # a finite budget: repeated compile failures spend it, the breaker
    # trips, and serving goes ready for everything else
    cluster, mgr = boot(1)
    cluster.apply(bad)  # one more failed reconcile beyond the boot ones
    assert mgr.tracker.satisfied()
    st = mgr.tracker.stats()["templates"]
    assert st["satisfied"] and st["cancelled"] == 1 and st["observed"] >= 1

    # nothing external retriggers reconcile: the manager's own backoff
    # requeue must spend the budget (a watch event only fires once —
    # without the requeue, /readyz would wedge forever at budget > 0)
    import time as _time

    _, mgr3 = boot(3)
    deadline = _time.time() + 15
    while _time.time() < deadline and not mgr3.tracker.satisfied():
        _time.sleep(0.2)
    assert mgr3.tracker.satisfied(), mgr3.tracker.stats()["templates"]


def test_metrics_render():
    m = MetricsRegistry()
    m.inc_counter("validation_request_count", {"admission_status": "allow"})
    m.set_gauge("constraints", 4, {"enforcement_action": "deny"})
    m.observe("validation_request_duration_seconds", 0.01)
    out = m.render()
    assert 'gatekeeper_validation_request_count{admission_status="allow"} 1' \
        in out
    assert 'gatekeeper_constraints{enforcement_action="deny"} 4' in out
    assert "gatekeeper_validation_request_duration_seconds_count 1" in out


def test_export_disk_rotation(tmp_path):
    sys_ = ExportSystem()
    sys_.upsert_connection("disk", "disk", {"path": str(tmp_path),
                                            "maxAuditResults": 2})
    for i in range(4):
        sys_.publish_audit_started(f"run{i}")
        sys_.publish({"event": "violation", "auditID": f"run{i}", "n": i})
        sys_.publish_audit_ended(f"run{i}")
    files = sorted(f for f in os.listdir(tmp_path) if f.startswith("audit_"))
    assert len(files) == 2  # rotation keeps newest N
    last = open(os.path.join(tmp_path, files[-1])).read().splitlines()
    assert json.loads(last[0])["event"] == "audit_started"
    assert json.loads(last[-1])["event"] == "audit_ended"


def test_provider_cache_and_placeholders():
    calls = []

    def fake_send(provider, keys):
        calls.append(list(keys))
        return {"response": {"items": [
            {"key": k, "value": f"resolved-{k}"} for k in keys
        ]}}

    cache = ProviderCache(send_fn=fake_send)
    with pytest.raises(ProviderError):
        cache.upsert({"apiVersion": "externaldata.gatekeeper.sh/v1beta1",
                      "kind": "Provider", "metadata": {"name": "p"},
                      "spec": {"url": "http://insecure"}})
    cache.upsert({"apiVersion": "externaldata.gatekeeper.sh/v1beta1",
                  "kind": "Provider", "metadata": {"name": "p"},
                  "spec": {"url": "https://provider.local:8443/validate",
                           "caBundle": "Zm9v", "timeout": 1}})
    out = cache.fetch("p", ["a", "b"])
    assert out["a"] == ("resolved-a", None)
    out2 = cache.fetch("p", ["a"])  # TTL cache: no second call
    assert calls == [["a", "b"]]

    # mutation placeholder end-to-end (Assign externalData source)
    from gatekeeper_tpu.mutation.system import MutationSystem

    system = MutationSystem(provider_cache=cache)
    system.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign", "metadata": {"name": "img"},
        "spec": {
            "applyTo": [{"groups": [""], "versions": ["v1"],
                         "kinds": ["Pod"]}],
            "location": "spec.containers[name: *].image",
            "parameters": {"assign": {"externalData": {
                "provider": "p", "failurePolicy": "UseDefault",
                "default": "fallback:latest"}}},
        },
    })
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "x", "namespace": "d"},
           "spec": {"containers": [{"name": "c", "image": "nginx"}]}}
    assert system.mutate(pod)
    assert pod["spec"]["containers"][0]["image"] == "resolved-nginx"
    # failure policy UseDefault on provider error
    def err_send(provider, keys):
        raise RuntimeError("down")

    cache2 = ProviderCache(send_fn=err_send)
    cache2.upsert(Provider(name="p", url="https://x", ca_bundle="x"))
    ph = ExternalDataPlaceholder(provider="p", failure_policy="UseDefault",
                                 default="dflt")
    assert cache2.resolve(ph) == "dflt"


def test_vap_generation_through_manager():
    """CEL templates with generateVAP produce VAP + VAPB objects in the
    cluster (reference: manageVAP/manageVAPB controllers)."""
    client, cluster, mgr = make_manager()
    from gatekeeper_tpu.drivers.cel_driver import CELDriver

    client.drivers.append(CELDriver())
    cluster.apply({
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8svaptest"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sVapTest"}}},
            "targets": [{"target": "admission.k8s.gatekeeper.sh",
                         "code": [{"engine": "K8sNativeValidation",
                                   "source": {
                                       "generateVAP": True,
                                       "validations": [{
                                           "expression": "object != null",
                                           "message": "m"}],
                                   }}]}],
        },
    })
    cluster.apply({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sVapTest", "metadata": {"name": "vap-c"}, "spec": {},
    })
    vaps = cluster.list(("admissionregistration.k8s.io", "v1",
                         "ValidatingAdmissionPolicy"))
    vapbs = cluster.list(("admissionregistration.k8s.io", "v1",
                          "ValidatingAdmissionPolicyBinding"))
    assert len(vaps) == 1 and vaps[0]["metadata"]["name"] == \
        "gatekeeper-k8svaptest"
    assert len(vapbs) == 1 and vapbs[0]["spec"]["policyName"] == \
        "gatekeeper-k8svaptest"


def test_webhook_certs(tmp_path):
    import ssl
    import subprocess

    from gatekeeper_tpu.webhook.certs import generate_certs

    out = generate_certs(str(tmp_path))
    assert out["ca_bundle"]
    # the serving cert verifies against the CA
    proc = subprocess.run(
        ["openssl", "verify", "-CAfile", out["ca"], out["cert"]],
        capture_output=True, text=True)
    assert "OK" in proc.stdout
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(out["cert"], out["key"])  # loads without error


def test_dapr_export_driver_publishes_to_sidecar():
    """dapr driver POSTs messages to the sidecar pub-sub HTTP API
    (reference export/dapr/dapr.go; a local HTTP server stands in)."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from gatekeeper_tpu.export.system import ExportSystem

    received = []

    class Sidecar(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, _json.loads(body)))
            self.send_response(204)
            self.end_headers()

    srv = HTTPServer(("127.0.0.1", 0), Sidecar)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        sys_ = ExportSystem()
        sys_.upsert_connection_cr({
            "metadata": {"name": "audit"},
            "spec": {"driver": "dapr",
                     "config": {"component": "pubsub",
                                "topic": "audit-channel",
                                "port": srv.server_address[1]}},
        })
        assert sys_.publish_audit_started("id-1") == []
        assert sys_.publish({"event": "violation", "x": 1}) == []
        path, body = received[0]
        assert path == "/v1.0/publish/pubsub/audit-channel"
        assert body["event"] == "audit_started"
        assert received[1][1] == {"event": "violation", "x": 1}
    finally:
        srv.shutdown()

    # sidecar down: publish surfaces a per-connection error (fed back to
    # the Connection CR status in the reference)
    sys2 = ExportSystem()
    sys2.upsert_connection("audit", "dapr",
                           {"port": srv.server_address[1]})
    errs = sys2.publish({"event": "violation"})
    assert errs and errs[0][0] == "audit"


def test_webhookconfig_cache_mirrors_scope_into_vap():
    """A ValidatingWebhookConfiguration's match scope is cached and
    mirrored into generated VAPs (reference webhookconfig controller +
    cache)."""
    from gatekeeper_tpu.apis.constraints import WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.controller.manager import Manager
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.sync.source import FakeCluster
    from gatekeeper_tpu.target.target import K8sValidationTarget

    client = Client(target=K8sValidationTarget(),
                    drivers=[TpuDriver(), CELDriver()],
                    enforcement_points=[WEBHOOK_EP])
    cluster = FakeCluster()
    mgr = Manager(client, cluster).start()
    cluster.apply({
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8scelscope"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sCelScope"}}},
            "targets": [{
                "target": "admission.k8s.gatekeeper.sh",
                "code": [{"engine": "K8sNativeValidation", "source": {
                    "generateVAP": True,
                    "validations": [{"expression": "1 == 1",
                                     "message": "x"}],
                }}],
            }],
        },
    })
    vap_key = ("admissionregistration.k8s.io", "v1",
               "ValidatingAdmissionPolicy")
    vaps = list(cluster.list(vap_key))
    assert vaps, "VAP not generated"
    mc = vaps[0]["spec"]["matchConstraints"]
    assert mc["resourceRules"][0]["apiGroups"] == ["*"]
    assert "namespaceSelector" not in mc

    cluster.apply({
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {"name": "gatekeeper-validating-webhook-configuration"},
        "webhooks": [{
            "name": "validation.gatekeeper.sh",
            "namespaceSelector": {"matchExpressions": [{
                "key": "admission.gatekeeper.sh/ignore",
                "operator": "DoesNotExist"}]},
            "rules": [{"apiGroups": [""], "apiVersions": ["v1"],
                       "operations": ["CREATE", "UPDATE"],
                       "resources": ["pods"]}],
        }],
    })
    vaps = list(cluster.list(vap_key))
    mc = vaps[0]["spec"]["matchConstraints"]
    assert mc["resourceRules"][0]["resources"] == ["pods"]
    assert mc["namespaceSelector"]["matchExpressions"][0]["key"] == \
        "admission.gatekeeper.sh/ignore"


def test_routing_cluster_splits_management_and_target():
    """Remote-cluster routing (reference pkg/routing): status group +
    Secrets go to the management cluster, workload traffic to the
    target."""
    from gatekeeper_tpu.sync.routing import RoutingCluster
    from gatekeeper_tpu.sync.source import FakeCluster

    mgmt, target = FakeCluster(), FakeCluster()
    rc = RoutingCluster(mgmt, target)

    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "p", "namespace": "default"}}
    status = {"apiVersion": "status.gatekeeper.sh/v1beta1",
              "kind": "ConstraintPodStatus",
              "metadata": {"name": "s", "namespace": "gatekeeper-system"}}
    secret = {"apiVersion": "v1", "kind": "Secret",
              "metadata": {"name": "gatekeeper-webhook-server-cert",
                           "namespace": "gatekeeper-system"}}
    rc.apply(pod)
    rc.apply(status)
    rc.apply(secret)
    assert target.list(("", "v1", "Pod")) == [pod]
    assert mgmt.list(("", "v1", "Pod")) == []
    assert mgmt.list(("status.gatekeeper.sh", "v1beta1",
                      "ConstraintPodStatus")) == [status]
    assert mgmt.list(("", "v1", "Secret")) == [secret]
    assert target.list(("", "v1", "Secret")) == []
    # reads and watches route the same way
    assert rc.get(("", "v1", "Pod"), "default", "p") == pod
    seen = []
    rc.subscribe(("", "v1", "Pod"), lambda e: seen.append(e.obj),
                 replay=True)
    assert seen == [pod]
    # the manager runs unmodified on a RoutingCluster
    from gatekeeper_tpu.apis.constraints import WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.controller.manager import Manager
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.target.target import K8sValidationTarget

    client = Client(target=K8sValidationTarget(), drivers=[TpuDriver()],
                    enforcement_points=[WEBHOOK_EP])
    mgr = Manager(client, rc).start()
    rc.apply({
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sroutedemo"},
        "spec": {"crd": {"spec": {"names": {"kind": "K8sRouteDemo"}}},
                 "targets": [{"target": "admission.k8s.gatekeeper.sh",
                              "rego": "package k8sroutedemo\n\n"
                                      "violation[{\"msg\": \"x\"}] "
                                      "{ input.review.object.spec.bad }"}]},
    })
    assert "K8sRouteDemo" in [t.kind for t in client.templates()]


def test_warn_log_sampling():
    """WARN+ lines rate-limit at 100/s; drop counts surface on the next
    emitted record (reference: zap sampling in main.go)."""
    import io
    import json as _json
    import logging as _logging

    from gatekeeper_tpu.utils import logging as gklog

    buf = io.StringIO()
    handler = _logging.StreamHandler(buf)
    gklog._logger.addHandler(handler)
    sampler = gklog._WarnSampler(rate=100)
    old = gklog._warn_sampler
    gklog._warn_sampler = sampler
    try:
        for i in range(250):
            gklog.log_event("warning", f"w{i}")
        lines = [ln for ln in buf.getvalue().splitlines() if ln]
        assert len(lines) == 100  # one 1s window admits the rate cap
        # info is never sampled
        gklog.log_event("info", "always")
        assert "always" in buf.getvalue()
        # force the window forward: drops surface on the next warn
        sampler._window -= 2.0
        sampler._count = 0
        buf.truncate(0), buf.seek(0)
        gklog.log_event("warning", "after-window")
        rec = _json.loads(buf.getvalue().splitlines()[-1])
        assert rec["sampled_dropped"] == 150
    finally:
        gklog._warn_sampler = old
        gklog._logger.removeHandler(handler)


def test_two_replicas_fold_per_pod_status():
    """Two replicas (distinct pod names) sharing one cluster: each writes
    its own *PodStatus CR; the status controllers fold BOTH entries into
    the parent's .status.byPod without write contention (reference
    multi-replica model, constraintstatus_controller.go:251)."""
    from gatekeeper_tpu.apis.constraints import WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.controller.manager import Manager
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.sync.source import FakeCluster
    from gatekeeper_tpu.target.target import K8sValidationTarget

    cluster = FakeCluster()

    def replica(pod_name, ops):
        client = Client(target=K8sValidationTarget(),
                        drivers=[TpuDriver()],
                        enforcement_points=[WEBHOOK_EP,
                                            "audit.gatekeeper.sh"])
        return Manager(client, cluster, operations=ops,
                       pod_name=pod_name).start()

    mgr_a = replica("gatekeeper-audit-0", ["audit"])
    mgr_b = replica("gatekeeper-webhook-0", ["webhook"])

    t = load_yaml_file(
        "/root/reference/demo/basic/templates/"
        "k8srequiredlabels_template.yaml")[0]
    cluster.apply(t)
    name = t["metadata"]["name"]
    gvk = ("templates.gatekeeper.sh", t["apiVersion"].split("/")[1],
           "ConstraintTemplate")
    stored = cluster.get(gvk, "", name)
    by_pod = stored["status"]["byPod"]
    assert [e["id"] for e in by_pod] == [
        "gatekeeper-audit-0", "gatekeeper-webhook-0"]
    assert by_pod[0]["operations"] == ["audit"]
    assert by_pod[1]["operations"] == ["webhook"]
    assert stored["status"]["created"] is True
    # a replica's pod-status update converges (no reconcile echo storm):
    # re-applying the same template leaves byPod unchanged
    cluster.apply(dict(t))
    stored2 = cluster.get(gvk, "", name)
    assert stored2["status"]["byPod"] == by_pod


def test_readiness_constraint_listers_and_pruner():
    """Boot with pre-existing template + constraints: the constraints
    become expectations (per-template listers); deleting the template
    prunes them (ExpectationsPruner) instead of wedging /readyz."""
    client = Client(target=K8sValidationTarget(), drivers=[TpuDriver()],
                    enforcement_points=[WEBHOOK_EP, "audit.gatekeeper.sh"])
    cluster = FakeCluster()
    t = load_yaml_file(
        "/root/reference/demo/basic/templates/"
        "k8srequiredlabels_template.yaml")[0]
    con = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "need-owner"},
        "spec": {"parameters": {"labels": ["owner"]}},
    }
    ghost = {**con, "metadata": {"name": "never-reconciled"}}
    cluster.apply(t)
    cluster.apply(con)
    cluster.apply(ghost)
    mgr = Manager(client, cluster).start()
    mgr.tracker.all_populated()
    # both constraints were expected; the dynamic watch observed them
    assert mgr.tracker.satisfied()
    st = mgr.tracker.stats()["constraints"]
    assert st["expected"] == 2 and st["observed"] >= 2

    # a template whose kind never compiles: its constraint expectations
    # prune away rather than wedge
    client2 = Client(target=K8sValidationTarget(), drivers=[TpuDriver()],
                     enforcement_points=[WEBHOOK_EP])
    cluster2 = FakeCluster()
    bad = load_yaml_file(
        "/root/reference/demo/basic/bad/bad_template.yaml")[0]
    bad_kind = bad["spec"]["crd"]["spec"]["names"]["kind"]
    cluster2.apply(bad)
    cluster2.apply({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": bad_kind, "metadata": {"name": "orphan"}, "spec": {},
    })
    mgr2 = Manager(client2, cluster2).start()
    mgr2.tracker.all_populated()
    assert mgr2.tracker.satisfied()  # pruned, not wedged


def test_readiness_data_pruner_on_watch_removal():
    """Unwatching a GVK prunes its data expectations (pruner.go:48-58)."""
    client, cluster, mgr = make_manager()
    mgr.tracker.for_kind("data")._populated = False
    mgr.tracker.expect(
        "data", ((("", "v1", "Secret")), "default", "ghost"))
    mgr.tracker.populated("data")
    cluster.apply({
        "apiVersion": "config.gatekeeper.sh/v1alpha1",
        "kind": "Config", "metadata": {"name": "config"},
        "spec": {"sync": {"syncOnly": [
            {"group": "", "version": "v1", "kind": "Secret"}]}},
    })
    assert not mgr.tracker.for_kind("data").satisfied()  # ghost expected
    # stop syncing Secrets: the expectation can never be observed -> prune
    cluster.apply({
        "apiVersion": "config.gatekeeper.sh/v1alpha1",
        "kind": "Config", "metadata": {"name": "config"},
        "spec": {"sync": {"syncOnly": []}},
    })
    assert mgr.tracker.for_kind("data").satisfied()


def test_upgrade_manager_prunes_stored_versions():
    """Boot-time CRD storedVersions migration (reference
    pkg/upgrade/manager.go:31-60): legacy stored versions no longer in
    spec.versions are pruned for owned CRDs; foreign CRDs untouched."""
    from gatekeeper_tpu.controller.upgrade import CRD_GVK, run_upgrade
    from gatekeeper_tpu.sync.source import FakeCluster

    cluster = FakeCluster()
    owned = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "k8srequiredlabels.constraints.gatekeeper.sh"},
        "spec": {"group": "constraints.gatekeeper.sh",
                 "versions": [{"name": "v1beta1", "served": True,
                               "storage": True}]},
        "status": {"storedVersions": ["v1alpha1", "v1beta1"]},
    }
    foreign = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "foos.example.com"},
        "spec": {"group": "example.com",
                 "versions": [{"name": "v1"}]},
        "status": {"storedVersions": ["v1alpha1", "v1"]},
    }
    cluster.apply(owned)
    cluster.apply(foreign)
    assert run_upgrade(cluster) == 1
    crds = {o["metadata"]["name"]: o for o in cluster.list(CRD_GVK)}
    assert crds["k8srequiredlabels.constraints.gatekeeper.sh"]["status"][
        "storedVersions"] == ["v1beta1"]
    assert crds["foos.example.com"]["status"]["storedVersions"] == [
        "v1alpha1", "v1"]
    # second run: converged, no-op
    assert run_upgrade(cluster) == 0
