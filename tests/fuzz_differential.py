"""Extended differential fuzzing: lowered programs vs the interpreter.

Not part of the default pytest run (no test_ prefix) — invoke manually:

    python tests/fuzz_differential.py [n_objects] [seeds...]

Generates randomized object populations against every library policy and
asserts verdict-set equality between TpuDriver.query_batch and the exact
interpreter, printing a summary per seed.  Exit 1 on any divergence.
"""

import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
# assignment, not setdefault: the ambient env may say "axon" and the package
# import hook honors JAX_PLATFORMS — a dead tunnel would hang the oracle
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from gatekeeper_tpu.apis.constraints import Constraint  # noqa: E402
from gatekeeper_tpu.apis.templates import ConstraintTemplate  # noqa: E402
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver  # noqa: E402
# the seeded object generator moved to the shared corpus module (ISSUE 17)
# so this manual fuzzer, tests/test_fuzz.py, and the soak harness draw
# identical populations per seed; re-exported here for callers that
# imported it from this module
from gatekeeper_tpu.fuzz.corpus import (IMAGES, VALUES,  # noqa: E402,F401
                                        rand_obj, rand_value)
from gatekeeper_tpu.target.review import AugmentedUnstructured  # noqa: E402
from gatekeeper_tpu.target.target import K8sValidationTarget  # noqa: E402
from gatekeeper_tpu.utils.unstructured import load_yaml_file  # noqa: E402

LIB = os.path.join(os.path.dirname(__file__), "..", "library", "general")
LIB_PSP = os.path.join(os.path.dirname(__file__), "..", "library",
                       "pod-security-policy")
TARGET = "admission.k8s.gatekeeper.sh"


def build_fuzz_driver():
    """(tpu, constraints): the full library incl. CEL templates on a
    unified TpuDriver, with referential inventory seeded."""

    from gatekeeper_tpu.drivers.cel_driver import CELDriver

    tpu = TpuDriver(batch_bucket=64, cel_driver=CELDriver())
    constraints = []
    entries = [os.path.join(LIB, n) for n in sorted(os.listdir(LIB))] + \
        [os.path.join(LIB_PSP, n) for n in sorted(os.listdir(LIB_PSP))]
    for entry in entries:
        t = ConstraintTemplate.from_unstructured(
            load_yaml_file(os.path.join(entry, "template.yaml"))[0])
        tpu.add_template(t)
        constraints.append(Constraint.from_unstructured(load_yaml_file(
            os.path.join(entry, "samples", "constraint.yaml"))[0]))
    # cluster-scope referential coverage (storageclass joins)
    for nm in ("standard", "fast"):
        tpu.add_data(
            TARGET, ["cluster", "storage.k8s.io/v1", "StorageClass", nm],
            {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
             "metadata": {"name": nm}})
    # referential coverage: seed the inventory with ingresses sharing
    # hosts/names/namespaces with the generated review objects
    inv_rng = random.Random(991)
    for i in range(25):
        ns = inv_rng.choice(["default", "prod", "kube-system"])
        name = inv_rng.choice([f"o{j}" for j in range(40)] + ["inv-only"])
        hosts = [inv_rng.choice(["a.com", "b.com", "", "inv.com"])
                 for _ in range(inv_rng.randint(0, 2))]
        tpu.add_data(
            TARGET, ["namespace", ns, "networking.k8s.io/v1", "Ingress",
                     f"{name}-{i}" if inv_rng.random() < 0.5 else name],
            {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
             "metadata": {"name": name, "namespace": ns},
             "spec": {"rules": [{"host": h} for h in hosts]}})
    assert not tpu.fallback_kinds(), (
        "library templates fell back to the interpreter — the fuzz would "
        f"compare the oracle to itself: {tpu.fallback_kinds()}")
    return tpu, constraints


def oracle_results(tpu, con, review):
    """The exact engine for one (constraint, review): the CEL evaluator
    for CEL-owned kinds, the Rego interpreter otherwise."""
    if con.kind in tpu._cel_kinds:
        return tpu._cel.query(TARGET, [con], review).results
    return tpu._interp.query(TARGET, [con], review).results


def run_fuzz(n, seeds, quiet=False, tpu=None, constraints=None):
    """Differential fuzz: returns the number of diverging objects."""
    if tpu is None or constraints is None:
        tpu, constraints = build_fuzz_driver()
    if not quiet:
        print(f"templates: {len(constraints)} "
              f"({len(tpu.lowered_kinds())} lowered)")

    target = K8sValidationTarget()
    failures = 0
    for seed in seeds:
        rng = random.Random(seed)
        objs = [rand_obj(rng, i) for i in range(n)]
        reviews = [target.handle_review(AugmentedUnstructured(object=o))
                   for o in objs]
        got = tpu.query_batch(TARGET, constraints, reviews)
        # raw grid lane: render_messages=False keeps every device hit as a
        # Result — the rendered lane re-checks hits through the exact
        # engine, which would MASK false-positive lowering bugs (the grid
        # drives audit totals, so its hits must be exact both ways)
        raw = tpu.query_batch(TARGET, constraints, reviews,
                              render_messages=False)
        mismatches = 0
        for oi, review in enumerate(reviews):
            expected = []
            exp_hit_kinds = set()
            for con in constraints:
                if not target.to_matcher(con.match).match(review):
                    continue
                results = oracle_results(tpu, con, review)
                expected.extend(results)
                if results:
                    exp_hit_kinds.add(con.name)
            key = lambda r: (r.constraint["metadata"]["name"], r.msg)
            raw_hits = {r.constraint["metadata"]["name"]
                        for r in raw[oi].results}
            ok_rendered = sorted(map(key, got[oi].results)) == sorted(
                map(key, expected))
            ok_raw = raw_hits == exp_hit_kinds
            if not (ok_rendered and ok_raw):
                mismatches += 1
                if mismatches <= 3:
                    print(f"  DIVERGENCE seed={seed} obj={oi}: {objs[oi]}")
                    if not ok_rendered:
                        print(f"    got:  {sorted(map(key, got[oi].results))}")
                        print(f"    want: {sorted(map(key, expected))}")
                    if not ok_raw:
                        print(f"    raw grid hits: {sorted(raw_hits)}")
                        print(f"    oracle hits:   {sorted(exp_hit_kinds)}")
        total = sum(len(g.results) for g in got)
        status = "OK" if mismatches == 0 else f"{mismatches} MISMATCHES"
        if not quiet or mismatches:
            print(f"seed {seed}: {n} objects, {total} violations -> {status}")
        failures += mismatches
    return failures


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 400
    seeds = [int(s) for s in sys.argv[2:]] or [0, 1, 2, 3, 4]
    return 1 if run_fuzz(n, seeds) else 0


if __name__ == "__main__":
    sys.exit(main())
