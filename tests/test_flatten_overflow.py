"""int64/float32 boundary differential tests (VERDICT weak #6).

The number→float32 cast policy is ONE function — ``ops.flatten.f32_sat``:
values beyond the float32 range saturate to ±inf explicitly (ordering
against in-range numbers preserved), never through numpy's silent
RuntimeWarning-carrying cast.  These tests pin the policy at the
boundaries and assert all three flatten lanes (Python dict, native dict,
native JSON) and the parameter tables produce bit-identical columns for
boundary values.  pytest.ini turns RuntimeWarning into an error, so any
reintroduced silent cast fails the suite loudly.
"""

import json
import math

import numpy as np
import pytest

from gatekeeper_tpu.ops import native
from gatekeeper_tpu.ops.flatten import (
    _F32_MAX,
    Flattener,
    ScalarCol,
    Schema,
    Vocab,
    f32_sat,
)

F32_MAX_INT = 2 ** 63 - 1  # int64 max: representable in float32 range
BOUNDARY_VALUES = [
    0,
    1,
    -1,
    2 ** 24,            # float32 integer-exactness limit
    2 ** 24 + 1,        # first int that rounds in float32
    2 ** 31 - 1,
    2 ** 53 + 1,        # first int that rounds in float64
    F32_MAX_INT,
    -(2 ** 63),
    2 ** 64,            # beyond int64, still in double range
    int(_F32_MAX),      # ~float32 max as an int
    3.4e38,             # just under float32 max
    3.5e38,             # just over float32 max -> inf
    -3.5e38,            # -> -inf
    1e300,              # far beyond float32, within double
    -1e300,
    2 ** 1100,          # beyond double range -> inf (OverflowError path)
    -(2 ** 1100),
    1.5,
    -2.75,
]


def test_f32_sat_policy():
    assert f32_sat(3.5e38) == math.inf
    assert f32_sat(-3.5e38) == -math.inf
    assert f32_sat(1e300) == math.inf
    assert f32_sat(2 ** 1100) == math.inf
    assert f32_sat(-(2 ** 1100)) == -math.inf
    # in-range values pass through exactly (as doubles; the float32
    # narrowing happens at array construction)
    assert f32_sat(1.5) == 1.5
    assert f32_sat(F32_MAX_INT) == float(F32_MAX_INT)
    # ordering against in-range thresholds is preserved for saturated
    # values — the device comparison a policy threshold performs
    assert f32_sat(3.5e38) > np.float32(f32_sat(100.0))
    assert f32_sat(-3.5e38) < np.float32(f32_sat(-100.0))
    # no RuntimeWarning materializing the policy into a float32 array
    # (pytest.ini: error::RuntimeWarning)
    arr = np.asarray([f32_sat(v) for v in BOUNDARY_VALUES], np.float32)
    assert np.isinf(arr).sum() >= 6


def _schema():
    s = Schema()
    s.scalars = [ScalarCol(("spec", "n"))]
    return s


def _objects():
    return [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": f"b{i}", "namespace": "default"},
         "spec": {"n": v}}
        for i, v in enumerate(BOUNDARY_VALUES)
    ]


def test_python_lane_boundary_columns():
    fl = Flattener(_schema(), Vocab(), use_native=False)
    batch = fl.flatten(_objects())
    col = batch.scalars[_schema().scalars[0]]
    got = col.num[: len(BOUNDARY_VALUES)]
    want = np.asarray([f32_sat(v) for v in BOUNDARY_VALUES], np.float32)
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(native.load() is None,
                    reason="native build unavailable")
def test_native_dict_lane_matches_python_at_boundaries():
    objs = _objects()
    py = Flattener(_schema(), Vocab(), use_native=False).flatten(objs)
    nat = Flattener(_schema(), Vocab(), use_native=True)._flatten_native(
        native.load(), objs, len(objs))
    spec = _schema().scalars[0]
    np.testing.assert_array_equal(py.scalars[spec].num[: len(objs)],
                                  nat.scalars[spec].num[: len(objs)])
    np.testing.assert_array_equal(py.scalars[spec].kind[: len(objs)],
                                  nat.scalars[spec].kind[: len(objs)])


@pytest.mark.skipif(native.load_json() is None,
                    reason="native JSON build unavailable")
def test_native_json_lane_matches_python_at_boundaries():
    from gatekeeper_tpu.utils.rawjson import RawJSON

    # ints beyond double range cannot ride the JSON lane (the C parser
    # reads doubles); everything up to ±1e300 must agree bit-for-bit
    vals = [v for v in BOUNDARY_VALUES
            if not (isinstance(v, int) and abs(v) > 2 ** 1023)]
    objs = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": f"b{i}", "namespace": "default"},
         "spec": {"n": v}}
        for i, v in enumerate(vals)
    ]
    raws = [RawJSON(json.dumps(o).encode()) for o in objs]
    vocab = Vocab()
    fl = Flattener(_schema(), vocab, use_native=True)
    jbatch = fl.flatten(raws)
    pybatch = Flattener(_schema(), vocab, use_native=False).flatten(objs)
    spec = _schema().scalars[0]
    np.testing.assert_array_equal(jbatch.scalars[spec].num[: len(objs)],
                                  pybatch.scalars[spec].num[: len(objs)])


def test_param_table_saturates_without_warning():
    """Constraint parameters beyond float32 saturate to ±inf through the
    same policy (ir/program.py uses f32_sat); with pytest's
    error::RuntimeWarning filter this test FAILS if the silent cast
    returns."""
    from gatekeeper_tpu.ir.program import build_param_table
    from gatekeeper_tpu.ir import nodes as N

    prog = N.Program(
        template_kind="K8sBoundary",
        expr=N.ParamTruthy("limit"),
        params=(N.ParamSpec(name="limit", kind="num"),
                N.ParamSpec(name="caps", kind="numlist")),
        schema=Schema(),
    )

    class _Con:
        def __init__(self, params):
            self.parameters = params

    cons = [_Con({"limit": 1e300, "caps": [3.5e38, 1.0, -1e300]}),
            _Con({"limit": 2 ** 1100, "caps": []})]
    table = build_param_table(prog, cons, Vocab())
    np.testing.assert_array_equal(table["limit__num"],
                                  np.asarray([np.inf, np.inf], np.float32))
    row = table["caps__nums"][0]
    assert row[0] == np.inf and row[1] == np.float32(1.0) \
        and row[2] == -np.inf
