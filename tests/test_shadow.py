"""Shadow canary lane: candidate policy against live traffic.

The safety pins the module docstring of ``replay/shadow.py`` promises:

1. THE serving-identity pin: with a shadow lane active (worker running,
   candidate evaluating), every served admission response is
   field-for-field identical to the lane-off response — the lane can
   never alter, delay, or answer an admission.
2. Divergence detection both ways: a candidate missing a deny-firing
   constraint reports ``would_allow``; the inverse deployment reports
   ``would_deny``; a candidate that errors reports ``would_error`` and
   a lane-internal crash is swallowed into ``lane_errors``.
3. Backpressure: a full queue drops the OLDEST item, counted, never
   blocking the submitter; served shed/error/deadline responses are
   skipped (nothing to shadow).
4. Promote/abort: ``promote()`` applies the candidate docs to the
   SERVING client (the generation-swap ride) so a previously-allowed
   admission turns deny; both end states refuse further submits.
5. The ``shadow-divergence-rate`` SLO objective sums the divergence
   counter ACROSS its {kind} labelsets (the labels-omitted ratio path).
6. ``/debug/shadow``: GET snapshot, POST promote/abort.

Wall budget: one module-scoped 3-template library + shared compile
cache; every runtime after the first loads with zero fresh lowerings.
"""

from __future__ import annotations

import copy
import importlib.util
import json
import os
import urllib.error
import urllib.request

import pytest

from gatekeeper_tpu.gator import reader
from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.observability import flightrec
from gatekeeper_tpu.observability.slo import SLOObjective
from gatekeeper_tpu.replay import core, shadow
from gatekeeper_tpu.replay.shadow import SHADOW_OBJECTIVE, ShadowLane
from gatekeeper_tpu.utils.unstructured import name_of
from gatekeeper_tpu.webhook.policy import ValidationResponse
from gatekeeper_tpu.webhook.server import WebhookServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_replay", os.path.join(REPO, "tools", "bench_replay.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ctx(tmp_path_factory):
    """Serving (full 3-template library) and candidate (same library
    minus one deny-firing constraint) runtimes over one shared compile
    cache, plus the traffic split by the serving verdict."""
    bench = _load_bench()
    cache_dir = str(tmp_path_factory.mktemp("shadow-cc"))
    full = bench._library_docs(3)
    bodies = bench._admission_bodies(40, seed=5)
    serving = core.load_candidate(full, compile_cache_dir=cache_dir)
    served = [serving.handler.handle(copy.deepcopy(b)) for b in bodies]
    denied = [b for b, r in zip(bodies, served) if not r.allowed]
    allowed = [b for b, r in zip(bodies, served) if r.allowed]
    assert denied and allowed, "traffic mix regressed; reseed the bodies"
    drop = sorted(core.recorded_constraints(
        next(r for r in served if not r.allowed).message))[0]
    minus = [d for d in full
             if not (reader.is_constraint(d) and name_of(d) == drop)]
    candidate = core.load_candidate(minus, compile_cache_dir=cache_dir)
    # only-dropped-constraint denials: the clean would_allow population
    solely = [b for b, r in zip(bodies, served)
              if not r.allowed and core.recorded_constraints(r.message)
              == {drop}]
    assert solely, f"no admission denied solely by {drop}"
    return {"cache_dir": cache_dir, "full": full, "minus": minus,
            "drop": drop, "serving": serving, "candidate": candidate,
            "bodies": bodies, "denied": denied, "allowed": allowed,
            "solely": solely}


def _fields(resp):
    return (resp.allowed, resp.message, resp.code,
            tuple(resp.warnings), resp.uid, resp.retry_after_s)


# --- 1. THE serving-identity pin -------------------------------------------

def test_shadow_lane_never_alters_served_response(ctx):
    handler = ctx["serving"].handler
    baseline = [_fields(handler.handle(copy.deepcopy(b)))
                for b in ctx["bodies"]]
    lane = ShadowLane(ctx["candidate"], max_queue=256).start()
    try:
        with shadow.activate(lane):
            shadowed = [_fields(handler.handle(copy.deepcopy(b)))
                        for b in ctx["bodies"]]
        lane.drain()
    finally:
        lane.stop()
    assert shadowed == baseline
    assert lane.submitted == len(ctx["bodies"])
    assert lane.evaluated == lane.submitted and lane.lane_errors == 0


# --- 2. divergence detection -----------------------------------------------

def test_shadow_reports_would_allow(ctx):
    metrics = MetricsRegistry()
    rec = flightrec.FlightRecorder(capacity=64)
    lane = ShadowLane(ctx["candidate"], recorder=rec,
                      metrics=metrics).start()
    try:
        with shadow.activate(lane):
            for b in ctx["solely"]:
                ctx["serving"].handler.handle(copy.deepcopy(b))
        lane.drain()
    finally:
        lane.stop()
    # every solely-dropped-constraint deny flips to allow in the shadow
    assert lane.divergences["would_allow"] == len(ctx["solely"])
    snap = lane.snapshot()
    assert snap["divergence_rate"] > 0
    assert snap["recent_divergences"]
    for d in snap["recent_divergences"]:
        assert d["served"] == "deny" and d["shadow"] == "allow"
    assert metrics.get_counter(M.SHADOW_DIVERGENCE,
                               {"kind": "would_allow"}) == \
        len(ctx["solely"])
    # shadow verdicts land on the recorder's shadow stream, never the
    # serving one
    entries = rec.snapshot()["decisions"]
    assert entries and all(e["endpoint"] == "shadow" for e in entries)
    assert any(e.get("divergence") == "would_allow" and
               e.get("served") == "deny" for e in entries)


def test_shadow_reports_would_deny(ctx):
    # inverse deployment: serving = minus, candidate = full library
    lane = ShadowLane(ctx["serving"]).start()
    try:
        with shadow.activate(lane):
            for b in ctx["solely"]:
                resp = ctx["candidate"].handler.handle(copy.deepcopy(b))
                assert resp.allowed  # the minus library allows these
        lane.drain()
    finally:
        lane.stop()
    assert lane.divergences["would_deny"] == len(ctx["solely"])


def test_shadow_reports_would_error_and_swallows_lane_crash(ctx,
                                                            monkeypatch):
    # candidate whose review path errors per item -> would_error
    lane = ShadowLane(ctx["candidate"]).start()
    try:
        monkeypatch.setattr(
            ctx["candidate"].client, "review_batch",
            lambda reviews, **kw: [RuntimeError("boom")] * len(reviews))
        with shadow.activate(lane):
            for b in ctx["allowed"][:3]:
                ctx["serving"].handler.handle(copy.deepcopy(b))
        lane.drain()
        assert lane.divergences["would_error"] == 3
        assert lane.decisions["error"] == 3
    finally:
        lane.stop()
    # candidate whose review path RAISES: the whole batch is swallowed
    # into lane_errors — a candidate bug stays invisible to serving
    lane2 = ShadowLane(ctx["candidate"]).start()
    try:
        def _raise(reviews, **kw):
            raise RuntimeError("candidate down")

        monkeypatch.setattr(ctx["candidate"].client, "review_batch",
                            _raise)
        with shadow.activate(lane2):
            resp = ctx["serving"].handler.handle(
                copy.deepcopy(ctx["allowed"][0]))
            assert resp.allowed  # serving unaffected
        lane2.drain()
        assert lane2.lane_errors == 1 and lane2.evaluated == 0
    finally:
        lane2.stop()


# --- 3. backpressure --------------------------------------------------------

def test_shadow_full_queue_drops_oldest_never_blocks(ctx):
    metrics = MetricsRegistry()
    lane = ShadowLane(ctx["candidate"], max_queue=4,
                      metrics=metrics)  # no worker: the queue fills
    body = {"request": {"uid": "q", "userInfo": {"username": "u"}}}
    for i in range(10):
        assert lane.submit(dict(body), ValidationResponse(allowed=True))
    assert lane.submitted == 10
    assert lane.dropped == 6
    assert lane._queue.qsize() == 4
    assert metrics.counter_total(M.SHADOW_DROPPED) == 6
    assert metrics.get_gauge(M.SHADOW_QUEUE_DEPTH) == 4


def test_shadow_skips_unserved_decisions(ctx):
    lane = ShadowLane(ctx["candidate"])
    body = {"request": {"uid": "e"}}
    for code in (500, 504):
        assert not lane.submit(dict(body), ValidationResponse(
            allowed=False, code=code))
    assert lane.skipped == 2 and lane.submitted == 0
    assert lane._queue.qsize() == 0


# --- 4. promote / abort -----------------------------------------------------

def test_promote_applies_candidate_to_serving(ctx):
    # a fresh "serving" stack running the MINUS library (warm cache)
    serving = core.load_candidate(ctx["minus"],
                                  compile_cache_dir=ctx["cache_dir"])
    body = ctx["solely"][0]
    assert serving.handler.handle(copy.deepcopy(body)).allowed
    lane = ShadowLane(ctx["candidate"], serving_client=serving.client,
                      candidate_docs=ctx["full"])
    out = lane.promote()
    assert out["state"] == "promoted" and lane.state == "promoted"
    assert out["applied"]["templates"] == 3
    assert out["applied"]["constraints"] > 0
    assert "errors" not in out
    # the candidate library now SERVES: the admission flips to deny
    resp = serving.handler.handle(copy.deepcopy(body))
    assert not resp.allowed and ctx["drop"] in resp.message
    # an ended lane refuses traffic
    assert not lane.submit({"request": {}},
                           ValidationResponse(allowed=True))


def test_abort_stops_shadowing(ctx):
    lane = ShadowLane(ctx["candidate"]).start()
    out = lane.abort(reason="divergence SLO breached")
    assert out == {"state": "aborted",
                   "reason": "divergence SLO breached"}
    assert not lane.submit({"request": {}},
                           ValidationResponse(allowed=True))


# --- 5. the SLO objective ---------------------------------------------------

def test_shadow_slo_objective_sums_divergence_kinds(ctx):
    metrics = MetricsRegistry()
    lane = ShadowLane(ctx["candidate"], metrics=metrics).start()
    try:
        with shadow.activate(lane):
            for b in ctx["solely"][:2] + ctx["allowed"][:3]:
                ctx["serving"].handler.handle(copy.deepcopy(b))
        lane.drain()
    finally:
        lane.stop()
    assert lane.evaluated == 5
    obj = SLOObjective(SHADOW_OBJECTIVE)
    bad, total = obj.sample(metrics, 0.0)
    # bad sums ACROSS {kind} labelsets; total counts every shadowed
    # decision regardless of {decision} label
    assert bad == sum(lane.divergences.values()) == 2
    assert total == 5
    assert obj.target == SHADOW_OBJECTIVE["target"]


# --- 6. /debug/shadow -------------------------------------------------------

def _http(url, body=None):
    req = urllib.request.Request(
        url, data=(json.dumps(body).encode() if body is not None
                   else None),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_debug_shadow_endpoint(ctx):
    serving = core.load_candidate(ctx["minus"],
                                  compile_cache_dir=ctx["cache_dir"])
    lane = ShadowLane(ctx["candidate"], serving_client=serving.client,
                      candidate_docs=ctx["full"])
    srv = WebhookServer(port=0).start()
    base = f"http://127.0.0.1:{srv.port}/debug/shadow"
    try:
        with shadow.activate(lane):
            doc = _http(base)
            assert doc["state"] == "shadowing"
            assert set(doc) >= {"submitted", "evaluated", "divergences",
                                "divergence_rate", "recent_divergences"}
            try:
                _http(base, {"action": "bogus"})
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
            out = _http(base, {"action": "promote"})
            assert out["state"] == "promoted"
            assert out["applied"]["templates"] == 3
        # lane uninstalled: the endpoint 404s like the other debug seams
        try:
            _http(base)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_debug_shadow_abort_endpoint(ctx):
    lane = ShadowLane(ctx["candidate"])
    srv = WebhookServer(port=0).start()
    base = f"http://127.0.0.1:{srv.port}/debug/shadow"
    try:
        with shadow.activate(lane):
            out = _http(base, {"action": "abort", "reason": "slo"})
            assert out == {"state": "aborted", "reason": "slo"}
    finally:
        srv.stop()


def test_slo_breach_auto_aborts_shadow_lane():
    """bind_slo: a RISING-EDGE breach of the shadow divergence
    objective aborts a shadowing lane; a promoted lane is immune, and
    a continued breach never re-fires (edge, not level)."""
    from gatekeeper_tpu.observability.slo import SLOEngine

    fake = {"t": 0.0}
    m = MetricsRegistry()
    eng = SLOEngine(
        m, objectives=[SHADOW_OBJECTIVE],
        tiers=[{"name": "page", "short_s": 60.0, "long_s": 300.0,
                "burn": 2.0}],
        clock=lambda: fake["t"], wall=lambda: 1_000_000.0 + fake["t"])
    lane = ShadowLane(runtime=None)  # never started: abort() is a
    lane.bind_slo(eng)               # state flip + no-op stop()
    eng.tick()  # t=0 baseline
    m.inc_counter("shadow_decisions_count", value=100.0)
    fake["t"] = 60.0
    out = eng.tick()
    assert not out["objectives"][0]["breach"]
    assert lane.state == "shadowing"
    # a divergent minute: 50/50 bad >> the 1% budget at burn 2.0
    m.inc_counter("shadow_divergence_count", {"kind": "verdict"},
                  value=50.0)
    m.inc_counter("shadow_decisions_count", value=50.0)
    fake["t"] = 120.0
    out = eng.tick()
    assert out["objectives"][0]["breach"]
    assert lane.state == "aborted"
    assert "slo auto-abort" in lane.abort_reason
    assert SHADOW_OBJECTIVE["name"] in lane.abort_reason
    # edge semantics: still breached on the next tick, but the hook
    # does not fire again (a lane resurrected by hand stays put)
    lane.state = "shadowing"
    m.inc_counter("shadow_divergence_count", {"kind": "verdict"},
                  value=50.0)
    m.inc_counter("shadow_decisions_count", value=50.0)
    fake["t"] = 121.0
    out = eng.tick()
    assert out["objectives"][0]["breach"]
    assert lane.state == "shadowing"


def test_slo_auto_abort_spares_promoted_lane():
    """The hook must never touch a lane that already promoted — the
    canary decision is done; only a shadowing lane may auto-abort."""
    from gatekeeper_tpu.observability.slo import SLOEngine

    fake = {"t": 0.0}
    m = MetricsRegistry()
    eng = SLOEngine(
        m, objectives=[SHADOW_OBJECTIVE],
        tiers=[{"name": "page", "short_s": 60.0, "long_s": 300.0,
                "burn": 2.0}],
        clock=lambda: fake["t"], wall=lambda: 1_000_000.0 + fake["t"])
    lane = ShadowLane(runtime=None)
    lane.bind_slo(eng)
    lane.state = "promoted"
    eng.tick()
    m.inc_counter("shadow_divergence_count", value=50.0)
    m.inc_counter("shadow_decisions_count", value=50.0)
    fake["t"] = 60.0
    out = eng.tick()
    assert out["objectives"][0]["breach"]
    assert lane.state == "promoted"
