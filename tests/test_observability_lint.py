"""Tier-1 gate: every fault_point site, every gatekeeper_* metric
constant, every tracer span name and every built-in SLO objective must
be documented in tools/observability_registry.md."""

import importlib.util
import pathlib

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "lint_observability", _TOOLS / "lint_observability.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_registry_is_in_sync():
    lint = _load_lint()
    problems = lint.check()
    assert problems == [], "\n".join(problems)


def test_source_scan_sees_known_sites_and_metrics():
    lint = _load_lint()
    sites = lint.fault_sites_in_source()
    # the multi-line kube call site and the f-string pipeline site are
    # the two parse hazards; both must resolve
    assert "kube.request" in sites
    assert "pipeline.stage.*" in sites
    assert "device.dispatch" in sites
    metrics = lint.metric_names_in_source()
    assert "gatekeeper_validation_request_count" in metrics
    assert "gatekeeper_trace_traces_kept_count" in metrics
    assert "gatekeeper_audit_pipeline_stage_busy_sum_seconds" in metrics
    # PREFIX itself is configuration, not a metric
    assert "gatekeeper_gatekeeper_" not in metrics
    # content-type constants are strings too but not metric names
    assert not any("openmetrics" in m for m in metrics)
    spans = lint.span_names_in_source()
    # the f-string pipeline span and a cross-module name must resolve
    assert "pipeline.stage.*" in spans
    assert "webhook.request" in spans
    assert "device.sweep_dispatch" in spans
    slo = lint.slo_objectives_in_source()
    assert "admission-latency-p99" in slo
    assert "audit-snapshot-staleness" in slo
    endpoints = lint.debug_endpoints_in_source()
    # the triage five plus profile/shadow — all route constants
    assert "/debug/slo" in endpoints
    assert "/debug/decisions" in endpoints
    assert "/debug/overload" in endpoints
    # serving paths (non-debug) stay out of the registry check
    assert not any(not p.startswith("/debug/") for p in endpoints)


def test_lint_flags_endpoint_drift(monkeypatch):
    """An undocumented /debug endpoint (or a stale documented one)
    must produce a problem in the matching direction."""
    lint = _load_lint()
    doc = lint.documented_endpoints()
    monkeypatch.setattr(
        lint, "debug_endpoints_in_source",
        lambda: {**{p: "OK_PATH" for p in doc},
                 "/debug/rogue": "ROGUE_PATH"})
    problems = lint.check()
    assert any("/debug/rogue" in p for p in problems)
    monkeypatch.setattr(
        lint, "debug_endpoints_in_source",
        lambda: {p: "OK_PATH" for p in sorted(doc)[1:]})
    problems = lint.check()
    assert any("stale documented debug endpoint" in p for p in problems)


def test_lint_flags_undocumented_additions(tmp_path, monkeypatch):
    """An undocumented site or metric must produce a problem (the gate
    actually gates)."""
    lint = _load_lint()
    doc_sites, doc_metrics, doc_spans, doc_slo = lint.documented()

    monkeypatch.setattr(
        lint, "fault_sites_in_source",
        lambda: {**{s: ["x:1"] for s in doc_sites},
                 "rogue.site": ["gatekeeper_tpu/rogue.py:1"]})
    monkeypatch.setattr(
        lint, "metric_names_in_source",
        lambda: {**{m: "OK" for m in doc_metrics},
                 "gatekeeper_rogue_count": "ROGUE"})
    monkeypatch.setattr(
        lint, "span_names_in_source",
        lambda: {**{s: ["x:1"] for s in doc_spans},
                 "rogue.span": ["gatekeeper_tpu/rogue.py:2"]})
    monkeypatch.setattr(
        lint, "slo_objectives_in_source",
        lambda: {**{s: "slo.py" for s in doc_slo},
                 "rogue-objective": "slo.py"})
    problems = lint.check()
    assert any("rogue.site" in p for p in problems)
    assert any("gatekeeper_rogue_count" in p for p in problems)
    assert any("rogue.span" in p for p in problems)
    assert any("rogue-objective" in p for p in problems)


def test_lint_flags_stale_documentation(monkeypatch):
    lint = _load_lint()
    doc_sites, doc_metrics, doc_spans, doc_slo = lint.documented()
    monkeypatch.setattr(
        lint, "documented",
        lambda: (doc_sites | {"gone.site"}, doc_metrics,
                 doc_spans | {"gone.span"}, doc_slo))
    problems = lint.check()
    assert any("gone.site" in p and "stale" in p for p in problems)
    assert any("gone.span" in p and "stale" in p for p in problems)
