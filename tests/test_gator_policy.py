"""gator policy: local catalog + OCI-image-layout bundle manager
(reference: pkg/gator/policy + pkg/oci)."""

import hashlib
import json
import os
import subprocess
import sys
import tarfile

import pytest
import yaml

from gatekeeper_tpu.gator import policy_cmd

REPO = os.path.join(os.path.dirname(__file__), "..")
LIB = os.path.join(REPO, "library", "general")


@pytest.fixture()
def catalog(tmp_path):
    bundles = tmp_path / "bundles"
    bundles.mkdir()
    tgz = bundles / "requiredlabels-1.1.2.tar.gz"
    with tarfile.open(tgz, "w:gz") as tf:
        tf.add(os.path.join(LIB, "requiredlabels"), arcname="requiredlabels")

    # OCI image layout whose single layer is a tar.gz bundle
    oci = bundles / "allowedrepos-oci"
    (oci / "blobs" / "sha256").mkdir(parents=True)
    layer = tmp_path / "layer.tgz"
    with tarfile.open(layer, "w:gz") as tf:
        tf.add(os.path.join(LIB, "allowedrepos"), arcname="allowedrepos")
    lb = layer.read_bytes()
    ld = hashlib.sha256(lb).hexdigest()
    (oci / "blobs" / "sha256" / ld).write_bytes(lb)
    manifest = json.dumps({"schemaVersion": 2, "layers": [
        {"mediaType": "application/vnd.oci.image.layer.v1.tar+gzip",
         "digest": f"sha256:{ld}"}]}).encode()
    md = hashlib.sha256(manifest).hexdigest()
    (oci / "blobs" / "sha256" / md).write_bytes(manifest)
    (oci / "index.json").write_text(json.dumps(
        {"schemaVersion": 2, "manifests": [{"digest": f"sha256:{md}"}]}))
    (oci / "oci-layout").write_text('{"imageLayoutVersion": "1.0.0"}')

    cat = tmp_path / "catalog.yaml"
    cat.write_text(yaml.safe_dump({"policies": [
        {"name": "requiredlabels",
         "description": "Requires resources to contain specified labels.",
         "versions": [
             {"version": "1.1.1", "ref": "bundles/requiredlabels-1.1.2.tar.gz"},
             {"version": "1.1.2", "ref": "bundles/requiredlabels-1.1.2.tar.gz"},
         ]},
        {"name": "allowedrepos",
         "description": "Allowed repos (OCI layout bundle).",
         "versions": [{"version": "2.0.0",
                       "ref": "bundles/allowedrepos-oci"}]},
    ]}))
    return str(cat)


def test_search(catalog):
    rows = policy_cmd.search(catalog, "labels")
    assert rows == [("requiredlabels", "1.1.2",
                     "Requires resources to contain specified labels.")]
    assert len(policy_cmd.search(catalog)) == 2


def test_install_upgrade_remove_roundtrip(catalog, tmp_path):
    target = str(tmp_path / "lib")
    out = policy_cmd.install(catalog, "requiredlabels", target,
                             version="1.1.1")
    assert "installed 1.1.1" in out
    assert os.path.exists(os.path.join(target, "requiredlabels",
                                       "template.yaml"))
    # double install refused; upgrade moves to latest
    with pytest.raises(policy_cmd.PolicyError):
        policy_cmd.install(catalog, "requiredlabels", target)
    out = policy_cmd.install(catalog, "requiredlabels", target,
                             upgrade=True)
    assert "upgraded to 1.1.2" in out
    assert policy_cmd.list_installed(target) == [("requiredlabels",
                                                  "1.1.2")]
    assert "removed" in policy_cmd.remove(target, "requiredlabels")
    assert policy_cmd.list_installed(target) == []
    assert not os.path.exists(os.path.join(target, "requiredlabels"))


def test_oci_layout_install_verifies(catalog, tmp_path):
    target = str(tmp_path / "lib")
    policy_cmd.install(catalog, "allowedrepos", target)
    assert os.path.exists(os.path.join(target, "allowedrepos",
                                       "suite.yaml"))
    # the installed bundle passes gator verify end-to-end
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "gatekeeper_tpu.gator", "verify", target],
        capture_output=True, text=True, timeout=180, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "--- ok: allowed-repos" in proc.stdout


def test_remote_refs_refused(catalog, tmp_path):
    with pytest.raises(policy_cmd.PolicyError, match="no network egress"):
        policy_cmd.load_catalog("oci://example.com/cat")
    with pytest.raises(policy_cmd.PolicyError, match="no network egress"):
        policy_cmd.fetch_bundle("https://x/y.tgz", ".", str(tmp_path / "d"))


def test_traversal_bundle_refused(tmp_path):
    evil = tmp_path / "evil.tar"
    with tarfile.open(evil, "w") as tf:
        info = tarfile.TarInfo("../../escape.txt")
        info.size = 0
        tf.addfile(info, fileobj=None)
    with pytest.raises(policy_cmd.PolicyError, match="unsafe path"):
        policy_cmd.fetch_bundle(str(evil), ".", str(tmp_path / "dest"))


def test_remote_transport_plug(tmp_path):
    """The transport seam (reference ORAS client, pkg/oci/oci.go:27): a
    deployment with egress registers a fetcher per scheme and
    fetch_bundle routes remote refs through it."""
    calls = []

    def fake_oras(ref, dest):
        calls.append(ref)
        os.makedirs(dest, exist_ok=True)
        with open(os.path.join(dest, "template.yaml"), "w") as f:
            f.write("kind: ConstraintTemplate\n")

    old = policy_cmd.REMOTE_TRANSPORTS["oci://"]
    policy_cmd.REMOTE_TRANSPORTS["oci://"] = fake_oras
    try:
        dest = tmp_path / "bundle"
        policy_cmd.fetch_bundle("oci://reg.example/p:1.0", ".", str(dest))
        assert calls == ["oci://reg.example/p:1.0"]
        assert (dest / "template.yaml").exists()
    finally:
        policy_cmd.REMOTE_TRANSPORTS["oci://"] = old
