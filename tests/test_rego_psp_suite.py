"""Run every PSP template in the reference's webhook-benchmark testdata against
its example pod; each example pod is crafted to violate its template
(reference: pkg/webhook/testdata/psp-all-violations, used by
BenchmarkValidationHandler at pkg/webhook/policy_benchmark_test.go:251)."""

import glob
import os

import pytest
import yaml

from gatekeeper_tpu.lang.rego.interp import Interpreter, compile_modules

ROOT = "/root/reference/pkg/webhook/testdata/psp-all-violations"

PAIRS = [
    ("privileged-containers-template.yaml", "privileged-containers-example.yaml",
     "privileged-containers-constraint.yaml"),
    ("host-filesystem-template.yaml", "host-filesystem-example.yaml",
     "host-filesystem-constraint.yaml"),
    ("host-namespace-template.yaml", "host-namespaces-example.yaml",
     "host-namespaces-constraint.yaml"),
    ("host-network-ports-template.yaml", "host-network-example.yaml",
     "host-network-constraint.yaml"),
    ("volume-template.yaml", "volumes-example.yaml", "volumes-constraint.yaml"),
]


def _load(p):
    with open(p) as f:
        return yaml.safe_load(f)


@pytest.mark.parametrize("tmpl,pod,constraint", PAIRS)
def test_psp_pod_violates(tmpl, pod, constraint):
    t = _load(os.path.join(ROOT, "psp-templates", tmpl))
    p = _load(os.path.join(ROOT, "psp-pods", pod))
    c = _load(os.path.join(ROOT, "psp-constraints", constraint))
    rego = t["spec"]["targets"][0]["rego"]
    mods = compile_modules([rego])
    pkg = list(mods.by_pkg.keys())[0]
    interp = Interpreter(mods)
    input_doc = {
        "review": {
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "operation": "CREATE",
            "name": p["metadata"]["name"],
            "object": p,
        },
        "parameters": c["spec"].get("parameters") or {},
    }
    out = interp.query_set_rule(pkg, "violation", input_doc)
    assert len(out) >= 1, f"{tmpl}: expected a violation"
    for v in out:
        assert isinstance(v["msg"], str) and v["msg"]
