"""sync/routing.py error paths beyond the happy path (VERDICT weak #8):
status-group write failure, the Secrets-client split fallback, and the
apiserver retry-on-conflict loop the routed writes rely on."""

import pytest

from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig, KubeError
from gatekeeper_tpu.sync.routing import OPERATOR_NAMESPACE, RoutingCluster
from gatekeeper_tpu.sync.source import FakeCluster


def _status_obj(name="tpl-status"):
    return {"apiVersion": "status.gatekeeper.sh/v1beta1",
            "kind": "ConstraintTemplatePodStatus",
            "metadata": {"name": name, "namespace": OPERATOR_NAMESPACE},
            "status": {"observed": True}}


def _secret(ns, name="tls-cert"):
    return {"apiVersion": "v1", "kind": "Secret",
            "metadata": {"name": name, "namespace": ns},
            "data": {"tls.crt": "x"}}


class _FailingCluster(FakeCluster):
    """ObjectSource double whose writes fail like a dead management
    apiserver."""

    def __init__(self, exc):
        super().__init__()
        self.exc = exc

    def apply(self, obj):
        raise self.exc

    def apply_status(self, obj):
        raise self.exc


def test_status_group_write_failure_propagates_and_target_untouched():
    """A dead management cluster fails STATUS writes loudly (callers own
    the retry policy) while target-side traffic is unaffected."""
    mgmt = _FailingCluster(KubeError(500, "management apiserver down"))
    target = FakeCluster()
    rc = RoutingCluster(mgmt, target)

    with pytest.raises(KubeError):
        rc.apply(_status_obj())
    with pytest.raises(KubeError):
        rc.apply_status(_status_obj())
    # target-side writes still work — the split isolates the failure
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "p", "namespace": "default"}}
    rc.apply(pod)
    assert rc.get(("", "v1", "Pod"), "default", "p") is not None


def test_apply_status_falls_back_to_plain_apply():
    """A management source without an apply_status method (FakeCluster
    shape) takes the getattr fallback — the status write lands as a
    full-object apply instead of crashing."""

    class _NoStatus(FakeCluster):
        def __getattribute__(self, name):
            if name == "apply_status":
                raise AttributeError(name)
            return super().__getattribute__(name)

    mgmt = _NoStatus()
    rc = RoutingCluster(mgmt, FakeCluster())
    rc.apply_status(_status_obj("s1"))
    got = mgmt.get(("status.gatekeeper.sh", "v1beta1",
                    "ConstraintTemplatePodStatus"),
                   OPERATOR_NAMESPACE, "s1")
    assert got is not None and got["status"] == {"observed": True}


def test_secret_split_write_routing_and_list_merge():
    """Operator-namespace Secrets (webhook certs) live management-side;
    the target cluster's Secrets stay ordinary audited objects.  A list
    merges both with management WINNING for the operator namespace."""
    mgmt, target = FakeCluster(), FakeCluster()
    rc = RoutingCluster(mgmt, target)
    gvk = ("", "v1", "Secret")

    rc.apply(_secret(OPERATOR_NAMESPACE))          # -> management
    rc.apply(_secret("default", "app-secret"))     # -> target
    assert mgmt.get(gvk, OPERATOR_NAMESPACE, "tls-cert") is not None
    assert target.get(gvk, OPERATOR_NAMESPACE, "tls-cert") is None
    assert target.get(gvk, "default", "app-secret") is not None

    # the target runs its OWN gatekeeper with a same-named cert secret:
    # the merged list must not show a duplicate identity, management wins
    target.apply({**_secret(OPERATOR_NAMESPACE),
                  "data": {"tls.crt": "target-side"}})
    listed = rc.list(gvk)
    op_side = [s for s in listed
               if s["metadata"]["namespace"] == OPERATOR_NAMESPACE]
    assert len(op_side) == 1
    assert op_side[0]["data"]["tls.crt"] == "x"  # management copy
    assert {s["metadata"]["name"] for s in listed} == \
        {"tls-cert", "app-secret"}

    # reads route the same way writes did
    assert rc.get(gvk, OPERATOR_NAMESPACE, "tls-cert")["data"][
        "tls.crt"] == "x"


def test_secret_delete_routes_management_for_operator_namespace():
    mgmt, target = FakeCluster(), FakeCluster()
    rc = RoutingCluster(mgmt, target)
    rc.apply(_secret(OPERATOR_NAMESPACE))
    rc.delete(_secret(OPERATOR_NAMESPACE))
    assert mgmt.get(("", "v1", "Secret"), OPERATOR_NAMESPACE,
                    "tls-cert") is None


# --- retry-on-conflict (the 409 loop routed writes depend on) -------------

def _kube_with_script(script):
    """KubeCluster whose transport replays a scripted response list:
    each entry is a KubeError to raise or a dict to return."""
    kc = KubeCluster(KubeConfig(server="http://unused"), retry_attempts=1)
    calls = []

    def fake(method, path, body=None, timeout=30.0):
        calls.append((method, path))
        step = script.pop(0)
        if isinstance(step, Exception):
            raise step
        return step

    kc._request_once = fake
    kc._discovery[("", "v1")] = {"Pod": ("pods", True)}
    return kc, calls


def _pod(rv="1"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "namespace": "d",
                         "resourceVersion": rv}}


def test_apply_retries_on_conflict_then_succeeds():
    kc, calls = _kube_with_script([
        KubeError(409, "exists"),          # POST -> exists
        _pod("7"),                         # GET current
        KubeError(409, "conflict"),        # PUT -> concurrent writer won
        _pod("8"),                         # GET again (fresh rv)
        {},                                # PUT ok
    ])
    kc.apply(_pod())
    assert [m for m, _ in calls] == ["POST", "GET", "PUT", "GET", "PUT"]


def test_apply_conflict_exhaustion_raises():
    script = [KubeError(409, "exists")]
    for _ in range(4):  # the bounded loop: 4 GET+PUT rounds, all conflict
        script += [_pod("7"), KubeError(409, "conflict")]
    kc, calls = _kube_with_script(script)
    with pytest.raises(KubeError) as ei:
        kc.apply(_pod())
    assert ei.value.status == 409
    assert [m for m, _ in calls].count("PUT") == 4


def test_apply_status_retry_on_conflict_and_deleted_object():
    # conflict once, then clean write through /status
    kc, calls = _kube_with_script([
        _pod("5"),                         # GET current
        KubeError(409, "conflict"),        # PUT status -> conflict
        _pod("6"),                         # GET again
        {},                                # PUT status ok
    ])
    kc.apply_status(_pod())
    puts = [p for m, p in calls if m == "PUT"]
    assert all(p.endswith("/status") for p in puts) and len(puts) == 2

    # object deleted between GET and PUT: 404 disambiguation, no resurrect
    kc2, calls2 = _kube_with_script([
        _pod("5"),                         # GET current
        KubeError(404, "status path"),     # PUT /status -> 404
        KubeError(404, "object gone"),     # re-GET -> object gone
    ])
    kc2.apply_status(_pod())               # returns silently: nothing to do
    assert [m for m, _ in calls2] == ["GET", "PUT", "GET"]
