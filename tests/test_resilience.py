"""Unit tests for the resilience layer: the fault-injection seam
(resilience/faults.py) and the policy primitives (resilience/policy.py —
deadline budgets, jittered retry, circuit breakers), plus their direct
integrations (external-data stale serving, apiserver retry, pipeline
stage-worker restart, webhook deadline guard)."""

import threading
import time

import pytest

from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.resilience.faults import (
    FaultError,
    FaultPlan,
    FaultSpec,
    fault_point,
    inject,
    load_chaos_spec,
)
from gatekeeper_tpu.resilience.policy import (
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    current_deadline,
    deadline_scope,
)


# --- fault seam -----------------------------------------------------------

def test_fault_plan_counting_is_deterministic():
    def pattern(plan):
        out = []
        with inject(plan):
            for _ in range(6):
                try:
                    fault_point("a.b")
                    out.append(0)
                except FaultError:
                    out.append(1)
        return out

    spec = {"site": "a.*", "mode": "error", "after": 1, "times": 2}
    assert pattern(FaultPlan([spec], seed=3)) == [0, 1, 1, 0, 0, 0]
    # same spec + seed -> same firing sequence (reproducible chaos)
    assert pattern(FaultPlan([spec], seed=3)) == \
        pattern(FaultPlan([spec], seed=3))


def test_fault_plan_every_and_probability_seeded():
    plan = FaultPlan([{"site": "s", "mode": "error", "every": 3}])
    hits = []
    with inject(plan):
        for _ in range(7):
            try:
                fault_point("s")
                hits.append(0)
            except FaultError:
                hits.append(1)
    assert hits == [1, 0, 0, 1, 0, 0, 1]

    def prob_pattern(seed):
        p = FaultPlan([{"site": "s", "mode": "error",
                        "probability": 0.5}], seed=seed)
        out = []
        with inject(p):
            for _ in range(16):
                try:
                    fault_point("s")
                    out.append(0)
                except FaultError:
                    out.append(1)
        return out

    assert prob_pattern(1) == prob_pattern(1)
    assert 0 < sum(prob_pattern(1)) < 16


def test_fault_modes_sleep_error_factory_partial():
    slept = []
    plan = FaultPlan(
        [FaultSpec(site="sl", mode="sleep", delay_s=0.25),
         FaultSpec(site="er", mode="error", error="boom", status=503),
         FaultSpec(site="pa", mode="partial", fraction=0.5)],
        sleep=slept.append)
    with inject(plan):
        assert fault_point("sl") is None
        assert slept == [0.25]

        class MyErr(Exception):
            def __init__(self, spec):
                super().__init__(spec.error)
                self.status = spec.status

        with pytest.raises(MyErr) as ei:
            fault_point("er", error_factory=lambda s: MyErr(s))
        assert ei.value.status == 503

        action = fault_point("pa")
        assert action is not None and action.mode == "partial"
        assert action.spec.fraction == 0.5
    # outside the scope the seam is inert
    assert fault_point("er") is None
    assert plan.fired() == 3
    assert plan.fired("sl") == 1


def test_fault_metrics_counted():
    from gatekeeper_tpu.resilience import faults

    reg = MetricsRegistry()
    faults.set_metrics_registry(reg)
    try:
        plan = FaultPlan([{"site": "m", "mode": "error"}])
        with inject(plan):
            with pytest.raises(FaultError):
                fault_point("m")
        assert reg.get_counter(M.RESILIENCE_FAULTS,
                               {"site": "m", "mode": "error"}) == 1
    finally:
        faults.set_metrics_registry(None)


def test_load_chaos_spec_validation(tmp_path):
    p = tmp_path / "chaos.json"
    p.write_text('{"seed": 5, "faults": [{"site": "kube.request", '
                 '"mode": "error", "status": 500, "times": 2}]}')
    plan = load_chaos_spec(str(p))
    assert plan.seed == 5 and plan.specs[0].status == 500
    with pytest.raises(ValueError):
        load_chaos_spec({"faults": [{"mode": "error"}]})  # no site
    with pytest.raises(ValueError):
        load_chaos_spec({"faults": [{"site": "x", "mode": "explode"}]})
    with pytest.raises(ValueError):
        load_chaos_spec({"faults": [{"site": "x", "typo_field": 1}]})


# --- deadline budgets -----------------------------------------------------

def test_deadline_budget_and_scope():
    clock = [0.0]
    dl = Deadline(1.0, clock=lambda: clock[0])
    assert not dl.expired and abs(dl.remaining() - 1.0) < 1e-9
    assert dl.bound(5.0) == 1.0 and dl.bound(0.2) == 0.2
    clock[0] = 2.0
    assert dl.expired
    with pytest.raises(DeadlineExceeded):
        dl.check("unit test")
    assert dl.bound(None) == 0.0

    unlimited = Deadline(0)
    assert unlimited.remaining() is None and not unlimited.expired
    assert unlimited.bound(3.0) == 3.0

    assert current_deadline() is None
    with deadline_scope(dl):
        assert current_deadline() is dl
    assert current_deadline() is None


# --- retry policy ---------------------------------------------------------

def test_retry_jitter_deterministic_and_capped():
    a = RetryPolicy(attempts=5, base_s=0.1, cap_s=0.3, seed=11)
    b = RetryPolicy(attempts=5, base_s=0.1, cap_s=0.3, seed=11)
    seq_a = [a.backoff(i) for i in range(4)]
    seq_b = [b.backoff(i) for i in range(4)]
    assert seq_a == seq_b
    assert all(d <= 0.3 for d in seq_a)
    assert all(d >= 0.05 for d in seq_a)  # full-jitter floor: hi*(1-0.5)


def test_retry_giveup_and_metrics():
    reg = MetricsRegistry()
    rp = RetryPolicy(attempts=4, base_s=0.001, metrics=reg,
                     dependency="dep", sleep=lambda s: None)
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise OSError("transient")
        return "ok"

    assert rp.call(flaky) == "ok"
    assert calls[0] == 3
    assert reg.get_counter(M.RESILIENCE_RETRIES,
                           {"dependency": "dep"}) == 2

    calls[0] = 0

    def fatal():
        calls[0] += 1
        raise ValueError("permanent")

    with pytest.raises(ValueError):
        rp.call(fatal, giveup=lambda e: isinstance(e, ValueError))
    assert calls[0] == 1  # no retry on non-transient


def test_retry_respects_deadline():
    clock = [0.0]
    dl = Deadline(0.5, clock=lambda: clock[0])

    def advance(s):
        clock[0] += 10.0  # any sleep blows the budget

    rp = RetryPolicy(attempts=10, base_s=0.1, sleep=advance)
    calls = [0]

    def failing():
        calls[0] += 1
        raise OSError("x")

    with pytest.raises((DeadlineExceeded, OSError)):
        rp.call(failing, deadline=dl)
    assert calls[0] <= 2  # budget cut the loop, not the attempt count


# --- circuit breaker ------------------------------------------------------

def test_breaker_state_machine_and_metrics():
    clock = [0.0]
    reg = MetricsRegistry()
    transitions = []
    b = CircuitBreaker("dep", failure_threshold=3, reset_timeout_s=10.0,
                       half_open_max=1, clock=lambda: clock[0],
                       metrics=reg,
                       on_transition=lambda o, n: transitions.append((o, n)))
    assert b.state == "closed" and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"  # below threshold
    b.record_failure()
    assert b.state == "open" and not b.allow()
    assert b.retry_after_s() == 10.0
    assert reg.get_gauge(M.RESILIENCE_BREAKER_STATE,
                         {"dependency": "dep"}) == 2

    clock[0] = 11.0
    assert b.state == "half_open"
    assert b.allow()          # the single probe slot
    assert not b.allow()      # second concurrent probe refused
    b.record_failure()        # probe failed -> reopen
    assert b.state == "open"
    clock[0] = 22.0
    assert b.allow()
    b.record_success()        # probe succeeded -> close
    assert b.state == "closed" and b.allow()
    assert transitions == [("closed", "open"), ("open", "half_open"),
                           ("half_open", "open"), ("open", "half_open"),
                           ("half_open", "closed")]
    # every transition counted (the acceptance criterion)
    total = sum(
        reg.get_counter(M.RESILIENCE_BREAKER_TRANSITIONS,
                        {"dependency": "dep", "from": o, "to": n})
        for o, n in set(transitions))
    assert total == len(transitions)


def test_breaker_call_wrapper():
    clock = [0.0]
    b = CircuitBreaker("d", failure_threshold=1, reset_timeout_s=5.0,
                       clock=lambda: clock[0])
    with pytest.raises(RuntimeError):
        b.call(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    with pytest.raises(BreakerOpen) as ei:
        b.call(lambda: "never")
    assert ei.value.dependency == "d"
    clock[0] = 6.0
    assert b.call(lambda: "ok") == "ok"
    assert b.state == "closed"


# --- external-data integration -------------------------------------------

def _provider_cache(send_fn, **kw):
    from gatekeeper_tpu.externaldata.providers import Provider, ProviderCache

    kw.setdefault("retry", RetryPolicy(attempts=2, base_s=0.001,
                                       sleep=lambda s: None))
    cache = ProviderCache(send_fn=send_fn, **kw)
    cache.upsert(Provider(name="p", url="https://x", ca_bundle="x"))
    return cache


def test_externaldata_serves_stale_when_provider_down():
    calls = [0]
    healthy = [True]

    def send(provider, keys):
        calls[0] += 1
        if not healthy[0]:
            raise RuntimeError("provider down")
        return {"response": {"items": [
            {"key": k, "value": f"v-{k}"} for k in keys]}}

    reg = MetricsRegistry()
    cache = _provider_cache(send, response_ttl_s=0.01, metrics=reg,
                            breaker_threshold=2)
    assert cache.fetch("p", ["a"])["a"] == ("v-a", None)
    time.sleep(0.02)  # TTL expired -> entry is stale now
    healthy[0] = False
    out = cache.fetch("p", ["a"])
    assert out["a"] == ("v-a", None)  # stale-from-TTL-cache fallback
    assert reg.get_counter(M.RESILIENCE_STALE_SERVED,
                           {"dependency": "externaldata/p"}) >= 1
    # a key never cached fails with a per-key error -> failure policy
    out = cache.fetch("p", ["never-seen"])
    val, err = out["never-seen"]
    assert val is None and "no cached value" in err


def test_externaldata_breaker_opens_and_skips_transport():
    def send(provider, keys):
        raise RuntimeError("down")

    cache = _provider_cache(send, breaker_threshold=2, breaker_reset_s=60)
    cache.fetch("p", ["k1"])  # failure 1 (retied internally)
    cache.fetch("p", ["k2"])  # failure 2 -> breaker opens
    assert cache._breaker("p").state == "open"
    before = cache._breaker("p")._failures
    out = cache.fetch("p", ["k3"])  # breaker open: transport untouched
    assert "circuit breaker open" in out["k3"][1]
    assert cache._breaker("p")._failures == before


def test_externaldata_partial_response_fault():
    def send(provider, keys):
        return {"response": {"items": [
            {"key": k, "value": f"v-{k}"} for k in keys]}}

    cache = _provider_cache(send)
    plan = FaultPlan([{"site": "externaldata.send", "mode": "partial",
                       "fraction": 0.5, "times": 1}])
    with inject(plan):
        out = cache.fetch("p", ["a", "b"])
    errs = [k for k, (v, e) in out.items() if e]
    assert len(errs) == 1 and out[errs[0]][1] == "key not returned"


def test_externaldata_resolve_failure_policies_still_hold():
    from gatekeeper_tpu.externaldata.placeholders import (
        ExternalDataPlaceholder,
    )
    from gatekeeper_tpu.externaldata.providers import ProviderError

    def send(provider, keys):
        raise RuntimeError("down")

    cache = _provider_cache(send)
    ph = ExternalDataPlaceholder(provider="p", failure_policy="UseDefault",
                                 default="dflt")
    assert cache.resolve(ph) == "dflt"
    ph2 = ExternalDataPlaceholder(provider="p", failure_policy="Fail")
    with pytest.raises(ProviderError):
        cache.resolve(ph2)


# --- apiserver (sync/kube.py) integration ---------------------------------

def test_kube_get_retries_transient_500():
    from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig, KubeError

    reg = MetricsRegistry()
    kc = KubeCluster(KubeConfig(server="http://unused"), metrics=reg)
    kc._retry._sleep = lambda s: None
    calls = [0]

    def flaky(method, path, body=None, timeout=30.0):
        calls[0] += 1
        if calls[0] < 3:
            raise KubeError(500, "storm")
        return {"ok": True}

    kc._request_once = flaky
    assert kc._request("GET", "/api") == {"ok": True}
    assert calls[0] == 3
    assert reg.get_counter(M.RESILIENCE_RETRIES,
                           {"dependency": "apiserver"}) == 2

    # 404 is semantic, not transient: no retry
    calls[0] = 0

    def not_found(method, path, body=None, timeout=30.0):
        calls[0] += 1
        raise KubeError(404, "nope")

    kc._request_once = not_found
    with pytest.raises(KubeError):
        kc._request("GET", "/api")
    assert calls[0] == 1

    # writes never auto-retry here (their 409 semantics live in apply)
    calls[0] = 0

    def post_fails(method, path, body=None, timeout=30.0):
        calls[0] += 1
        raise KubeError(500, "storm")

    kc._request_once = post_fails
    with pytest.raises(KubeError):
        kc._request("POST", "/api/v1/pods", body={})
    assert calls[0] == 1


def test_kube_fault_site_maps_to_kube_error():
    from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig, KubeError

    kc = KubeCluster(KubeConfig(server="http://unused"), retry_attempts=1)
    plan = FaultPlan([{"site": "kube.request", "mode": "error",
                       "status": 503, "error": "injected outage"}])
    with inject(plan):
        with pytest.raises(KubeError) as ei:
            kc._request("GET", "/api")
    assert ei.value.status == 503


# --- pipeline stage-worker restart ---------------------------------------

def test_pipeline_stage_retry_recovers_and_counts():
    from gatekeeper_tpu.pipeline import PipelineError, Stage, StagedPipeline

    failed_once = set()

    def flaky(x):
        if x not in failed_once:
            failed_once.add(x)
            raise RuntimeError(f"crash on {x}")
        return x * 2

    out = []
    pipe = StagedPipeline([
        Stage("flaky", flaky, max_retries=1),
        Stage("sink", lambda x: out.append(x)),
    ])
    run = pipe.run(range(5))
    assert out == [0, 2, 4, 6, 8]
    assert run.stage("flaky").retries == 5
    assert run.summary()["stages"]["flaky"]["retries"] == 5

    # past the restart budget the pipeline aborts (callers degrade)
    def always(x):
        raise RuntimeError("dead")

    pipe2 = StagedPipeline([Stage("dead", always, max_retries=2)])
    with pytest.raises(PipelineError):
        pipe2.run(range(3))


def test_pipeline_stage_fault_site():
    from gatekeeper_tpu.pipeline import Stage, StagedPipeline

    out = []
    plan = FaultPlan([{"site": "pipeline.stage.work", "mode": "error",
                       "times": 2}])
    with inject(plan):
        pipe = StagedPipeline([
            Stage("work", lambda x: x, max_retries=2),
            Stage("sink", lambda x: out.append(x)),
        ])
        run = pipe.run(range(4))
    assert out == [0, 1, 2, 3]
    assert run.stage("work").retries == 2


# --- webhook deadline guard ----------------------------------------------

class _EmptyResponses:
    stats_entries: list = []

    def results(self):
        return []


class _StubClient:
    drivers: list = []

    def review(self, augmented, **kw):
        return _EmptyResponses()


def _admission_body(uid="u1"):
    return {
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": uid, "operation": "CREATE",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "userInfo": {"username": "alice"},
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "x", "namespace": "default"},
                       "spec": {"containers": [{"name": "c"}]}},
        },
    }


def test_webhook_deadline_fail_open_and_closed():
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    plan = FaultPlan([{"site": "webhook.review", "mode": "hang",
                       "delay_s": 1.5}])
    reg = MetricsRegistry()
    with inject(plan):
        h = ValidationHandler(_StubClient(), metrics=reg,
                              deadline_budget_s=0.15,
                              failure_policy="ignore")
        t0 = time.perf_counter()
        resp = h.handle(_admission_body())
        elapsed = time.perf_counter() - t0
        assert elapsed < 1.0  # answered within budget, not the hang
        assert resp.allowed
        assert any("deadline budget" in w for w in resp.warnings)

        h2 = ValidationHandler(_StubClient(), metrics=reg,
                               deadline_budget_s=0.15,
                               failure_policy="fail")
        t0 = time.perf_counter()
        resp2 = h2.handle(_admission_body("u2"))
        assert time.perf_counter() - t0 < 1.0
        assert not resp2.allowed and resp2.code == 504
        assert "deadline budget" in resp2.message
    assert reg.get_counter(M.RESILIENCE_DEADLINE_EXCEEDED,
                           {"component": "webhook",
                            "policy": "ignore"}) == 1
    assert reg.get_counter(M.RESILIENCE_DEADLINE_EXCEEDED,
                           {"component": "webhook", "policy": "fail"}) == 1


def test_webhook_no_deadline_runs_inline():
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    h = ValidationHandler(_StubClient())
    main_thread = threading.get_ident()
    seen = []

    class _Client(_StubClient):
        def review(self, augmented, **kw):
            seen.append(threading.get_ident())
            return _EmptyResponses()

    h.client = _Client()
    resp = h.handle(_admission_body())
    assert resp.allowed
    assert seen == [main_thread]  # pre-resilience path: no helper thread
