"""Overload protection (ISSUE 5): adaptive concurrency, cost-aware load
shedding, brownout ladder.

Acceptance pins:
- sheds honor failurePolicy exactly like a deadline miss (Ignore =
  allow + warning annotation, Fail = 429 with Retry-After);
- the limiter enabled but unloaded is bit-identical to limiter-off over
  the shipped library corpus;
- under an injected 4x offered-load burst, accepted-request P99 stays
  within 2x the unloaded P99 and every shed carries the
  failurePolicy-correct verdict;
- the brownout ladder degrades optional work (namespace lookups,
  external-data joins, audit device lane) BEFORE any request is shed.
"""

import http.client
import json
import threading
import time

import pytest

from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.resilience import overload as ovl
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.webhook.policy import ValidationHandler
from gatekeeper_tpu.webhook.server import WebhookServer


class _EmptyResponses:
    stats_entries: list = []

    def results(self):
        return []


class _StubClient:
    """Review stub with a configurable service time."""

    drivers: list = []

    def __init__(self, service_s: float = 0.0):
        self.service_s = service_s
        self.reviews = 0

    def constraints(self):
        return []

    def review(self, augmented, **kw):
        self.reviews += 1
        if self.service_s:
            time.sleep(self.service_s)
        return _EmptyResponses()


def _review_body(uid="u1", kind="Pod", namespace=""):
    return {
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": uid, "operation": "CREATE",
                    "kind": {"group": "", "version": "v1", "kind": kind},
                    "namespace": namespace,
                    "userInfo": {"username": "load"},
                    "object": {"apiVersion": "v1", "kind": kind,
                               "metadata": {"name": "x",
                                            "namespace": namespace}}},
    }


def _tiny_controller(metrics=None, **over):
    kw = dict(min_inflight=1, max_inflight=1, initial_inflight=1,
              queue_depth=0, queue_timeout_s=0.05)
    kw.update(over)
    return ovl.OverloadController(ovl.OverloadConfig(**kw),
                                  metrics=metrics)


# --- AIMD limiter unit behavior -------------------------------------------

def test_limiter_seeded_trajectory_replays_exactly():
    """Same (config, seed, sample sequence) => identical limit + baseline
    trajectory — chaos/overload runs are replayable."""
    cfg = ovl.OverloadConfig(seed=42, update_window=4, initial_inflight=8)
    trajectories = []
    for _ in range(2):
        lim = ovl.AdaptiveLimiter(cfg)
        traj = []
        for s in [0.01] * 8 + [0.8] * 12 + [0.01] * 8:
            assert lim.try_acquire()
            lim.release(s)
            traj.append((lim.limit, round(lim.baseline_s, 9)))
        trajectories.append(traj)
    assert trajectories[0] == trajectories[1]


def test_limiter_aimd_decrease_and_recovery():
    """A latency spike multiplicatively decreases the limit; healthy
    windows additively recover it."""
    cfg = ovl.OverloadConfig(seed=0, update_window=4, initial_inflight=16,
                             max_inflight=32, latency_threshold=2.0,
                             decrease_factor=0.5, congested_sample_p=0.0)
    lim = ovl.AdaptiveLimiter(cfg)
    for s in [0.01] * 8:  # establish the baseline
        lim.try_acquire()
        lim.release(s)
    healthy = lim.limit
    assert healthy >= 16  # additive increase happened
    for s in [1.0] * 4:  # one bad window: avg >> 2x baseline
        lim.try_acquire()
        lim.release(s)
    assert lim.limit <= healthy // 2  # multiplicative decrease
    dropped = lim.limit
    for s in [0.01] * 8:  # recovery: +1 per healthy window
        lim.try_acquire()
        lim.release(s)
    assert lim.limit == dropped + 2


def test_limiter_respects_bounds():
    # ewma_alpha=0 freezes the baseline at the first sample so the slow
    # run keeps registering as overload (a drifting baseline would
    # legitimately learn uniform slowness as the new normal)
    cfg = ovl.OverloadConfig(min_inflight=2, max_inflight=4,
                             initial_inflight=3, update_window=2,
                             decrease_factor=0.1, congested_sample_p=0.0,
                             ewma_alpha=0.0)
    lim = ovl.AdaptiveLimiter(cfg)
    for s in [0.001] * 20:
        lim.try_acquire()
        lim.release(s)
    assert lim.limit == 4  # clamped at max
    for s in [5.0] * 20:
        lim.try_acquire()
        lim.release(s)
    assert lim.limit == 2  # clamped at min


def test_cost_estimate_scales_with_bytes_and_constraints():
    body = _review_body()
    base = ovl.estimate_cost(body, cost_hint=1000,
                             constraint_count=lambda kind: 1)
    assert base == 1000.0
    assert ovl.estimate_cost(body, cost_hint=1000,
                             constraint_count=lambda kind: 7) == 7000.0
    # no hint: sized from the serialized object, never zero
    assert ovl.estimate_cost(body) > 0


# --- controller: queue bounds + shed --------------------------------------

def test_queue_bounds_shed_and_freed_slot_admits():
    reg = MetricsRegistry()
    c = _tiny_controller(metrics=reg, queue_depth=1, queue_timeout_s=2.0)
    held, release = threading.Event(), threading.Event()

    def hold():
        with c.admit(10):
            held.set()
            release.wait(5)

    t = threading.Thread(target=hold)
    t.start()
    assert held.wait(2)
    results = {}

    def queued():
        try:
            with c.admit(10):
                results["queued"] = "admitted"
        except ovl.Shed as e:
            results["queued"] = e.reason

    t2 = threading.Thread(target=queued)
    t2.start()
    time.sleep(0.05)  # let it take the single queue slot
    with pytest.raises(ovl.Shed) as ei:
        with c.admit(10):
            pass
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after_s > 0
    release.set()
    t.join(2)
    t2.join(2)
    assert results["queued"] == "admitted"  # freed slot went to the queue
    assert reg.get_counter(M.OVERLOAD_SHED, {"reason": "queue_full"}) == 1


def test_queue_cost_bound_sheds_expensive_request():
    c = _tiny_controller(queue_depth=100, queue_cost=50.0,
                         queue_timeout_s=2.0)
    held, release = threading.Event(), threading.Event()

    def hold():
        with c.admit(1):
            held.set()
            release.wait(5)

    t = threading.Thread(target=hold)
    t.start()
    assert held.wait(2)
    try:
        with pytest.raises(ovl.Shed) as ei:
            with c.admit(100.0):  # alone exceeds the cost bound
                pass
        assert ei.value.reason == "queue_cost"
    finally:
        release.set()
        t.join(2)


def test_queue_timeout_sheds():
    c = _tiny_controller(queue_depth=4, queue_timeout_s=0.05)
    held, release = threading.Event(), threading.Event()

    def hold():
        with c.admit(1):
            held.set()
            release.wait(5)

    t = threading.Thread(target=hold)
    t.start()
    assert held.wait(2)
    try:
        t0 = time.perf_counter()
        with pytest.raises(ovl.Shed) as ei:
            with c.admit(1):
                pass
        assert ei.value.reason == "queue_timeout"
        assert time.perf_counter() - t0 < 1.0
    finally:
        release.set()
        t.join(2)


# --- brownout ladder -------------------------------------------------------

def test_brownout_ladder_levels_and_hysteresis():
    c = ovl.OverloadController(ovl.OverloadConfig(
        queue_depth=10, queue_cost=1e9,
        brownout1_enter=0.1, brownout1_exit=0.0,
        brownout2_enter=0.5, brownout2_exit=0.25))
    with c._cv:
        assert c._brownout == 0
        c._queue_len = 1
        c._pressure_locked()
        assert c._brownout == 1  # 10% fill: optional work degrades
        c._queue_len = 6
        c._pressure_locked()
        assert c._brownout == 2  # 60% fill: audit yields the device lane
        c._queue_len = 3
        c._pressure_locked()
        assert c._brownout == 2  # hysteresis: 30% > exit threshold 25%
        c._queue_len = 2
        c._pressure_locked()
        assert c._brownout == 1  # fell through level-2 exit
        c._queue_len = 0
        c._pressure_locked()
        assert c._brownout == 0


def test_namespace_lookup_serves_stale_under_brownout():
    calls = []

    def lookup(name):
        calls.append(name)
        return {"metadata": {"name": name, "labels": {"v": str(len(calls))}}}

    reg = MetricsRegistry()
    c = _tiny_controller(metrics=reg)
    h = ValidationHandler(_StubClient(), namespace_lookup=lookup,
                          overload=c, metrics=reg)
    body = _review_body(namespace="prod")
    h.handle(body)
    assert calls == ["prod"]  # level 0: live lookup, cache primed
    with c._cv:
        c._queue_len = 1
        c._queue_cost = 1.0
        c._brownout = 1
    h.handle(body)
    assert calls == ["prod"]  # brownout: served stale, no second lookup
    assert reg.get_counter(
        M.RESILIENCE_STALE_SERVED,
        {"dependency": "webhook/namespace_lookup"}) == 1
    with c._cv:
        c._queue_len = 0
        c._queue_cost = 0.0
        c._brownout = 0
    h.handle(body)
    assert calls == ["prod", "prod"]  # recovered: live again


def test_externaldata_serves_stale_under_brownout():
    from gatekeeper_tpu.externaldata.providers import Provider, ProviderCache

    sends = []

    def send(provider, keys):
        sends.append(list(keys))
        return {"response": {"items": [
            {"key": k, "value": f"v-{k}"} for k in keys]}}

    reg = MetricsRegistry()
    cache = ProviderCache(send_fn=send, metrics=reg, response_ttl_s=0.0)
    cache.upsert(Provider(name="p", url="http://x", timeout_s=1))
    out = cache.fetch("p", ["a"])  # primes the (expired-on-arrival) cache
    assert out["a"][0] == "v-a"
    assert sends == [["a"]]
    ctl = _tiny_controller()
    with ctl._cv:
        ctl._brownout = 1
    with ovl.activate(ctl):
        out = cache.fetch("p", ["a", "b"])
    assert sends == [["a"]]  # no transport under brownout
    assert out["a"] == ("v-a", None)  # stale hit
    assert out["b"][1] is not None  # never-fetched key: per-key error
    assert "brownout" in out["b"][1]
    assert reg.get_counter(M.RESILIENCE_STALE_SERVED,
                           {"dependency": "externaldata/p"}) >= 1
    out = cache.fetch("p", ["c"])  # ladder released: transport again
    assert sends == [["a"], ["c"]]


def test_audit_yield_device_lane_bounded():
    ctl = _tiny_controller()
    with ctl._cv:
        ctl._brownout = 2
    with ovl.activate(ctl):
        t0 = time.perf_counter()
        waited = ovl.yield_device_lane(max_wait_s=0.06, poll_s=0.01)
        wall = time.perf_counter() - t0
    assert 0.04 <= waited <= 0.08  # yielded, but bounded
    assert wall < 1.0
    # below the level threshold: no yield at all
    with ctl._cv:
        ctl._brownout = 1
    with ovl.activate(ctl):
        assert ovl.yield_device_lane() == 0.0
    assert ovl.yield_device_lane() == 0.0  # nothing installed


# --- shed semantics over HTTP (failurePolicy parity) ----------------------

def _burst(port, n, uid_prefix="u"):
    """POST n concurrent admissions; returns [(status, doc, retry_after)]."""
    out = []
    lock = threading.Lock()

    def post(i):
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
        body = json.dumps(_review_body(uid=f"{uid_prefix}{i}")).encode()
        c.request("POST", "/v1/admit", body,
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        doc = json.loads(r.read())
        with lock:
            out.append((r.status, doc, r.getheader("Retry-After")))
        c.close()

    threads = [threading.Thread(target=post, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    return out


def test_shed_failure_policy_fail_is_429_with_retry_after():
    reg = MetricsRegistry()
    ctl = _tiny_controller(metrics=reg)
    h = ValidationHandler(_StubClient(service_s=0.2), metrics=reg,
                          failure_policy="fail", overload=ctl)
    srv = WebhookServer(validation_handler=h, port=0, metrics=reg).start()
    try:
        out = _burst(srv.port, 4)
    finally:
        srv.stop(drain_timeout=3)
    sheds = [o for o in out if not o[1]["response"]["allowed"]]
    served = [o for o in out if o[1]["response"]["allowed"]]
    assert served and sheds  # the burst overflowed a 1-slot limiter
    for status, doc, retry_after in sheds:
        assert status == 200  # AdmissionReview protocol: HTTP stays 200
        assert doc["response"]["status"]["code"] == 429
        assert "overload" in doc["response"]["status"]["message"]
        assert retry_after is not None and int(retry_after) >= 1
        assert doc["response"]["uid"]  # verdict addressed to its request
    assert reg.get_counter(M.REQUEST_COUNT,
                           {"admission_status": "shed"}) == len(sheds)
    assert reg.counter_total(M.OVERLOAD_SHED) == len(sheds)


def test_shed_failure_policy_ignore_allows_with_warning():
    reg = MetricsRegistry()
    ctl = _tiny_controller(metrics=reg)
    h = ValidationHandler(_StubClient(service_s=0.2), metrics=reg,
                          failure_policy="ignore", overload=ctl)
    srv = WebhookServer(validation_handler=h, port=0, metrics=reg).start()
    try:
        out = _burst(srv.port, 4)
    finally:
        srv.stop(drain_timeout=3)
    sheds = [o for o in out
             if any("overload" in w
                    for w in o[1]["response"].get("warnings", []))]
    assert sheds  # the burst overflowed
    for status, doc, retry_after in sheds:
        assert doc["response"]["allowed"] is True  # failurePolicy=Ignore
        assert retry_after is None  # admitted: no backoff demanded
    # every response (shed or served) is allowed under Ignore
    assert all(o[1]["response"]["allowed"] for o in out)


def test_chaos_site_webhook_overload_forces_shed():
    """The webhook.overload fault site: an injected error sheds even an
    unloaded request, resolved per failurePolicy."""
    ctl = ovl.OverloadController(ovl.OverloadConfig())
    h = ValidationHandler(_StubClient(), failure_policy="fail",
                          overload=ctl)
    plan = FaultPlan([{"site": "webhook.overload", "mode": "error",
                       "times": 1}])
    with inject(plan):
        resp = h.handle(_review_body(uid="chaos-1"))
    assert plan.fired() == 1
    assert resp.allowed is False
    assert resp.code == 429
    assert resp.retry_after_s > 0
    # the plan exhausted: the next request flows normally
    resp2 = h.handle(_review_body(uid="chaos-2"))
    assert resp2.allowed is True


# --- the overload differential (library corpus) ---------------------------

@pytest.fixture(scope="module")
def library_setup():
    from gatekeeper_tpu.apis.constraints import WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.synthetic import (load_library,
                                                make_cluster_objects)

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP])
    load_library(client)
    objects = make_cluster_objects(60, seed=23)
    return client, objects


def _admission_bodies(objects):
    from gatekeeper_tpu.utils.unstructured import gvk_of

    bodies = []
    for i, obj in enumerate(objects):
        g, v, k = gvk_of(obj)
        bodies.append({
            "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {
                "uid": f"u{i}", "operation": "CREATE",
                "kind": {"group": g, "version": v, "kind": k},
                "name": (obj.get("metadata") or {}).get("name", ""),
                "namespace": (obj.get("metadata") or {}).get(
                    "namespace", ""),
                "userInfo": {"username": "differential"},
                "object": obj,
            },
        })
    return bodies


def _signature(resp):
    return (resp.allowed, resp.message, resp.code, tuple(resp.warnings),
            resp.uid, resp.retry_after_s)


def test_limiter_on_unloaded_bit_identical_to_off(library_setup):
    """The overload differential: limiter installed but unloaded
    (sequential corpus) must not perturb one verdict bit vs limiter-off —
    and must shed nothing and stay at brownout 0."""
    client, objects = library_setup
    bodies = _admission_bodies(objects)
    off = ValidationHandler(client)
    baseline = [_signature(off.handle(b)) for b in bodies]
    ctl = ovl.OverloadController(ovl.OverloadConfig())
    on = ValidationHandler(client, overload=ctl)
    with ovl.activate(ctl):
        overloaded = [_signature(on.handle(b)) for b in bodies]
    assert overloaded == baseline
    assert ctl.shed_count == 0
    assert ctl.brownout_level() == 0
    assert any(not sig[0] for sig in baseline)  # non-vacuous: real denies


def test_qos_off_bit_identical_to_pr5_fifo_over_library(library_setup):
    """The ISSUE 10 compat differential: qos=None (the ``--qos off``
    default) IS the PR 5 single-FIFO code path — verdict-for-verdict
    identical to no limiter at all over the library corpus; and QoS ON
    while unloaded perturbs nothing either (zero sheds, brownout 0,
    same signatures, every trajectory event a grant)."""
    from gatekeeper_tpu.resilience.qos import QoSConfig

    client, objects = library_setup
    bodies = _admission_bodies(objects)
    baseline = [_signature(ValidationHandler(client).handle(b))
                for b in bodies]
    off_ctl = ovl.OverloadController(ovl.OverloadConfig())
    assert off_ctl._queue_qos is None  # the PR 5 branch, literally
    with ovl.activate(off_ctl):
        off_sigs = [_signature(
            ValidationHandler(client, overload=off_ctl).handle(b))
            for b in bodies]
    assert off_sigs == baseline
    assert off_ctl.shed_count == 0 and len(off_ctl.trajectory) == 0
    qos_ctl = ovl.OverloadController(ovl.OverloadConfig(
        qos=QoSConfig()))
    with ovl.activate(qos_ctl):
        qos_sigs = [_signature(
            ValidationHandler(client, overload=qos_ctl).handle(b))
            for b in bodies]
    assert qos_sigs == baseline
    assert qos_ctl.shed_count == 0
    assert qos_ctl.brownout_level() == 0
    assert all(e[0] == "grant" for e in qos_ctl.trajectory)
    assert any(not sig[0] for sig in baseline)  # non-vacuous: real denies


def test_burst_p99_bounded_and_sheds_policy_correct(library_setup):
    """4x offered-load burst against a chaos-slowed review: accepted P99
    stays within 2x the unloaded P99, every shed is failurePolicy-shaped,
    and zero requests are lost (every call returns a verdict)."""
    client, objects = library_setup
    bodies = _admission_bodies(objects)

    service_s = 0.25
    plan = FaultPlan([{"site": "webhook.review", "mode": "sleep",
                       "delay_s": service_s}])
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=2, max_inflight=2, initial_inflight=2,
        queue_depth=2, queue_timeout_s=0.1))
    h = ValidationHandler(client, failure_policy="fail", overload=ctl)

    with inject(plan), ovl.activate(ctl):
        # unloaded anchor: sequential, no queueing
        unloaded = []
        for b in bodies[:6]:
            t0 = time.perf_counter()
            h.handle(b)
            unloaded.append(time.perf_counter() - t0)
        unloaded_p99 = sorted(unloaded)[-1]

        # burst: 8 concurrent against an in-flight limit of 2
        results = []
        lock = threading.Lock()

        def one(i):
            t0 = time.perf_counter()
            resp = h.handle(bodies[i % len(bodies)])
            with lock:
                results.append((time.perf_counter() - t0, resp))

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)

    assert len(results) == 16  # zero lost: every request got a verdict
    sheds = [r for _, r in results if r.code == 429]
    accepted = [dt for dt, r in results if r.code != 429]
    assert sheds, "a 8x-concurrency burst against limit 2 must shed"
    for r in sheds:
        assert r.allowed is False and r.retry_after_s > 0  # policy=fail
    accepted_p99 = sorted(accepted)[-1]
    # the acceptance bound: accepted P99 within 2x the unloaded P99
    # (queue_timeout + service fits comfortably; without the limiter the
    # convoy would be ~16 x service_s deep)
    assert accepted_p99 <= 2.0 * unloaded_p99, \
        f"accepted P99 {accepted_p99:.3f}s vs unloaded {unloaded_p99:.3f}s"
