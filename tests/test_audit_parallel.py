"""Audit sweep over a sharded virtual mesh (8 CPU devices via conftest) —
the multi-chip path of BASELINE config #6 (1M-object sweep shape)."""

import numpy as np
import yaml

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh, topk_violations
from gatekeeper_tpu.target.target import K8sValidationTarget

PSP = "/root/reference/pkg/webhook/testdata/psp-all-violations"


def _load(p):
    with open(p) as f:
        return yaml.safe_load(f)


def build_client():
    tpu = TpuDriver(batch_bucket=16)
    client = Client(target=K8sValidationTarget(), drivers=[tpu],
                    enforcement_points=["audit.gatekeeper.sh"])
    client.add_template(_load(
        f"{PSP}/psp-templates/privileged-containers-template.yaml"))
    client.add_template(_load(
        "/root/reference/demo/basic/templates/k8srequiredlabels_template.yaml"))
    client.add_constraint(_load(
        f"{PSP}/psp-constraints/privileged-containers-constraint.yaml"))
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "need-owner"},
        "spec": {"match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
                 "parameters": {"labels": ["owner"]}},
    })
    return client, tpu


def make_pods(n):
    pods = []
    for i in range(n):
        meta = {"name": f"p{i}", "namespace": "default"}
        if i % 3 == 0:
            meta["labels"] = {"owner": "me"}
        pods.append({
            "apiVersion": "v1", "kind": "Pod", "metadata": meta,
            "spec": {"containers": [
                {"name": "c",
                 "securityContext": {"privileged": i % 7 == 0}}]},
        })
    return pods


def test_topk_violations_kernel():
    v = np.zeros((2, 32), bool)
    v[0, [3, 9, 30]] = True
    idx, valid = topk_violations(v, 2)
    assert idx.shape == (2, 2)
    assert sorted(np.asarray(idx)[0][np.asarray(valid)[0]].tolist()) == [3, 9]
    assert not np.asarray(valid)[1].any()


def test_sharded_audit_sweep_matches_totals():
    client, tpu = build_client()
    mesh = make_mesh()  # all 8 virtual devices
    evaluator = ShardedEvaluator(tpu, mesh, violations_limit=5)
    pods = make_pods(200)
    mgr = AuditManager(
        client, lister=lambda: iter(pods),
        config=AuditConfig(chunk_size=128, violations_limit=5),
        evaluator=evaluator,
    )
    run = mgr.audit()
    assert run.total_objects == 200
    priv_total = run.total_violations[("K8sPSPPrivilegedContainer",
                                       "psp-privileged-container")]
    assert priv_total == sum(1 for i in range(200) if i % 7 == 0)
    lab_total = run.total_violations[("K8sRequiredLabels", "need-owner")]
    assert lab_total == sum(1 for i in range(200) if i % 3 != 0)
    kept = run.kept[("K8sRequiredLabels", "need-owner")]
    assert len(kept) == 5  # capped at limit
    assert all("you must provide labels" in v.message for v in kept)
    # status written back onto constraints (reference: manager.go:1065)
    con = client.get_constraint("K8sRequiredLabels", "need-owner")
    assert con.raw["status"]["totalViolations"] == lab_total
    assert len(con.raw["status"]["violations"]) == 5


def test_audit_interpreter_only_path_agrees():
    client, tpu = build_client()
    pods = make_pods(100)
    mgr_plain = AuditManager(client, lister=lambda: iter(pods),
                             config=AuditConfig(chunk_size=64))
    run_plain = mgr_plain.audit()
    mesh = make_mesh(4)
    mgr_shard = AuditManager(
        client, lister=lambda: iter(pods),
        config=AuditConfig(chunk_size=64),
        evaluator=ShardedEvaluator(tpu, mesh),
    )
    run_shard = mgr_shard.audit()
    assert run_plain.total_violations == run_shard.total_violations


def test_exact_totals_count_results_not_objects():
    """Reference parity: a pod with 2 privileged containers contributes 2 to
    totalViolations (audit/manager.go counts results, not objects)."""
    client, tpu = build_client()
    pods = [{
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "multi", "namespace": "default",
                     "labels": {"owner": "x"}},
        "spec": {"containers": [
            {"name": "a", "securityContext": {"privileged": True}},
            {"name": "b", "securityContext": {"privileged": True}},
        ]},
    }]
    key = ("K8sPSPPrivilegedContainer", "psp-privileged-container")
    mesh = make_mesh(2)
    run_exact = AuditManager(
        client, lister=lambda: iter(pods),
        config=AuditConfig(exact_totals=True),
        evaluator=ShardedEvaluator(tpu, mesh),
    ).audit()
    assert run_exact.total_violations[key] == 2
    assert len(run_exact.kept[key]) == 2
    # interpreter-only path agrees
    run_plain = AuditManager(client, lister=lambda: iter(pods)).audit()
    assert run_plain.total_violations[key] == 2
    # approximate mode counts objects
    run_approx = AuditManager(
        client, lister=lambda: iter(pods),
        config=AuditConfig(exact_totals=False),
        evaluator=ShardedEvaluator(tpu, mesh),
    ).audit()
    assert run_approx.total_violations[key] == 1


def test_kept_respects_limit_with_multi_result_objects():
    client, tpu = build_client()
    pods = [{
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": f"m{i}", "namespace": "default",
                     "labels": {"owner": "x"}},
        "spec": {"containers": [
            {"name": "a", "securityContext": {"privileged": True}},
            {"name": "b", "securityContext": {"privileged": True}},
            {"name": "c", "securityContext": {"privileged": True}},
        ]},
    } for i in range(4)]
    key = ("K8sPSPPrivilegedContainer", "psp-privileged-container")
    run = AuditManager(
        client, lister=lambda: iter(pods),
        config=AuditConfig(violations_limit=5, exact_totals=True),
        evaluator=ShardedEvaluator(tpu, make_mesh(2), violations_limit=5),
    ).audit()
    assert run.total_violations[key] == 12  # all results counted
    assert len(run.kept[key]) == 5  # but kept hard-capped at the limit


def test_pipelined_chunks_match_synchronous():
    """The pipelined chunk loop (submit N+1 before collecting N) must
    produce identical totals/kept as single-chunk processing."""
    client, tpu = build_client()
    pods = make_pods(500)
    mesh = make_mesh(4)
    run_small_chunks = AuditManager(
        client, lister=lambda: iter(pods),
        config=AuditConfig(chunk_size=64, violations_limit=7),
        evaluator=ShardedEvaluator(tpu, mesh, violations_limit=7),
    ).audit()
    run_one_chunk = AuditManager(
        client, lister=lambda: iter(pods),
        config=AuditConfig(chunk_size=100000, violations_limit=7),
        evaluator=ShardedEvaluator(tpu, mesh, violations_limit=7),
    ).audit()
    assert run_small_chunks.total_violations == run_one_chunk.total_violations
    for key in run_one_chunk.kept:
        assert (
            [v.name for v in run_small_chunks.kept[key]]
            == [v.name for v in run_one_chunk.kept[key]]
        )


def test_evaluator_without_batch_driver_falls_back():
    """An evaluator without any query_batch-capable driver must fall back to
    the interpreter loop instead of crashing."""
    from gatekeeper_tpu.drivers.rego_driver import RegoDriver

    client = Client(target=K8sValidationTarget(), drivers=[RegoDriver()],
                    enforcement_points=["audit.gatekeeper.sh"])
    client.add_template(_load(
        "/root/reference/demo/basic/templates/"
        "k8srequiredlabels_template.yaml"))
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "need-owner"},
        "spec": {"parameters": {"labels": ["owner"]}},
    })
    tpu_elsewhere = TpuDriver()  # an evaluator whose driver isn't registered
    mgr = AuditManager(
        client, lister=lambda: iter(make_pods(20)),
        config=AuditConfig(chunk_size=8),
        evaluator=ShardedEvaluator(tpu_elsewhere, make_mesh(2)),
    )
    run = mgr.audit()
    assert run.total_objects == 20
    assert run.total_violations[("K8sRequiredLabels", "need-owner")] > 0


def test_cel_constraints_not_dropped_by_evaluator_path():
    """Round-2 regression: constraints owned by a non-batch driver (CEL
    templates) must still be evaluated when the device evaluator handles
    the lowered kinds (the old code only routed TpuDriver fallback kinds)."""
    import os

    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    lib = os.path.join(os.path.dirname(__file__), "..", "library",
                       "general", "containerlimitscel")
    tpu = TpuDriver(batch_bucket=16)
    client = Client(target=K8sValidationTarget(),
                    drivers=[tpu, CELDriver()],
                    enforcement_points=["audit.gatekeeper.sh"])
    client.add_template(load_yaml_file(f"{lib}/template.yaml")[0])
    client.add_constraint(load_yaml_file(f"{lib}/samples/constraint.yaml")[0])
    bad = load_yaml_file(f"{lib}/samples/example_disallowed.yaml")[0]
    mgr = AuditManager(
        client, lister=lambda: iter([bad]),
        evaluator=ShardedEvaluator(tpu, make_mesh(2)),
    )
    run = mgr.audit()
    assert sum(run.total_violations.values()) == 1


def test_restricted_inventory_rendering_matches_full():
    """TPU-driver render_query with join-candidate-restricted inventory must
    produce bit-identical messages to the full-inventory interpreter."""
    import os

    from gatekeeper_tpu.drivers.base import ReviewCfg
    from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
    from gatekeeper_tpu.target.review import AugmentedUnstructured
    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    lib = os.path.join(os.path.dirname(__file__), "..", "library",
                       "general", "uniqueingresshost")
    tpu = TpuDriver(batch_bucket=16)
    client = Client(target=K8sValidationTarget(), drivers=[tpu],
                    enforcement_points=["audit.gatekeeper.sh"])
    client.add_template(load_yaml_file(f"{lib}/template.yaml")[0])
    con = client.add_constraint(
        load_yaml_file(f"{lib}/samples/constraint.yaml")[0])

    def ing(i, host, ns="default"):
        return {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
                "metadata": {"name": f"ing-{i}", "namespace": ns},
                "spec": {"rules": [{"host": host}]}}

    ingresses = [ing(0, "dup.example.com"), ing(1, "dup.example.com", "ns2"),
                 ing(2, "solo.example.com"), ing(3, "other.example.com")]
    for o in ingresses:
        client.add_data(o)
    target = client.target
    cfg = ReviewCfg(enforcement_point="audit.gatekeeper.sh")
    specs = tpu._render_restrict_specs(con.kind)
    assert specs, "uniqueingresshost join subject should be restrictable"
    for o in ingresses:
        review = target.handle_review(
            AugmentedUnstructured(object=o, source=SOURCE_ORIGINAL))
        full = tpu._interp.query(target.name, [con], review, cfg)
        res = tpu.render_query(target.name, con, review, cfg)
        assert sorted(r.msg for r in full.results) == \
            sorted(r.msg for r in res.results), o["metadata"]
    # the duplicated-host pair violates; the solo hosts do not
    review = target.handle_review(AugmentedUnstructured(
        object=ingresses[0], source=SOURCE_ORIGINAL))
    assert tpu.render_query(target.name, con, review, cfg).results


def test_render_restrict_rejects_unwalkable_subjects():
    """A join whose subject the object walk can't reproduce (review-level
    or transformed) must disable restriction, not restrict to nothing."""
    from gatekeeper_tpu.ir import nodes as N
    from gatekeeper_tpu.drivers.tpu_driver import _col_restrictable
    from gatekeeper_tpu.ops.flatten import ScalarCol

    assert _col_restrictable(ScalarCol(("spec", "host")))
    assert not _col_restrictable(ScalarCol(("__review__", "namespace")))
