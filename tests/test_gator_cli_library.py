"""gator verify/bench/sync CLIs + the shipped policy library."""

import glob
import io
import os

import yaml

from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.gator import verify as verify_mod
from gatekeeper_tpu.gator.bench import run_bench
from gatekeeper_tpu.gator.sync_cmd import missing_requirements
from gatekeeper_tpu.utils.unstructured import load_yaml_file

LIBRARY = os.path.join(os.path.dirname(__file__), "..", "library")
REF_VERIFY = "/root/reference/test/gator/verify/suite.yaml"


def test_reference_verify_suite_passes():
    sr = verify_mod.run_suite(REF_VERIFY)
    assert not sr.failed(), [
        (t.name, c.name, c.error) for t in sr.tests for c in t.cases
        if c.error
    ]
    assert len(sr.tests) == 5


def test_library_suites_all_pass():
    suites = verify_mod.find_suites([LIBRARY])
    assert len(suites) >= 11
    for path in suites:
        sr = verify_mod.run_suite(path)
        assert not sr.failed(), (path, [
            (t.name, c.name, c.error or t.error)
            for t in sr.tests for c in t.cases or [type("x", (), {
                "name": "", "error": ""})()]
        ])


def test_assertion_semantics():
    class R:
        def __init__(self, msg):
            self.msg = msg

    results = [R("foo is bad"), R("bar is bad")]
    assert verify_mod._assert_case([{"violations": 2}], results) is None
    assert verify_mod._assert_case(
        [{"violations": 1, "message": "foo"}], results) is None
    assert verify_mod._assert_case([{"violations": "no"}], []) is None
    assert verify_mod._assert_case([{}], results) is None  # default yes
    assert verify_mod._assert_case([{}], []) is not None
    assert verify_mod._assert_case([{"violations": 3}], results) is not None
    assert verify_mod._assert_case(
        [{"violations": "maybe"}], results) is not None


def test_library_templates_lowering_coverage():
    """Most shipped Rego policies should compile to the TPU verdict path."""
    tpu = TpuDriver()
    rego_kinds = []
    for path in sorted(glob.glob(f"{LIBRARY}/general/*/template.yaml")):
        doc = load_yaml_file(path)[0]
        t = ConstraintTemplate.from_unstructured(doc)
        if not t.targets[0].rego:
            continue  # CEL-engine library entries
        rego_kinds.append(t.kind)
        tpu.add_template(t)
    lowered = set(tpu.lowered_kinds())
    assert {"K8sHostNamespace", "K8sHostNetworkingPorts", "K8sBlockNodePort",
            "K8sAllowedRepos", "K8sDisallowedTags", "K8sContainerLimits",
            "K8sReplicaLimits"} <= lowered
    # legitimately interpreter-bound: map-key/value iteration with regex
    # (requiredlabels/annotations clause 2), dynamic field access by param
    # (requiredprobes), referential data (uniqueingresshost)
    assert len(lowered) * 2 >= len(rego_kinds), (
        sorted(lowered), tpu.fallback_kinds()
    )


def test_bench_runs_on_library_sample():
    objs = []
    for f in ("template.yaml", "samples/constraint.yaml",
              "samples/example_allowed.yaml",
              "samples/example_disallowed.yaml"):
        objs.extend(load_yaml_file(
            os.path.join(LIBRARY, "general", "allowedrepos", f)))
    r = run_bench(objs, "rego", iterations=3)
    assert r.reviews_per_sec > 0
    assert r.violations == 1
    r_tpu = run_bench(objs, "tpu", iterations=2)
    assert r_tpu.violations == 1


def test_sync_requirements():
    t = load_yaml_file(os.path.join(
        LIBRARY, "general", "uniqueingresshost", "template.yaml"))[0]
    missing = missing_requirements([t])
    assert "k8suniqueingresshost" in missing
    syncset = {
        "apiVersion": "syncset.gatekeeper.sh/v1alpha1",
        "kind": "SyncSet",
        "metadata": {"name": "s"},
        "spec": {"gvks": [{"group": "networking.k8s.io", "version": "v1",
                           "kind": "Ingress"}]},
    }
    assert missing_requirements([t, syncset]) == {}
    config = {
        "apiVersion": "config.gatekeeper.sh/v1alpha1",
        "kind": "Config",
        "metadata": {"name": "config"},
        "spec": {"sync": {"syncOnly": [
            {"group": "networking.k8s.io", "version": "v1",
             "kind": "Ingress"}]}},
    }
    assert missing_requirements([t, config]) == {}


def test_bench_tpu_engine_handles_cel_templates():
    objs = []
    for f in ("template.yaml", "samples/constraint.yaml",
              "samples/example_disallowed.yaml"):
        objs.extend(load_yaml_file(
            os.path.join(LIBRARY, "general", "containerlimitscel", f)))
    r = run_bench(objs, "tpu", iterations=2)
    assert r.violations == 1
