"""The CompileCache vocab snapshot-replay rule under adversarial churn.

Every cached lowering entry records the FULL interned-vocab string
table at lowering completion; a hit replays that snapshot into the
current vocab.  The rule (``drivers/generation.py:CompileCache.get``):
the current table must be a PREFIX of the snapshot — then the tail
interns in recorded order, reproducing every sid the cached program
baked.  Anything else (a vocab grown past the snapshot, a different
intern order, a colliding sid) is a counted ``vocab`` miss that leaves
the entry on disk — it is perfectly fine for the NEXT process that
boots in recorded order.

These are the pure-vocab unit pins; the end-to-end spill-side two-way
rule (snapshot ⊆ current also hits, for fleet mode) is pinned in
tests/test_replay.py, and the whole-library restart differential in
tests/test_snapshot_persist.py.
"""

from __future__ import annotations

import os

import pytest

from gatekeeper_tpu.drivers.generation import (MISS_COLD, MISS_VOCAB,
                                               CompileCache)
from gatekeeper_tpu.ops.flatten import Vocab

TDIG = "t" * 64
ENGINE = "rego"


def _vocab(*strings):
    v = Vocab()
    for s in strings:
        v.intern(s)
    return v


@pytest.fixture()
def seeded(tmp_path):
    """One stored entry whose vocab snapshot is ["", a, b, c] (an
    error-payload entry: the vocab rule is payload-agnostic, and an
    error entry needs no real lowered program)."""
    cc = CompileCache(str(tmp_path))
    writer = _vocab("a", "b", "c")
    cc.put(TDIG, ENGINE, None, "lower fallback: pinned", writer)
    assert cc.stores == 1
    return {"cc": cc, "root": str(tmp_path),
            "snap": list(writer._to_str), "writer": writer}


def _entry_paths(seeded):
    key = seeded["cc"].entry_key(TDIG, ENGINE)
    return [os.path.join(seeded["root"], key + ".json"),
            os.path.join(seeded["root"], key + ".pkl")]


def test_prefix_vocab_hits_and_replays_tail(seeded):
    reader = _vocab("a")  # strict prefix: ["", "a"]
    cc = CompileCache(seeded["root"])
    assert cc.get(TDIG, ENGINE, reader) == \
        ("error", "lower fallback: pinned")
    assert cc.stats()["hits"] == 1
    # the tail replayed in recorded order: every sid matches the writer
    assert reader._to_str == seeded["snap"]
    for s in ("a", "b", "c"):
        assert reader.intern(s) == seeded["writer"].intern(s)


def test_identical_vocab_hits_with_nothing_to_replay(seeded):
    reader = _vocab("a", "b", "c")
    assert seeded["cc"].get(TDIG, ENGINE, reader) is not None
    assert reader._to_str == seeded["snap"]


def test_empty_vocab_hits_cold_boot_shape(seeded):
    reader = Vocab()  # a cold process: [""], always a prefix
    assert seeded["cc"].get(TDIG, ENGINE, reader) is not None
    assert reader._to_str == seeded["snap"]


def test_vocab_grown_past_snapshot_misses(seeded):
    reader = _vocab("a", "b", "c", "d")  # longer than the snapshot
    cc = CompileCache(seeded["root"])
    assert cc.get(TDIG, ENGINE, reader) is None
    assert cc.stats()["miss_reasons"] == {MISS_VOCAB: 1}
    # the reader's table is untouched: no partial replay on a miss
    assert reader._to_str == ["", "a", "b", "c", "d"]


def test_reordered_intern_misses(seeded):
    reader = _vocab("b", "a")  # same strings, different sids
    cc = CompileCache(seeded["root"])
    assert cc.get(TDIG, ENGINE, reader) is None
    assert cc.stats()["miss_reasons"] == {MISS_VOCAB: 1}


def test_colliding_sid_misses(seeded):
    reader = _vocab("a", "x")  # sid 2 points at "x" here, "b" there
    cc = CompileCache(seeded["root"])
    assert cc.get(TDIG, ENGINE, reader) is None
    assert cc.stats()["miss_reasons"] == {MISS_VOCAB: 1}


def test_vocab_miss_keeps_entry_for_the_next_boot(seeded):
    """A vocab miss is about THIS process's intern history, not the
    entry: the files stay, and a prefix-ordered reader still hits."""
    bad = _vocab("z")
    cc = CompileCache(seeded["root"])
    assert cc.get(TDIG, ENGINE, bad) is None
    assert all(os.path.exists(p) for p in _entry_paths(seeded))
    good = _vocab("a", "b")
    cc2 = CompileCache(seeded["root"])
    assert cc2.get(TDIG, ENGINE, good) is not None
    assert good._to_str == seeded["snap"]


def test_churn_storm_interleaving(seeded):
    """Adversarial churn: hit, grow, then re-ask — the same process
    that replayed a snapshot and kept interning must MISS the same
    entry afterwards (its table is now longer than the snapshot), and
    the sids it already baked stay stable throughout."""
    reader = _vocab("a")
    cc = CompileCache(seeded["root"])
    assert cc.get(TDIG, ENGINE, reader) is not None
    sid_c = reader.intern("c")
    reader.intern("churned-later")
    assert cc.get(TDIG, ENGINE, reader) is None
    assert cc.stats()["miss_reasons"] == {MISS_VOCAB: 1}
    assert reader.intern("c") == sid_c  # append-only: sids never move


def test_cold_miss_reason(tmp_path):
    cc = CompileCache(str(tmp_path))
    assert cc.get(TDIG, ENGINE, Vocab()) is None
    assert cc.stats()["miss_reasons"] == {MISS_COLD: 1}
