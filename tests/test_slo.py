"""SLO engine: objective parsing, SLI computation from the histogram
buckets, multi-window burn rates on an injected clock, breach edge
(span + counter), staleness aging, and the brownout-ladder pressure
input."""

import pytest

from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.observability import slo, tracing
from gatekeeper_tpu.resilience import overload as ovl

LAT = {
    "name": "lat-p90", "type": "latency", "metric": "lat_seconds",
    "threshold": 0.1, "target": 0.9,
}
TIER = [{"name": "page", "short_s": 60.0, "long_s": 300.0, "burn": 2.0}]


def _engine(m, objectives=(LAT,), clock=None, wall=None, **kw):
    fake = {"t": 0.0, "w": 1_000_000.0}
    eng = slo.SLOEngine(
        m, objectives=list(objectives), tiers=TIER,
        clock=clock or (lambda: fake["t"]),
        wall=wall or (lambda: fake["w"]), **kw)
    return eng, fake


def test_objective_validation():
    with pytest.raises(ValueError):
        slo.SLOObjective({"name": "x", "type": "nope"})
    with pytest.raises(ValueError):
        slo.SLOEngine(MetricsRegistry(), objectives=[LAT, LAT])


def test_latency_sli_from_buckets_and_gauges():
    m = MetricsRegistry()
    eng, fake = _engine(m)
    for _ in range(9):
        m.observe("lat_seconds", 0.01)
    m.observe("lat_seconds", 5.0)
    out = eng.tick()
    ev = out["objectives"][0]
    assert ev["sli"] == pytest.approx(0.9)
    assert ev["compliant"] is True  # exactly at target
    assert m.get_gauge(M.SLO_SLI, {"objective": "lat-p90"}) == \
        pytest.approx(0.9)
    assert m.get_gauge(M.SLO_COMPLIANT, {"objective": "lat-p90"}) == 1.0


def test_burn_rate_windows_and_breach_edge():
    m = MetricsRegistry()
    eng, fake = _engine(m)
    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        eng.tick()  # t=0 baseline (no data)
        # a healthy minute
        for _ in range(20):
            m.observe("lat_seconds", 0.01)
        fake["t"] = 60.0
        out = eng.tick()
        assert out["objectives"][0]["burn"]["60s"] == 0.0
        assert not out["objectives"][0]["breach"]
        # then a fully-bad minute: bad fraction 1.0 over the short
        # window = burn 10x the 0.1 budget; the long window sees the
        # mixed history but still far over the 2.0 tier threshold
        for _ in range(40):
            m.observe("lat_seconds", 3.0)
        fake["t"] = 120.0
        out = eng.tick()
        ev = out["objectives"][0]
        assert ev["burn"]["60s"] == pytest.approx(10.0)
        assert ev["burn"]["300s"] == pytest.approx(
            (40 / 60) / 0.1, rel=1e-3)
        assert ev["breach"] and ev["breach_tier"] == "page"
        assert m.get_counter(M.SLO_BREACHES,
                             {"objective": "lat-p90"}) == 1
        # the breach landed in the trace timeline as its own root span
        names = [s["name"] for tr in tracer.traces()
                 for s in tr["spans"]]
        assert "slo.breach" in names
        # still breached next tick: the counter counts TRANSITIONS
        fake["t"] = 121.0
        for _ in range(5):
            m.observe("lat_seconds", 3.0)
        eng.tick()
        assert m.get_counter(M.SLO_BREACHES,
                             {"objective": "lat-p90"}) == 1
        # recovery: a fast-only minute ends the short-window burn
        for _ in range(200):
            m.observe("lat_seconds", 0.01)
        fake["t"] = 200.0
        out = eng.tick()
        assert not out["objectives"][0]["breach"]


def test_ratio_objective_shed_rate():
    m = MetricsRegistry()
    obj = {"name": "shed-rate", "type": "ratio",
           "bad_metric": "validation_request_count",
           "bad_labels": {"admission_status": "shed"},
           "total_metric": "validation_request_count",
           "target": 0.99}
    eng, fake = _engine(m, objectives=[obj])
    eng.tick()
    for _ in range(98):
        m.inc_counter("validation_request_count",
                      {"admission_status": "allow"})
    m.inc_counter("validation_request_count",
                  {"admission_status": "shed"}, value=2)
    fake["t"] = 60.0
    out = eng.tick()
    ev = out["objectives"][0]
    assert ev["sli"] == pytest.approx(0.98)
    assert ev["compliant"] is False
    assert ev["burn"]["60s"] == pytest.approx(2.0)


def test_staleness_objective_ages_a_timestamp_gauge():
    m = MetricsRegistry()
    obj = {"name": "stale", "type": "staleness",
           "gauge": "audit_last_run_end_time", "threshold": 300.0}
    eng, fake = _engine(m, objectives=[obj])
    out = eng.tick()  # gauge unset: nothing has run, nothing is stale
    assert out["objectives"][0]["sli"] == 0.0
    assert out["objectives"][0]["compliant"] is True
    m.set_gauge("audit_last_run_end_time", fake["w"] - 100.0)
    out = eng.tick()
    assert out["objectives"][0]["sli"] == pytest.approx(100.0)
    assert out["objectives"][0]["compliant"] is True
    m.set_gauge("audit_last_run_end_time", fake["w"] - 700.0)
    out = eng.tick()
    ev = out["objectives"][0]
    assert ev["sli"] == pytest.approx(700.0)
    assert not ev["compliant"]
    assert ev["breach"]  # stale past the ceiling pages immediately
    assert m.get_counter(M.SLO_BREACHES, {"objective": "stale"}) == 1


def test_pressure_feeds_the_brownout_ladder():
    """The PR 5 integration: SLO burn as a brownout input — a burning
    latency objective browns out optional work even while the admission
    queue itself is empty, and recovery releases the ladder."""
    m = MetricsRegistry()
    ctl = ovl.OverloadController(ovl.OverloadConfig())
    eng, fake = _engine(m, brownout=ctl)
    ctl.set_slo_input(eng.pressure)
    eng.tick()
    assert ctl.brownout_level() == 0
    for _ in range(50):
        m.observe("lat_seconds", 3.0)  # everything slow
    fake["t"] = 60.0
    eng.tick()  # burn 10 / tier 2.0 -> pressure 1.0 -> level 2
    assert eng.pressure() == 1.0
    assert ctl.brownout_level() == 2
    # recovery: fast-only window drops pressure to 0 -> ladder releases
    for _ in range(500):
        m.observe("lat_seconds", 0.01)
    fake["t"] = 130.0
    eng.tick()
    assert eng.pressure() == 0.0
    assert ctl.brownout_level() == 0


def test_default_objectives_parse_and_tick():
    m = MetricsRegistry()
    eng = slo.SLOEngine(m)
    out = eng.tick()
    names = {ev["name"] for ev in out["objectives"]}
    assert names == {"admission-latency-p99", "mutation-latency-p99",
                     "admission-shed-rate", "audit-snapshot-staleness"}
    assert all(ev["compliant"] for ev in out["objectives"])
    assert eng.snapshot()["objectives"]


def test_load_config(tmp_path):
    import json

    p = tmp_path / "slo.json"
    p.write_text(json.dumps({
        "objectives": [{"name": "o1", "type": "latency",
                        "metric": "x_seconds", "threshold": 1.0}],
        "tiers": [{"name": "t", "short_s": 10, "long_s": 20,
                   "burn": 3.0}],
    }))
    cfg = slo.load_config(str(p))
    assert [o.name for o in cfg["objectives"]] == ["o1"]
    assert cfg["tiers"][0]["burn"] == 3.0
    p2 = tmp_path / "slo_list.json"
    p2.write_text(json.dumps([{"name": "o2", "type": "latency",
                               "metric": "y_seconds"}]))
    cfg2 = slo.load_config(str(p2))
    assert [o.name for o in cfg2["objectives"]] == ["o2"]
    assert cfg2["tiers"] is None


def test_load_config_registers_custom_actions(tmp_path):
    """A top-level "actions" list registers custom degradation actions
    BEFORE objective maps validate, so an objective may name one."""
    import json

    p = tmp_path / "slo_actions.json"
    p.write_text(json.dumps({
        "actions": [
            {"name": "drain_extdata_pool",
             "description": "park the external-data worker pool"},
            {"name": "quiesce_gator"},
        ],
        "objectives": [{
            "name": "o-act", "type": "latency", "metric": "x_seconds",
            "threshold": 1.0,
            "degradation": ["drain_extdata_pool",
                            ovl.DEVICE_RESIDENCY_EVICT],
        }],
    }))
    reg = ovl.DegradationRegistry()
    cfg = slo.load_config(str(p), degradations=reg)
    assert cfg["actions"] == ["drain_extdata_pool", "quiesce_gator"]
    assert {"drain_extdata_pool", "quiesce_gator",
            ovl.DEVICE_RESIDENCY_EVICT} <= reg.known()
    # registered actions behave like builtins: activate/poll/release
    assert reg.activate("drain_extdata_pool", "o-act")
    assert "drain_extdata_pool" in reg.active_names()
    reg.release("drain_extdata_pool", "o-act")
    # without a registry the list still parses (names returned, inert)
    assert slo.load_config(str(p))["actions"] == [
        "drain_extdata_pool", "quiesce_gator"]


def test_load_config_rejects_malformed_actions(tmp_path):
    """Malformed action entries fail CLOSED with the actions[i] path —
    the boot-time contract of --slo-config."""
    import json

    cases = [
        ({"actions": "nope"}, "'actions' must be a list"),
        ({"actions": ["bare-string"]}, "actions[0]"),
        ({"actions": [{"description": "no name"}]}, "actions[0]"),
        ({"actions": [{"name": ""}]}, "actions[0]"),
        ({"actions": [{"name": "ok"}, {"name": "x", "desc": "typo"}]},
         "actions[1]"),
        ({"actions": [{"name": "x", "description": 7}]}, "actions[0]"),
    ]
    for i, (doc, needle) in enumerate(cases):
        p = tmp_path / f"bad_{i}.json"
        p.write_text(json.dumps({"objectives": [], **doc}))
        with pytest.raises(slo.SLOConfigError) as ei:
            slo.load_config(str(p), degradations=ovl.DegradationRegistry())
        assert needle in str(ei.value), (doc, str(ei.value))
    # an objective naming an UNREGISTERED action still fails validation
    p = tmp_path / "bad_map.json"
    p.write_text(json.dumps({
        "objectives": [{"name": "o", "type": "latency",
                        "metric": "x_seconds",
                        "degradation": ["never_registered"]}]}))
    with pytest.raises(slo.SLOConfigError) as ei:
        slo.load_config(str(p), degradations=ovl.DegradationRegistry())
    assert "never_registered" in str(ei.value)
