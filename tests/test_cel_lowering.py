"""Differential tests for CEL → device lowering (ir/lower_cel.py): the
fused verdict grid must agree with the CEL evaluator on every
(object, constraint) pair — including CEL's error outcomes (failurePolicy
Fail: an erroring validation VIOLATES, and the lowered ``Not(t(E))`` form
must reproduce that)."""

import os
import random

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.unstructured import load_yaml_file

LIB = os.path.join(os.path.dirname(__file__), "..", "library", "general")
TARGET = "admission.k8s.gatekeeper.sh"


def _driver_with(*names):
    tpu = TpuDriver(batch_bucket=16, cel_driver=CELDriver())
    cons = []
    for name, params in names:
        tdoc = load_yaml_file(
            os.path.join(LIB, name, "template.yaml"))[0]
        t = ConstraintTemplate.from_unstructured(tdoc)
        tpu.add_template(t)
        cdoc = load_yaml_file(
            os.path.join(LIB, name, "samples", "constraint.yaml"))[0]
        if params is not None:
            cdoc.setdefault("spec", {})["parameters"] = params
            cdoc["metadata"]["name"] += "-alt"
        con = Constraint.from_unstructured(cdoc)
        tpu.add_constraint(con)
        cons.append(con)
    return tpu, cons


def _adversarial_pods(n, seed=7):
    """Objects probing CEL error semantics: mixed-type fields, missing
    guards' targets, unparseable quantities, non-bool privileged."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        containers = []
        for j in range(rng.randint(0, 3)):
            c = {"name": f"c{j}"}
            if rng.random() < 0.85:
                c["image"] = rng.choice([
                    "openpolicyagent/opa", "exempt/me:v1", "nginx",
                    "exempt/other", 7, True,
                ])
            if rng.random() < 0.7:
                r = rng.random()
                if r < 0.5:
                    c["resources"] = {"limits": {
                        "memory": rng.choice([
                            "512Mi", "2Gi", "1e3", "banana", 512, None,
                            "100m",
                        ]),
                    }}
                elif r < 0.7:
                    c["resources"] = {"limits": {}}
                elif r < 0.85:
                    c["resources"] = {}
                else:
                    c["resources"] = rng.choice(["notadict", 5])
            if rng.random() < 0.5:
                c["securityContext"] = {
                    "privileged": rng.choice(
                        [True, False, "yes", 1, None]),
                }
            elif rng.random() < 0.2:
                c["securityContext"] = rng.choice([{}, "bad"])
            containers.append(c)
        spec = {}
        if rng.random() < 0.9:
            spec["containers"] = containers
        if rng.random() < 0.25:
            spec["initContainers"] = [
                {"name": "init",
                 "securityContext": {"privileged": rng.random() < 0.5},
                 "image": "init/image"},
            ]
        obj = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": f"p{i}"}}
        if rng.random() < 0.95:
            obj["spec"] = spec
        out.append(obj)
    return out


def _assert_agreement(tpu, cons, objects):
    target = K8sValidationTarget()
    reviews = [target.handle_review(AugmentedUnstructured(object=o))
               for o in objects]
    got = tpu.query_batch(TARGET, cons, reviews)
    cel = tpu._cel
    for oi, review in enumerate(reviews):
        expected = []
        for con in cons:
            if not target.to_matcher(con.match).match(review):
                continue
            expected.extend(cel.query(TARGET, [con], review).results)
        key = lambda r: (r.constraint["metadata"]["name"], r.msg)
        assert sorted(map(key, got[oi].results)) == \
            sorted(map(key, expected)), (
                f"divergence on object {oi}: {objects[oi]}\n"
                f"got={sorted(map(key, got[oi].results))}\n"
                f"want={sorted(map(key, expected))}")


def test_cel_library_templates_lower():
    tpu, _ = _driver_with(("noprivileged", None),
                          ("containerlimitscel", None))
    assert set(tpu.lowered_kinds()) == {
        "K8sNoPrivileged", "K8sContainerLimitsCEL"}
    assert not tpu.fallback_kinds()


def test_cel_differential_library_sample_params():
    tpu, cons = _driver_with(("noprivileged", None),
                             ("containerlimitscel", None))
    _assert_agreement(tpu, cons, _adversarial_pods(250))


def test_cel_differential_alt_params():
    # exemptImages exercised; memory param absent (the !has(params.memory)
    # arm) and present-but-unparseable
    tpu, cons = _driver_with(
        ("noprivileged", {"exemptImages": ["exempt/"]}),
        ("containerlimitscel", {}),
    )
    _assert_agreement(tpu, cons, _adversarial_pods(250, seed=11))
    tpu2, cons2 = _driver_with(
        ("noprivileged", {"exemptImages": []}),
        ("containerlimitscel", {"memory": "banana"}),
    )
    _assert_agreement(tpu2, cons2, _adversarial_pods(150, seed=13))


def test_cel_library_suites_still_pass_with_unified_driver():
    """gator verify suites for the CEL library entries, through a client
    whose TpuDriver owns the CEL templates."""
    from gatekeeper_tpu.gator import verify as verify_mod

    for name in ("noprivileged", "containerlimitscel"):
        sr = verify_mod.run_suite(os.path.join(LIB, name, "suite.yaml"))
        assert not sr.failed(), [
            (t.name, c.name, c.error) for t in sr.tests for c in t.cases
            if c.error
        ]


def test_cel_delete_reviews_route_to_evaluator():
    """DELETE admission reviews diverge for CEL kinds (object unset for the
    evaluator while the grid sees the copied oldObject): query_batch must
    agree with the evaluator's DELETE semantics."""
    from gatekeeper_tpu.target.review import AdmissionRequest, AugmentedReview

    tpu, cons = _driver_with(("containerlimitscel", None))
    target = K8sValidationTarget()
    bad = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "del-me"},
           "spec": {"containers": [{"name": "c"}]}}
    req = AdmissionRequest(
        uid="u", kind={"group": "", "version": "v1", "kind": "Pod"},
        resource={}, sub_resource="", name="del-me", namespace="",
        operation="DELETE", user_info={}, object=None, old_object=bad,
        dry_run=False, options=None,
    )
    review = target.handle_review(AugmentedReview(admission_request=req))
    got = tpu.query_batch(TARGET, cons, [review])
    want = tpu._cel.query(TARGET, cons, review)
    assert sorted(r.msg for r in got[0].results) == \
        sorted(r.msg for r in want.results)
    assert got[0].results  # the old object violates (no memory limit)


def _mini_cel(source_yaml_validations, kind="K8sCelMini", params_schema=None):
    import yaml as _yaml

    tpu = TpuDriver(batch_bucket=16, cel_driver=CELDriver())
    doc = {
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind},
                             "validation": {"openAPIV3Schema":
                                            params_schema or
                                            {"type": "object"}}}},
            "targets": [{
                "target": TARGET,
                "code": [{"engine": "K8sNativeValidation",
                          "source": _yaml.safe_load(
                              source_yaml_validations)}],
            }],
        },
    }
    t = ConstraintTemplate.from_unstructured(doc)
    tpu.add_template(t)
    con = Constraint.from_unstructured({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind, "metadata": {"name": "mini"},
        "spec": {},
    })
    tpu.add_constraint(con)
    return tpu, con


def test_cel_heterogeneous_inequality_is_defined_false():
    """CEL `!=` on mixed types is a DEFINED true (heterogeneous equality),
    not an error — a non-string field must not produce a phantom hit."""
    tpu, con = _mini_cel("""
validations:
  - expression: 'object.spec.tier != "forbidden"'
    message: tier forbidden
""", kind="K8sCelNeq")
    assert "K8sCelNeq" in tpu.lowered_kinds(), tpu.fallback_kinds()
    objs = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "n"},
         "spec": {"tier": 3}},                     # mixed type: != is true
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "f"},
         "spec": {"tier": "forbidden"}},           # violates
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "ok"},
         "spec": {"tier": "gold"}},                # fine
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "ab"},
         "spec": {}},                              # absent: error: violates
    ]
    _assert_agreement(tpu, [con], objs)


def test_cel_bool_and_num_equality_heterogeneous():
    tpu, con = _mini_cel("""
validations:
  - expression: 'object.spec.flag == true || object.spec.count == 3.0'
    message: bad
""", kind="K8sCelHet")
    assert "K8sCelHet" in tpu.lowered_kinds(), tpu.fallback_kinds()
    objs = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"flag": "yes", "count": "3"}},   # both mixed: false||false
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"flag": True, "count": 0}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {"flag": False, "count": 3}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "d"},
         "spec": {"flag": None}},                  # count absent: || error
    ]
    _assert_agreement(tpu, [con], objs)


def test_cel_var_free_macro_body_lowers_via_map_branch():
    """A macro whose body never dereferences the loop variable evaluates
    fine over map KEYS — the kind-branched map lowering (item-independent
    body under the key binding) now represents that exactly, so the
    template stays on the device (it fell back before round 3)."""
    tpu, con = _mini_cel("""
validations:
  - expression: >-
      !has(object.metadata.annotations) ? true :
      object.metadata.annotations.all(a, has(object.spec.ok))
    message: bad
""", kind="K8sCelKeys")
    assert "K8sCelKeys" in tpu.lowered_kinds(), tpu.fallback_kinds()
    objs = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "m", "annotations": {"k1": "v", "k2": "v"}},
         "spec": {"ok": True}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "n", "annotations": {"k": "v"}}, "spec": {}},
    ]
    _assert_agreement(tpu, [con], objs)


def test_cel_absorbed_deref_falls_back():
    """`has(c.x) || true` is TRUE over map keys (absorbed error): bodies
    whose outcome can be decided without dereferencing the variable must
    not lower."""
    tpu, _con = _mini_cel("""
variables:
  - name: containers
    expression: >-
      !has(object.spec.containers) ? [] : object.spec.containers
validations:
  - expression: 'variables.containers.all(c, has(c.image) || true)'
    message: bad
""", kind="K8sCelAbsorb")
    assert "K8sCelAbsorb" in tpu.fallback_kinds()


def test_cel_object_macro_nested_in_param_macro():
    """ADVICE r2 (high): a StrPred needle under AnyAxis inside a
    param-list macro (object-list macro nested in a param-list macro)
    must either lower with its needle bound — evaluating the [N, M, K]
    grid — or fall back at add_template time.  It must NEVER lower
    'successfully' into a program that raises on every query."""
    import yaml as _yaml

    kind = "K8sCelNestedElem"
    tpu = TpuDriver(batch_bucket=16, cel_driver=CELDriver())
    doc = {
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind},
                             "validation": {"openAPIV3Schema": {
                                 "type": "object",
                                 "properties": {"prefixes": {
                                     "type": "array",
                                     "items": {"type": "string"}}}}}}},
            "targets": [{
                "target": TARGET,
                "code": [{"engine": "K8sNativeValidation",
                          "source": _yaml.safe_load("""
validations:
  - expression: >-
      params.prefixes.exists(p,
      object.spec.containers.all(c, c.image.startsWith(p)))
    message: no common registry prefix
""")}],
            }],
        },
    }
    tpu.add_template(ConstraintTemplate.from_unstructured(doc))
    con = Constraint.from_unstructured({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind, "metadata": {"name": "nested"},
        "spec": {"parameters": {"prefixes": ["good/", "ok-"]}},
    })
    tpu.add_constraint(con)
    objs = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"name": "c", "image": "good/x"},
                                 {"name": "d", "image": "good/y"}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"name": "c", "image": "good/x"},
                                 {"name": "d", "image": "bad/y"}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {"containers": [{"name": "c", "image": "ok-1"}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "d"},
         "spec": {"containers": []}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "e"},
         "spec": {}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "f"},
         "spec": {"containers": [{"name": "c", "image": 7}]}},
    ]
    # whichever way it resolved (device or fallback), verdicts must match
    # the CEL oracle — and queries must not raise
    _assert_agreement(tpu, [con], objs)
    # with the AnyAxis recursion the template should stay on the device
    assert kind in tpu.lowered_kinds(), tpu.fallback_kinds()


def test_cel_map_key_predicate_body_lowers():
    """Map-key predicate bodies (`annotations.exists(k, k.startsWith(p))`)
    lower to string ops over the MapKeyColumn, kind-branched so LIST
    values keep list semantics (VERDICT r2 missing #2)."""
    tpu, con = _mini_cel("""
validations:
  - expression: '!object.metadata.annotations.exists(k, k.startsWith("seccomp."))'
    message: no seccomp annotations allowed
""", kind="K8sCelMapKey")
    assert "K8sCelMapKey" in tpu.lowered_kinds(), tpu.fallback_kinds()
    meta = lambda name, ann: {"name": name, **({"annotations": ann}
                                               if ann is not None else {})}
    objs = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("hit", {"seccomp.alpha": "x", "other": "y"})},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("miss", {"app": "x"})},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("empty", {})},     # vacuous exists -> false -> ok
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("absent", None)},  # error -> violation
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("alist", ["seccomp.alpha"])},  # LIST: items
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("scalar", "notamap")},  # error -> violation
    ]
    _assert_agreement(tpu, [con], objs)


def test_cel_exists_one_lowers():
    """exists_one: exactly-one semantics with no short-circuit — any
    erroring item errors the whole macro (VERDICT r2 missing #2)."""
    tpu, con = _mini_cel("""
validations:
  - expression: 'object.spec.containers.exists_one(c, c.name == "main")'
    message: need exactly one main container
""", kind="K8sCelExistsOne")
    assert "K8sCelExistsOne" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pod = lambda name, cs: {"apiVersion": "v1", "kind": "Pod",
                            "metadata": {"name": name},
                            "spec": {"containers": cs}}
    objs = [
        pod("zero", [{"name": "a"}, {"name": "b"}]),      # 0 -> violation
        pod("one", [{"name": "main"}, {"name": "b"}]),    # 1 -> ok
        pod("two", [{"name": "main"}, {"name": "main"}]), # 2 -> violation
        pod("err", [{"name": "main"}, {}]),  # missing name: heterogeneous
        pod("empty", []),                                 # 0 -> violation
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "nolist"}, "spec": {}},     # error
    ]
    _assert_agreement(tpu, [con], objs)


def test_cel_two_variable_map_macro():
    """Two-variable macros: over a map (key, value) the key binds to the
    MapKeyColumn; over a LIST, CEL binds (index, value) and the
    string-method body errors per item, so the list branch reduces to
    vacuous/error (VERDICT r2 missing #2)."""
    tpu, con = _mini_cel("""
validations:
  - expression: 'object.metadata.labels.all(k, v, !k.startsWith("forbidden."))'
    message: forbidden label prefix
""", kind="K8sCelTwoVar")
    assert "K8sCelTwoVar" in tpu.lowered_kinds(), tpu.fallback_kinds()
    meta = lambda name, labels: {"name": name, **({"labels": labels}
                                                  if labels is not None
                                                  else {})}
    objs = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("hit", {"forbidden.x": "1", "app": "a"})},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("ok", {"app": "a"})},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("empty", {})},    # vacuous all -> true -> ok
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("absent", None)},  # error -> violation
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": meta("alist", ["x"])},  # int keys: error -> violation
    ]
    _assert_agreement(tpu, [con], objs)


def test_cel_two_variable_value_body_falls_back():
    """A two-variable body that can decide from the VALUE alone has real
    list semantics (index keys don't error it) — must fall back, and
    agree with the oracle through query_batch."""
    tpu, con = _mini_cel("""
validations:
  - expression: 'object.metadata.labels.all(k, v, v != "")'
    message: empty label value
""", kind="K8sCelTwoVarVal")
    assert "K8sCelTwoVarVal" in tpu.fallback_kinds(), tpu.lowered_kinds()
    objs = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "a", "labels": {"x": ""}}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "b", "labels": {"x": "1"}}},
    ]
    _assert_agreement(tpu, [con], objs)
