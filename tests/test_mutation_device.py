"""Differential tests for the mutation path-match device kernel
(mutation/device.py): grid[m, n] must equal "core.mutate changes object n"
for every lowerable (mutator, object) pair — including the walk's error
outcomes (BASELINE config #4; ref semantics
pkg/mutation/mutators/core/mutation_function.go:26-239)."""

import copy
import random

import numpy as np

from gatekeeper_tpu.mutation.core import MutateError
from gatekeeper_tpu.mutation.device import MutationPrefilter
from gatekeeper_tpu.mutation.mutators import from_unstructured


def _mutator(kind, name, location, value, extra_params=None):
    params = {"assign": {"value": value}}
    params.update(extra_params or {})
    spec = {"location": location, "parameters": params}
    if kind == "Assign":
        spec["applyTo"] = [{"groups": [""], "versions": ["v1"],
                            "kinds": ["Pod"]}]
    return from_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": kind, "metadata": {"name": name},
        "spec": spec,
    })


MUTATORS = [
    _mutator("Assign", "pull-policy",
             "spec.containers[name: *].imagePullPolicy", "Always"),
    _mutator("Assign", "keyed-image",
             "spec.containers[name: app].image", "nginx:1.19"),
    _mutator("Assign", "scalar-host", "spec.hostNetwork", False),
    _mutator("Assign", "nested-scalar",
             "spec.securityContext.runAsNonRoot", True),
    _mutator("Assign", "priority-num", "spec.priority", 100),
    _mutator("Assign", "deep-glob",
             "spec.containers[name: *].securityContext.readOnlyRootFilesystem",
             True),
    _mutator("AssignMetadata", "owner-label",
             "metadata.labels.owner", "platform-team"),
    _mutator("AssignMetadata", "note-ann",
             "metadata.annotations.note", "n1"),
]


def rand_obj(rng, i):
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": f"p{i}"}}
    r = rng.random()
    if r < 0.3:
        obj["metadata"]["labels"] = rng.choice(
            [{"owner": "platform-team"}, {"owner": "other"},
             {"app": "x"}, "notadict", {}])
    if r < 0.2:
        obj["metadata"]["annotations"] = rng.choice(
            [{"note": "n1"}, {"note": "other"}, {}])
    spec = {}
    if rng.random() < 0.9:
        containers = []
        for j in range(rng.randint(0, 3)):
            c = {}
            if rng.random() < 0.9:
                c["name"] = rng.choice(["app", "side", "app"])
            if rng.random() < 0.7:
                c["imagePullPolicy"] = rng.choice(
                    ["Always", "IfNotPresent", True, 5])
            if rng.random() < 0.5:
                c["image"] = rng.choice(["nginx:1.19", "nginx:1.20", 7])
            if rng.random() < 0.4:
                c["securityContext"] = rng.choice(
                    [{"readOnlyRootFilesystem": True},
                     {"readOnlyRootFilesystem": False},
                     {}, "bogus"])
            containers.append(c)
        if rng.random() < 0.08:
            spec["containers"] = rng.choice(["notalist", {"a": {}}, 5])
        else:
            spec["containers"] = containers
    if rng.random() < 0.4:
        spec["hostNetwork"] = rng.choice([True, False, "false", 0])
    if rng.random() < 0.3:
        spec["securityContext"] = rng.choice(
            [{"runAsNonRoot": True}, {"runAsNonRoot": False}, {},
             "bogus", 3])
    if rng.random() < 0.3:
        spec["priority"] = rng.choice([100, 100.0, 50, True, "100"])
    obj["spec"] = spec
    return obj


def host_would_change(mutator, obj) -> bool:
    clone = copy.deepcopy(obj)
    try:
        return bool(mutator.mutate_obj(clone))
    except MutateError:
        return False  # walk error: the system records it, object unchanged


def test_device_grid_matches_host_walk():
    pre = MutationPrefilter()
    for m in MUTATORS:
        assert pre.add_mutator(m), (m.id, pre.unsupported())
    rng = random.Random(42)
    objects = [rand_obj(rng, i) for i in range(400)]
    grid = pre.would_change(MUTATORS, objects)
    for mi, m in enumerate(MUTATORS):
        for oi, obj in enumerate(objects):
            want = host_would_change(m, obj)
            assert bool(grid[mi, oi]) == want, (
                f"divergence: mutator={m.id} object={obj}")


def test_unsupported_mutators_fall_back():
    pre = MutationPrefilter()
    # assignIf → host-only
    m = _mutator("Assign", "cond", "spec.x", "v",
                 {"assignIf": {"in": ["a"]}})
    assert not pre.add_mutator(m)
    assert any("cond" in str(k) for k in pre.unsupported())
    # ModifySet → host-only
    ms = from_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "ModifySet", "metadata": {"name": "args"},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": ["Pod"]}],
                 "location": "spec.containers[name: *].args",
                 "parameters": {"operation": "merge",
                                "values": {"fromList": ["-v"]}}},
    })
    assert not pre.add_mutator(ms)
    # grid rows for non-lowered mutators stay False
    grid = pre.would_change([m], [{"apiVersion": "v1", "kind": "Pod",
                                   "metadata": {"name": "p"},
                                   "spec": {}}])
    assert not grid.any()


def test_grid_prefilters_system_batch():
    """The intended integration: run the host fixed-point only on objects
    some mutator would actually change."""
    pre = MutationPrefilter()
    lowerable = [m for m in MUTATORS if pre.add_mutator(m)]
    rng = random.Random(7)
    objects = [rand_obj(rng, i) for i in range(100)]
    grid = pre.would_change(lowerable, objects)
    needs_walk = grid.any(axis=0)
    for oi, obj in enumerate(objects):
        host_any = any(host_would_change(m, obj) for m in lowerable)
        assert bool(needs_walk[oi]) == host_any


def test_system_mutate_batch_parity():
    """mutate_batch (device-prefiltered) must match per-object mutate,
    including raising MutateError for the same objects."""
    from gatekeeper_tpu.mutation.system import MutationSystem

    sys_a, sys_b = MutationSystem(), MutationSystem()
    for m in MUTATORS:
        sys_a.upsert(m)
        sys_b.upsert(m)
    rng = random.Random(99)
    objs = [rand_obj(rng, i) for i in range(120)]

    def outcome(system, obj):
        try:
            return system.mutate(obj), None
        except MutateError as e:
            return "error", str(e)

    n_err = 0
    for obj in objs:
        a, b = copy.deepcopy(obj), copy.deepcopy(obj)
        flag_b, err_b = outcome(sys_b, b)
        try:
            flag_a = sys_a.mutate_batch([a])[0]
            err_a = None
        except MutateError as e:
            flag_a, err_a = "error", str(e)
        assert flag_a == flag_b, (obj, err_a, err_b)
        if err_b:
            n_err += 1
        else:
            assert a == b  # identical post-mutation trees
    assert n_err > 0  # the corpus exercises the error-parity path
