"""Webhook plane: real HTTP AdmissionReview round-trips.

Reference behaviors exercised: deny/warn partition incl. scoped + dryrun
(policy.go:205-355), gatekeeper-resource validation fast path, gk service
account bypass, namespace exclusion, mutation JSON patch, namespace-label
guard, the microbatch lane.
"""

import base64
import json
import threading
import urllib.request

import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.mutation.system import MutationSystem
from gatekeeper_tpu.sync.process import ProcessExcluder
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.unstructured import load_yaml_file
from gatekeeper_tpu.webhook.mutation import MutationHandler, json_patch
from gatekeeper_tpu.webhook.namespacelabel import NamespaceLabelHandler
from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
from gatekeeper_tpu.webhook.server import WebhookServer

DEMO = "/root/reference/demo/basic"


def make_client():
    client = Client(target=K8sValidationTarget(), drivers=[TpuDriver()],
                    enforcement_points=["validation.gatekeeper.sh"])
    client.add_template(load_yaml_file(
        f"{DEMO}/templates/k8srequiredlabels_template.yaml")[0])
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "ns-must-have-gk"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Namespace"]}]},
                 "parameters": {"labels": ["gatekeeper"]}},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "warn-owner"},
        "spec": {"enforcementAction": "warn",
                 "match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Namespace"]}]},
                 "parameters": {"labels": ["owner"]}},
    })
    return client


def admission_review(obj, operation="CREATE", username="alice", uid="u1",
                     namespace=""):
    from gatekeeper_tpu.utils.unstructured import gvk_of

    group, version, kind = gvk_of(obj)
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": uid,
            "kind": {"group": group, "version": version, "kind": kind},
            "name": (obj.get("metadata") or {}).get("name", ""),
            "namespace": namespace,
            "operation": operation,
            "userInfo": {"username": username},
            "object": obj,
        },
    }


def post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


@pytest.fixture(scope="module")
def server():
    client = make_client()
    excluder = ProcessExcluder()
    excluder.add(["webhook"], ["kube-*"])
    handler = ValidationHandler(client, process_excluder=excluder)
    mut_system = MutationSystem()
    mut_system.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign", "metadata": {"name": "pull-policy"},
        "spec": {
            "applyTo": [{"groups": [""], "versions": ["v1"],
                         "kinds": ["Pod"]}],
            "location": "spec.containers[name: *].imagePullPolicy",
            "parameters": {"assign": {"value": "Always"}},
        },
    })
    srv = WebhookServer(
        validation_handler=handler,
        mutation_handler=MutationHandler(mut_system),
        namespace_label_handler=NamespaceLabelHandler(
            exempt_namespaces=["gatekeeper-system"],
            exempt_prefixes=["kube-"]),
        port=0,
        readiness_check=lambda: True,
    ).start()
    yield srv
    srv.stop()


def ns(name, labels=None):
    meta = {"name": name}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Namespace", "metadata": meta}


def test_deny_and_warn_partition(server):
    out = post(server.port, "/v1/admit", admission_review(ns("bad")))
    r = out["response"]
    assert r["allowed"] is False
    assert r["status"]["code"] == 403
    assert 'you must provide labels: {"gatekeeper"}' in r["status"]["message"]
    assert any("owner" in w for w in r.get("warnings", []))
    assert r["uid"] == "u1"


def test_allow_with_warning_only(server):
    out = post(server.port, "/v1/admit",
               admission_review(ns("ok", {"gatekeeper": "x"})))
    r = out["response"]
    assert r["allowed"] is True
    assert any("owner" in w for w in r.get("warnings", []))


def test_gk_service_account_bypass(server):
    out = post(server.port, "/v1/admit", admission_review(
        ns("bad"), username="system:serviceaccount:gatekeeper-system:gk"))
    assert out["response"]["allowed"] is True


def test_namespace_exclusion(server):
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "p", "namespace": "kube-system"}}
    out = post(server.port, "/v1/admit",
               admission_review(pod, namespace="kube-system"))
    assert out["response"]["allowed"] is True


def test_template_validation_fast_path(server):
    bad_template = load_yaml_file(f"{DEMO}/bad/bad_template.yaml")[0]
    out = post(server.port, "/v1/admit", admission_review(bad_template))
    r = out["response"]
    assert r["allowed"] is False
    assert "lowercase" in r["status"]["message"]
    good = load_yaml_file(f"{DEMO}/templates/k8srequiredlabels_template.yaml")
    out = post(server.port, "/v1/admit", admission_review(good[0]))
    assert out["response"]["allowed"] is True


def test_constraint_validation_fast_path(server):
    bad = {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
           "kind": "K8sRequiredLabels",
           "metadata": {"name": "x"},
           "spec": {"enforcementAction": "maybe"}}
    out = post(server.port, "/v1/admit", admission_review(bad))
    assert out["response"]["allowed"] is False


def test_mutation_patch(server):
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "p", "namespace": "default"},
           "spec": {"containers": [{"name": "c", "image": "nginx"}]}}
    out = post(server.port, "/v1/mutate", admission_review(pod))
    r = out["response"]
    assert r["allowed"] is True
    assert r["patchType"] == "JSONPatch"
    patch = json.loads(base64.b64decode(r["patch"]))
    assert {"op": "add",
            "path": "/spec/containers/0/imagePullPolicy",
            "value": "Always"} in patch or any(
        p["op"] == "replace" and "containers" in p["path"] for p in patch)


def test_mutate_delete_passthrough(server):
    pod = {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p"}}
    body = admission_review(pod, operation="DELETE")
    body["request"]["oldObject"] = pod
    out = post(server.port, "/v1/mutate", body)
    assert out["response"]["allowed"] is True
    assert "patch" not in out["response"]


def test_namespace_label_guard(server):
    # exemption is by the NAMESPACE's name (namespacelabel.go:63-66), not by
    # the requesting user
    labeled = ns("sneaky", {"admission.gatekeeper.sh/ignore": "true"})
    out = post(server.port, "/v1/admitlabel", admission_review(labeled))
    assert out["response"]["allowed"] is False
    out = post(server.port, "/v1/admitlabel", admission_review(
        labeled, username="system:serviceaccount:kube-system:admin"))
    assert out["response"]["allowed"] is False
    exempt = ns("gatekeeper-system",
                {"admission.gatekeeper.sh/ignore": "true"})
    out = post(server.port, "/v1/admitlabel", admission_review(exempt))
    assert out["response"]["allowed"] is True
    prefixed = ns("kube-public", {"admission.gatekeeper.sh/ignore": "true"})
    out = post(server.port, "/v1/admitlabel", admission_review(prefixed))
    assert out["response"]["allowed"] is True
    out = post(server.port, "/v1/admitlabel", admission_review(ns("plain")))
    assert out["response"]["allowed"] is True
    # non-namespace objects pass through
    pod = {"apiVersion": "v1", "kind": "Pod", "metadata": {
        "name": "p", "labels": {"admission.gatekeeper.sh/ignore": "x"}}}
    out = post(server.port, "/v1/admitlabel", admission_review(pod))
    assert out["response"]["allowed"] is True


def test_health_endpoint(server):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/healthz"
    ) as resp:
        assert json.loads(resp.read())["ready"] is True


def test_json_patch_generator():
    before = {"a": 1, "b": {"c": [1, 2]}, "d": "x"}
    after = {"a": 1, "b": {"c": [1, 2, 3]}, "e": True}
    ops = json_patch(before, after)
    assert {"op": "remove", "path": "/d"} in ops
    assert {"op": "add", "path": "/e", "value": True} in ops
    assert {"op": "replace", "path": "/b/c", "value": [1, 2, 3]} in ops


def test_batcher_coalesces_requests():
    client = make_client()
    # small_batch=1 pins the review_batch grid lane (the auto default
    # would route an 8-request batch through the interpreter lane)
    batcher = Batcher(client, window_s=0.02, max_batch=16,
                      small_batch=1).start()
    try:
        handler = ValidationHandler(client, batcher=batcher)
        results = {}

        def one(i):
            body = admission_review(ns(f"n{i}"), uid=f"u{i}")
            results[i] = handler.handle(body)

        threads = [threading.Thread(target=one, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 8
        assert all(not r.allowed for r in results.values())
        assert all("gatekeeper" in r.message for r in results.values())
    finally:
        batcher.stop()


def test_metrics_endpoint_and_request_counters():
    import urllib.request

    from gatekeeper_tpu.metrics.registry import MetricsRegistry

    client = make_client()
    metrics = MetricsRegistry()
    srv = WebhookServer(
        validation_handler=ValidationHandler(client, metrics=metrics),
        port=0, metrics=metrics,
    ).start()
    try:
        post(srv.port, "/v1/admit", admission_review(ns("nolabels")))
        post(srv.port, "/v1/admit",
             admission_review(ns("ok", {"gatekeeper": "x"})))
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics"
        ) as resp:
            body = resp.read().decode()
        assert ('gatekeeper_validation_request_count'
                '{admission_status="deny"} 1') in body
        assert ('gatekeeper_validation_request_count'
                '{admission_status="allow"} 1') in body
        assert "gatekeeper_validation_request_duration_seconds_count 2" \
            in body
    finally:
        srv.stop()


def test_admission_trace_and_stats(caplog):
    """Config spec.validation.traces[]: a matching (user, GVK) request is
    reviewed with tracing and its TraceDump logged (policy.go:632-675);
    --log-stats-admission logs per-request engine stats."""
    import logging

    client = make_client()
    traces = [{"user": "alice", "kind": {"group": "", "version": "v1",
                                         "kind": "Namespace"},
               "dump": "All"}]
    handler = ValidationHandler(
        client, trace_config=lambda: traces, log_stats=True)
    review = admission_review(ns("bad"), username="alice")
    with caplog.at_level(logging.INFO):
        out = handler.handle(review)
    assert out.allowed is False
    text = caplog.text
    assert "admission_trace" in text
    assert "admission_trace_dump" in text  # dump: All
    assert "admission_stats" in text
    # a non-matching user reviews without tracing
    caplog.clear()
    with caplog.at_level(logging.INFO):
        handler.handle(admission_review(ns("bad"), username="bob"))
    assert "admission_trace" not in caplog.text


def test_concurrent_keepalive_connections(server):
    """Serving-layer regression (round-2 load test findings): HTTP/1.1
    keep-alive must hold across concurrent persistent connections, and
    the listen backlog must absorb a 48-connection burst without resets."""
    import http.client

    results = []
    errors = []
    lock = threading.Lock()

    def worker(wid):
        try:
            c = http.client.HTTPConnection("127.0.0.1", server.port,
                                           timeout=30)
            sock = None
            for i in range(6):
                body = json.dumps(admission_review(
                    ns(f"w{wid}-{i}", {"gatekeeper": "x"}))).encode()
                c.request("POST", "/v1/admit", body=body,
                          headers={"Content-Type": "application/json"})
                r = json.loads(c.getresponse().read())
                # true keep-alive: the SAME socket across requests
                # (http.client silently reconnects on server close, which
                # would mask an HTTP/1.0 regression)
                if sock is None:
                    sock = c.sock
                    assert sock is not None
                else:
                    assert c.sock is sock, "connection was not kept alive"
                with lock:
                    results.append(r["response"]["allowed"])
            c.close()
        except Exception as e:
            with lock:
                errors.append(f"{wid}: {e}")

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(48)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    assert len(results) == 48 * 6
    assert all(results)  # labeled namespaces admit
