"""Differential tests: the device verdict grid must agree with the exact
interpreter on every (object, constraint) pair — the kernel-vs-reference
harness SURVEY.md §4 calls non-negotiable."""

import glob
import random

import pytest
import yaml

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.drivers.rego_driver import RegoDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget

PSP = "/root/reference/pkg/webhook/testdata/psp-all-violations"
TARGET = "admission.k8s.gatekeeper.sh"


def _load(p):
    with open(p) as f:
        return yaml.safe_load(f)


def _template(path):
    return ConstraintTemplate.from_unstructured(_load(path))


def _constraint(path):
    return Constraint.from_unstructured(_load(path))


def make_pod(rng: random.Random, i: int) -> dict:
    containers = []
    for j in range(rng.randint(0, 3)):
        c = {"name": f"c{j}", "image": rng.choice(["nginx", "bad/x", "repo/y"])}
        if rng.random() < 0.4:
            c["securityContext"] = {
                "privileged": rng.choice([True, False, "yes"])
            }
        if rng.random() < 0.5:
            c["ports"] = [
                {"hostPort": rng.choice([80, 443, 8080, 9999, 22])}
                for _ in range(rng.randint(0, 2))
            ]
        containers.append(c)
    spec = {"containers": containers}
    if rng.random() < 0.3:
        spec["initContainers"] = [
            {"name": "init", "securityContext": {"privileged": rng.random() < 0.5}}
        ]
    for key in ("hostNetwork", "hostPID", "hostIPC"):
        if rng.random() < 0.3:
            spec[key] = rng.choice([True, False])
    labels = {}
    for lab in ("app", "owner", "team", "gatekeeper"):
        if rng.random() < 0.4:
            labels[lab] = f"v{rng.randint(0, 3)}"
    meta = {"name": f"pod-{i}", "namespace": rng.choice(
        ["default", "kube-system", "prod", "dev"])}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": spec}


@pytest.fixture(scope="module")
def drivers_and_fixtures():
    tpu = TpuDriver(batch_bucket=16)
    templates = [
        _template(f"{PSP}/psp-templates/privileged-containers-template.yaml"),
        _template(f"{PSP}/psp-templates/host-namespace-template.yaml"),
        _template(f"{PSP}/psp-templates/host-network-ports-template.yaml"),
        _template(f"{PSP}/psp-templates/volume-template.yaml"),
        _template(f"{PSP}/psp-templates/host-filesystem-template.yaml"),
        _template(
            "/root/reference/demo/basic/templates/"
            "k8srequiredlabels_template.yaml"
        ),
    ]
    for t in templates:
        tpu.add_template(t)
    constraints = [
        _constraint(f"{PSP}/psp-constraints/privileged-containers-constraint.yaml"),
        _constraint(f"{PSP}/psp-constraints/host-namespaces-constraint.yaml"),
        _constraint(f"{PSP}/psp-constraints/host-network-constraint.yaml"),
        _constraint(f"{PSP}/psp-constraints/volumes-constraint.yaml"),
        _constraint(f"{PSP}/psp-constraints/host-filesystem-constraint.yaml"),
        Constraint.from_unstructured({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sRequiredLabels",
            "metadata": {"name": "pods-must-have-owner"},
            "spec": {
                "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
                          "namespaces": ["prod", "kube-*"]},
                "parameters": {"labels": ["owner", "team"]},
            },
        }),
    ]
    for c in constraints:
        tpu.add_constraint(c)
    return tpu, constraints


def test_expected_templates_lower(drivers_and_fixtures):
    tpu, _ = drivers_and_fixtures
    lowered = set(tpu.lowered_kinds())
    assert {"K8sPSPPrivilegedContainer", "K8sPSPHostNamespace",
            "K8sPSPHostNetworkingPorts", "K8sRequiredLabels"} <= lowered
    # these use set-comprehension-over-item-keys / array params of objects:
    # interpreter fallback is the correct behavior
    fallback = tpu.fallback_kinds()
    assert "K8sPSPVolumeTypes" in fallback
    assert "K8sPSPHostFilesystem" in fallback


def test_differential_verdicts(drivers_and_fixtures):
    tpu, constraints = drivers_and_fixtures
    rng = random.Random(42)
    pods = [make_pod(rng, i) for i in range(200)]
    # include the reference example pods
    for p in sorted(glob.glob(f"{PSP}/psp-pods/*.yaml")):
        pods.append(_load(p))

    target = K8sValidationTarget()
    reviews = [target.handle_review(AugmentedUnstructured(object=p))
               for p in pods]

    batch_responses = tpu.query_batch(TARGET, constraints, reviews)

    # oracle: interpreter + host matcher per (constraint, object)
    interp = tpu._interp
    for oi, review in enumerate(reviews):
        expected = []
        for con in constraints:
            if not target.to_matcher(con.match).match(review):
                continue
            qr = interp.query(TARGET, [con], review)
            expected.extend(qr.results)
        got = batch_responses[oi].results
        key = lambda r: (r.constraint["metadata"]["name"], r.msg)
        assert sorted(map(key, got)) == sorted(map(key, expected)), (
            f"divergence on pod {oi}: {pods[oi]}"
        )


def test_batch_faster_than_interp_smoke(drivers_and_fixtures):
    """Not a perf gate (CPU, tiny batch) — just ensures the batch path runs
    end-to-end and produces violations on the reference example pods."""
    tpu, constraints = drivers_and_fixtures
    target = K8sValidationTarget()
    pods = [_load(p) for p in sorted(glob.glob(f"{PSP}/psp-pods/*.yaml"))]
    reviews = [target.handle_review(AugmentedUnstructured(object=p))
               for p in pods]
    responses = tpu.query_batch(TARGET, constraints, reviews)
    assert sum(len(r.results) for r in responses) >= 5


def test_independent_wildcards_are_independent_existentials():
    """`containers[_].a; containers[_].b` is (∃i. a_i) ∧ (∃j. b_j), not
    ∃i. a_i ∧ b_i."""
    from gatekeeper_tpu.apis.templates import ConstraintTemplate

    tpu = TpuDriver(batch_bucket=8)
    tpu.add_template(ConstraintTemplate.from_unstructured({
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8stwowild"},
        "spec": {"crd": {"spec": {"names": {"kind": "K8sTwoWild"}}},
                 "targets": [{"target": TARGET, "rego": """
package k8stwowild

violation[{"msg": "both"}] {
  input.review.object.spec.containers[_].privileged
  input.review.object.spec.containers[_].hostBad
}
"""}]},
    }))
    assert "K8sTwoWild" in tpu.lowered_kinds()
    con = Constraint.from_unstructured({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sTwoWild", "metadata": {"name": "x"}, "spec": {}})
    tpu.add_constraint(con)
    target = K8sValidationTarget()
    pods = [
        # different containers satisfy the two conditions -> violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"privileged": True}, {"hostBad": True}]}},
        # only one condition -> no violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"privileged": True}]}},
    ]
    reviews = [target.handle_review(AugmentedUnstructured(object=p))
               for p in pods]
    resp = tpu.query_batch(TARGET, [con], reviews)
    assert len(resp[0].results) == 1
    assert len(resp[1].results) == 0


def test_negated_wildcard_closes_over_existential():
    """`not containers[_].privileged` is ¬∃i, not ∃i.¬."""
    from gatekeeper_tpu.apis.templates import ConstraintTemplate

    tpu = TpuDriver(batch_bucket=8)
    tpu.add_template(ConstraintTemplate.from_unstructured({
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8snegwild"},
        "spec": {"crd": {"spec": {"names": {"kind": "K8sNegWild"}}},
                 "targets": [{"target": TARGET, "rego": """
package k8snegwild

violation[{"msg": "no privileged container found"}] {
  not input.review.object.spec.containers[_].privileged
}
"""}]},
    }))
    assert "K8sNegWild" in tpu.lowered_kinds()
    con = Constraint.from_unstructured({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNegWild", "metadata": {"name": "x"}, "spec": {}})
    tpu.add_constraint(con)
    target = K8sValidationTarget()
    pods = [
        # one privileged among two -> ∃ privileged -> NOT a violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"privileged": True}, {"name": "x"}]}},
        # none privileged -> violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"name": "x"}]}},
    ]
    reviews = [target.handle_review(AugmentedUnstructured(object=p))
               for p in pods]
    resp = tpu.query_batch(TARGET, [con], reviews)
    assert len(resp[0].results) == 0
    assert len(resp[1].results) == 1


def test_mask_generate_name_objects():
    from gatekeeper_tpu.ir import masks as masks_mod
    from gatekeeper_tpu.ops.flatten import Flattener, Schema, Vocab

    con = Constraint.from_unstructured({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sX", "metadata": {"name": "m"},
        "spec": {"match": {"name": "web-*"}}})
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"generateName": "web-", "namespace": "d"}}]
    vocab = Vocab()
    batch = Flattener(Schema(), vocab).flatten(objs)
    mask = masks_mod.constraint_masks([con], batch, vocab, objs)
    assert mask[0, 0]  # generateName "web-" matches name glob "web-*"


def _mini_driver(rego, kind):
    from gatekeeper_tpu.apis.templates import ConstraintTemplate

    tpu = TpuDriver(batch_bucket=8)
    tpu.add_template(ConstraintTemplate.from_unstructured({
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {"crd": {"spec": {"names": {"kind": kind}}},
                 "targets": [{"target": TARGET, "rego": rego}]},
    }))
    con = Constraint.from_unstructured({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind, "metadata": {"name": "x"}, "spec": {}})
    tpu.add_constraint(con)
    return tpu, con


def _verdicts(tpu, con, pods):
    target = K8sValidationTarget()
    reviews = [target.handle_review(AugmentedUnstructured(object=p))
               for p in pods]
    resp = tpu.query_batch(TARGET, [con], reviews, render_messages=False)
    return [len(r.results) for r in resp]


def test_named_iteration_var_shares_instance():
    """containers[i].a; containers[i].b requires the SAME container."""
    tpu, con = _mini_driver("""
package k8ssamevar

violation[{"msg": "same"}] {
  input.review.object.spec.containers[i].privileged
  input.review.object.spec.containers[i].hostBad
}
""", "K8sSameVar")
    assert "K8sSameVar" in tpu.lowered_kinds()
    pods = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"privileged": True}, {"hostBad": True}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"privileged": True, "hostBad": True}]}},
    ]
    assert _verdicts(tpu, con, pods) == [0, 1]


def test_message_assignment_definedness_gates_clause():
    """msg := sprintf(..., [c.name]) makes the clause undefined when c.name
    is missing (interpreter semantics preserved in the lowered program)."""
    tpu, con = _mini_driver("""
package k8smsgdef

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  c.securityContext.privileged
  msg := sprintf("bad: %v", [c.name])
}
""", "K8sMsgDef")
    assert "K8sMsgDef" in tpu.lowered_kinds()
    pods = [
        # privileged but NO name -> sprintf arg undefined -> no violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"securityContext": {"privileged": True}}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [
             {"name": "c1", "securityContext": {"privileged": True}}]}},
    ]
    assert _verdicts(tpu, con, pods) == [0, 1]


def test_bool_equality_is_exact_on_kind():
    """x == true must not match truthy non-booleans."""
    tpu, con = _mini_driver("""
package k8sbooleq

violation[{"msg": "hostNetwork true"}] {
  input.review.object.spec.hostNetwork == true
}
""", "K8sBoolEq")
    assert "K8sBoolEq" in tpu.lowered_kinds()
    pods = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"hostNetwork": True}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"hostNetwork": "yes"}},  # truthy string, not == true
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {}},
    ]
    assert _verdicts(tpu, con, pods) == [1, 0, 0]


def test_library_differential():
    """Every library template (lowered or fallback) must agree with the
    interpreter across a randomized object population."""
    import os

    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    lib = os.path.join(os.path.dirname(__file__), "..", "library", "general")
    tpu = TpuDriver(batch_bucket=16)
    constraints = []
    for name in sorted(os.listdir(lib)):
        tdoc = load_yaml_file(os.path.join(lib, name, "template.yaml"))[0]
        t = ConstraintTemplate.from_unstructured(tdoc)
        if not t.targets[0].rego:
            continue
        tpu.add_template(t)
        cdoc = load_yaml_file(
            os.path.join(lib, name, "samples", "constraint.yaml"))[0]
        con = Constraint.from_unstructured(cdoc)
        tpu.add_constraint(con)
        constraints.append(con)

    rng = random.Random(1234)

    def rand_obj(i):
        kind = rng.choice(["Pod", "Deployment", "Service", "Namespace"])
        meta = {"name": f"o{i}", "namespace": rng.choice(
            ["default", "prod", ""]) or None}
        meta = {k: v for k, v in meta.items() if v}
        if rng.random() < 0.5:
            meta["labels"] = {
                k: rng.choice(["user.agilebank.demo", "user", "x"])
                for k in rng.sample(["owner", "app", "team"],
                                    rng.randint(1, 3))
            }
        obj = {"apiVersion": "apps/v1" if kind == "Deployment" else "v1",
               "kind": kind, "metadata": meta}
        spec = {}
        if kind in ("Pod",):
            containers = []
            for j in range(rng.randint(0, 3)):
                c = {"name": f"c{j}",
                     "image": rng.choice([
                         "openpolicyagent/opa:0.9.2", "nginx",
                         "nginx:latest", "repo/app:v1", "nginx:1.19",
                     ])}
                if rng.random() < 0.5:
                    c["resources"] = {"limits": {
                        "cpu": rng.choice(["100m", "500m", 1, "2"]),
                        "memory": rng.choice(["512Mi", "2Gi", "64Mi"]),
                    }}
                if rng.random() < 0.2:
                    del c["image"]
                if rng.random() < 0.3:
                    c["ports"] = [{"hostPort": rng.choice([79, 808, 9001])}]
                containers.append(c)
            spec["containers"] = containers
            if rng.random() < 0.2:
                spec["hostPID"] = True
            if rng.random() < 0.2:
                spec["hostNetwork"] = True
        if kind == "Deployment":
            if rng.random() < 0.8:
                spec["replicas"] = rng.choice([1, 3, 50, 100])
        if kind == "Service":
            spec["type"] = rng.choice(["ClusterIP", "NodePort"])
        obj["spec"] = spec
        return obj

    objects = [rand_obj(i) for i in range(300)]
    target = K8sValidationTarget()
    reviews = [target.handle_review(AugmentedUnstructured(object=o))
               for o in objects]
    got = tpu.query_batch(TARGET, constraints, reviews)
    interp = tpu._interp
    for oi, review in enumerate(reviews):
        expected = []
        for con in constraints:
            if not target.to_matcher(con.match).match(review):
                continue
            expected.extend(interp.query(TARGET, [con], review).results)
        key = lambda r: (r.constraint["metadata"]["name"], r.msg)
        assert sorted(map(key, got[oi].results)) == sorted(
            map(key, expected)), (
            f"divergence on object {oi}: {objects[oi]}\n"
            f"got={sorted(map(key, got[oi].results))}\n"
            f"want={sorted(map(key, expected))}"
        )


def test_map_value_iteration_matches_interpreter():
    """xs[_] over a MAP iterates values (flattener must enumerate dict
    values, not return an empty axis)."""
    tpu, con = _mini_driver("""
package k8smapiter

violation[{"msg": "sensitive volume"}] {
  v := input.review.object.spec.volumes[_]
  v.hostPath
}
""", "K8sMapIter")
    assert "K8sMapIter" in tpu.lowered_kinds()
    pods = [
        # volumes as a MAP keyed by name (CRD-style): values iterated
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"volumes": {"cache": {"hostPath": {"path": "/tmp"}}}}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"volumes": [{"hostPath": {"path": "/x"}}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {"volumes": {"data": {"emptyDir": {}}}}},
    ]
    assert _verdicts(tpu, con, pods) == [1, 1, 0]


def test_cross_type_comparison_term_order():
    """Rego ordered comparisons are total across types (term order: null <
    bool < number < string < composites) — `hostPort > 9000` is TRUE for a
    string-typed hostPort (fuzzer-found divergence)."""
    tpu, con = _mini_driver("""
package k8scmprank

violation[{"msg": "port out of range"}] {
  port := input.review.object.spec.containers[_].ports[_].hostPort
  port > input.parameters.max
}

violation[{"msg": "neq mismatch"}] {
  input.review.object.spec.replicas != input.parameters.max
}
""", "K8sCmpRank")
    con.parameters = {"max": 9000}
    con.raw["spec"]["parameters"] = {"max": 9000}
    assert "K8sCmpRank" in tpu.lowered_kinds()
    pods = [
        # string port: ranks above any number -> violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"ports": [{"hostPort": "80"}]}],
                  "replicas": 9000}},
        # numeric port within range; replicas != max is false -> no violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"ports": [{"hostPort": 80}]}],
                  "replicas": 9000}},
        # bool port: bool < number -> not greater; replicas string != 9000 ->
        # neq true (cross-type inequality is DEFINED in Rego)
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {"containers": [{"ports": [{"hostPort": True}]}],
                  "replicas": "9000"}},
        # null port: null < number; missing replicas -> neq undefined
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "d"},
         "spec": {"containers": [{"ports": [{"hostPort": None}]}]}},
    ]
    got = _verdicts(tpu, con, pods)
    # oracle agreement is the real assertion
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert (g > 0) == (want > 0), (pod, g, want)
    assert got == [1, 0, 1, 0]


def test_dynamic_field_access_and_shared_param_instance():
    """container[probe] lowers via ragged key sets; a param element shared
    between a guard (probe == "x") and the dynamic access is ONE existential
    (reduced in a single AnyParamList)."""
    tpu, con = _mini_driver("""
package k8ssharedelem

violation[{"msg": "missing gated probe"}] {
  probe := input.parameters.probes[_]
  probe == "livenessProbe"
  c := input.review.object.spec.containers[_]
  not c[probe]
}
""", "K8sSharedElem")
    con.parameters = {"probes": ["livenessProbe", "readinessProbe"]}
    con.raw["spec"]["parameters"] = con.parameters
    assert "K8sSharedElem" in tpu.lowered_kinds()
    pods = [
        # livenessProbe present -> no violation (guard selects it)
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"name": "c", "livenessProbe": {"x": 1}}]}},
        # only readinessProbe -> livenessProbe missing -> violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"name": "c", "readinessProbe": {"x": 1}}]}},
        # FALSE-valued livenessProbe: defined-but-false -> statement truthy
        # fails -> violation (truthy-key semantics)
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {"containers": [{"name": "c", "livenessProbe": False}]}},
    ]
    got = _verdicts(tpu, con, pods)
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert (g > 0) == (want > 0), (pod, g, want)
    assert got == [0, 1, 1]


def test_map_key_iteration_as_value():
    """labels[key] with the bound key used as a VALUE (the required-labels /
    required-annotations clause-2 pattern): map keys columnize to a MapKeyCol;
    the param-element × axis-item equality lowers to a dual existential
    (reference library/general/requiredlabels template clause 2)."""
    tpu, con = _mini_driver("""
package k8skeyval

violation[{"msg": msg}] {
  value := input.review.object.metadata.labels[key]
  expected := input.parameters.labels[_]
  expected.key == key
  not re_match(expected.allowedRegex, value)
  msg := sprintf("<%v: %v> fails %v", [key, value, expected.allowedRegex])
}
""", "K8sKeyVal")
    con.parameters = {"labels": [{"key": "owner", "allowedRegex": "^team-"}]}
    con.raw["spec"]["parameters"] = dict(con.parameters)
    assert "K8sKeyVal" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        # matching key, regex holds -> no violation
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "a", "labels": {"owner": "team-a"}}},
        # matching key, regex fails -> violation
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "b", "labels": {"owner": "alice"}}},
        # key absent -> clause can't bind -> no violation
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "c", "labels": {"app": "x"}}},
        # non-string value: re_match errors -> undefined -> not ... is TRUE
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "d", "labels": {"owner": False}}},
        # no labels at all
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "e"}},
    ]
    got = _verdicts(tpu, con, pods)
    # oracle agreement first, then the expected pattern
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [0, 1, 0, 1, 0]


def test_list_axis_iteration_key_is_not_a_string():
    """Iterating a LIST binds the key var to an integer index; string
    equality against it is false on both engines (MapKeyCol sid -1)."""
    tpu, con = _mini_driver("""
package k8slistkey

violation[{"msg": "named index"}] {
  c := input.review.object.spec.containers[key]
  expected := input.parameters.names[_]
  expected == key
}
""", "K8sListKey")
    con.parameters = {"names": ["0", "c0"]}
    con.raw["spec"]["parameters"] = dict(con.parameters)
    assert "K8sListKey" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"name": "c0"}]}},
        # map-shaped containers: key "c0" IS a string -> violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": {"c0": {"image": "x"}}}},
    ]
    got = _verdicts(tpu, con, pods)
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [0, 1]


def test_shared_param_instance_across_dual_and_plain():
    """expected := params.xs[_] used in BOTH a dual (axis×param) predicate
    and a plain param predicate must reduce in ONE AnyParamList."""
    tpu, con = _mini_driver("""
package k8ssharedelem

violation[{"msg": "match"}] {
  value := input.review.object.metadata.labels[key]
  expected := input.parameters.xs[_]
  expected.key == key
  expected.mode == "enforce"
  not startswith(value, expected.prefix)
}
""", "K8sSharedElem")
    con.parameters = {"xs": [
        {"key": "owner", "mode": "enforce", "prefix": "team-"},
        {"key": "app", "mode": "audit", "prefix": "svc-"},
    ]}
    con.raw["spec"]["parameters"] = dict(con.parameters)
    assert "K8sSharedElem" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        # owner enforced and bad prefix -> violation; app is audit-mode (its
        # elem fails mode check, so bad app prefix alone must NOT violate)
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "a",
                      "labels": {"owner": "alice", "app": "bad"}}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "b",
                      "labels": {"owner": "team-a", "app": "bad"}}},
    ]
    got = _verdicts(tpu, con, pods)
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [1, 0]


def test_neq_against_list_iteration_key():
    """`expected != key` over a LIST axis: Rego binds key to an int index and
    cross-type inequality is defined-TRUE — the device must not mask the
    map-key slot as absent (review-found divergence)."""
    tpu, con = _mini_driver("""
package k8slistkeyneq

violation[{"msg": "index neq"}] {
  c := input.review.object.spec.containers[key]
  expected := input.parameters.names[_]
  expected != key
}
""", "K8sListKeyNeq")
    con.parameters = {"names": ["c0"]}
    con.raw["spec"]["parameters"] = dict(con.parameters)
    assert "K8sListKeyNeq" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        # list axis: key=0, "c0" != 0 is defined-true -> violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"name": "c0"}]}},
        # map axis with the exact key: "c0" != "c0" false -> no violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": {"c0": {"image": "x"}}}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {"containers": {"other": {"image": "x"}}}},
    ]
    got = _verdicts(tpu, con, pods)
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [1, 0, 1]


def test_partial_builtin_assignment_falls_back():
    """lower() is undefined on a number, so a message assignment through it
    gates the clause in a way the device can't express -> the template must
    FALL BACK, not fabricate violations (review-found regression guard)."""
    tpu, con = _mini_driver("""
package k8spartialfn

violation[{"msg": m}] {
  input.review.object.spec.replicas > 0
  m := lower(input.review.object.spec.replicas)
}
""", "K8sPartialFn")
    assert "K8sPartialFn" in tpu.fallback_kinds(), tpu.lowered_kinds()
    pods = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"replicas": 3}},
    ]
    # lower(3) undefined -> clause undefined -> NO violation
    assert _verdicts(tpu, con, pods) == [0]


def test_inlined_function_shares_caller_existential():
    """not f(c) with c bound: the inlined body's predicates must merge into
    the CALLER's AnyAxis (∃c: name ∧ ¬f(c)), not close their own
    object-level existential (fuzzer-found divergence: a single compliant
    container masked violations by its siblings)."""
    tpu, con = _mini_driver("""
package k8sinlineshare

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  not read_only(c)
  msg := sprintf("container <%v>", [c.name])
}

read_only(c) {
  c.securityContext.readOnlyRootFilesystem == true
}
""", "K8sInlineShare")
    assert "K8sInlineShare" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        # one compliant + one violating container: must still violate
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [
             {"name": "good",
              "securityContext": {"readOnlyRootFilesystem": True}},
             {"name": "bad"}]}},
        # all compliant
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [
             {"name": "good",
              "securityContext": {"readOnlyRootFilesystem": True}}]}},
        # string-typed true is NOT boolean true
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {"containers": [
             {"name": "strtrue",
              "securityContext": {"readOnlyRootFilesystem": "true"}}]}},
    ]
    got = _verdicts(tpu, con, pods)
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [1, 0, 1]


def test_correlated_nested_axes_lower_per_parent():
    """Predicates on a parent item AND a nested sub-list (c.name with
    c.caps.drop[_]) must evaluate per-parent (NestedAny), never as two
    independent existentials (fuzzer-found divergence, now lowered via the
    parent-index column)."""
    tpu, con = _mini_driver("""
package k8scorrelated

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  d := c.securityContext.capabilities.drop[_]
  d == "ALL"
  msg := sprintf("container <%v> drops ALL", [c.name])
}
""", "K8sCorrelated")
    assert "K8sCorrelated" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        # the dropping container has no name: interpreter yields NO
        # violation (msg undefined); independent existentials would
        # wrongly combine c0's name with c1's drop
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [
             {"name": "c0"},
             {"securityContext": {"capabilities": {"drop": ["ALL"]}}}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [
             {"name": "c0",
              "securityContext": {"capabilities": {"drop": ["ALL"]}}}]}},
    ]
    assert _verdicts(tpu, con, pods) == [0, 1]


def test_uncorrelated_nested_axis_still_lowers():
    """Nested iteration WITHOUT parent-item predicates (the
    hostnetworkingports shape) keeps its single flattened pair axis."""
    tpu, con = _mini_driver("""
package k8spairax

violation[{"msg": "big port"}] {
  input.review.object.spec.containers[_].ports[_].hostPort > 9000
}
""", "K8sPairAx")
    assert "K8sPairAx" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"ports": [{"hostPort": 80}]},
                                 {"ports": [{"hostPort": 9001}]}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"ports": [{"hostPort": 80}]}]}},
    ]
    assert _verdicts(tpu, con, pods) == [1, 0]


def test_negated_nested_axis_under_bound_item():
    """`c := containers[_]; not c.ports[_].hostPort` — the ¬∃ must close
    over c's OWN pairs (per-parent NestedAny), not all containers'
    (review-found divergence)."""
    tpu, con = _mini_driver("""
package k8snegnested

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  not c.ports[_].hostPort
  msg := sprintf("container <%v> has no hostPort", [c.name])
}
""", "K8sNegNested")
    assert "K8sNegNested" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        # c0 has no ports: interpreter violates; independent ¬∃ over all
        # pairs would see c1's port and say no violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [
             {"name": "c0"},
             {"name": "c1", "ports": [{"hostPort": 80}]}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"name": "c0",
                                  "ports": [{"hostPort": 80}]}]}},
    ]
    assert _verdicts(tpu, con, pods) == [1, 0]


def test_count_of_path_value():
    """count(obj.spec.tls) OP n on device: composite item count, string
    LENGTH for strings, undefined for scalars/null (CountNum node)."""
    tpu, con = _mini_driver("""
package k8scountpath

violation[{"msg": "too few tls"}] {
  count(input.review.object.spec.tls) == 0
}

violation[{"msg": "big name"}] {
  count(input.review.object.metadata.nick) > 3
}
""", "K8sCountPath")
    assert "K8sCountPath" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        # empty list: count 0 -> violation 1
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "a", "nick": "ab"}, "spec": {"tls": []}},
        # non-empty map counts entries; nick len 5 > 3 -> violation 2
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "b", "nick": "abcde"},
         "spec": {"tls": {"x": 1}}},
        # tls missing -> count undefined -> no violation; no nick
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {}},
        # tls is a NUMBER: count undefined (not a collection/string)
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "d"},
         "spec": {"tls": 7}},
        # tls is a string: count = length 3 != 0 -> no violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "e"},
         "spec": {"tls": "abc"}},
    ]
    got = _verdicts(tpu, con, pods)
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [1, 1, 0, 0, 0]


def test_param_elem_subject_and_trim_suffix():
    """The forbiddensysctls shape: the param ELEMENT is the string-pred
    subject (endswith(forbidden, "*")) and the needle is
    trim_suffix(forbidden, "*") — wildcard-prefix matching on device."""
    tpu, con = _mini_driver("""
package k8strimsfx

violation[{"msg": msg}] {
  name := input.review.object.spec.sysctls[_].name
  bad(name)
  msg := sprintf("forbidden <%v>", [name])
}

bad(name) {
  input.parameters.forbidden[_] == name
}

bad(name) {
  f := input.parameters.forbidden[_]
  endswith(f, "*")
  startswith(name, trim_suffix(f, "*"))
}
""", "K8sTrimSfx")
    con.parameters = {"forbidden": ["kernel.*", "net.core.somaxconn"]}
    con.raw["spec"]["parameters"] = dict(con.parameters)
    assert "K8sTrimSfx" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"sysctls": [{"name": "kernel.msgmax"}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"sysctls": [{"name": "net.core.somaxconn"}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {"sysctls": [{"name": "net.ipv4.ip_forward"}]}},
        # exact-match clause must NOT wildcard: "kernel." prefix only via *
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "d"},
         "spec": {"sysctls": [{"name": "net.core.somaxconn2"}]}},
    ]
    got = _verdicts(tpu, con, pods)
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [1, 1, 0, 0]


def test_callee_preds_on_caller_bound_child_axis():
    """big(p) with p a caller-bound PAIR item: the callee's predicates must
    merge into the caller's pair existential, then close per-parent as ONE
    NestedAny — never two independent reductions (review-found
    divergence)."""
    tpu, con = _mini_driver("""
package k8scalleechild

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  p := c.ports[_]
  big(p)
  p.hostPort < 200
  msg := sprintf("container <%v>", [c.name])
}

big(p) {
  p.hostPort > 100
}
""", "K8sCalleeChild")
    assert "K8sCalleeChild" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        # no single port in (100, 200): ports 300 and 50 -> NO violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [
             {"name": "c0",
              "ports": [{"hostPort": 300}, {"hostPort": 50}]}]}},
        # port 150 satisfies both -> violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"name": "c0",
                                  "ports": [{"hostPort": 150}]}]}},
        # 150 in one container, name in the other: per-container NestedAny
        # still violates via c1 (both preds on the same pair)
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "c"},
         "spec": {"containers": [
             {"name": "c0", "ports": [{"hostPort": 300}]},
             {"name": "c1", "ports": [{"hostPort": 150}]}]}},
    ]
    got = _verdicts(tpu, con, pods)
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [0, 1, 1]


def test_plain_and_dual_preds_share_pair_binding():
    """p.name == params.names[_] AND p.hostPort > 100 on the same bound
    pair p: one conjunction over one existential, not two decorrelated
    reductions (review-found divergence)."""
    tpu, con = _mini_driver("""
package k8spairshare

violation[{"msg": "match"}] {
  c := input.review.object.spec.containers[_]
  p := c.ports[_]
  p.name == input.parameters.names[_]
  p.hostPort > 100
}
""", "K8sPairShare")
    con.parameters = {"names": ["web"]}
    con.raw["spec"]["parameters"] = dict(con.parameters)
    assert "K8sPairShare" in tpu.lowered_kinds(), tpu.fallback_kinds()
    pods = [
        # no single port is both named "web" AND > 100 -> NO violation
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"containers": [{"ports": [
             {"name": "web", "hostPort": 50},
             {"name": "x", "hostPort": 200}]}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"ports": [
             {"name": "web", "hostPort": 200}]}]}},
    ]
    got = _verdicts(tpu, con, pods)
    target = K8sValidationTarget()
    for pod, g in zip(pods, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [0, 1]


def test_referential_unique_ingress_host():
    """data.inventory join on device (InventoryUniqueJoin): host-built
    owner-count tables with identical() self-exclusion — the
    uniqueingresshost policy (reference: referential policies over synced
    inventory)."""
    import os

    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    lib = os.path.join(os.path.dirname(__file__), "..", "library",
                       "general", "uniqueingresshost")
    tpu = TpuDriver(batch_bucket=8)
    from gatekeeper_tpu.apis.templates import ConstraintTemplate

    tpu.add_template(ConstraintTemplate.from_unstructured(
        load_yaml_file(os.path.join(lib, "template.yaml"))[0]))
    assert "K8sUniqueIngressHost" in tpu.lowered_kinds(), \
        tpu.fallback_kinds()
    con = Constraint.from_unstructured(load_yaml_file(
        os.path.join(lib, "samples", "constraint.yaml"))[0])
    tpu.add_constraint(con)

    def ing(name, ns, hosts):
        return {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
                "metadata": {"name": name, "namespace": ns},
                "spec": {"rules": [{"host": h} for h in hosts]}}

    # inventory: two ingresses; one shares a host with the review object
    for obj in [ing("a", "default", ["a.com", "shared.com"]),
                ing("b", "prod", ["b.com"])]:
        tpu.add_data("admission.k8s.gatekeeper.sh",
                     ["namespace", obj["metadata"]["namespace"],
                      "networking.k8s.io/v1", "Ingress",
                      obj["metadata"]["name"]], obj)

    reviews_objs = [
        # conflicts with inventory ingress a
        ing("new", "default", ["shared.com"]),
        # no conflict
        ing("new2", "default", ["unique.com"]),
        # IS inventory ingress a (self): its own hosts don't conflict,
        # b's don't match -> no violation
        ing("a", "default", ["a.com", "shared.com"]),
        # same name, DIFFERENT namespace: not identical -> conflict
        ing("a", "prod", ["a.com"]),
        # conflicts with b
        ing("x", "default", ["b.com"]),
        # no rules at all
        {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
         "metadata": {"name": "y", "namespace": "default"}, "spec": {}},
    ]
    got = _verdicts(tpu, con, reviews_objs)
    target = K8sValidationTarget()
    for pod, g in zip(reviews_objs, got):
        review = target.handle_review(AugmentedUnstructured(object=pod))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (pod, g, want)
    assert got == [1, 0, 0, 1, 1, 0]

    # data mutation invalidates the cache: removing ingress a clears the
    # shared.com conflict
    tpu.remove_data("admission.k8s.gatekeeper.sh",
                    ["namespace", "default", "networking.k8s.io/v1",
                     "Ingress", "a"])
    assert _verdicts(tpu, con, [reviews_objs[0]]) == [0]

    # non-string join value in inventory -> runtime fallback (exactness)
    tpu.add_data("admission.k8s.gatekeeper.sh",
                 ["namespace", "default", "networking.k8s.io/v1",
                  "Ingress", "weird"],
                 {"metadata": {"name": "weird", "namespace": "default"},
                  "spec": {"rules": [{"host": 5}]}})
    assert not tpu.inventory_exact("K8sUniqueIngressHost")
    # verdicts still exact via the interpreter route
    rv = {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
          "metadata": {"name": "n", "namespace": "default"},
          "spec": {"rules": [{"host": 5}]}}
    got = _verdicts(tpu, con, [rv])
    review = target.handle_review(AugmentedUnstructured(object=rv))
    want = len(tpu._interp.query(TARGET, [con], review).results)
    assert got == [want] == [1]  # 5 == 5 cross-entry conflict


def test_referential_upstream_template_shape():
    """The upstream uniqueingresshost form: NAMED inventory slot vars, a
    re_match apiVersion filter, and slot vars in the message — still one
    fused device join (reference library shape)."""
    tpu, con = _mini_driver("""
package k8srefupstream

identical(obj, review) {
  obj.metadata.namespace == review.object.metadata.namespace
  obj.metadata.name == review.object.metadata.name
}

violation[{"msg": msg}] {
  input.review.kind.kind == "Ingress"
  host := input.review.object.spec.rules[_].host
  other := data.inventory.namespace[ns][otherapiversion]["Ingress"][name]
  re_match("^(extensions|networking.k8s.io)/", otherapiversion)
  not identical(other, input.review)
  other.spec.rules[_].host == host
  msg := sprintf("host <%v> taken by %v/%v", [host, ns, name])
}
""", "K8sRefUpstream")
    assert "K8sRefUpstream" in tpu.lowered_kinds(), tpu.fallback_kinds()

    def ing(name, ns, hosts):
        return {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
                "metadata": {"name": name, "namespace": ns},
                "spec": {"rules": [{"host": h} for h in hosts]}}

    tpu.add_data(TARGET, ["namespace", "default", "networking.k8s.io/v1",
                          "Ingress", "a"], ing("a", "default", ["x.com"]))
    # an entry under a NON-matching apiVersion key: filtered out
    tpu.add_data(TARGET, ["namespace", "default", "fake.io/v1",
                          "Ingress", "b"], ing("b", "default", ["y.com"]))
    objs = [
        ing("new", "default", ["x.com"]),   # conflict via a
        ing("new2", "default", ["y.com"]),  # b filtered by apiver regex
        ing("a", "default", ["x.com"]),     # self
    ]
    got = _verdicts(tpu, con, objs)
    target = K8sValidationTarget()
    for o, g in zip(objs, got):
        review = target.handle_review(AugmentedUnstructured(object=o))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (o, g, want)
    assert got == [1, 0, 0]


def test_new_library_differential_adversarial():
    """Round-3 library growth (PSP suite + arithmetic + cluster-scope
    referential joins + dotted params): device grids must agree with the
    interpreter over an adversarial population probing the NEW lowering
    constructs — NumBin partiality (non-numeric operands, missing
    fields), dotted param paths, param object-lists, map-key startswith
    over annotations, negated cluster inventory joins."""
    import os

    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    lib = os.path.join(os.path.dirname(__file__), "..", "library")
    names = [
        ("pod-security-policy", "allowprivilegeescalation"),
        ("pod-security-policy", "procmount"),
        ("pod-security-policy", "flexvolumes"),
        ("pod-security-policy", "seccomp"),
        ("pod-security-policy", "selinux"),
        ("pod-security-policy", "users"),
        ("pod-security-policy", "fsgroup"),
        ("pod-security-policy", "apparmor"),
        ("pod-security-policy", "volumes"),
        ("general", "horizontalpodautoscaler"),
        ("general", "poddisruptionbudget"),
        ("general", "storageclass"),
        ("general", "verifydeprecatedapi"),
        ("general", "disallowedrepos"),
        ("general", "containerrequests"),
        ("general", "ephemeralstoragelimit"),
        ("general", "blockloadbalancer"),
    ]
    tpu = TpuDriver(batch_bucket=16)
    constraints = []
    for cat, name in names:
        tdoc = load_yaml_file(
            os.path.join(lib, cat, name, "template.yaml"))[0]
        tpu.add_template(ConstraintTemplate.from_unstructured(tdoc))
        cdoc = load_yaml_file(
            os.path.join(lib, cat, name, "samples", "constraint.yaml"))[0]
        con = Constraint.from_unstructured(cdoc)
        tpu.add_constraint(con)
        constraints.append(con)
    assert not tpu.fallback_kinds(), tpu.fallback_kinds()

    # referential inventory for storageclass (cluster-scoped join:
    # data.inventory.cluster[apiVersion][Kind][name])
    for nm in ("standard", "fast"):
        tpu.add_data(
            TARGET,
            ["cluster", "storage.k8s.io/v1", "StorageClass", nm],
            {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
             "metadata": {"name": nm}})

    rng = random.Random(20260729)

    def sec_ctx():
        sc = {}
        if rng.random() < 0.5:
            sc["allowPrivilegeEscalation"] = rng.choice(
                [True, False, "false", None, 0])
        if rng.random() < 0.3:
            sc["procMount"] = rng.choice(
                ["Default", "Unmasked", "unmasked", 3])
        if rng.random() < 0.4:
            sc["seccompProfile"] = rng.choice([
                {"type": "RuntimeDefault"}, {"type": "Unconfined"},
                {"type": 5}, {}, "RuntimeDefault"])
        if rng.random() < 0.3:
            sc["seLinuxOptions"] = rng.choice([
                {"level": "s0:c123,c456", "role": "object_r",
                 "type": "svirt_sandbox_file_t", "user": "system_u"},
                {"level": "s1:c9"}, {"level": 7}, {}, []])
        if rng.random() < 0.4:
            sc["runAsUser"] = rng.choice(
                [0, 100, 150, 250, -3, "150", 2.5, None, True])
        return sc

    def rand_obj(i):
        roll = rng.random()
        if roll < 0.5:
            meta = {"name": f"p{i}"}
            if rng.random() < 0.4:
                prefix = "container.apparmor.security.beta.kubernetes.io/"
                meta["annotations"] = {
                    rng.choice([prefix + "c0", prefix, "other/ann",
                                prefix + "zzz"]): rng.choice(
                        ["runtime/default", "unconfined", 7, None, True])
                    for _ in range(rng.randint(1, 3))
                }
            spec = {}
            cs = []
            for j in range(rng.randint(0, 3)):
                c = {"name": f"c{j}",
                     "image": rng.choice(["nginx", "k8s.gcr.io/x",
                                          "safeimages.corp/y", 7])}
                if rng.random() < 0.6:
                    c["securityContext"] = sec_ctx()
                if rng.random() < 0.4:
                    c["resources"] = {
                        rng.choice(["requests", "limits"]): {
                            "cpu": rng.choice(["100m", "5", 1, True]),
                            "memory": rng.choice(["512Mi", "4Gi", "x"]),
                            "ephemeral-storage": rng.choice(
                                ["100Mi", "3Gi", 7, "zz"]),
                        }}
                cs.append(c)
            spec["containers"] = cs
            if rng.random() < 0.3:
                spec["initContainers"] = [
                    {"name": "i", "image": "busybox",
                     "securityContext": sec_ctx()}]
            if rng.random() < 0.4:
                spec["securityContext"] = {
                    k: v for k, v in (
                        ("runAsUser", rng.choice([0, 120, 300, "x"])),
                        ("fsGroup", rng.choice([5, 500, 1500, "500",
                                                2.5, None])),
                        ("seccompProfile", rng.choice(
                            [{"type": "RuntimeDefault"},
                             {"type": "Localhost"}])),
                        ("seLinuxOptions",
                         {"level": "s0:c123,c456", "role": "object_r",
                          "type": "svirt_sandbox_file_t",
                          "user": "system_u"}),
                    ) if rng.random() < 0.5}
            if rng.random() < 0.4:
                vols = []
                for v in range(rng.randint(1, 3)):
                    vol = {"name": f"v{v}"}
                    vol[rng.choice(["emptyDir", "hostPath", "configMap",
                                    "flexVolume", "weird-type"])] = \
                        rng.choice([{}, {"driver": "example/lvm"},
                                    {"driver": "example/nope"},
                                    {"driver": 9}, "x", None])
                    vols.append(vol)
                spec["volumes"] = vols
            return {"apiVersion": "v1", "kind": "Pod",
                    "metadata": meta, "spec": spec}
        if roll < 0.65:
            return {"apiVersion": "autoscaling/v2",
                    "kind": "HorizontalPodAutoscaler",
                    "metadata": {"name": f"h{i}"},
                    "spec": {k: v for k, v in (
                        ("minReplicas", rng.choice(
                            [1, 5, 11, "3", 2.5, None, True])),
                        ("maxReplicas", rng.choice(
                            [2, 5, 25, "9", 0, None])),
                    ) if rng.random() < 0.9}}
        if roll < 0.75:
            return {"apiVersion": "policy/v1",
                    "kind": "PodDisruptionBudget",
                    "metadata": {"name": f"b{i}"},
                    "spec": rng.choice([
                        {"maxUnavailable": 0}, {"maxUnavailable": "0"},
                        {"maxUnavailable": 1}, {"minAvailable": "100%"},
                        {"minAvailable": 2}, {}])}
        if roll < 0.9:
            return {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
                    "metadata": {"name": f"v{i}"},
                    "spec": {k: v for k, v in (
                        ("storageClassName", rng.choice(
                            ["standard", "fast", "nope", 7, None])),
                    ) if rng.random() < 0.8}}
        return {"apiVersion": rng.choice(
                    ["extensions/v1beta1", "networking.k8s.io/v1"]),
                "kind": "Ingress", "metadata": {"name": f"g{i}"},
                "spec": {}}

    objects = [rand_obj(i) for i in range(400)]
    target = K8sValidationTarget()
    reviews = [target.handle_review(AugmentedUnstructured(object=o))
               for o in objects]
    got = tpu.query_batch(TARGET, constraints, reviews)
    # raw-grid lane: render_messages=False returns the grid verdicts
    # directly — the rendered lane re-checks hits through the exact
    # engine and so MASKS false-positive grid bugs (repo invariant)
    raw = tpu.query_batch(TARGET, constraints, reviews,
                          render_messages=False)
    interp = tpu._interp
    for oi, review in enumerate(reviews):
        expected = []
        for con in constraints:
            if not target.to_matcher(con.match).match(review):
                continue
            expected.extend(interp.query(TARGET, [con], review).results)
        key = lambda r: (r.constraint["metadata"]["name"], r.msg)
        assert sorted(map(key, got[oi].results)) == sorted(
            map(key, expected)), (
            f"divergence on object {oi}: {objects[oi]}\n"
            f"got={sorted(map(key, got[oi].results))}\n"
            f"want={sorted(map(key, expected))}"
        )
        from collections import Counter

        raw_counts = Counter(r.constraint["metadata"]["name"]
                             for r in raw[oi].results)
        want_counts = Counter(r.constraint["metadata"]["name"]
                              for r in expected)
        # the grid is per (constraint, object): multiple violations of
        # one constraint collapse to one raw hit
        assert set(raw_counts) == set(want_counts), (
            f"raw-grid divergence on object {oi}: {objects[oi]}\n"
            f"raw={sorted(raw_counts)} want={sorted(want_counts)}")


def test_referential_unique_service_selector():
    """Selector-map join (VERDICT r2 missing #3): the flatten_selector
    idiom lowers to a canonical-selector column + ns-qualified
    owner-count table (N.InvTableSpec transform='selector_canon',
    ns_scoped) with identical() self-exclusion."""
    import os

    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    lib = os.path.join(os.path.dirname(__file__), "..", "library",
                       "general", "uniqueserviceselector")
    tpu = TpuDriver(batch_bucket=8)
    from gatekeeper_tpu.apis.templates import ConstraintTemplate

    tpu.add_template(ConstraintTemplate.from_unstructured(
        load_yaml_file(os.path.join(lib, "template.yaml"))[0]))
    assert "K8sUniqueServiceSelector" in tpu.lowered_kinds(), \
        tpu.fallback_kinds()
    con = Constraint.from_unstructured(load_yaml_file(
        os.path.join(lib, "samples", "constraint.yaml"))[0])
    tpu.add_constraint(con)

    def svc(name, ns, selector):
        doc = {"apiVersion": "v1", "kind": "Service",
               "metadata": {"name": name, "namespace": ns},
               "spec": {"ports": [{"port": 443}]}}
        if selector is not None:
            doc["spec"]["selector"] = selector
        return doc

    for obj in [svc("a", "default", {"app": "x", "tier": "web"}),
                svc("b", "prod", {"app": "x", "tier": "web"}),
                svc("nosel", "default", None)]:
        tpu.add_data("admission.k8s.gatekeeper.sh",
                     ["namespace", obj["metadata"]["namespace"],
                      "v1", "Service", obj["metadata"]["name"]], obj)

    reviews_objs = [
        # same selector as a, same namespace (key order must not matter)
        svc("new", "default", {"tier": "web", "app": "x"}),
        # same selector but DIFFERENT namespace than a: only b matches,
        # and b is in prod -> violation only for prod
        svc("new2", "prod", {"app": "x", "tier": "web"}),
        # same selector, a namespace with no synced services
        svc("new3", "staging", {"app": "x", "tier": "web"}),
        # unique selector
        svc("new4", "default", {"app": "y"}),
        # IS service a (self-exclusion)
        svc("a", "default", {"app": "x", "tier": "web"}),
        # selector-less matches the selector-less inventory entry
        # (upstream flatten_selector of a missing selector is "")
        svc("new5", "default", None),
        # non-string selector value: OPA's non-strict builtin error makes
        # the pair UNDEFINED (skipped) -> canon "" matches selector-less
        svc("new6", "default", {"app": True}),
        # no namespace: the namespace assignment fails -> no violation
        {"apiVersion": "v1", "kind": "Service",
         "metadata": {"name": "new7"},
         "spec": {"selector": {"app": "x", "tier": "web"}}},
    ]
    got = _verdicts(tpu, con, reviews_objs)
    target = K8sValidationTarget()
    for obj, g in zip(reviews_objs, got):
        review = target.handle_review(AugmentedUnstructured(object=obj))
        want = len(tpu._interp.query(TARGET, [con], review).results)
        assert g == want, (obj, g, want)
    assert got == [1, 1, 0, 0, 0, 1, 1, 0]

    # data mutation invalidates the table: removing service a clears the
    # default-namespace conflict
    tpu.remove_data("admission.k8s.gatekeeper.sh",
                    ["namespace", "default", "v1", "Service", "a"])
    assert _verdicts(tpu, con, [reviews_objs[0]]) == [0]


def test_feat_eq_feat_update_delta_differential():
    """object-vs-oldObject scalar comparison (FeatEqFeat): the device
    grid must agree with the interpreter across scalar kinds, absence,
    operations, and the allowed-user exemption (upstream
    noupdateserviceaccount).  Composite values are excluded by contract
    (the node's docstring: apiserver-typed scalar fields only)."""
    from gatekeeper_tpu.target.review import AdmissionRequest

    tpu = TpuDriver()
    tpu.add_template(_template(
        "library/general/noupdateserviceaccount/template.yaml"))
    con = _constraint(
        "library/general/noupdateserviceaccount/samples/constraint.yaml")
    tpu.add_constraint(con)
    assert "K8sNoUpdateServiceAccount" in tpu.lowered_kinds()

    rng = random.Random(7)
    values = ["web-sa", "other-sa", "", 3, 3.0, 7, True, False, None,
              "MISSING"]
    users = ["alice",
             "system:serviceaccount:kube-system:replicaset-controller"]
    reviews = []
    for i in range(240):
        def pod(v):
            spec = {"containers": [{"name": "c", "image": "nginx"}]}
            if v != "MISSING":
                spec["serviceAccountName"] = v
            return {"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"p{i}", "namespace": "default"},
                    "spec": spec}

        req = AdmissionRequest(
            uid=f"u{i}",
            kind={"group": "", "version": "v1", "kind": "Pod"},
            operation=rng.choice(["UPDATE", "UPDATE", "CREATE"]),
            user_info={"username": rng.choice(users)},
            object=pod(rng.choice(values)),
            old_object=(pod(rng.choice(values))
                        if rng.random() < 0.9 else None),
        )
        reviews.append(K8sValidationTarget().handle_review(req))

    got = tpu.query_batch(TARGET, [con], reviews)
    interp = tpu._interp
    for oi, review in enumerate(reviews):
        expected = interp.query(TARGET, [con], review).results
        key = lambda r: (r.constraint["metadata"]["name"], r.msg)
        assert sorted(map(key, got[oi].results)) == \
            sorted(map(key, expected)), (
            f"divergence on review {oi}: "
            f"op={review.request.operation} "
            f"new={review.request.object} old={review.request.old_object}")


def test_numeric_boundary_saturation_differential():
    """Out-of-float32-range numbers saturate to ±inf on the device
    (ops/flatten._classify explicit policy, VERDICT r4 weak #6): ORDER
    comparisons against in-range thresholds must still agree with the
    exact interpreter at the int64 / float32 boundaries."""
    tpu = TpuDriver(batch_bucket=8)
    tpu.add_template(ConstraintTemplate.from_unstructured({
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8snumbound"},
        "spec": {"crd": {"spec": {"names": {"kind": "K8sNumBound"}}},
                 "targets": [{"target": TARGET, "rego": """
package k8snumbound

violation[{"msg": "too big"}] {
  input.review.object.spec.value > input.parameters.max
}
violation[{"msg": "too small"}] {
  input.review.object.spec.value < input.parameters.min
}
"""}]},
    }))
    assert "K8sNumBound" in tpu.lowered_kinds()
    con = Constraint.from_unstructured({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNumBound", "metadata": {"name": "bounds"},
        "spec": {"parameters": {"max": 1_000_000, "min": -5000}}})
    tpu.add_constraint(con)
    f32_max = 3.4028234663852886e38
    values = [
        2**63 - 1, -(2**63), 2**127, -(2**127),  # int64 and beyond
        1e308, -1e308,                            # near double max
        f32_max, -f32_max,                        # exactly float32 max
        f32_max * 1.001, -f32_max * 1.001,        # just past float32 max
        16777216, 16777217,                       # float32 integer gap edge
        999_999, 1_000_000, 1_000_001, -5000, -5001, 0,
    ]
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": f"o{i}"}, "spec": {"value": v}}
            for i, v in enumerate(values)]
    target = K8sValidationTarget()
    reviews = [target.handle_review(AugmentedUnstructured(object=o))
               for o in objs]
    got = tpu.query_batch(TARGET, [con], reviews)
    interp = tpu._interp
    for oi, review in enumerate(reviews):
        expected = interp.query(TARGET, [con], review).results
        assert sorted(r.msg for r in got[oi].results) == \
            sorted(r.msg for r in expected), f"divergence on value {values[oi]}"
