"""Round-4 sweep-path pins: prefix-axis dedup, kind-bucketed routing,
bit-packed masks, width stabilization, and peek_kind.

The load-bearing invariant for all of it: the routed, deduped, narrowed
sweep must produce BIT-IDENTICAL verdicts/totals/kept to the exact
interpreter and to the unrouted device path.
"""

import numpy as np
import pytest

from gatekeeper_tpu.ops.flatten import (Axis, Flattener, RaggedCol, Schema,
                                        dedup_schema)
from gatekeeper_tpu.utils.rawjson import RawJSON, as_raw, peek_kind


def test_dedup_schema_prefix_chain():
    a1 = Axis(((("spec", "containers"),),))
    a2 = Axis(((("spec", "containers"),), (("spec", "initContainers"),)))
    a3 = Axis(((("spec", "containers"),), (("spec", "initContainers"),),
               (("spec", "ephemeralContainers"),)))
    s = Schema()
    s.raggeds = [RaggedCol(a1, ("image",)), RaggedCol(a2, ("image",)),
                 RaggedCol(a3, ("image",)), RaggedCol(a2, ("name",))]
    exec_s, alias = dedup_schema(s)
    # every ragged collapses onto the widest axis
    assert all(r.axis == a3 for r in exec_s.raggeds)
    assert len(exec_s.raggeds) == 2  # image + name, once each
    assert alias[RaggedCol(a1, ("image",))] == RaggedCol(a3, ("image",))
    assert alias[RaggedCol(a2, ("name",))] == RaggedCol(a3, ("name",))
    # deduped axes keep their counts via extra_axes
    assert a1 in exec_s.extra_axes and a2 in exec_s.extra_axes


def test_dedup_flatten_aliases_same_arrays():
    a1 = Axis(((("spec", "containers"),),))
    a3 = Axis(((("spec", "containers"),), (("spec", "initContainers"),)))
    s = Schema()
    s.raggeds = [RaggedCol(a1, ("image",)), RaggedCol(a3, ("image",))]
    fl = Flattener(s, use_native=False)
    objs = [
        {"kind": "Pod",
         "spec": {"containers": [{"image": "a"}, {"image": "b"}],
                  "initContainers": [{"image": "c"}]}},
        {"kind": "Pod", "spec": {"containers": [{"image": "d"}]}},
    ]
    batch = fl.flatten(objs, pad_n=2)
    narrow = batch.raggeds[RaggedCol(a1, ("image",))]
    wide = batch.raggeds[RaggedCol(a3, ("image",))]
    assert narrow.sid is wide.sid  # identity alias: zero extra extraction
    # prefix property: the narrow axis's items are the first c1 of the
    # wide enumeration, gated by the narrow count
    c1 = batch.axis_counts[a1]
    assert list(c1[:2]) == [2, 1]
    v = fl.vocab
    assert v.string(int(wide.sid[0, 0])) == "a"
    assert v.string(int(wide.sid[0, 1])) == "b"
    assert v.string(int(wide.sid[0, 2])) == "c"  # beyond narrow count


def test_peek_kind_no_materialization():
    r = as_raw({"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "x"}})
    assert peek_kind(r) == "Pod"
    assert not r._loaded  # the whole point
    # nested kind before top-level, odd orders, strings containing "kind"
    cases = [
        ({"metadata": {"ownerReferences": [{"kind": "RS"}]},
          "kind": "Pod"}, "Pod"),
        ({"msg": 'x "kind" y', "kind": "Odd"}, "Odd"),
        ({"kind": 5}, ""),
        ({}, ""),
        ({"kind": "Service", "apiVersion": "v1"}, "Service"),
    ]
    for obj, want in cases:
        assert peek_kind(as_raw(obj)) == want, obj
    # loaded instances answer from dict state
    r2 = as_raw({"kind": "Pod"})
    r2["kind"] = "Mutated"
    assert peek_kind(r2) == "Mutated"


@pytest.fixture(scope="module")
def library_client():
    from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.synthetic import load_library

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    load_library(client)
    return client, tpu


@pytest.mark.slow  # tier-1 wall budget (PR 16): 43s full-library
# differential; the module's cheaper routing pins stay in tier 1.
def test_routed_audit_matches_unrouted(library_client):
    """Kind-bucketed routing must be invisible: EXACT totals equality vs
    the unrouted device sweep (both count violating objects), and
    per-violating-object agreement vs the pure interpreter."""
    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
    from gatekeeper_tpu.parallel.sharded import (ShardedEvaluator,
                                                 make_mesh)
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects

    client, tpu = library_client
    objects = make_cluster_objects(512, seed=11)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)

    def run_with(evaluator, raws):
        cfg = AuditConfig(violations_limit=5, chunk_size=128,
                          exact_totals=False)
        mgr = AuditManager(client, lister=lambda: iter(raws), config=cfg,
                           evaluator=evaluator)
        return mgr.audit()

    raws = [as_raw(o) for o in objects]
    ev = ShardedEvaluator(tpu, make_mesh(1), violations_limit=5)
    ev.warm_pass(client.constraints(), raws, 128)
    routed = run_with(ev, raws)

    # unrouted device sweep over the same corpus: one evaluator, full
    # constraint set per chunk — totals must match the routed run EXACTLY
    # (same violating-object counting on both lanes)
    ev2 = ShardedEvaluator(tpu, make_mesh(1), violations_limit=5)
    ev2.warm_pass(client.constraints(), raws, 128, route=False)
    unrouted_totals: dict = {}
    cons = client.constraints()
    for i in range(0, len(raws), 128):
        swept = ev2.sweep(cons, raws[i:i + 128])
        for kind, (kcons, _i2, _v2, counts, _b) in swept.items():
            for ci, con in enumerate(kcons):
                k = con.key()
                unrouted_totals[k] = (unrouted_totals.get(k, 0)
                                      + int(counts[ci]))
    for key, total in routed.total_violations.items():
        assert total == unrouted_totals.get(key, 0), (
            key, total, unrouted_totals.get(key, 0))

    # interpreter ground truth: the routed run's violating-object SET per
    # constraint must equal the exact engine's (totals differ by
    # multiplicity — interp counts results — so compare object identity
    # via kept sets under a limit big enough to be exhaustive here)
    interp = run_with(None, [as_raw(o) for o in objects])
    assert routed.total_objects == interp.total_objects == 512
    for key, vs in routed.kept.items():
        got = {(v.kind, v.name, v.message) for v in vs}
        want = {(v.kind, v.name, v.message) for v in interp.kept[key]}
        if len(interp.kept[key]) < 5 and len(vs) < 5:
            # neither lane hit the limit: the kept sets are exhaustive
            # and must agree exactly
            assert got == want, (key, got ^ want)
        else:
            # a lane truncated at the limit: every routed render must
            # still be a violation the exact engine produces
            assert got <= want or want <= got, (key, got ^ want)


def test_mask_bitpack_roundtrip():
    from gatekeeper_tpu.parallel.sharded import (pack_transfer_cols,
                                                 unpack_transfer_cols)
    import jax

    # identity alias dedup: two keys sharing one array ship once
    a = np.arange(32, dtype=np.int32).reshape(8, 4)
    cols = {"rg:x:f": {"sid": a}, "rg:y:f": {"sid": a},
            "sc:z": {"kind": np.ones(8, np.int8)}}
    bufs, layout = pack_transfer_cols(cols, 8)
    kinds = [e[2] for e in layout]
    assert "alias" in kinds
    out = unpack_transfer_cols(
        {k: np.asarray(v) for k, v in bufs.items()}, layout, 8)
    np.testing.assert_array_equal(np.asarray(out["rg:x:f"]["sid"]), a)
    np.testing.assert_array_equal(np.asarray(out["rg:y:f"]["sid"]), a)
    # total stored bytes: the aliased array must not ship twice
    stored = sum(b.nbytes for b in bufs.values())
    assert stored <= a.nbytes + 8 * 2  # one copy + the int8 col
