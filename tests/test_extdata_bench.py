"""tools/bench_extdata.py smoke (slow lane) — the script embeds a
batched-vs-perkey verdict cross-check, so a diverging lane fails here,
and the acceptance shape (bulk dedupe >= 10x at chunk >= 64, warm
steady state zero transport) is pinned at smoke scale."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_extdata_smoke():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "bench_extdata.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=root)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout)
    assert rec["chunk_size"] >= 64
    assert rec["dedupe_ratio"] >= 10.0
    assert rec["warm_round_trips"] == 0
    assert rec["batched_round_trips"] >= 1
    assert rec["violations"] > 0
