"""Differential tests for the threaded JSON flattener (flattenjsonmod.c).

The JSON lane interns strings in thread-partition order, so vocab *order*
differs from the sequential Python walk.  Exactness contract instead: run
the JSON lane FIRST, then the Python oracle over the SAME vocab — every
Python intern is then a lookup hit, so all sid arrays must be
bit-identical.  (Same trick as the audit pipeline: one shared driver
vocab, consistency is what matters, not order.)
"""

import json
import os
import random

import numpy as np
import pytest

from gatekeeper_tpu.ops import native
from gatekeeper_tpu.ops.flatten import (
    Axis,
    CanonCol,
    Flattener,
    KeySetCol,
    MapKeyCol,
    ParentIdxCol,
    RaggedCol,
    RaggedKeySetCol,
    ScalarCol,
    Schema,
    Vocab,
)
from gatekeeper_tpu.utils.rawjson import RawJSON, as_raw

jmod = native.load_json()


def rich_schema():
    containers = Axis(((("spec", "containers"),),
                       (("spec", "initContainers"),)))
    ports = Axis(((("spec", "containers"), ("ports",)),
                  (("spec", "initContainers"), ("ports",))))
    labels = Axis(((("metadata", "labels"),),))
    s = Schema()
    s.scalars = [
        ScalarCol(("spec", "hostNetwork")),
        ScalarCol(("spec", "priority")),
        ScalarCol(("metadata", "name")),
        ScalarCol(("spec", "nodeName")),
        ScalarCol(("__review__", "kind", "group")),
        ScalarCol(("__review__", "kind", "kind")),
        ScalarCol(("__review__", "operation")),
        ScalarCol(("__review__", "namespace")),
        ScalarCol(("__review__", "userInfo", "username")),
    ]
    s.raggeds = [
        RaggedCol(containers, ("securityContext", "privileged")),
        RaggedCol(containers, ("name",)),
        RaggedCol(containers, ()),
        RaggedCol(ports, ("hostPort",)),
        RaggedCol(labels, ()),
    ]
    s.keysets = [KeySetCol(("metadata", "labels")),
                 KeySetCol(("metadata", "annotations"))]
    s.map_keys = [MapKeyCol(labels)]
    s.ragged_keysets = [RaggedKeySetCol(axis=containers, subpath=()),
                        RaggedKeySetCol(axis=containers,
                                        subpath=("resources", "limits"))]
    s.parent_idx = [ParentIdxCol(axis=ports, parent=containers)]
    s.canons = [CanonCol(("metadata", "labels")),
                CanonCol(("spec", "selector"), ns_scoped=True)]
    return s


def rich_objects(n, seed=0):
    rng = random.Random(seed)
    objs = []
    strings = ["a", "", "b" * 50, "unié中文", "tab\there",
               'quote"back\\slash', "line\nbreak", "☃ snowman"]
    for i in range(n):
        containers = []
        for j in range(rng.randint(0, 5)):
            c = {"name": f"c{j}-{rng.choice(strings)}"}
            if rng.random() < 0.5:
                c["securityContext"] = {"privileged": rng.choice(
                    [True, False, "x", 1, None, {"m": 1}, [1]])}
            if rng.random() < 0.4:
                c["ports"] = [{"hostPort": rng.choice(
                    [rng.randint(1, 70000), 2.5, -1, 1e300, "80"])}
                    for _ in range(rng.randint(0, 3))]
            if rng.random() < 0.4:
                c["resources"] = {"limits": {
                    rng.choice(["cpu", "memory", "gpu"]): "1"
                    for _ in range(rng.randint(0, 3))}}
            if rng.random() < 0.2:
                c["flag"] = False  # truthy-key filter in ragged keysets
            containers.append(c)
        obj = {
            "apiVersion": rng.choice(["v1", "apps/v1", "batch/v1", ""]),
            "kind": rng.choice(["Pod", "Deployment", "ReplicaSet"]),
            "metadata": {
                "name": f"o{i}",
                "namespace": rng.choice(["default", "kube-system", ""]),
            },
            "spec": {"containers": containers},
        }
        if rng.random() < 0.4:
            obj["metadata"]["labels"] = {
                f"k{x}{rng.choice(strings)}": rng.choice(
                    [f"v{x}", True, False, None, 3])
                for x in range(rng.randint(1, 5))
            }
        if rng.random() < 0.2:
            obj["metadata"]["annotations"] = {
                "a": "b", "c": False, "d": rng.choice(strings)}
        if rng.random() < 0.2:
            obj["metadata"]["generateName"] = "gen-"
        if rng.random() < 0.3:
            obj["spec"]["hostNetwork"] = rng.choice([True, False, "maybe"])
        if rng.random() < 0.3:
            obj["spec"]["priority"] = rng.choice(
                [1, 2.5, -3, "high", None, 10 ** 400, -(10 ** 400), 0.1])
        if rng.random() < 0.3:
            obj["spec"]["nodeName"] = rng.choice(strings)
        if rng.random() < 0.3:
            obj["spec"]["selector"] = rng.choice([
                {"app": f"a{i % 7}", "tier": rng.choice(strings)},
                {"x": 3, "app": "mixed-types"},  # non-string pair skipped
                {},
                ["not", "a", "map"],
                "scalar",
            ])
        if rng.random() < 0.2:
            obj["spec"]["initContainers"] = [
                {"name": "init", "ports": [{"hostPort": 53}]}]
        objs.append(obj)
    return objs


def assert_batches_equal(schema, a, b):
    np.testing.assert_array_equal(a.group_sid, b.group_sid)
    np.testing.assert_array_equal(a.kind_sid, b.kind_sid)
    np.testing.assert_array_equal(a.ns_sid, b.ns_sid)
    np.testing.assert_array_equal(a.name_sid, b.name_sid)
    for spec in schema.scalars:
        np.testing.assert_array_equal(
            a.scalars[spec].kind, b.scalars[spec].kind, err_msg=str(spec))
        np.testing.assert_array_equal(
            a.scalars[spec].num, b.scalars[spec].num, err_msg=str(spec))
        np.testing.assert_array_equal(
            a.scalars[spec].sid, b.scalars[spec].sid, err_msg=str(spec))
    for axis in schema.axes():
        np.testing.assert_array_equal(a.axis_counts[axis],
                                      b.axis_counts[axis])
    for spec in schema.raggeds:
        np.testing.assert_array_equal(
            a.raggeds[spec].kind, b.raggeds[spec].kind, err_msg=str(spec))
        np.testing.assert_array_equal(
            a.raggeds[spec].num, b.raggeds[spec].num, err_msg=str(spec))
        np.testing.assert_array_equal(
            a.raggeds[spec].sid, b.raggeds[spec].sid, err_msg=str(spec))
    for spec in schema.keysets:
        np.testing.assert_array_equal(a.keysets[spec].sid,
                                      b.keysets[spec].sid)
        np.testing.assert_array_equal(a.keysets[spec].count,
                                      b.keysets[spec].count)
    for spec in schema.map_keys:
        np.testing.assert_array_equal(a.map_keys[spec].sid,
                                      b.map_keys[spec].sid)
    for spec in schema.parent_idx:
        np.testing.assert_array_equal(a.parent_idx[spec].idx,
                                      b.parent_idx[spec].idx)
    for spec in schema.ragged_keysets:
        np.testing.assert_array_equal(a.ragged_keysets[spec].sid,
                                      b.ragged_keysets[spec].sid)
        np.testing.assert_array_equal(a.ragged_keysets[spec].count,
                                      b.ragged_keysets[spec].count)
    for spec in getattr(schema, "canons", []):
        np.testing.assert_array_equal(a.canons[spec], b.canons[spec],
                                      err_msg=str(spec))


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_json_matches_python_shared_vocab():
    schema = rich_schema()
    objs = rich_objects(400)
    raws = [as_raw(o) for o in objs]
    vocab = Vocab()
    # JSON lane first: it creates every interning; the Python oracle then
    # only looks up, so sids must agree bitwise.
    nat = Flattener(schema, vocab).flatten_raw(raws, pad_n=512)
    py = Flattener(schema, vocab, use_native=False).flatten(objs, pad_n=512)
    assert_batches_equal(schema, py, nat)
    # genname presence column
    want = np.zeros(512, np.uint8)
    for i, o in enumerate(objs):
        if "generateName" in (o.get("metadata") or {}):
            want[i] = 1
    np.testing.assert_array_equal(nat.has_generate_name, want)


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_json_thread_counts_agree():
    """1-thread and 8-thread runs decode to the same strings (ids may
    differ — vocabularies are independent)."""
    schema = rich_schema()
    objs = rich_objects(300, seed=7)
    raws = [as_raw(o) for o in objs]
    outs = []
    for nt in ("1", "8"):
        os.environ["GTPU_FLATTEN_THREADS"] = nt
        try:
            v = Vocab()
            outs.append((v, Flattener(schema, v).flatten_raw(
                raws, pad_n=320)))
        finally:
            del os.environ["GTPU_FLATTEN_THREADS"]
    (v1, b1), (v8, b8) = outs

    def decode(v, arr):
        flat = arr.ravel()
        return [v.string(s) if s >= 0 else None for s in flat.tolist()]

    assert decode(v1, b1.name_sid) == decode(v8, b8.name_sid)
    for spec in schema.raggeds:
        assert decode(v1, b1.raggeds[spec].sid) == \
            decode(v8, b8.raggeds[spec].sid)
        np.testing.assert_array_equal(b1.raggeds[spec].kind,
                                      b8.raggeds[spec].kind)
    for spec in schema.keysets:
        assert decode(v1, b1.keysets[spec].sid) == \
            decode(v8, b8.keysets[spec].sid)


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_json_invalid_raises():
    """Truly malformed bytes raise through BOTH lanes: the C reject
    falls back to the dict lane, whose json.loads reject propagates
    as a ValueError into the audit chunk retry/drop machinery."""
    schema = rich_schema()
    raws = [as_raw({"kind": "Pod"}), RawJSON(b"{not json")]
    with pytest.raises(ValueError):
        Flattener(schema, Vocab()).flatten_raw(raws, pad_n=8)


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_json_c_reject_falls_back_to_dict_lane():
    """Input the C parser rejects but json.loads accepts (nesting past
    the C 256-depth cap) lands on the dict lane with oracle-identical
    columns instead of failing the batch."""
    deep = (b'{"kind":"Pod","metadata":{"name":"deep"},"spec":'
            + b'{"a":' * 300 + b"1" + b"}" * 300 + b"}")
    docs = [deep, b'{"kind":"Pod","metadata":{"name":"flat"}}']
    schema = rich_schema()
    vocab = Vocab()
    f = Flattener(schema, vocab)
    nat = f.flatten_raw([RawJSON(d) for d in docs], pad_n=8)
    assert f.lane_used in ("dict", "py")  # the fallback lane ran
    py = Flattener(schema, vocab, use_native=False).flatten(
        [json.loads(d) for d in docs], pad_n=8)
    assert_batches_equal(schema, py, nat)


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_json_truncated_bytes_fall_back_then_raise():
    """Truncated page bytes (a torn ingest) fail the C parser AND the
    dict-lane reparse: the error must surface (chunk machinery retries
    or drops the chunk), never silently flatten as an empty row."""
    whole = as_raw({"kind": "Pod", "metadata": {"name": "x"}})
    torn = RawJSON(whole.raw[:-5])
    f = Flattener(rich_schema(), Vocab())
    with pytest.raises(ValueError):
        f.flatten_raw([torn], pad_n=8)


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_json_weird_documents():
    schema = rich_schema()
    vocab = Vocab()
    cases = [b"{}", b"[1,2]", b"null", b'"str"', b"3.5",
             b'{"spec": null}', b'{"spec": {"containers": "x"}}',
             b'{"apiVersion": 7, "kind": null}',
             b'{"metadata": {"name": null, "namespace": 3}}',
             b'{"a": "\\u00e9\\u4e2d\\ud83d\\ude00"}']
    raws = [RawJSON(c) for c in cases]
    nat = Flattener(schema, vocab).flatten_raw(raws, pad_n=16)
    # dict-parseable cases must agree with the Python path; non-dict roots
    # behave as empty rows (identity "")
    objs = [json.loads(c) for c in cases]
    dict_rooted = [isinstance(o, dict) for o in objs]
    objs = [o if isinstance(o, dict) else {} for o in objs]
    py = Flattener(schema, vocab, use_native=False).flatten(objs, pad_n=16)
    nocanon = rich_schema()
    nocanon.canons = []
    assert_batches_equal(nocanon, py, nat)
    # canon columns: object-rooted rows match the oracle; a non-object
    # root stays -2 in the raw lane (the parse path's "yields nothing"),
    # where the {}-substituted oracle row interns "" instead
    for spec in schema.canons:
        for i, isdict in enumerate(dict_rooted):
            if isdict:
                assert nat.canons[spec][i] == py.canons[spec][i], (spec, i)
            else:
                assert nat.canons[spec][i] == -2, (spec, i)


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_flatten_delegates_rawjson():
    """Flattener.flatten() auto-routes all-RawJSON batches to the native
    JSON lane; a materialized (possibly mutated) RawJSON disables it."""
    schema = rich_schema()
    objs = rich_objects(50, seed=3)
    vocab = Vocab()
    raws = [as_raw(o) for o in objs]
    nat = Flattener(schema, vocab).flatten(raws, pad_n=64)
    assert nat.has_generate_name is not None  # proof the JSON lane ran
    py = Flattener(schema, vocab, use_native=False).flatten(objs, pad_n=64)
    assert_batches_equal(schema, py, nat)
    # a touched (materialized) RawJSON stays on the JSON lane via
    # re-serialization of its current dict state — mutations are honored
    raws2 = [as_raw(o) for o in objs]
    _ = raws2[0]["kind"]
    raws2[1]["metadata"]["name"] = "mutated"  # diverges from .raw
    touched = Flattener(schema, vocab).flatten(raws2, pad_n=64)
    assert touched.has_generate_name is not None
    assert vocab.string(int(touched.name_sid[1])) == "mutated"
    objs2 = [dict(o) for o in objs]
    objs2[1] = json.loads(json.dumps(objs2[1]))
    objs2[1]["metadata"]["name"] = "mutated"
    py2 = Flattener(schema, vocab, use_native=False).flatten(
        objs2, pad_n=64)
    assert_batches_equal(schema, py2, touched)


@pytest.mark.skipif(jmod is None, reason="native json build unavailable")
def test_json_review_docs_override():
    """Provided review docs (webhook lane) override synthesized
    __review__ columns."""
    schema = rich_schema()
    objs = rich_objects(20, seed=9)
    reviews = [{"kind": {"group": "apps", "version": "v1",
                         "kind": "Deployment"},
                "operation": "CREATE", "name": f"n{i}", "namespace": "ns",
                "userInfo": {"username": f"u{i}"}}
               for i in range(len(objs))]
    vocab = Vocab()
    raws = [as_raw(o) for o in objs]
    nat = Flattener(schema, vocab).flatten_raw(raws, pad_n=32,
                                               reviews=reviews)
    py = Flattener(schema, vocab, use_native=False).flatten(
        objs, pad_n=32, reviews=reviews)
    assert_batches_equal(schema, py, nat)


def test_rawjson_mutation_and_copy_semantics():
    """Review findings: writes before first read must survive the lazy
    parse; deepcopy of a mutated instance must capture current state
    (the mutation system's clear()/update() rollback pattern)."""
    import copy

    r = as_raw({"kind": "Pod", "metadata": {"name": "a"}})
    r["kind"] = "Deployment"          # write before any read
    assert r["kind"] == "Deployment"  # parse must not clobber the write
    assert r["metadata"]["name"] == "a"

    r2 = as_raw({"kind": "Pod", "spec": {"x": 1}})
    r2["spec"]["x"] = 2               # materialize + mutate nested
    snap = copy.deepcopy(r2)
    assert snap["spec"]["x"] == 2     # deepcopy sees mutated state
    r2.clear()
    assert len(r2) == 0               # raw must not resurrect keys
    r2.update(snap)
    assert r2["spec"]["x"] == 2       # rollback pattern round-trips

    r3 = as_raw({"a": 1})
    assert copy.deepcopy(r3)["a"] == 1  # unloaded path still works
