"""Staged host-pipeline executor: ordering, backpressure, degradation,
and the pipelined-vs-serial differential over the shipped library corpus
(the tier-1 guarantee that the overlap schedule changes NOTHING about
audit output)."""

import threading
import time

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.pipeline import (PipelineError, Stage, StagedPipeline,
                                     resolve_schedule)
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects


# --- executor unit behavior ------------------------------------------------

def test_executor_preserves_order_across_worker_pool():
    """Multi-worker stages must emit in INPUT order (the fold stage's
    bit-identity depends on it), regardless of completion order."""
    import random

    out = []

    def jitter(x):
        time.sleep(random.random() * 0.003)
        return None if x % 7 == 3 else x * 2  # None = dropped item

    run = StagedPipeline([
        Stage("jitter", jitter, workers=4, queue_cap=2),
        Stage("sink", lambda x: (out.append(x), None)[1], queue_cap=2),
    ]).run(range(150))
    assert out == [x * 2 for x in range(150) if x % 7 != 3]
    assert run.source_items == 150
    assert run.stage("jitter").items == 150
    assert run.stage("sink").items == len(out)


def test_executor_backpressure_bounds_queues_and_completes():
    """Tiny queue bounds: the pipeline must neither deadlock nor queue
    unboundedly — a fast producer stalls (bounded buffering = bounded
    RSS) instead of piling chunks up in front of a slow stage."""
    out = []
    run = StagedPipeline([
        Stage("slow", lambda x: (time.sleep(0.002), x)[1], queue_cap=1),
        Stage("sink", lambda x: (out.append(x), None)[1], queue_cap=1),
    ], source_cap=1).run(range(60))
    assert out == list(range(60))
    for s in run.stages:
        assert s.queue_highwater <= 1, (s.name, s.queue_highwater)
    # the source measurably stalled on the bounded queue (backpressure
    # reached all the way upstream)
    assert run.source_stall_s > 0


def test_executor_stage_error_propagates_without_hanging():
    def boom(x):
        if x == 5:
            raise ValueError("stage blew up")
        return x

    t0 = time.perf_counter()
    with pytest.raises(PipelineError) as ei:
        StagedPipeline([
            Stage("boom", boom, queue_cap=1),
            Stage("sink", lambda x: None, queue_cap=1),
        ]).run(range(1000))
    assert time.perf_counter() - t0 < 30  # unwound, not deadlocked
    assert ei.value.stage == "boom"
    assert isinstance(ei.value.__cause__, ValueError)


def test_executor_source_error_propagates():
    def src():
        yield 1
        raise RuntimeError("lister died")

    with pytest.raises(PipelineError) as ei:
        StagedPipeline([Stage("s", lambda x: None)]).run(src())
    assert ei.value.stage == "<source>"


def test_executor_overlap_is_measurable():
    """Two stages doing real (releasing-the-GIL) waits must overlap:
    stage busy sum > pipeline wall."""
    run = StagedPipeline([
        Stage("a", lambda x: (time.sleep(0.01), x)[1], queue_cap=2),
        Stage("b", lambda x: (time.sleep(0.01), None)[1], queue_cap=2),
    ]).run(range(20))
    assert run.stage_busy_sum() > run.wall_s * 1.3, (
        run.stage_busy_sum(), run.wall_s)


# --- schedule resolution ---------------------------------------------------

def test_schedule_resolution_one_core_degrades_to_serial(monkeypatch):
    import gatekeeper_tpu.pipeline as P

    monkeypatch.setattr(P, "effective_cpu_count", lambda: 1)
    assert P.resolve_schedule("auto", True) == "serial"
    monkeypatch.setattr(P, "effective_cpu_count", lambda: 8)
    assert P.resolve_schedule("auto", True) == "pipelined"
    # forced modes ignore core count; off and non-capable always serial
    monkeypatch.setattr(P, "effective_cpu_count", lambda: 1)
    assert P.resolve_schedule("on", True) == "pipelined"
    assert P.resolve_schedule("off", True) == "serial"
    assert P.resolve_schedule("on", False) == "serial"
    with pytest.raises(ValueError):
        P.resolve_schedule("sideways", True)


# --- audit-manager integration --------------------------------------------

def _library_client():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client)
    return client, tpu


def _mgr(client, tpu, objects, **cfg_kw):
    cfg_kw.setdefault("exact_totals", False)
    cfg = AuditConfig(chunk_size=96, **cfg_kw)
    return AuditManager(
        client, lister=lambda: iter(objects), config=cfg,
        evaluator=ShardedEvaluator(tpu, make_mesh(), violations_limit=20),
    )


def _kept_signature(run):
    return {
        k: [(v.message, v.kind, v.name, v.namespace, v.enforcement_action)
            for v in vs]
        for k, vs in run.kept.items()
    }


def test_pipelined_vs_serial_differential_on_library_corpus():
    """Acceptance: bit-identical verdicts AND rendered messages between
    the serial eager-poll schedule and the staged pipeline, over the full
    shipped library against a mixed synthetic cluster."""
    client, tpu = _library_client()
    objects = make_cluster_objects(260, seed=11)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)  # referential join inventory

    run_serial = _mgr(client, tpu, objects, pipeline="off").audit()
    # 2 flatten workers: covers the executor's order-restoring reorder
    # buffer on the real sweep path, not just the unit test
    mgr_pipe = _mgr(client, tpu, objects, pipeline="on",
                    pipeline_flatten_workers=2)
    run_pipe = mgr_pipe.audit()

    assert mgr_pipe.perf["pipelined"] == 1.0
    assert mgr_pipe.pipe_stats is not None
    assert run_serial.total_objects == run_pipe.total_objects == 260
    assert run_serial.total_violations == run_pipe.total_violations
    assert _kept_signature(run_serial) == _kept_signature(run_pipe)
    assert sum(run_serial.total_violations.values()) > 0  # non-vacuous

    # the built-in differential mode asserts the same equivalence inline
    mgr_diff = _mgr(client, tpu, objects, pipeline="differential")
    run_diff = mgr_diff.audit()
    assert mgr_diff.perf.get("pipeline_differential_ok") == 1.0
    assert run_diff.total_violations == run_serial.total_violations


@pytest.mark.slow  # tier-1 wall budget (PR 16): 27s; the non-exact
# pipelined-vs-serial differential above stays in tier 1.
def test_pipelined_exact_totals_matches_serial():
    """Exact-totals mode ships verdict bitmaps; the pipelined fold must
    count and render them identically."""
    client, tpu = _library_client()
    objects = make_cluster_objects(150, seed=29)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
    r_s = _mgr(client, tpu, objects, pipeline="off",
               exact_totals=True).audit()
    r_p = _mgr(client, tpu, objects, pipeline="on",
               exact_totals=True).audit()
    assert r_s.total_violations == r_p.total_violations
    assert _kept_signature(r_s) == _kept_signature(r_p)


def test_audit_one_core_detection_takes_serial_path(monkeypatch):
    """Acceptance: on a one-core host (or --pipeline=off) the audit runs
    the existing eager-poll serial schedule — no stage threads."""
    import gatekeeper_tpu.pipeline as P

    client, tpu = _library_client()
    objects = make_cluster_objects(80, seed=5)

    monkeypatch.setattr(P, "effective_cpu_count", lambda: 1)
    mgr = _mgr(client, tpu, objects, pipeline="auto")
    run = mgr.audit()
    assert mgr.perf["pipelined"] == 0.0
    assert mgr.pipe_stats is None
    assert run.total_objects == 80

    # multi-core auto flips to the pipeline, same output
    monkeypatch.setattr(P, "effective_cpu_count", lambda: 8)
    mgr2 = _mgr(client, tpu, objects, pipeline="auto")
    run2 = mgr2.audit()
    assert mgr2.perf["pipelined"] == 1.0
    assert run2.total_violations == run.total_violations

    mgr3 = _mgr(client, tpu, objects, pipeline="off")
    run3 = mgr3.audit()
    assert mgr3.perf["pipelined"] == 0.0
    assert run3.total_violations == run.total_violations


@pytest.mark.slow  # tier-1 wall budget (PR 15): the pipelined-vs-
# serial differential above keeps the schedule's bit-identity in
# tier-1; this backpressure stress (tiny queue bounds, 1-core) rides
# the slow lane
def test_audit_pipeline_backpressure_tiny_bounds():
    """Acceptance: queue bound of 1 + submit window of 1 over many small
    chunks — no deadlock, bounded in-flight depth, identical output."""
    client, tpu = _library_client()
    objects = make_cluster_objects(200, seed=3)
    mgr = _mgr(client, tpu, objects, pipeline="on",
               pipeline_queue_cap=1, submit_window=1)
    mgr.config.chunk_size = 16  # many chunks through the tiny windows
    done = []
    t = threading.Thread(target=lambda: done.append(mgr.audit()))
    t.start()
    t.join(timeout=300)
    assert not t.is_alive(), "pipelined audit deadlocked under tiny bounds"
    run = done[0]
    for name, s in mgr.pipe_stats["stages"].items():
        cap = 1 if name != "collect" else max(1, mgr.config.submit_window)
        assert s["queue_highwater"] <= cap, (name, s)
    serial = _mgr(client, tpu, objects, pipeline="off")
    serial.config.chunk_size = 16
    run_s = serial.audit()
    assert run.total_violations == run_s.total_violations
    assert _kept_signature(run) == _kept_signature(run_s)


def test_pipeline_stats_flow_into_metrics_registry():
    from gatekeeper_tpu.metrics import registry as M

    client, tpu = _library_client()
    objects = make_cluster_objects(60, seed=7)
    metrics = M.MetricsRegistry()
    cfg = AuditConfig(chunk_size=32, exact_totals=False, pipeline="on")
    mgr = AuditManager(
        client, lister=lambda: iter(objects), config=cfg,
        evaluator=ShardedEvaluator(tpu, make_mesh(), violations_limit=20),
        metrics=metrics,
    )
    mgr.audit()
    rendered = metrics.render()
    for stage in ("flatten", "dispatch", "collect", "fold_render"):
        assert metrics.get_gauge(M.PIPELINE_STAGE_SECONDS,
                                 {"stage": stage}) is not None, stage
    assert metrics.get_gauge(M.PIPELINE_DEVICE_IDLE) is not None
    assert M.PREFIX + M.PIPELINE_STAGE_OCCUPANCY in rendered
    assert metrics.get_counter(
        M.AUDIT_DURATION, None) == 0.0  # histogram, not counter
    assert M.PREFIX + M.AUDIT_DURATION in rendered


def test_lowering_fallback_counter_increments():
    """Satellite: a template the lowering cannot compile increments the
    fallback counter (visible in metrics + gator bench output)."""
    from gatekeeper_tpu.metrics import registry as M

    metrics = M.MetricsRegistry()
    tpu = TpuDriver(metrics=metrics)
    client = Client(target=K8sValidationTarget(), drivers=[tpu],
                    enforcement_points=[AUDIT_EP])
    # http.send is not lowerable: guaranteed interpreter fallback
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sfallbackprobe"},
        "spec": {"crd": {"spec": {"names": {"kind": "K8sFallbackProbe"}}},
                 "targets": [{"target": "admission.k8s.gatekeeper.sh",
                              "rego": """
package k8sfallbackprobe
violation[{"msg": msg}] {
  resp := http.send({"method": "get", "url": "http://example.invalid"})
  resp.status_code != 200
  msg := "probe failed"
}
"""}]},
    })
    assert metrics.counter_total(M.LOWERING_FALLBACK) == 1
    stats = tpu.lowering_stats()
    assert stats["fallback"] == 1 and stats["lowered"] == 0
    assert stats["fallback_fraction"] == 1.0
    assert "K8sFallbackProbe" in stats["fallback_kinds"]
