"""Mutation + expansion subsystem tests, golden-checked against the
reference's gator-expand fixtures (test/gator/expand/fixtures)."""

import glob
import os

import pytest
import yaml

from gatekeeper_tpu.expansion.expander import Expander
from gatekeeper_tpu.gator import reader
from gatekeeper_tpu.mutation import path_parser
from gatekeeper_tpu.mutation.core import MutateError
from gatekeeper_tpu.mutation.mutators import (
    MutatorError,
    from_unstructured,
    split_image,
)
from gatekeeper_tpu.mutation.path_parser import ListNode, ObjectNode
from gatekeeper_tpu.mutation.system import MutationSystem, NotConvergingError

FIXTURES = "/root/reference/test/gator/expand/fixtures"


# --- path parser ----------------------------------------------------------


def test_path_parser_basic():
    nodes = path_parser.parse("spec.containers[name: foo].securityContext")
    assert nodes == [
        ObjectNode("spec"),
        ObjectNode("containers"),
        ListNode("name", "foo"),
        ObjectNode("securityContext"),
    ]


def test_path_parser_glob_and_quotes():
    nodes = path_parser.parse('metadata.labels."my.dotted/key"')
    assert nodes[-1] == ObjectNode("my.dotted/key")
    nodes = path_parser.parse("spec.containers[name:*].image")
    assert nodes[2] == ListNode("name", None)
    assert nodes[2].glob


def test_path_parser_errors():
    for bad in ("", "a..b", "a[name foo]", "a[name: x", 'a."unterminated'):
        with pytest.raises(Exception):
            path_parser.parse(bad)


# --- mutators -------------------------------------------------------------


def _assign(location, value, apply_kinds=("Pod",), extra_params=None,
            match=None):
    params = {"assign": {"value": value}}
    if extra_params:
        params.update(extra_params)
    spec = {
        "applyTo": [{"groups": [""], "versions": ["v1"],
                     "kinds": list(apply_kinds)}],
        "location": location,
        "parameters": params,
    }
    if match is not None:
        spec["match"] = match
    return from_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign",
        "metadata": {"name": "m"},
        "spec": spec,
    })


def pod(**spec):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "namespace": "default"}, "spec": spec}


def test_assign_scalar_and_creation():
    m = _assign("spec.priorityClassName", "low")
    obj = pod()
    assert m.mutate_obj(obj)
    assert obj["spec"]["priorityClassName"] == "low"
    assert not m.mutate_obj(obj)  # idempotent


def test_assign_keyed_list_glob():
    m = _assign("spec.containers[name: *].imagePullPolicy", "Always")
    obj = pod(containers=[{"name": "a"}, {"name": "b"}])
    assert m.mutate_obj(obj)
    assert all(c["imagePullPolicy"] == "Always"
               for c in obj["spec"]["containers"])


def test_assign_keyed_list_creates_missing_item():
    m = _assign("spec.tolerations[key: reserved]",
                {"operator": "Exists", "effect": "NoSchedule"})
    obj = pod()
    assert m.mutate_obj(obj)
    assert obj["spec"]["tolerations"] == [
        {"key": "reserved", "operator": "Exists", "effect": "NoSchedule"}
    ]


def test_assign_key_invariance():
    m = _assign("spec.containers[name: a]", {"name": "CHANGED"})
    obj = pod(containers=[{"name": "a"}])
    with pytest.raises(MutateError):
        m.mutate_obj(obj)


def test_assign_if_in_not_in():
    m = _assign("spec.dnsPolicy", "ClusterFirst",
                extra_params={"assignIf": {"in": ["Default", "None"]}})
    obj = pod(dnsPolicy="Default")
    assert m.mutate_obj(obj)
    obj2 = pod(dnsPolicy="ClusterFirstWithHostNet")
    assert not m.mutate_obj(obj2)
    obj3 = pod()  # absent: 'in' requires a current value
    assert not m.mutate_obj(obj3)
    m2 = _assign("spec.dnsPolicy", "ClusterFirst",
                 extra_params={"assignIf": {"notIn": ["ClusterFirst"]}})
    obj4 = pod()
    assert m2.mutate_obj(obj4)


def test_assign_cannot_touch_metadata():
    with pytest.raises(MutatorError):
        _assign("metadata.labels.x", "y")


def test_path_tests():
    m = _assign(
        "spec.securityContext.runAsNonRoot", True,
        extra_params={"pathTests": [
            {"subPath": "spec.securityContext", "condition": "MustExist"}
        ]},
    )
    obj = pod()
    assert not m.mutate_obj(obj)  # securityContext missing -> no-op
    obj2 = pod(securityContext={})
    assert m.mutate_obj(obj2)
    assert obj2["spec"]["securityContext"]["runAsNonRoot"] is True


def test_assign_metadata_never_overwrites():
    m = from_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1beta1",
        "kind": "AssignMetadata",
        "metadata": {"name": "owner"},
        "spec": {"location": "metadata.labels.owner",
                 "parameters": {"assign": {"value": "admin"}}},
    })
    obj = pod()
    assert m.mutate_obj(obj)
    assert obj["metadata"]["labels"]["owner"] == "admin"
    obj2 = pod()
    obj2["metadata"]["labels"] = {"owner": "someone"}
    assert not m.mutate_obj(obj2)
    assert obj2["metadata"]["labels"]["owner"] == "someone"


def test_modify_set_merge_prune():
    base = {
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "ModifySet",
        "metadata": {"name": "args"},
        "spec": {
            "applyTo": [{"groups": [""], "versions": ["v1"],
                         "kinds": ["Pod"]}],
            "location": "spec.containers[name: *].args",
            "parameters": {"values": {"fromList": ["--verbose"]}},
        },
    }
    m = from_unstructured(base)
    obj = pod(containers=[{"name": "a", "args": ["--x"]}, {"name": "b"}])
    assert m.mutate_obj(obj)
    assert obj["spec"]["containers"][0]["args"] == ["--x", "--verbose"]
    assert obj["spec"]["containers"][1]["args"] == ["--verbose"]
    assert not m.mutate_obj(obj)
    import copy

    prune = copy.deepcopy(base)
    prune["spec"]["parameters"]["operation"] = "prune"
    mp = from_unstructured(prune)
    assert mp.mutate_obj(obj)
    assert obj["spec"]["containers"][0]["args"] == ["--x"]


def test_split_image():
    assert split_image("nginx") == ("", "nginx", "")
    assert split_image("nginx:1.14") == ("", "nginx", ":1.14")
    assert split_image("library/nginx") == ("", "library/nginx", "")
    assert split_image("docker.io/library/nginx:v1") == (
        "docker.io", "library/nginx", ":v1")
    assert split_image("localhost:5000/img@sha256:abc") == (
        "localhost:5000", "img", "@sha256:abc")


def test_assign_image():
    m = from_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1alpha1",
        "kind": "AssignImage",
        "metadata": {"name": "img"},
        "spec": {
            "applyTo": [{"groups": [""], "versions": ["v1"],
                         "kinds": ["Pod"]}],
            "location": "spec.containers[name:*].image",
            "parameters": {"assignDomain": "registry.corp", "assignTag": ":v2"},
        },
    })
    obj = pod(containers=[{"name": "a", "image": "nginx:1.14"}])
    assert m.mutate_obj(obj)
    assert obj["spec"]["containers"][0]["image"] == "registry.corp/nginx:v2"


# --- system ---------------------------------------------------------------


def test_system_fixed_point_and_order():
    s = MutationSystem()
    s.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign", "metadata": {"name": "b-second"},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": ["Pod"]}],
                 "location": "spec.a", "parameters": {"assign": {"value": 1}}},
    })
    s.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign", "metadata": {"name": "a-first"},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": ["Pod"]}],
                 "location": "spec.b", "parameters": {"assign": {"value": 2}}},
    })
    obj = pod()
    assert s.mutate(obj)
    assert obj["spec"] == {"a": 1, "b": 2}


def test_system_schema_conflict_disables_both():
    s = MutationSystem()
    s.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign", "metadata": {"name": "as-object"},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": ["Pod"]}],
                 "location": "spec.containers.x",
                 "parameters": {"assign": {"value": 1}}},
    })
    s.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign", "metadata": {"name": "as-list"},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": ["Pod"]}],
                 "location": "spec.containers[name: a].x",
                 "parameters": {"assign": {"value": 2}}},
    })
    assert len(s.conflicts()) == 2
    obj = pod()
    assert not s.mutate(obj)  # both disabled
    s.remove(list(s.conflicts())[0])
    # hmm: removal by id; conflicts recompute
    assert len(s.conflicts()) == 0


# --- expansion golden fixtures -------------------------------------------


def _expand_fixture(name):
    objs = reader.read_sources([os.path.join(FIXTURES, name, "input")])
    expander = Expander(objs)
    out = []
    for obj in objs:
        out.extend(expander.expand(obj))
    return [r.obj for r in out]


def _golden(name):
    path = os.path.join(FIXTURES, name, "output", "output.yaml")
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


@pytest.mark.parametrize("name", [
    "basic-expansion",
    "basic-expansion-nonmatching-configs",
    "expand-cr",
    "expand-with-ns",
])
def test_expand_fixture_golden(name):
    got = _expand_fixture(name)
    want = _golden(name)
    for doc in want:
        assert doc in got, (
            f"{name}: expected resultant missing.\nWANT: {doc}\nGOT: {got}"
        )


def test_expand_missing_ns_no_error():
    # reference bats: exit 0, no output assertions (empty golden)
    got = _expand_fixture("expand-with-missing-ns")
    assert isinstance(got, list)


def test_external_data_prefetch_overlaps_providers():
    """Multiple providers' fetches overlap (async batch join): two slow
    providers resolve in ~one RTT, not two, and per-key values land
    correctly."""
    import threading
    import time as _time

    from gatekeeper_tpu.externaldata.providers import (
        Provider,
        ProviderCache,
    )

    calls = []

    def slow_send(provider, keys):
        calls.append((provider.name, tuple(keys)))
        _time.sleep(0.3)
        return {"response": {"items": [
            {"key": k, "value": f"{provider.name}:{k}"} for k in keys]}}

    cache = ProviderCache(send_fn=slow_send)
    for name in ("p1", "p2"):
        cache.upsert(Provider(name=name, url=f"https://{name}/v1"))

    t0 = _time.perf_counter()
    cache.prefetch([("p1", "a"), ("p2", "b"), ("p1", "c")])
    elapsed = _time.perf_counter() - t0
    assert elapsed < 0.55, f"providers fetched serially ({elapsed:.2f}s)"
    assert len(calls) == 2  # one batched call per provider
    # resolves are now cache hits
    n_calls = len(calls)
    assert cache.fetch("p1", ["a"])["a"] == ("p1:a", None)
    assert cache.fetch("p2", ["b"])["b"] == ("p2:b", None)
    assert len(calls) == n_calls
