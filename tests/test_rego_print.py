"""Rego print() builtin: hook capture, undefined-arg tolerance, and the
gator verify wiring (reference: PrintEnabled/PrintHook in the verify
runner, SURVEY.md §2.8)."""

import os
import textwrap

import yaml

from gatekeeper_tpu.gator.verify import print_result, run_suite
from gatekeeper_tpu.lang.rego import builtins as rego_builtins

TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8sprintprobe"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sPrintProbe"}}},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "rego": textwrap.dedent("""
                package k8sprintprobe
                violation[{"msg": msg}] {
                  print("inspecting", input.review.object.metadata.name)
                  print("labels:", input.review.object.metadata.labels)
                  print("absent:", input.review.object.metadata.annotations.missing)
                  not input.review.object.metadata.labels.owner
                  msg := "missing owner label"
                }
            """),
        }],
    },
}

CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sPrintProbe",
    "metadata": {"name": "need-owner"},
    "spec": {},
}

BAD_POD = {
    "apiVersion": "v1", "kind": "Pod",
    "metadata": {"name": "orphan", "namespace": "default",
                 "labels": {"app": "x"}},
}


def _write_suite(tmp_path):
    def dump(name, obj):
        p = os.path.join(tmp_path, name)
        with open(p, "w") as f:
            yaml.safe_dump(obj, f)
        return name

    suite = {
        "apiVersion": "test.gatekeeper.sh/v1alpha1",
        "kind": "Suite",
        "metadata": {"name": "print-suite"},
        "tests": [{
            "name": "print-probe",
            "template": dump("template.yaml", TEMPLATE),
            "constraint": dump("constraint.yaml", CONSTRAINT),
            "cases": [{
                "name": "missing-owner",
                "object": dump("bad.yaml", BAD_POD),
                "assertions": [{"violations": 1}],
            }],
        }],
    }
    path = os.path.join(tmp_path, "suite.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(suite, f)
    return path


def test_verify_captures_print_output(tmp_path):
    sr = run_suite(_write_suite(str(tmp_path)))
    assert not sr.failed(), [
        (t.name, t.error, [(c.name, c.error) for c in t.cases])
        for t in sr.tests]
    case = sr.tests[0].cases[0]
    assert "inspecting orphan" in case.prints
    # non-string args format as JSON; undefined args print <undefined>
    # instead of making the rule body undefined (the violation still fired)
    assert 'labels: {"app":"x"}' in case.prints
    assert "absent: <undefined>" in case.prints

    import io

    out = io.StringIO()
    print_result(sr, out=out)
    text = out.getvalue()
    assert "print: inspecting orphan" in text
    assert "--- PASS: print-probe/missing-owner" in text


def test_print_hook_is_context_scoped():
    """Without a hook, print() is a silent no-op that still succeeds;
    a hook reset stops capture (webhook threads never observe a verify
    run's hook — the contextvar scopes it)."""
    import contextvars

    captured = []
    tok = rego_builtins.set_print_hook(captured.append)
    try:
        rego_builtins.print_message(["direct"])
    finally:
        rego_builtins.reset_print_hook(tok)
    assert captured == ["direct"]

    # after the reset the context has no hook: drops silently
    rego_builtins.print_message(["dropped"])
    assert captured == ["direct"]

    # a copied context made while no hook is set never captures
    contextvars.copy_context().run(
        lambda: rego_builtins.print_message(["dropped-too"]))
    assert captured == ["direct"]
