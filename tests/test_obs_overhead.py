"""Tier-1 observability-overhead smoke (ISSUE 8 satellite): the full
stack (bucketed-histogram metrics + cost attribution + flight recorder
+ keep-all tracer) must cost < 3% on the serial 1-core path, webhook
and sweep alike.

Medians over interleaved bare/instrumented passes cancel drift; when
the host itself is too noisy to resolve 3% (bare-pass spread above the
guard), the assertion is skipped rather than turned into a coin flip —
the full bench (tools/bench_obs_overhead.py, BENCH_TPU.json history)
is the durable record."""

import importlib.util
import pathlib

import pytest

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"

OVERHEAD_BOUND_PCT = 3.0
# noise_spread_pct is a median-absolute-deviation measure (robust to
# one outlier pass); above this the median comparison itself is mush
NOISE_GUARD_PCT = 5.0


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_obs_overhead", _TOOLS / "bench_obs_overhead.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow  # tier-1 wall budget (PR 16): 45s best-of-3 timing
# probe; the on/off bit-identity pin stays in test_obs_integration.
def test_observability_overhead_under_bound():
    """Best-of-3 attempts: scheduler noise only ever INFLATES a measured
    overhead (the instrumented pass that catches a reschedule looks
    slower), so the least-noisy attempt is the honest upper-bound
    estimate — a real >3% regression fails all three."""
    bench = _load_bench()
    entries = []
    for _ in range(3):
        entry = bench.run(n_objects=140, passes=5, append=False)
        # sanity: both variants really ran (non-degenerate times)
        assert entry["webhook_bare_s"] > 0 and entry["sweep_bare_s"] > 0
        entries.append(entry)
        # min-of-passes overheads: scheduler noise strictly adds time,
        # so the fastest pass per variant is the cleanest comparison
        if entry["webhook_overhead_min_pct"] < OVERHEAD_BOUND_PCT and \
                entry["sweep_overhead_min_pct"] < OVERHEAD_BOUND_PCT \
                and entry["degradation_overhead_min_pct"] \
                < OVERHEAD_BOUND_PCT:
            return
    if all(e["noise_spread_pct"] > NOISE_GUARD_PCT for e in entries):
        pytest.skip(
            f"host too noisy to resolve {OVERHEAD_BOUND_PCT}% in 3 "
            f"attempts (spreads "
            f"{[e['noise_spread_pct'] for e in entries]}%); see "
            f"tools/bench_obs_overhead.py for the durable record")
    raise AssertionError(
        f"observability overhead above {OVERHEAD_BOUND_PCT}% in every "
        f"attempt: " + str([(e["webhook_overhead_min_pct"],
                             e["sweep_overhead_min_pct"],
                             e["degradation_overhead_min_pct"],
                             e["noise_spread_pct"]) for e in entries]))
