"""External-data failure semantics in the batched join lane (PR 11).

The lane rides ProviderCache.fetch, so the PR 2 semantics — per-key
errors, retry, breaker, stale-from-TTL fallback, brownout — must hold
PER KEY regardless of how keys are batched: partial provider responses,
chaos error/latency via the ``externaldata.send`` fault site, breaker-
tripped stale serving, and both mutation failurePolicies are pinned
identical (values + verdicts) between the batched and per-key lanes."""

import pytest

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.extdata import ExtDataLane, activate
from gatekeeper_tpu.externaldata.providers import Provider, ProviderCache
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.resilience.policy import RetryPolicy

from tests.test_extdata import (RULES_ERRORS, TARGET, CountingTransport,
                                result_key, reviews_of, tmpl)


def fast_retry():
    return RetryPolicy(attempts=2, base_s=0.001, cap_s=0.002,
                       dependency="externaldata")


def make_pair(**cache_kw):
    """(batched lane, perkey lane) over independent caches sharing one
    transport double, so cross-lane pins compare equal-footing state."""
    lanes = {}
    transports = {}
    for mode in ("batched", "perkey"):
        transport = CountingTransport()
        cache = ProviderCache(send_fn=transport, retry=fast_retry(),
                              **cache_kw)
        cache.upsert(Provider(name="trusted", url="https://t",
                              ca_bundle="x"))
        cache.upsert(Provider(name="digest", url="https://d",
                              ca_bundle="x"))
        lanes[mode] = ExtDataLane(cache, mode=mode)
        transports[mode] = transport
    return lanes, transports


def keyed_outcomes(lane, provider, keys):
    """(value, had_error) per key — error STRINGS may legitimately
    differ between lanes (a breaker opens at different call counts),
    the per-key outcome may not."""
    res = lane.resolve_keys(provider, keys)
    return {k: (v, bool(e)) for k, (v, e) in res.items()}


def driver_for(lane):
    tpu = TpuDriver(batch_bucket=8)
    tpu.extdata_lane = lane
    tpu.add_template(tmpl("K8sExtData", RULES_ERRORS))
    con = Constraint(kind="K8sExtData", name="x", match={}, parameters={},
                     enforcement_action="deny")
    tpu.add_constraint(con)
    return tpu, [con]


def pods():
    out = []
    for i in range(12):
        img = f"bad/i{i % 3}" if i % 4 == 0 else f"ok/i{i % 5}"
        out.append({"kind": "Pod", "metadata": {"name": f"p{i}"},
                    "spec": {"containers": [{"name": "c", "image": img}]}})
    return out


def _raw_results(lane, corpus):
    tpu, cons = driver_for(lane)
    _t, reviews = reviews_of(corpus)
    with activate(lane):
        got = tpu.query_batch(TARGET, cons, reviews)
    return [r.results for r in got]


def verdicts(lane, corpus):
    return [sorted(map(result_key, vs)) for vs in _raw_results(lane, corpus)]


# --- partial provider responses ------------------------------------------

def test_partial_response_surfaces_per_key_errors():
    lanes, transports = make_pair()
    lane = lanes["batched"]
    plan = FaultPlan([{"site": "externaldata.send", "mode": "partial",
                       "fraction": 0.5, "times": 1}])
    keys = [f"k{i}" for i in range(8)]
    with inject(plan):
        with activate(lane):
            res = lane.resolve_keys("trusted", keys)
    returned = [k for k, (v, e) in res.items() if e is None]
    dropped = [k for k, (v, e) in res.items() if e]
    assert len(returned) == 4 and len(dropped) == 4
    for k in dropped:
        assert "key not returned" in res[k][1]
    # the dropped keys are resident AS errors (negative caching, same as
    # the transport cache) until TTL; a later batch refetches nothing new
    calls = transports["batched"].calls
    with activate(lane):
        lane.resolve_keys("trusted", keys)
    assert transports["batched"].calls == calls


def test_partial_response_errors_flow_into_verdicts():
    lanes, _tr = make_pair()
    lane = lanes["batched"]
    corpus = pods()
    plan = FaultPlan([{"site": "externaldata.send", "mode": "partial",
                       "fraction": 0.0, "times": 1}])
    with inject(plan):
        got = verdicts(lane, corpus)
    # NO key returned: every pod with a present image key violates
    assert all(v for v in got)


# --- chaos error / latency via externaldata.send --------------------------

def test_chaos_error_identical_outcomes_across_lanes():
    lanes, _tr = make_pair()
    corpus = pods()
    keys = sorted({c["spec"]["containers"][0]["image"] for c in corpus})
    plan = FaultPlan([{"site": "externaldata.send", "mode": "error",
                       "error": "provider exploded"}])
    out = {}
    for mode, lane in lanes.items():
        with inject(plan):
            with activate(lane):
                out[mode] = keyed_outcomes(lane, "trusted", keys)
            # verdict SETS must agree; the rendered message embeds the
            # per-key error string, which legitimately reads "breaker
            # open" vs the transport error depending on each lane's own
            # call history — compare violations, not prose
            out[mode + ":verdicts"] = [
                sorted((r.constraint or {}).get("kind", "")
                       for r in vs)
                for vs in _raw_results(lane, corpus)]
    # nothing cached + failing transport: every key errors, both lanes
    assert out["batched"] == out["perkey"]
    assert all(had_err for _v, had_err in out["batched"].values())
    assert out["batched:verdicts"] == out["perkey:verdicts"]
    assert all(v for v in out["batched:verdicts"])


def test_chaos_latency_keeps_lanes_identical():
    lanes, _tr = make_pair()
    corpus = pods()
    plan = FaultPlan([{"site": "externaldata.send", "mode": "sleep",
                       "delay_s": 0.01}])
    out = {}
    for mode, lane in lanes.items():
        with inject(plan):
            out[mode] = verdicts(lane, corpus)
    assert out["batched"] == out["perkey"]
    assert any(v for v in out["batched"])  # bad/* keys still violate
    assert not all(v for v in out["batched"])  # ok/* keys resolve clean


# --- breaker-tripped stale serving ---------------------------------------

def test_breaker_tripped_serves_stale_identically():
    lanes, _tr = make_pair(response_ttl_s=0.0)
    corpus = pods()
    keys = sorted({c["spec"]["containers"][0]["image"] for c in corpus})
    out = {}
    for mode, lane in lanes.items():
        lane.column_ttl_s = 0.0  # every batch re-ensures through fetch
        for col in [lane.column("trusted")]:
            col.ttl_s = 0.0
        with activate(lane):
            clean = keyed_outcomes(lane, "trusted", keys)  # warm cache
        plan = FaultPlan([{"site": "externaldata.send", "mode": "error",
                           "error": "down"}])
        with inject(plan):
            # trip the breaker (threshold 3), then the stale fallback
            # serves every key its last good value with NO error
            for _ in range(4):
                with activate(lane):
                    stale = keyed_outcomes(lane, "trusted", keys)
            with activate(lane):
                out[mode] = (clean, keyed_outcomes(lane, "trusted", keys))
        assert stale == clean, mode  # stale values == last good values
        breaker = lane.cache._breaker("trusted")
        assert not breaker.allow() or breaker.state != "closed"
    assert out["batched"] == out["perkey"]


# --- both failurePolicies on the mutation side ----------------------------

def _mutator(policy):
    return {
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign",
        "metadata": {"name": f"pin-{policy.lower()}"},
        "spec": {
            "applyTo": [{"groups": [""], "versions": ["v1"],
                         "kinds": ["Pod"]}],
            "location": "spec.containers[name:*].image",
            "parameters": {"assign": {"externalData": {
                "provider": "digest",
                "dataSource": "ValueAtLocation",
                "failurePolicy": policy,
                "default": "fallback:latest"}}},
        },
    }


@pytest.mark.parametrize("policy,expect",
                         [("Ignore", "repo/a"),
                          ("UseDefault", "fallback:latest")])
def test_failure_policy_identical_across_lanes(policy, expect):
    from gatekeeper_tpu.mutation.system import MutationSystem

    plan = FaultPlan([{"site": "externaldata.send", "mode": "error",
                       "error": "down"}])
    results = {}
    for mode in ("batched", "perkey"):
        transport = CountingTransport()
        cache = ProviderCache(send_fn=transport, retry=fast_retry())
        cache.upsert(Provider(name="digest", url="https://d",
                              ca_bundle="x"))
        lane = ExtDataLane(cache, mode=mode)
        sys_ = MutationSystem(provider_cache=cache)
        sys_.upsert_unstructured(_mutator(policy))
        obj = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "m"},
               "spec": {"containers": [{"name": "a", "image": "repo/a"}]}}
        with inject(plan):
            with activate(lane):
                sys_.mutate(obj)
        results[mode] = obj["spec"]["containers"][0]["image"]
    assert results["batched"] == results["perkey"] == expect


def test_failure_policy_fail_raises_identically():
    from gatekeeper_tpu.externaldata.providers import ProviderError
    from gatekeeper_tpu.mutation.system import MutationSystem

    plan = FaultPlan([{"site": "externaldata.send", "mode": "error",
                       "error": "down"}])
    for mode in ("batched", "perkey"):
        transport = CountingTransport()
        cache = ProviderCache(send_fn=transport, retry=fast_retry())
        cache.upsert(Provider(name="digest", url="https://d",
                              ca_bundle="x"))
        lane = ExtDataLane(cache, mode=mode)
        sys_ = MutationSystem(provider_cache=cache)
        sys_.upsert_unstructured(_mutator("Fail"))
        obj = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "m"},
               "spec": {"containers": [{"name": "a", "image": "repo/a"}]}}
        with inject(plan):
            with activate(lane):
                with pytest.raises(ProviderError):
                    sys_.mutate(obj)


# --- brownout: the overload ladder degrades the join, never sheds it ------

def test_brownout_serves_stale_without_transport():
    from gatekeeper_tpu.resilience import overload as ovl

    transport = CountingTransport()
    cache = ProviderCache(send_fn=transport, retry=fast_retry(),
                          response_ttl_s=0.0)
    cache.upsert(Provider(name="trusted", url="https://t", ca_bundle="x"))
    lane = ExtDataLane(cache, mode="batched", column_ttl_s=0.0)
    with activate(lane):
        clean = lane.resolve_keys("trusted", ["a", "b"])
        calls = transport.calls
        ctl = ovl.OverloadController(ovl.OverloadConfig())
        with ovl.activate(ctl):
            ctl._brownout = 1
            browned = lane.resolve_keys("trusted", ["a", "b"])
    assert transport.calls == calls  # zero transport under brownout
    assert browned == clean  # stale-from-cache, no errors


# --- response-schema validation at the ingest boundary --------------------

def test_response_schema_gate_unit():
    """Only well-formed ``key -> (json value, error-or-None)`` entries
    land clean; everything else degrades to the per-key malformed
    error, and non-str keys (nothing requested them) drop."""
    from gatekeeper_tpu.extdata.lane import _MALFORMED, validate_landed

    clean, bad = validate_landed({
        "ok": ("v", None),
        "ok-err": (None, "boom"),
        "ok-nested": ({"a": [1, None]}, None),
        "wrong-arity": ("v",),
        "wrong-value": (object(), None),
        "wrong-error": ("v", 7),
        "not-a-pair": "v",
        3: ("v", None),
    })
    assert bad == 5
    assert clean["ok"] == ("v", None)
    assert clean["ok-err"] == (None, "boom")
    assert clean["ok-nested"] == ({"a": [1, None]}, None)
    for k in ("wrong-arity", "wrong-value", "wrong-error", "not-a-pair"):
        assert clean[k] == (None, _MALFORMED)
    assert 3 not in clean


def test_malformed_provider_response_degrades_per_key():
    """A rogue transport smuggling schema-breaking entries through the
    bulk fetch: the good key lands, each malformed key becomes the
    pinned per-key error semantics (counted, resident, no crash), and
    the poisoned entries never reach the column as values."""
    from gatekeeper_tpu.extdata.lane import _MALFORMED
    from gatekeeper_tpu.metrics.registry import (EXTDATA_KEYS,
                                                 MetricsRegistry)

    lanes, _tr = make_pair()
    lane = lanes["batched"]
    lane.metrics = MetricsRegistry()
    orig = lane.cache.fetch

    def rogue(provider, keys):
        res = dict(orig(provider, keys))
        if "k1" in res:
            res["k1"] = "not a pair"
        if "k2" in res:
            res["k2"] = ("v", 123)
        return res

    lane.cache.fetch = rogue
    with activate(lane):
        res = lane.resolve_keys("trusted", ["k0", "k1", "k2"])
    assert res["k0"] == ("k0", None)
    assert res["k1"] == (None, _MALFORMED)
    assert res["k2"] == (None, _MALFORMED)
    assert lane.metrics.get_counter(
        EXTDATA_KEYS, {"provider": "trusted", "outcome": "malformed"}) == 2
    # malformed entries are resident AS errors: the next resolve is
    # answered from the column, no refetch storm
    calls = [0]

    def counting(provider, keys):
        calls[0] += 1
        return rogue(provider, keys)

    lane.cache.fetch = counting
    with activate(lane):
        again = lane.resolve_keys("trusted", ["k0", "k1", "k2"])
    assert again == res and calls[0] == 0
