"""Evaluate-sidecar seam tests: the control plane evaluates only through
gRPC (SURVEY.md §7 "only Driver.Query crosses the boundary"); verdicts,
messages and audit results must be identical to the in-process driver."""

import json
import os

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.remote import RemoteDriver, RemoteEvaluator
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.rpc.sidecar import serve
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects

LIB = os.path.join(os.path.dirname(__file__), "..", "library")


@pytest.fixture(scope="module")
def sidecar():
    server, port, servicer = serve(port=0, violations_limit=20)
    yield f"127.0.0.1:{port}", servicer
    server.stop(grace=1)


def _remote_client(address):
    remote = RemoteDriver(address)
    client = Client(target=K8sValidationTarget(),
                    drivers=[remote, CELDriver()],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    return client, remote


def _local_client():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    return client, tpu


def test_remote_driver_review_parity(sidecar):
    address, _svc = sidecar
    rc, remote = _remote_client(address)
    lc, _tpu = _local_client()
    load_library(rc)
    load_library(lc)
    assert remote.fallback_kinds() == {}
    assert len(remote.lowered_kinds()) >= 40  # full shipped library

    objects = make_cluster_objects(120, seed=17)
    for o in objects:
        if o.get("kind") == "Ingress":
            rc.add_data(o)
            lc.add_data(o)
    for o in objects[:60]:
        aug = AugmentedUnstructured(object=o, source=SOURCE_ORIGINAL)
        rr = rc.review(aug, enforcement_point=AUDIT_EP)
        lr = lc.review(aug, enforcement_point=AUDIT_EP)
        key = lambda r: ((r.constraint.get("metadata") or {})
                         .get("name", ""), r.msg)
        assert sorted(map(key, rr.results())) == \
            sorted(map(key, lr.results())), o.get("metadata")


def test_remote_audit_sweep_parity(sidecar):
    address, _svc = sidecar
    rc, remote = _remote_client(address)
    lc, ltpu = _local_client()
    load_library(rc)
    load_library(lc)
    remote.wipe_data()  # the module-scoped sidecar keeps prior tests' data
    objects = make_cluster_objects(300, seed=23)
    for o in objects:
        if o.get("kind") == "Ingress":
            rc.add_data(o)
            lc.add_data(o)

    r_mgr = AuditManager(
        rc, lister=lambda: iter(objects),
        config=AuditConfig(chunk_size=128, exact_totals=False),
        evaluator=RemoteEvaluator(remote, violations_limit=20),
    )
    l_mgr = AuditManager(
        lc, lister=lambda: iter(objects),
        config=AuditConfig(chunk_size=128, exact_totals=False),
        evaluator=ShardedEvaluator(ltpu, make_mesh(), violations_limit=20),
    )
    r_run = r_mgr.audit()
    l_run = l_mgr.audit()
    assert r_run.total_objects == l_run.total_objects == 300
    assert r_run.total_violations == l_run.total_violations
    for k in l_run.kept:
        assert sorted(v.message for v in r_run.kept[k]) == \
            sorted(v.message for v in l_run.kept[k]), k


def test_remote_exact_totals(sidecar):
    """exact_totals through the sidecar must match the local exact path
    (the CEL noprivileged template yields ONE result per violating pod —
    its validation is size(badContainers)==0 — so totals count pods)."""
    address, _svc = sidecar
    rc, remote = _remote_client(address)
    lc, ltpu = _local_client()
    for c in (rc, lc):
        load_library(c, skip_kinds=("K8sUniqueIngressHost",))
    remote.wipe_data()
    pods = [{
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": f"p{i}"},
        "spec": {"containers": [
            {"name": "a", "image": "x", "securityContext":
                {"privileged": True}},
            {"name": "b", "image": "y", "securityContext":
                {"privileged": True}},
        ]},
    } for i in range(4)]
    r_mgr = AuditManager(
        rc, lister=lambda: iter(pods), config=AuditConfig(),
        evaluator=RemoteEvaluator(remote, violations_limit=20,
                                  exact_totals=True),
    )
    l_mgr = AuditManager(
        lc, lister=lambda: iter(pods),
        config=AuditConfig(exact_totals=True),
        evaluator=ShardedEvaluator(ltpu, make_mesh(), violations_limit=20),
    )
    r_run, l_run = r_mgr.audit(), l_mgr.audit()
    assert r_run.total_violations == l_run.total_violations
    key = ("K8sNoPrivileged", "no-privileged-containers")
    assert r_run.total_violations[key] == 4  # one result per violating pod


def test_sidecar_process_e2e(tmp_path):
    """Two real processes: device-owning sidecar + control plane running
    an audit through it (the reference's two-pod deployment shape)."""
    import socket
    import subprocess
    import sys
    import time

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    root = os.path.join(os.path.dirname(__file__), "..")
    side = subprocess.Popen(
        [sys.executable, "-m", "gatekeeper_tpu.rpc.sidecar",
         "--port", str(port)],
        env=env, cwd=root, stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            line = side.stderr.readline()
            if "serving on" in line:
                break
        else:
            pytest.fail("sidecar never came up")
        mani = tmp_path / "m"
        mani.mkdir()
        for name in ("noprivileged", "containerlimitscel"):
            src = os.path.join(LIB, "general", name)
            (mani / f"{name}-t.yaml").write_text(
                open(os.path.join(src, "template.yaml")).read())
            (mani / f"{name}-c.yaml").write_text(
                open(os.path.join(src, "samples", "constraint.yaml"))
                .read())
        (mani / "bad.yaml").write_text(json.dumps({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "privpod"},
            "spec": {"containers": [{
                "name": "c", "image": "x",
                "securityContext": {"privileged": True}}]},
        }))
        out = subprocess.run(
            [sys.executable, "-m", "gatekeeper_tpu",
             "--manifests", str(mani),
             "--evaluate-sidecar", f"127.0.0.1:{port}", "--once"],
            env=env, cwd=root, capture_output=True, text=True,
            timeout=180)
        assert "Privileged container is not allowed" in out.stdout, (
            out.stdout, out.stderr[-2000:])
        assert "memory limit" in out.stdout
    finally:
        side.terminate()
        side.wait(timeout=10)


def test_concurrent_sweeps_pipeline_and_agree(sidecar):
    """Round-3 de-serialization: the Sweep handler holds the lock only
    through flatten+submit; device waits overlap.  Four threads sweeping
    concurrently must each get results identical to a serial sweep of
    the same chunk (correctness under contention), and the concurrent
    wall-clock must not exceed the serial wall-clock by more than a
    small factor (the old one-lock design serialized fully)."""
    import threading
    import time

    address, _svc = sidecar
    rc, remote = _remote_client(address)
    load_library(rc)
    remote.wipe_data()
    ev = RemoteEvaluator(remote, violations_limit=20)
    cons = [c for c in rc.constraints()]

    chunks = [make_cluster_objects(200, seed=100 + i) for i in range(4)]

    # serial reference pass (also warms vocab + jit for both lanes)
    serial = []
    t0 = time.perf_counter()
    for ch in chunks:
        serial.append(ev.sweep(cons, ch))
    serial_s = time.perf_counter() - t0

    results = [None] * 4
    errors = []

    # instrument the pipelining claim directly: with the split lock, one
    # RPC's flatten+submit (lock-held) runs WHILE another RPC waits on
    # the device in sweep_collect (unlocked).  Record both spans per
    # server thread; a cross-call submit/collect overlap proves the
    # split — under the old one-lock design every span is mutually
    # exclusive, so no overlap can ever be observed.  The sleep widens
    # the wait window so scheduling jitter can't mask genuine overlap.
    orig_submit = _svc.evaluator.sweep_submit
    orig_collect = _svc.evaluator.sweep_collect
    spans = []  # (phase, server-thread id, t0, t1)
    spans_lock = threading.Lock()

    def timed(phase, orig):
        def wrapper(*a, **k):
            if phase == "collect":
                time.sleep(0.05)
            t0 = time.perf_counter()
            try:
                return orig(*a, **k)
            finally:
                with spans_lock:
                    spans.append((phase, threading.get_ident(), t0,
                                  time.perf_counter()))
        return wrapper

    _svc.evaluator.sweep_submit = timed("submit", orig_submit)
    _svc.evaluator.sweep_collect = timed("collect", orig_collect)

    def run(i):
        try:
            results[i] = ev.sweep(cons, chunks[i])
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    concurrent_s = time.perf_counter() - t0
    _svc.evaluator.sweep_submit = orig_submit
    _svc.evaluator.sweep_collect = orig_collect
    assert not errors, errors
    # the collect wrapper's sleep sits BEFORE its span, widening the
    # window in which another thread's submit can land
    submits = [s for s in spans if s[0] == "submit"]
    collects = [s for s in spans if s[0] == "collect"]
    overlapped = any(
        st != ct and s0 < c1 and c0 < s1
        for _, st, s0, s1 in submits
        for _, ct, c0, c1 in collects)
    pre_waits = [(ct, c0 - 0.05, c1) for _, ct, c0, c1 in collects]
    overlapped = overlapped or any(
        st != ct and s0 < c1 and c0 < s1
        for _, st, s0, s1 in submits
        for ct, c0, c1 in pre_waits)
    assert overlapped, (
        "no cross-call submit/collect overlap: sweeps serialized\n"
        + "\n".join(map(str, spans)))

    def fold(swept):
        # RemoteEvaluator.sweep returns {(kind, name): (total, kept)}
        return {k: (total, sorted(oi for oi, _m, _d in kept))
                for k, (total, kept) in swept.items()}

    for i in range(4):
        assert fold(results[i]) == fold(serial[i]), f"chunk {i} diverged"
    # not a benchmark: just catch a regression to full serialization
    # (warm serial pass vs concurrent pass of identical work)
    assert concurrent_s < serial_s * 2.0, (concurrent_s, serial_s)
