import pytest

from gatekeeper_tpu.apis import Constraint, ConstraintTemplate
from gatekeeper_tpu.apis.constraints import ConstraintError, GATOR_EP, WEBHOOK_EP
from gatekeeper_tpu.apis.templates import ENGINE_REGO, TemplateError
from gatekeeper_tpu.utils.unstructured import load_yaml_file

DEMO = "/root/reference/demo/basic/templates/k8srequiredlabels_template.yaml"


def test_template_from_demo_yaml():
    obj = load_yaml_file(DEMO)[0]
    ct = ConstraintTemplate.from_unstructured(obj)
    assert ct.name == "k8srequiredlabels"
    assert ct.kind == "K8sRequiredLabels"
    src = ct.targets[0].source_for(ENGINE_REGO)
    assert "violation[{" in src["rego"]
    crd = ct.constraint_crd()
    assert crd["spec"]["names"]["kind"] == "K8sRequiredLabels"


def test_template_name_kind_mismatch():
    obj = {
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "wrongname"},
        "spec": {"crd": {"spec": {"names": {"kind": "K8sFoo"}}},
                 "targets": [{"target": "t", "rego": "package x"}]},
    }
    with pytest.raises(TemplateError):
        ConstraintTemplate.from_unstructured(obj)


def _constraint(action="deny", scoped=None):
    spec = {"match": {}, "parameters": {"labels": ["owner"]}}
    if action is not None:
        spec["enforcementAction"] = action
    if scoped is not None:
        spec["scopedEnforcementActions"] = scoped
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "must-have-owner"},
        "spec": spec,
    }


def test_constraint_parse_and_actions():
    c = Constraint.from_unstructured(_constraint())
    assert c.actions_for(WEBHOOK_EP) == ["deny"]
    c2 = Constraint.from_unstructured(
        _constraint(
            action="scoped",
            scoped=[
                {"action": "warn", "enforcementPoints": [{"name": WEBHOOK_EP}]},
                {"action": "deny", "enforcementPoints": [{"name": "*"}]},
            ],
        )
    )
    assert c2.actions_for(WEBHOOK_EP) == ["warn", "deny"]
    assert c2.actions_for(GATOR_EP) == ["deny"]


def test_constraint_scoped_validation():
    with pytest.raises(ConstraintError):
        Constraint.from_unstructured(_constraint(action="scoped"))
    with pytest.raises(ConstraintError):
        Constraint.from_unstructured(
            _constraint(action="deny", scoped=[{"action": "warn"}])
        )
