"""Graceful drain (ISSUE 5): zero-loss shutdown of the serving path.

Pins:
- the drain state machine (serving -> draining -> stopped);
- /healthz answers 503 {"draining": true} once drain starts;
- ``--webhook-backlog`` sizes the kernel accept queue;
- Batcher.stop drains its queue (reviews queued at stop time get their
  verdicts — the old stop dropped them);
- server.stop drains in-flight handlers + the batcher within the budget:
  every ACCEPTED admission is ANSWERED (counted by uid);
- SIGTERM on a real ``python -m gatekeeper_tpu`` process mid-burst exits
  cleanly within --drain-timeout (slow lane).
"""

import http.client
import json
import threading
import time

import pytest

from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.resilience import overload as ovl
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
from gatekeeper_tpu.webhook.server import WebhookServer


class _EmptyResponses:
    stats_entries: list = []

    def results(self):
        return []


class _SlowClient:
    drivers: list = []

    def __init__(self, service_s=0.05):
        self.service_s = service_s
        self.reviews = 0
        self._lock = threading.Lock()

    def constraints(self):
        return []

    def review(self, augmented, **kw):
        time.sleep(self.service_s)
        with self._lock:
            self.reviews += 1
        return _EmptyResponses()


def _review_body(uid):
    return {
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": uid, "operation": "CREATE",
                    "kind": {"group": "", "version": "v1", "kind": "Pod"},
                    "userInfo": {"username": "drain"},
                    "object": {"apiVersion": "v1", "kind": "Pod",
                               "metadata": {"name": uid}}},
    }


# --- drain state machine ---------------------------------------------------

def test_drain_coordinator_state_machine():
    reg = MetricsRegistry()
    clock = [100.0]
    d = ovl.DrainCoordinator(metrics=reg, clock=lambda: clock[0])
    assert d.state == ovl.SERVING
    assert not d.draining
    assert d.begin("SIGTERM") is True
    assert d.state == ovl.DRAINING and d.draining
    assert d.begin("SIGTERM again") is False  # first caller wins
    clock[0] = 102.5
    dt = d.finish()
    assert d.state == ovl.STOPPED
    assert dt == pytest.approx(2.5)
    assert reg.get_gauge(M.DRAIN_SECONDS) == pytest.approx(2.5)
    assert d.finish() == pytest.approx(2.5)  # idempotent
    assert d.wait_stopped(0.1)


def test_healthz_draining_503():
    srv = WebhookServer(validation_handler=None, port=0,
                        readiness_check=lambda: True).start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=5)
        c.request("GET", "/healthz")
        r = c.getresponse()
        assert r.status == 200
        assert json.loads(r.read())["ready"] is True
        c.close()
        srv.begin_drain()
        assert srv.draining
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=5)
        c.request("GET", "/healthz")
        r = c.getresponse()
        doc = json.loads(r.read())
        assert r.status == 503
        assert doc == {"ready": False, "draining": True}
        # draining replies retire their connections (LB reconnects
        # elsewhere)
        assert r.getheader("Connection") == "close"
        c.close()
    finally:
        srv.stop(drain_timeout=2)


def test_webhook_backlog_configurable():
    srv = WebhookServer(validation_handler=None, port=0, backlog=7)
    try:
        assert srv._server.request_queue_size == 7
    finally:
        srv._server.server_close()
    # the default stays at the measured burst-absorbing 128
    srv2 = WebhookServer(validation_handler=None, port=0)
    try:
        assert srv2._server.request_queue_size == 128
    finally:
        srv2._server.server_close()


# --- batcher drain (satellite: queued reviews must not drop) ---------------

def test_batcher_stop_drains_queued_reviews():
    """Reviews sitting in the batcher queue when stop() is called still
    get their verdicts — nothing is silently dropped."""
    client = _SlowClient(service_s=0.05)
    b = Batcher(client, small_batch=64).start()
    results: dict = {}
    errors: dict = {}

    def one(i):
        aug = AugmentedUnstructured(
            object={"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"p{i}"}})
        try:
            results[i] = b.review(aug)
        except Exception as e:
            errors[i] = e

    threads = [threading.Thread(target=one, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    time.sleep(0.02)  # most entries still queued behind the slow lane
    drained = b.stop(timeout=10)
    for t in threads:
        t.join(10)
    assert drained
    assert errors == {}
    assert len(results) == 12  # every queued review answered
    assert b.queue_depth() == 0


def test_batcher_stop_idempotent():
    b = Batcher(_SlowClient(service_s=0.0)).start()
    assert b.stop()
    assert b.stop()  # second stop is a no-op, not an error


# --- the acceptance drain: accepted == answered ---------------------------

def test_server_stop_mid_burst_answers_every_accepted_request():
    """SIGTERM-equivalent mid-burst (ISSUE acceptance): begin_drain +
    stop() while a burst is in flight — every request the server ACCEPTED
    (entered the handler) is ANSWERED with its own uid, in-flight and
    batcher-queued reviews included, within the drain budget."""
    client = _SlowClient(service_s=0.08)
    reg = MetricsRegistry()
    batcher = Batcher(client, small_batch=64, metrics=reg).start()
    accepted: list = []
    accept_lock = threading.Lock()

    handler = ValidationHandler(client, batcher=batcher, metrics=reg)
    inner_handle = handler.handle

    def tracking_handle(body, cost_hint=0):
        with accept_lock:
            accepted.append(body["request"]["uid"])
        return inner_handle(body, cost_hint=cost_hint)

    handler.handle = tracking_handle
    srv = WebhookServer(validation_handler=handler, port=0, metrics=reg,
                        batcher=batcher).start()

    answered: dict = {}
    failures: list = []
    lock = threading.Lock()

    def post(i):
        uid = f"burst-{i}"
        try:
            c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                           timeout=20)
            c.request("POST", "/v1/admit",
                      json.dumps(_review_body(uid)).encode(),
                      {"Content-Type": "application/json"})
            doc = json.loads(c.getresponse().read())
            with lock:
                answered[uid] = doc["response"]
            c.close()
        except Exception as e:
            # refused/reset connects are requests the server never
            # accepted — allowed during shutdown, but an accepted uid
            # must never land here (asserted below)
            with lock:
                failures.append((uid, e))

    threads = [threading.Thread(target=post, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    time.sleep(0.1)  # burst in flight: handlers busy + batcher queued
    t0 = time.perf_counter()
    drained = srv.stop(drain_timeout=15)
    drain_wall = time.perf_counter() - t0
    for t in threads:
        t.join(20)

    assert drained, "drain must complete inside the budget"
    assert drain_wall < 15
    with accept_lock:
        accepted_set = set(accepted)
    assert accepted_set, "the burst must have been accepted"
    answered_set = set(answered)
    # the zero-loss pin: every ACCEPTED admission was ANSWERED
    lost = accepted_set - answered_set
    assert lost == set(), f"accepted but never answered: {sorted(lost)}"
    for uid in accepted_set:
        assert answered[uid]["uid"] == uid
        assert answered[uid]["allowed"] is True
    failed_uids = {u for u, _ in failures}
    assert failed_uids & accepted_set == set()
    assert batcher.queue_depth() == 0
    assert reg.get_gauge(M.DRAIN_SECONDS) is not None
    assert reg.get_gauge(M.WEBHOOK_INFLIGHT) == 0


def test_chaos_burst_sigterm_zero_loss_with_overload():
    """The full composition: chaos-slowed reviews + overload limiter +
    drain mid-burst.  Sheds answer immediately (they are verdicts too);
    every accepted uid is answered; nothing is lost."""
    from gatekeeper_tpu.resilience.faults import FaultPlan, inject

    client = _SlowClient(service_s=0.0)
    reg = MetricsRegistry()
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        min_inflight=2, max_inflight=2, initial_inflight=2,
        queue_depth=4, queue_timeout_s=0.3), metrics=reg)
    handler = ValidationHandler(client, metrics=reg,
                                failure_policy="fail", overload=ctl)
    srv = WebhookServer(validation_handler=handler, port=0,
                        metrics=reg).start()
    plan = FaultPlan([{"site": "webhook.review", "mode": "sleep",
                       "delay_s": 0.1}])
    answered: dict = {}
    failures: list = []
    lock = threading.Lock()

    def post(i):
        uid = f"chaos-{i}"
        try:
            c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                           timeout=20)
            c.request("POST", "/v1/admit",
                      json.dumps(_review_body(uid)).encode(),
                      {"Content-Type": "application/json"})
            doc = json.loads(c.getresponse().read())
            with lock:
                answered[uid] = doc["response"]
            c.close()
        except Exception as e:
            with lock:
                failures.append((uid, e))

    with inject(plan):
        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        time.sleep(0.05)  # mid-burst
        drained = srv.stop(drain_timeout=10)
        for t in threads:
            t.join(20)

    assert drained
    # every request that reached the server got a verdict bearing its uid
    for uid, resp in answered.items():
        assert resp["uid"] == uid
        # shed (429) or reviewed (allow): both are valid verdicts
        assert resp["allowed"] is True or \
            resp.get("status", {}).get("code") == 429
    assert len(answered) + len(failures) == 12
    assert reg.get_gauge(M.WEBHOOK_INFLIGHT) == 0


# --- the mutate endpoint shares the zero-loss drain ------------------------

def test_server_stop_mid_burst_answers_every_accepted_mutation():
    """SIGTERM-equivalent mid-burst on `/v1/mutate`: the mutation
    batcher is drained inside server.stop exactly like the validation
    batcher — every ACCEPTED mutate review is ANSWERED with its own uid
    and patch, in-flight and batcher-queued entries included."""
    import base64

    from gatekeeper_tpu.mutation.system import MutationSystem
    from gatekeeper_tpu.mutlane import (BatchedMutationHandler,
                                        MutationBatcher, MutationLane)
    from gatekeeper_tpu.resilience.faults import FaultPlan, inject

    system = MutationSystem()
    system.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1", "kind": "Assign",
        "metadata": {"name": "host-network"},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": ["Pod"]}],
                 "location": "spec.hostNetwork",
                 "parameters": {"assign": {"value": False}}},
    })
    reg = MetricsRegistry()
    lane = MutationLane(system, metrics=reg)
    # tiny batches + a chaos-slowed lane: the burst piles up queued
    # entries behind in-flight flushes, the drain must answer them all
    batcher = MutationBatcher(lane, max_batch=2, metrics=reg).start()
    handler = BatchedMutationHandler(system, lane=lane, batcher=batcher,
                                     metrics=reg)
    accepted: list = []
    accept_lock = threading.Lock()
    inner_handle = handler.handle

    def tracking_handle(body, cost_hint=0):
        with accept_lock:
            accepted.append(body["request"]["uid"])
        return inner_handle(body, cost_hint=cost_hint)

    handler.handle = tracking_handle
    srv = WebhookServer(mutation_handler=handler, port=0, metrics=reg,
                        mutation_batcher=batcher).start()

    answered: dict = {}
    failures: list = []
    lock = threading.Lock()

    def mutate_body(uid):
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {"uid": uid, "operation": "CREATE",
                        "kind": {"group": "", "version": "v1",
                                 "kind": "Pod"},
                        "userInfo": {"username": "drain"},
                        "object": {"apiVersion": "v1", "kind": "Pod",
                                   "metadata": {"name": uid},
                                   "spec": {}}},
        }

    def post(i):
        uid = f"mut-{i}"
        try:
            c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                           timeout=20)
            c.request("POST", "/v1/mutate",
                      json.dumps(mutate_body(uid)).encode(),
                      {"Content-Type": "application/json"})
            doc = json.loads(c.getresponse().read())
            with lock:
                answered[uid] = doc["response"]
            c.close()
        except Exception as e:
            with lock:
                failures.append((uid, e))

    plan = FaultPlan([{"site": "mutation.batch", "mode": "sleep",
                       "delay_s": 0.08}])
    with inject(plan):
        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(14)]
        for t in threads:
            t.start()
        time.sleep(0.1)  # mid-burst: flushes in flight + entries queued
        drained = srv.stop(drain_timeout=15)
        for t in threads:
            t.join(20)

    assert drained, "mutate drain must complete inside the budget"
    with accept_lock:
        accepted_set = set(accepted)
    assert accepted_set, "the mutate burst must have been accepted"
    lost = accepted_set - set(answered)
    assert lost == set(), f"accepted but never answered: {sorted(lost)}"
    for uid in accepted_set:
        resp = answered[uid]
        assert resp["uid"] == uid
        assert resp["allowed"] is True
        patch = json.loads(base64.b64decode(resp["patch"]))
        assert patch == [{"op": "add", "path": "/spec/hostNetwork",
                          "value": False}]
    assert {u for u, _ in failures} & accepted_set == set()
    assert batcher.queue_depth() == 0


# --- real-process SIGTERM (slow lane) --------------------------------------

@pytest.mark.slow
def test_sigterm_real_process_drains_within_budget(tmp_path):
    """python -m gatekeeper_tpu serving a burst takes a SIGTERM and exits
    0 within --drain-timeout + slack, answering what it accepted."""
    import os
    import signal
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "gatekeeper_tpu",
         "--operation", "webhook", "--port", str(port),
         "--drain-timeout", "8", "--audit-interval", "3600"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        deadline = time.time() + 180
        while time.time() < deadline:
            try:
                c = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=2)
                c.request("GET", "/healthz")
                c.getresponse().read()
                c.close()
                break
            except OSError:
                if proc.poll() is not None:
                    out, err = proc.communicate()
                    pytest.fail(f"server died during boot: {err[-2000:]}")
                time.sleep(1.0)
        else:
            pytest.fail("server never came up")

        answered: dict = {}
        lock = threading.Lock()

        def post(i):
            uid = f"sig-{i}"
            try:
                c = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=30)
                c.request("POST", "/v1/admit",
                          json.dumps(_review_body(uid)).encode(),
                          {"Content-Type": "application/json"})
                doc = json.loads(c.getresponse().read())
                with lock:
                    answered[uid] = doc["response"]["uid"]
                c.close()
            except Exception:
                pass

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        proc.send_signal(signal.SIGTERM)  # mid-burst
        for t in threads:
            t.join(30)
        rc = proc.wait(timeout=30)
        _out, err = proc.communicate(timeout=10)
        assert rc == 0, f"non-zero exit: {err[-2000:]}"
        assert "draining" in err
        assert "drain complete" in err
        for uid, resp_uid in answered.items():
            assert resp_uid == uid
    finally:
        if proc.poll() is None:
            proc.kill()
