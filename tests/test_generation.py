"""Generations: background compile, executable swap, on-disk compile cache.

The load-bearing claims (ISSUE 12):

- verdicts are bit-identical across ``--generation-swap on|off`` while
  templates churn mid-burst and mid-sweep (compared after quiescence —
  pre-swap batches intentionally serve the OLD generation);
- a killed background compile leaves the serving generation untouched;
- corrupted / version-drifted / vocab-incompatible compile-cache entries
  are rejected and rebuilt, never served;
- a warm-cache cold start performs ZERO lowering (hit counter pinned);
- a snapshot tick spanning a swap re-chunks resident rows against the
  new generation without a relist.
"""

from __future__ import annotations

import copy
import glob
import os
import threading
import time

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.generation import (CompileCache, MISS_COLD,
                                               MISS_CORRUPT, MISS_DIGEST,
                                               MISS_VOCAB)
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import (library_dir, load_library,
                                            make_cluster_objects)
from gatekeeper_tpu.utils.unstructured import load_yaml_file


def _template_paths():
    return sorted(
        glob.glob(os.path.join(library_dir(), "general", "*",
                               "template.yaml")) +
        glob.glob(os.path.join(library_dir(), "pod-security-policy", "*",
                               "template.yaml")))


def _all_kinds():
    out = []
    for p in _template_paths():
        doc = load_yaml_file(p)[0]
        out.append((doc["spec"]["crd"]["spec"]["names"]["kind"], p))
    return out


# a small template subset keeps per-test compile+trace wall bounded on
# the 1-core tier-1 host (tier-1 runs ~35s under its timeout; every
# fresh client here pays compile + one trace pass); the full-corpus
# differential runs in the slow lane below
_KEEP = 8


def _small_client(generation_swap: bool, cache=None):
    kinds = _all_kinds()
    skip = tuple(k for k, _p in kinds[_KEEP:])
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel, generation_swap=generation_swap,
                    compile_cache=cache)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    load_library(client, skip_kinds=skip)
    if tpu.gen_coord is not None:
        tpu.gen_coord.constraints_fn = client.constraints
    return client, tpu


def _reviews(objects, n=10):
    return [AugmentedUnstructured(object=o, source=SOURCE_ORIGINAL)
            for o in objects[:n]]


def _sig(client, reviews):
    out = []
    for r in client.review_batch(reviews):
        out.append(tuple(sorted(res.msg for res in r.results())))
    return out


def _churn_doc(idx=0):
    """(kind, template doc, constraint docs) of the idx-th KEPT
    template."""
    kind, tpath = _all_kinds()[idx]
    tdoc = load_yaml_file(tpath)[0]
    cons = []
    cpath = os.path.join(os.path.dirname(tpath), "samples",
                         "constraint.yaml")
    if os.path.exists(cpath):
        cons = load_yaml_file(cpath)
    return kind, tdoc, cons


@pytest.fixture(scope="module")
def objects():
    return make_cluster_objects(32, seed=23)


@pytest.fixture(scope="module")
def reference(objects):
    """The swap-off client and its verdict signature — the oracle every
    swap-on quiescent state must match."""
    client, tpu = _small_client(False)
    revs = _reviews(objects)
    return client, _sig(client, revs), revs


# --- swap differential -----------------------------------------------------

def test_swap_on_quiesced_matches_inline(reference, objects):
    """Mid-burst template churn with the background thread running:
    after quiescence the verdicts equal the inline-compile client's,
    and bursts issued DURING the churn never error (they serve the old
    generation)."""
    _ref_client, ref_sig, revs = reference
    client, tpu = _small_client(True)
    coord = tpu.gen_coord
    assert coord is not None
    assert _sig(client, revs) == ref_sig  # pre-churn parity (inline boot)
    coord.start()
    kind, tdoc, cons = _churn_doc(0)
    gen0 = coord.gen_id
    client.remove_template(kind)
    # bursts while the background compile is in flight: old generation
    # answers, no errors, no stalls from lowering on this thread
    for _ in range(3):
        _sig(client, revs)
    client.add_template(tdoc)
    for cdoc in cons:
        client.add_constraint(cdoc)
    for _ in range(2):
        _sig(client, revs)
    assert coord.wait_idle(60.0)
    assert coord.gen_id > gen0
    assert coord.last_error is None
    assert _sig(client, revs) == ref_sig
    coord.stop()


def test_generation_pins_inflight_state(reference, objects):
    """A swap REPLACES the serving dicts; the captured old dict (what an
    in-flight batch holds) is untouched, so the batch finishes on the
    generation it started on."""
    _c, _s, revs = reference
    client, tpu = _small_client(True)
    old_programs = tpu._programs
    old_uids = {k: p.uid for k, p in old_programs.items()}
    kind, tdoc, cons = _churn_doc(1)
    client.remove_template(kind)  # inline (not started): swap happens now
    assert tpu._programs is not old_programs
    assert kind not in tpu._programs
    # the captured generation still holds the removed kind's program
    assert old_uids == {k: p.uid for k, p in old_programs.items()}
    # unchanged kinds' programs carried over by object (executable reuse)
    for k, p in tpu._programs.items():
        assert p is old_programs[k]


def test_killed_background_compile_leaves_serving(reference, objects):
    """compile.generation chaos: the build dies mid-flight — the
    serving generation keeps answering (verdicts = pre-churn), the
    error is recorded, and the next churn event retries cleanly."""
    _ref_client, ref_sig, revs = reference
    client, tpu = _small_client(True)
    coord = tpu.gen_coord
    coord.start()
    sig_before = _sig(client, revs)
    kind, tdoc, cons = _churn_doc(0)
    gen0, swaps0 = coord.gen_id, coord.swap_count
    plan = FaultPlan([{"site": "compile.generation", "mode": "error",
                       "times": 1}])
    with inject(plan):
        client.remove_template(kind)
        deadline = time.monotonic() + 30.0
        while plan.fired() < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert plan.fired() == 1
        deadline = time.monotonic() + 30.0
        while coord.last_error is None and time.monotonic() < deadline:
            time.sleep(0.01)
    assert coord.last_error is not None
    # no swap landed from the killed build
    assert coord.gen_id == gen0 and coord.swap_count == swaps0
    # serving untouched: the removed template still answers
    assert _sig(client, revs) == sig_before == ref_sig
    # the next churn event retries the whole desired set and recovers
    client.add_template(tdoc)  # no-op content-wise; re-triggers a build
    assert coord.wait_idle(60.0)
    assert coord.last_error is None
    # now the earlier removal finally lands with the retried build:
    # desired set == all templates (the re-add restored kind), so the
    # verdicts still match the reference
    assert _sig(client, revs) == ref_sig
    coord.stop()


# --- on-disk compile cache -------------------------------------------------

def test_compile_cache_cold_start_zero_lowering(tmp_path, reference,
                                                objects):
    """THE acceptance pin: a second process start against a warm
    --compile-cache performs zero lowering — every template answers
    from disk (hit counter == template count) with identical
    verdicts."""
    import gatekeeper_tpu.drivers.tpu_driver as TD
    import gatekeeper_tpu.ir.lower_rego as LR

    _ref_client, ref_sig, revs = reference
    cc1 = CompileCache(str(tmp_path))
    client1, tpu1 = _small_client(False, cache=cc1)
    n_templates = len(client1.templates())
    assert cc1.stats()["stores"] == n_templates
    assert _sig(client1, revs) == ref_sig

    calls = [0]
    orig = LR.lower_template

    def counting(*a, **k):
        calls[0] += 1
        return orig(*a, **k)

    TD.lower_template = counting
    try:
        cc2 = CompileCache(str(tmp_path))
        client2, tpu2 = _small_client(False, cache=cc2)
    finally:
        TD.lower_template = orig
    assert calls[0] == 0  # ZERO lowering
    assert cc2.hits == n_templates
    assert cc2.misses == 0
    assert _sig(client2, revs) == ref_sig


def test_compile_cache_corruption_rejected(tmp_path, reference, objects):
    """Tampered payload bytes, stale version fields and digest
    mismatches are rejected (and deleted) on load — never served — and
    the rebuild re-stores a clean entry."""
    import json

    _ref_client, ref_sig, revs = reference
    cc1 = CompileCache(str(tmp_path))
    _small_client(False, cache=cc1)
    pkls = sorted(glob.glob(os.path.join(str(tmp_path), "*.pkl")))
    metas = sorted(glob.glob(os.path.join(str(tmp_path), "*.json")))
    assert pkls and metas
    # corrupt one payload (bit flip)
    raw = bytearray(open(pkls[0], "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(pkls[0], "wb").write(bytes(raw))
    # version-drift another entry's meta (a jax upgrade)
    meta = json.load(open(metas[1]))
    meta["jax"] = "0.0.0-stale"
    json.dump(meta, open(metas[1], "w"))
    cc2 = CompileCache(str(tmp_path))
    client2, _tpu2 = _small_client(False, cache=cc2)
    st = cc2.stats()
    assert st["miss_reasons"].get(MISS_CORRUPT, 0) >= 1
    assert st["miss_reasons"].get(MISS_DIGEST, 0) >= 1
    assert st["hits"] == len(client2.templates()) - st["misses"]
    # rejected entries were rebuilt and re-stored
    assert st["stores"] == st["misses"]
    assert _sig(client2, revs) == ref_sig
    # third start: everything hits again (the rebuilt entries are clean)
    cc3 = CompileCache(str(tmp_path))
    client3, _tpu3 = _small_client(False, cache=cc3)
    assert cc3.stats()["misses"] == 0
    assert _sig(client3, revs) == ref_sig


def test_compile_cache_vocab_drift_is_a_miss(tmp_path, reference,
                                             objects):
    """A process whose vocab already diverged from the entry's snapshot
    must not consume baked sids: the load is a clean miss (reason
    vocab) and the template lowers fresh with correct verdicts."""
    _ref_client, ref_sig, revs = reference
    cc1 = CompileCache(str(tmp_path))
    _small_client(False, cache=cc1)

    cc2 = CompileCache(str(tmp_path))
    kinds = _all_kinds()
    skip = tuple(k for k, _p in kinds[_KEEP:])
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel, compile_cache=cc2)
    # poison the vocab BEFORE loading templates: sid 1 is now a string
    # the snapshot assigned differently
    tpu.vocab.intern("a-string-the-snapshot-never-interned-first")
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    load_library(client, skip_kinds=skip)
    st = cc2.stats()
    assert st["hits"] == 0
    assert st["miss_reasons"].get(MISS_VOCAB, 0) == \
        len(client.templates())
    assert _sig(client, revs) == ref_sig


def test_compile_cache_cold_reason_counted(tmp_path):
    cc = CompileCache(str(tmp_path))
    from gatekeeper_tpu.ops.flatten import Vocab

    assert cc.get("deadbeef", "rego", Vocab()) is None
    assert cc.stats()["miss_reasons"] == {MISS_COLD: 1}


# --- mutlane rides the generation machinery --------------------------------

def test_mutlane_background_recompile(reference):
    from gatekeeper_tpu.mutation.system import MutationSystem
    from gatekeeper_tpu.mutlane import MutationLane

    _c, _s, _r = reference
    client, tpu = _small_client(True)
    coord = tpu.gen_coord
    system = MutationSystem()
    lane = MutationLane(system, coordinator=coord)
    c0 = lane.compiled()
    assert c0.revision == system.revision()
    coord.start()
    # mutator churn: the serving burst keeps the OLD compiled revision
    # until the background install
    system.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "AssignMetadata",
        "metadata": {"name": "gen-label"},
        "spec": {"location": "metadata.labels.gen",
                 "parameters": {"assign": {"value": "x"}}},
    })
    assert system.revision() != c0.revision
    stale = lane.compiled()
    assert stale is c0  # served stale, recompile enqueued
    assert coord.wait_idle(30.0)
    fresh = lane.compiled()
    assert fresh is not c0 and fresh.revision == system.revision()
    # and the new mutator actually applies through the batched pass
    out = lane.mutate_objects([{"apiVersion": "v1", "kind": "Pod",
                                "metadata": {"name": "p"}}])
    assert out[0].changed and out[0].patch
    coord.stop()


# --- snapshot re-chunk across a swap ---------------------------------------

def test_snapshot_tick_spans_swap_without_relist(objects):
    """A tick after a template add/remove re-chunks resident rows
    against the new generation: zero relist calls, row ids intact, and
    totals identical to a fresh relist audit of the same state."""
    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
    from gatekeeper_tpu.parallel.sharded import (ShardedEvaluator,
                                                 make_mesh)
    from gatekeeper_tpu.snapshot import ClusterSnapshot, SnapshotConfig
    from gatekeeper_tpu.sync.source import FakeCluster

    client, tpu = _small_client(False)
    evaluator = ShardedEvaluator(tpu, make_mesh(), violations_limit=20,
                                 collect="reduced")
    cluster = FakeCluster()
    for o in objects:
        cluster.apply(copy.deepcopy(o))
    lists = [0]

    def lister():
        lists[0] += 1
        return iter(cluster.list())

    snapshot = ClusterSnapshot(evaluator, SnapshotConfig())
    cfg = dict(chunk_size=64, pipeline="off", exact_totals=False)
    snap_mgr = AuditManager(client, lister=lister,
                            config=AuditConfig(audit_source="snapshot",
                                               **cfg),
                            evaluator=evaluator, snapshot=snapshot)
    relist_mgr = AuditManager(client, lister=lister,
                              config=AuditConfig(**cfg),
                              evaluator=evaluator)
    snap_mgr.audit()  # initial build (one relist)
    assert lists[0] == 1

    kind, tdoc, cons = _churn_doc(2)
    client.remove_template(kind)
    run = snap_mgr.audit_tick()
    assert lists[0] == 1  # NO relist: the plan change re-chunked
    assert snapshot.rechunk_count == 1
    ref = relist_mgr.audit()
    lists[0] = 1
    assert run.total_objects == ref.total_objects
    diff = AuditManager._verdicts_differ_canonical(
        run.kept, run.total_violations, ref.kept, ref.total_violations,
        20)
    assert diff is None, diff

    # re-add: another plan change, another rechunk, still no relist
    client.add_template(tdoc)
    for cdoc in cons:
        client.add_constraint(cdoc)
    run2 = snap_mgr.audit_tick()
    assert lists[0] == 1
    assert snapshot.rechunk_count == 2
    ref2 = relist_mgr.audit()
    diff = AuditManager._verdicts_differ_canonical(
        run2.kept, run2.total_violations, ref2.kept,
        ref2.total_violations, 20)
    assert diff is None, diff


# --- the full-corpus differential + bench smoke (slow lane) ----------------

@pytest.mark.slow
def test_library_corpus_churn_differential_full():
    """The satellite's full claim: verdicts bit-identical across
    --generation-swap on|off over the WHOLE library corpus while
    templates churn mid-burst, compared after quiescence."""
    objects = make_cluster_objects(60, seed=7)

    def full_client(swap):
        cel = CELDriver()
        tpu = TpuDriver(cel_driver=cel, generation_swap=swap)
        client = Client(target=K8sValidationTarget(),
                        drivers=[tpu, cel],
                        enforcement_points=[WEBHOOK_EP, AUDIT_EP])
        load_library(client)
        if tpu.gen_coord is not None:
            tpu.gen_coord.constraints_fn = client.constraints
        return client, tpu

    ref_client, _ = full_client(False)
    revs = _reviews(objects, 16)
    ref_sig = _sig(ref_client, revs)
    client, tpu = full_client(True)
    tpu.gen_coord.start()
    stop = threading.Event()
    errs: list = []

    def serve():
        while not stop.is_set():
            try:
                _sig(client, revs)
            except Exception as e:  # pragma: no cover — the assertion
                errs.append(e)

    th = threading.Thread(target=serve, daemon=True)
    th.start()
    for idx in (0, 3, 5):
        kind, tdoc, cons = _churn_doc(idx)
        client.remove_template(kind)
        time.sleep(0.05)
        client.add_template(tdoc)
        for cdoc in cons:
            client.add_constraint(cdoc)
    assert tpu.gen_coord.wait_idle(120.0)
    stop.set()
    th.join(30.0)
    assert not errs
    assert _sig(client, revs) == ref_sig
    tpu.gen_coord.stop()


@pytest.mark.slow
def test_bench_churn_smoke(tmp_path):
    """tools/bench_churn.py --smoke: runs end to end, records history,
    pins the warm-cache zero-lowering claim, and the swap lane's storm
    P99 never degrades past the inline lane's."""
    import json
    import subprocess
    import sys

    out = tmp_path / "CHURN_BENCH.json"
    r = subprocess.run(
        [sys.executable, "tools/bench_churn.py", "--smoke", "--out",
         str(out)],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["kind"] == "churn_bench"
    assert "host_cpus" in rec and "history" in rec
    assert rec["cache"]["warm_fresh_lowerings"] == 0
    on = rec["modes"]["on"]
    off = rec["modes"]["off"]
    assert on["burst_errors"] == 0 and off["burst_errors"] == 0
    assert on["swaps"] > 0
    # the swap lane must not be WORSE than inline under the same storm
    # (the 2x-of-steady bound itself is asserted on the recorded
    # artifact when the host can hold it — 1-core runs measure GIL
    # contention the background thread cannot remove)
    assert on["p99_ratio"] <= off["p99_ratio"]


def test_warm_yield_sized_from_core_count():
    """ISSUE 14 satellite: the per-kernel cooperative-yield gap comes
    from the host's core count — 5ms on 1-core (pinned: the measured
    CHURN_BENCH behavior must not move), a token 1ms on few-core, zero
    on many-core (a gap there only delays the swap)."""
    from gatekeeper_tpu.drivers.generation import warm_yield_s

    assert warm_yield_s(1) == 0.005  # 1-core behavior pinned unchanged
    assert warm_yield_s(2) == 0.001
    assert warm_yield_s(3) == 0.001
    assert warm_yield_s(4) == 0.0
    assert warm_yield_s(64) == 0.0
    # the default reads the real host
    import os

    assert warm_yield_s() == warm_yield_s(os.cpu_count() or 1)
