"""Fleet mode: one evaluator, N clusters (gatekeeper_tpu/fleet/).

1. THE fleet differential: K=4 clusters (mixed sizes, overlapping and
   disjoint template sets) swept PACKED vs independently — per-cluster
   verdicts, kept messages and row ids bit-identical, with the packed
   lane paying fewer device dispatches.
2. Runtime sharing: the second same-library cluster attaches with zero
   fresh lowerings and ZERO fused retraces; a distinct-but-overlapping
   library's runtime boots entirely from the shared on-disk compile
   cache.
3. Per-cluster snapshot spill under one root: loading a fleet = N
   spills against one shared vocab replay (warm restart evaluates
   nothing); a cluster-id mismatch is a counted miss + clean relist
   and never deletes the foreign spill.
4. Cluster-axis QoS: one noisy cluster's user flood cannot displace
   another cluster's system lane; displacement targets the noisy
   cluster's heaviest tenant deterministically.
5. Satellites: `/v1/mutate` raw-bytes ingest (outcome parity + the
   column differential lane), the flight recorder / `gator decisions`
   `cluster` axis, and the FLEET_BENCH smoke (dispatch reduction >= 2x
   at K=4).

Wall-budget note: one module-scoped fleet (5-template library slice,
<=48 objects per cluster) and a shared compile-cache dir; the bench
smoke reuses the same cache (tier-1 budget was freed by moving two
overlapping heavy tests to the slow lane — see test_pipeline.py /
test_tracing_integration.py).
"""

from __future__ import annotations

import copy
import glob
import json
import os

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.generation import CompileCache
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.fleet import FleetEvaluator, check_cluster_id
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.snapshot import (ClusterSnapshot, SnapshotConfig,
                                     SnapshotSpill, templates_digest)
from gatekeeper_tpu.snapshot.persist import MISS_CLUSTER
from gatekeeper_tpu.sync.source import FakeCluster
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import (library_dir, load_library,
                                            make_cluster_objects)
from gatekeeper_tpu.utils.unstructured import load_yaml_file

_KEEP = 5  # library-A slice: bounded compile+trace wall (tier-1)


def _all_kinds():
    paths = sorted(
        glob.glob(os.path.join(library_dir(), "general", "*",
                               "template.yaml")) +
        glob.glob(os.path.join(library_dir(), "pod-security-policy", "*",
                               "template.yaml")))
    return [load_yaml_file(p)[0]["spec"]["crd"]["spec"]["names"]["kind"]
            for p in paths]


def _builder(cache_dir, skip):
    def build():
        cel = CELDriver()
        tpu = TpuDriver(cel_driver=cel,
                        compile_cache=CompileCache(str(cache_dir)))
        client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                        enforcement_points=[AUDIT_EP])
        load_library(client, skip_kinds=skip)
        ev = ShardedEvaluator(tpu, make_mesh(), violations_limit=20)
        return client, tpu, ev

    return build


def _source(n, seed):
    src = FakeCluster()
    for o in make_cluster_objects(n, seed=seed):
        src.apply(copy.deepcopy(o))
    return src


def _independent_reference(fc):
    """This cluster swept ALONE through the standard snapshot audit
    path over a FRESH snapshot (fresh relist + flatten) — the fleet
    differential's oracle.  Returns (run, {con key: [row gids]})."""
    rt = fc.runtime
    snap = ClusterSnapshot(rt.evaluator, SnapshotConfig())
    mgr = AuditManager(
        rt.client, lister=fc.lister,
        config=AuditConfig(audit_source="snapshot", chunk_size=64,
                           exact_totals=False, pipeline="off"),
        evaluator=rt.evaluator, snapshot=snap)
    run = mgr.audit()
    gids = {ck: [g for g, _c, _m in snap.verdicts.rows(ck)]
            for ck in run.total_violations}
    return run, gids


def _assert_identical(run_a, run_b, limit=20):
    diff = AuditManager._verdicts_differ_canonical(
        run_a.kept, run_a.total_violations,
        run_b.kept, run_b.total_violations, limit)
    assert diff is None, diff


@pytest.fixture(scope="module")
def fleet_ctx(tmp_path_factory):
    """The module-scoped fleet story: a+b share library A (the sharing
    pins), c runs an overlapping subset, d a disjoint slice; packed
    sweep vs per-cluster independent references; spills; restart."""
    import gatekeeper_tpu.ir.lower_rego as LR

    cache_dir = tmp_path_factory.mktemp("fleet-cache")
    spill_root = tmp_path_factory.mktemp("fleet-spill")
    kinds = _all_kinds()
    skip_a = tuple(kinds[_KEEP:])             # templates 0..4
    skip_c = tuple(kinds[3:])                 # 0..2 (overlap with A)
    skip_d = tuple(kinds[:_KEEP] + kinds[8:])  # 5..7 (disjoint from A)

    lowers = [0]
    orig = LR.lower_template

    def counting(*a, **k):
        lowers[0] += 1
        return orig(*a, **k)

    import gatekeeper_tpu.drivers.tpu_driver as TD

    TD.lower_template = counting
    try:
        fleet = FleetEvaluator(chunk_size=64, exact_totals=False,
                               spill_root=str(spill_root))
        sources = {
            "a": _source(48, seed=1), "b": _source(48, seed=7),
            "c": _source(32, seed=3), "d": _source(24, seed=5)}
        fleet.add_cluster("a", sources["a"], "libA",
                          _builder(cache_dir, skip_a))
        lowers_a = lowers[0]
        # warm library A's executables at the 48-row geometry
        fleet.sweep(full=True)
        rt_a = fleet.clusters["a"].runtime
        tc0, low0 = rt_a.evaluator.trace_count, lowers[0]
        fcb = fleet.add_cluster("b", sources["b"], "libA",
                                _builder(cache_dir, skip_a))
        run_b_first = fcb.sweep_independent(full=True)
        second_cluster = {
            "lowers_delta": lowers[0] - low0,
            "traces_delta": rt_a.evaluator.trace_count - tc0,
            "shared_boots": fleet.shared_boots,
            "same_runtime": fcb.runtime is rt_a,
        }
        low1 = lowers[0]
        fcc = fleet.add_cluster("c", sources["c"], "libC",
                                _builder(cache_dir, skip_c))
        subset_library = {
            "fresh_lowers": lowers[0] - low1,
            "cache": dict(fcc.runtime.driver._compile_cache.stats()),
        }
        fleet.add_cluster("d", sources["d"], "libD",
                          _builder(cache_dir, skip_d))

        # THE packed fleet pass over all four clusters (every row
        # re-dirtied so the pass evaluates the full corpus)
        for fc in fleet.clusters.values():
            for _store, rows in fc.snapshot.all_rows().items():
                fc.snapshot._dirty.update(g for g, _p in rows)
        d0 = {rt.key: rt.evaluator.dispatch_count
              for rt in fleet.runtimes()}
        packed_runs = fleet.sweep(full=True)
        packed_dispatches = sum(
            rt.evaluator.dispatch_count - d0[rt.key]
            for rt in fleet.runtimes())
        packed_gids = {
            cid: {ck: [g for g, _c, _m in
                       fc.snapshot.verdicts.rows(ck)]
                  for ck in packed_runs[cid].total_violations}
            for cid, fc in fleet.clusters.items()}

        # independent references (fresh snapshots, standard path)
        refs = {}
        ref_gids = {}
        for cid, fc in fleet.clusters.items():
            refs[cid], ref_gids[cid] = _independent_reference(fc)

        fleet.spill_all()
        ctx = {
            "fleet": fleet, "sources": sources,
            "cache_dir": str(cache_dir), "spill_root": str(spill_root),
            "skip_a": skip_a, "lowers_a_boot": lowers_a,
            "second_cluster": second_cluster,
            "subset_library": subset_library,
            "packed_runs": packed_runs,
            "packed_gids": packed_gids,
            "packed_dispatches": packed_dispatches,
            "refs": refs, "ref_gids": ref_gids,
            "run_b_first": run_b_first,
        }
        yield ctx
        fleet.stop()
    finally:
        TD.lower_template = orig


# --- 0. unit ---------------------------------------------------------------

def test_cluster_id_validation():
    assert check_cluster_id("prod-eu.1_a") == "prod-eu.1_a"
    for bad in ("", "..", "a/b", "a b", "x\n"):
        with pytest.raises(ValueError):
            check_cluster_id(bad)


# --- 1. THE fleet differential --------------------------------------------

def test_fleet_packed_matches_independent_per_cluster(fleet_ctx):
    """K=4 clusters packed vs independently: per-cluster totals, kept
    messages AND verdict-store row ids bit-identical."""
    for cid in ("a", "b", "c", "d"):
        _assert_identical(fleet_ctx["packed_runs"][cid],
                          fleet_ctx["refs"][cid])
        assert fleet_ctx["packed_gids"][cid] == \
            fleet_ctx["ref_gids"][cid], f"row ids differ for {cid}"


def test_fleet_packing_reduces_dispatches(fleet_ctx):
    """The packed pass dispatched fewer device chunks than the four
    clusters' chunk counts sum to (same-library same-group chunks
    coalesced), and actually packed multi-cluster dispatches."""
    fleet = fleet_ctx["fleet"]
    assert fleet.packed_dispatches > 0
    # a+b (same runtime, 2 groups each at chunk 64) would pay 4
    # dispatches independently; packed they share
    assert fleet_ctx["packed_dispatches"] < 4 + 2 + 2


def test_fleet_sweep_runs_annotated(fleet_ctx):
    for cid, run in fleet_ctx["packed_runs"].items():
        assert not run.incomplete
        assert run.total_objects == \
            fleet_ctx["fleet"].clusters[cid].snapshot.live_count()


def test_fleet_statuses_are_per_cluster(fleet_ctx):
    """Status writeback lands in each cluster's own sink — the
    runtime's Constraint objects are shared, so con.raw mutation would
    make the last-swept cluster win."""
    for cid, run in fleet_ctx["packed_runs"].items():
        fc = fleet_ctx["fleet"].clusters[cid]
        assert fc.statuses, f"no statuses for {cid}"
        for key, status in fc.statuses.items():
            assert status["totalViolations"] == \
                run.total_violations.get(key, 0)


# --- 2. runtime sharing ----------------------------------------------------

def test_second_same_library_cluster_boots_free(fleet_ctx):
    """The acceptance pin: cluster b (same library as a) attached with
    zero fresh lowerings and ZERO fused retraces, and its first sweep
    reused a's executables (same runtime, trace_count unchanged)."""
    sc = fleet_ctx["second_cluster"]
    assert sc["same_runtime"]
    assert sc["shared_boots"] >= 1
    assert sc["lowers_delta"] == 0, "second cluster paid a lowering"
    assert sc["traces_delta"] == 0, "second cluster retraced"
    # and its verdicts came out (the sweep actually ran)
    assert fleet_ctx["run_b_first"].total_objects == 48


def test_overlapping_library_shares_disk_cache(fleet_ctx):
    """Cluster c's library is a SUBSET of a's: a distinct runtime, but
    every lowering answered by the shared on-disk CompileCache (the
    vocab prefix-replay rule composes across load orders)."""
    sub = fleet_ctx["subset_library"]
    assert sub["fresh_lowers"] == 0
    assert sub["cache"]["hits"] >= 3


# --- 3. per-cluster spill --------------------------------------------------

def test_fleet_spill_restart_warm(fleet_ctx):
    """Loading a fleet = N spills against one shared vocab replay: a
    restarted two-cluster fleet boots warm (zero rows evaluated on the
    first pass) with verdicts identical to the pre-restart packed
    sweep."""
    spill_root = fleet_ctx["spill_root"]
    assert sorted(os.listdir(spill_root)) == ["a", "b", "c", "d"]
    fleet2 = FleetEvaluator(chunk_size=64, exact_totals=False,
                            spill_root=spill_root)
    try:
        fleet2.add_cluster("a", fleet_ctx["sources"]["a"], "libA",
                           _builder(fleet_ctx["cache_dir"],
                                    fleet_ctx["skip_a"]))
        fleet2.add_cluster("b", fleet_ctx["sources"]["b"], "libA",
                           _builder(fleet_ctx["cache_dir"],
                                    fleet_ctx["skip_a"]))
        fa, fb = fleet2.clusters["a"], fleet2.clusters["b"]
        assert fa.warm_booted and fb.warm_booted
        runs = fleet2.sweep(full=None)
        assert fa.manager.perf.get("snapshot_rows_evaluated", 0) == 0
        assert fb.manager.perf.get("snapshot_rows_evaluated", 0) == 0
        _assert_identical(runs["a"], fleet_ctx["packed_runs"]["a"])
        _assert_identical(runs["b"], fleet_ctx["packed_runs"]["b"])
    finally:
        fleet2.stop()


def test_fleet_warm_root_round_trip(fleet_ctx, tmp_path):
    """``warm_root`` wires persisted warm EXECUTION state (sweep
    traces) per library runtime: save_warm_all() persists, and a fresh
    fleet's runtime replays it at build time — a full sweep of the same
    geometry then retraces nothing."""
    warm_root = str(tmp_path / "warm")
    fleet1 = FleetEvaluator(chunk_size=64, exact_totals=False,
                            warm_root=warm_root)
    try:
        fleet1.add_cluster("wa", _source(24, seed=17), "libA",
                           _builder(fleet_ctx["cache_dir"],
                                    fleet_ctx["skip_a"]))
        rt1 = fleet1.clusters["wa"].runtime
        assert rt1.warm_cache is not None
        assert not rt1.warm_replayed["hit"]  # nothing persisted yet
        fleet1.sweep(full=True)
        assert fleet1.save_warm_all() == 1
    finally:
        fleet1.stop()
    fleet2 = FleetEvaluator(chunk_size=64, exact_totals=False,
                            warm_root=warm_root)
    try:
        fleet2.add_cluster("wa", _source(24, seed=17), "libA",
                           _builder(fleet_ctx["cache_dir"],
                                    fleet_ctx["skip_a"]))
        rt2 = fleet2.clusters["wa"].runtime
        assert rt2.warm_replayed["hit"]
        assert rt2.warm_replayed["sweep_traces"] > 0
        tc0 = rt2.evaluator.trace_count
        fleet2.sweep(full=True)
        assert rt2.evaluator.trace_count == tc0  # geometry replayed
    finally:
        fleet2.stop()


def test_spill_cluster_mismatch_counted_not_deleted(fleet_ctx):
    """Pointing cluster x at b's spill dir: a counted ``cluster`` miss
    and a clean relist; the foreign spill survives untouched."""
    fleet = fleet_ctx["fleet"]
    rt = fleet.clusters["b"].runtime
    spill = SnapshotSpill(os.path.join(fleet_ctx["spill_root"], "b"),
                          cluster_id="x")
    snap = ClusterSnapshot(rt.evaluator, SnapshotConfig())
    out = spill.load(snap, rt.audit_constraints(),
                     templates=templates_digest(rt.client))
    assert out is None
    assert spill.miss_reasons == {MISS_CLUSTER: 1}
    assert snap.stale  # untouched: the boot relists
    assert os.path.exists(os.path.join(fleet_ctx["spill_root"], "b",
                                       "snapshot.json"))


# --- 4. cluster-axis QoS ---------------------------------------------------

def test_noisy_cluster_cannot_displace_other_clusters_system_lane():
    """Cluster identity rides the tenant key (cluster:tenant): a noisy
    cluster's user flood fills the queue, yet (1) another cluster's
    system ticket displaces the NOISY cluster's heaviest tenant, and
    (2) the noisy cluster's next user ticket cannot displace the queued
    system ticket — system sheds last, per cluster or across them."""
    from gatekeeper_tpu.resilience.qos import (QoSConfig, QoSQueue,
                                               Ticket,
                                               tenant_of_request)

    cfg = QoSConfig()
    lv_user = cfg.classify("team-a", "")
    lv_system = cfg.classify("kube-system", "")
    assert lv_system.order < lv_user.order
    q = QoSQueue(cfg)
    seq = 0
    # noisy cluster: two tenants' user tickets fill the queue (depth 4)
    for ns, cost in (("team-a", 100.0), ("team-a", 100.0),
                     ("team-b", 10.0), ("team-b", 10.0)):
        t = Ticket(seq, tenant_of_request({"namespace": ns},
                                          cluster="noisy"),
                   lv_user, cost)
        admitted, victim, reason = q.enqueue(t, 4, 1e9)
        assert admitted and victim is None, reason
        seq += 1
    # quiet cluster's system ticket: displaces noisy's heaviest tenant
    sys_t = Ticket(seq, tenant_of_request({"namespace": "kube-system"},
                                          cluster="quiet"),
                   lv_system, 1.0)
    seq += 1
    admitted, victim, reason = q.enqueue(sys_t, 4, 1e9)
    assert admitted and victim is not None
    assert victim.tenant == "noisy:team-a"  # heaviest queued tenant
    assert victim.shed == "displaced"
    # noisy's next user ticket: queue full again, and nothing below it
    # to displace that it outranks — the system ticket is untouchable
    nxt = Ticket(seq, "noisy:team-a", lv_user, 100.0)
    admitted, victim, reason = q.enqueue(nxt, 4, 1e9)
    assert victim is None or victim.tenant != "quiet:kube-system"
    snap = q.snapshot()
    sys_lane = next(l for l in snap["lanes"]
                    if l["priority"] == lv_system.name)
    assert "quiet:kube-system" in sys_lane["tenants"]


def test_fleet_tenant_key_partitions_clusters():
    from gatekeeper_tpu.resilience.qos import tenant_of_request

    req = {"namespace": "team-a"}
    assert tenant_of_request(req) == "team-a"
    assert tenant_of_request(req, cluster="c1") == "c1:team-a"
    assert tenant_of_request(req, cluster="c2") != \
        tenant_of_request(req, cluster="c1")


# --- 5. satellites ---------------------------------------------------------

_ASSIGN = {
    "apiVersion": "mutations.gatekeeper.sh/v1", "kind": "Assign",
    "metadata": {"name": "set-pull-policy"},
    "spec": {
        "applyTo": [{"groups": [""], "versions": ["v1"],
                     "kinds": ["Pod"]}],
        "location": "spec.imagePullPolicy",
        "parameters": {"assign": {"value": "IfNotPresent"}}}}


def _mutation_burst(n=12):
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": f"p{i}", "namespace": "default",
                          "labels": {"i": str(i)}},
             "spec": {"containers": [{"name": "c", "image": "x"}]}}
            for i in range(n)]
    objs[3]["spec"]["imagePullPolicy"] = "Always"  # replace path
    objs[5]["kind"] = "ConfigMap"  # noop lane
    return objs


def test_mutate_ingest_raw_matches_dict():
    """The PR 7 NEXT closed: mutate bursts columnize through the PR 4
    raw-bytes lane; outcomes (patches, lanes, changed flags) are
    identical to the dict path, and the differential ingest lane —
    which asserts raw and dict COLUMNS bit-identical per batch inside
    the flattener — runs clean over the burst."""
    from gatekeeper_tpu.mutation.system import MutationSystem
    from gatekeeper_tpu.mutlane.lane import MutationLane

    system = MutationSystem()
    system.upsert_unstructured(copy.deepcopy(_ASSIGN))
    burst = _mutation_burst()
    ref = MutationLane(system, ingest="dict").mutate_objects(
        [copy.deepcopy(o) for o in burst])
    raw = MutationLane(system, ingest="raw").mutate_objects(
        [copy.deepcopy(o) for o in burst])
    dif = MutationLane(system, ingest="differential").mutate_objects(
        [copy.deepcopy(o) for o in burst])
    for a, b, c in zip(ref, raw, dif):
        assert (a.patch, a.lane, a.changed, a.error) == \
            (b.patch, b.lane, b.changed, b.error)
        assert (a.patch, a.changed, a.error) == \
            (c.patch, c.changed, c.error)
    assert any(o.patch for o in raw)  # the burst actually mutated


def test_mutate_ingest_rejects_unknown_lane():
    from gatekeeper_tpu.mutation.system import MutationSystem
    from gatekeeper_tpu.mutlane.lane import MutationLane

    with pytest.raises(ValueError):
        MutationLane(MutationSystem(), ingest="bogus")


def test_flight_recorder_cluster_axis(tmp_path):
    """Decisions carry the cluster field; /debug/decisions' snapshot
    and the offline `gator decisions` reader both filter on it."""
    from gatekeeper_tpu.gator.decisions_cmd import read_decisions
    from gatekeeper_tpu.observability.flightrec import FlightRecorder

    sink = str(tmp_path / "decisions.jsonl")
    rec = FlightRecorder(capacity=16, sink_path=sink)
    rec.record("validate", "allow", uid="u1", cluster="east",
               tenant="east:team-a")
    rec.record("validate", "deny", uid="u2", cluster="west")
    rec.record("mutate", "allow", uid="u3")  # clusterless (single mode)
    rec.close()
    snap = rec.snapshot(cluster="east")
    assert snap["matched"] == 1
    assert snap["decisions"][0]["uid"] == "u1"
    assert snap["decisions"][0]["cluster"] == "east"
    # compose with a decision-kind filter
    assert rec.snapshot(cluster="west",
                        kinds={"deny"})["matched"] == 1
    assert rec.snapshot(cluster="west",
                        kinds={"allow"})["matched"] == 0
    doc = read_decisions(sink, cluster="west")
    assert doc["matched"] == 1 and doc["decisions"][0]["uid"] == "u2"


def test_costattr_cluster_axis_closes():
    """Packed-pass wall apportioned across clusters sums back exactly
    (the closure contract), and the snapshot exposes the roll-up."""
    from gatekeeper_tpu.observability.costattr import (CostAttribution,
                                                       EP_AUDIT)

    attr = CostAttribution()
    attr.attribute_clusters(2.0, {"a": 30, "b": 10, "c": 0}, EP_AUDIT)
    totals = attr.cluster_totals(EP_AUDIT)
    assert abs(sum(totals.values()) - 2.0) < 1e-9
    assert totals["a"] == pytest.approx(1.5)
    snap = attr.snapshot()
    assert {c["cluster"] for c in snap["clusters"]} == {"a", "b", "c"}


def test_fleet_config_roundtrip(tmp_path):
    from gatekeeper_tpu.fleet import load_fleet_config

    p = tmp_path / "clusters.json"
    p.write_text(json.dumps({
        "clusters": [{"id": "a", "manifests": ["ma"]},
                     {"id": "b", "manifests": ["mb"]}],
        "packChunks": 3}))
    cfg = load_fleet_config(str(p))
    assert [c.cluster_id for c in cfg.clusters] == ["a", "b"]
    assert cfg.pack_chunks == 3
    p.write_text(json.dumps({"clusters": [{"id": "a"}, {"id": "a"}]}))
    with pytest.raises(ValueError):
        load_fleet_config(str(p))


# --- 6. FLEET_BENCH smoke --------------------------------------------------

def test_bench_fleet_smoke_pins_dispatch_reduction(fleet_ctx):
    """tools/bench_fleet.py --smoke in-process (shared compile cache):
    K=4 small clusters packed vs sequential — dispatch reduction >= 2x,
    verdicts bit-identical, second cluster zero lowering."""
    import importlib.util
    import pathlib

    tools = pathlib.Path(__file__).resolve().parent.parent / "tools"
    spec = importlib.util.spec_from_file_location(
        "bench_fleet", tools / "bench_fleet.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    rec = bench.run_bench(k=4, n_objects=40, write=False,
                          cache_dir=fleet_ctx["cache_dir"])
    hl = rec["headline"]
    assert hl["verdicts_bit_identical"]
    assert hl["second_cluster_zero_lowering"]
    assert hl["dispatch_reduction"] >= 2.0, hl
    assert rec["lanes"]["packed"]["dispatches"] < \
        rec["lanes"]["sequential"]["dispatches"]
