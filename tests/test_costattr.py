"""Per-template cost attribution: apportionment closure (shares sum
back to the measured wall), the sweep-path closure against the parent
device.sweep_dispatch spans (the acceptance bound: within 5%), the
webhook query_batch path, render-exact attribution, and /debug/cost."""

import json
import urllib.request

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.observability import costattr, tracing
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects
from gatekeeper_tpu.webhook.server import WebhookServer


# --- unit ------------------------------------------------------------------

def test_attribute_distributes_wall_exactly():
    a = costattr.CostAttribution()
    a.attribute(2.0, {"A": 3.0, "B": 1.0}, "audit", "dispatch",
                rows={"A": 300, "B": 100})
    assert a.total_seconds() == pytest.approx(2.0)
    top = a.snapshot()["top"]
    assert top[0]["template"] == "A"
    assert top[0]["seconds"] == pytest.approx(1.5)
    assert top[1]["seconds"] == pytest.approx(0.5)
    assert top[0]["rows"] == 300


def test_attribute_zero_weights_fall_back_to_even_split():
    a = costattr.CostAttribution()
    a.attribute(1.0, {"A": 0.0, "B": 0.0}, "audit", "dispatch")
    assert a.total_seconds() == pytest.approx(1.0)
    by = {t["template"]: t["seconds"] for t in a.snapshot()["top"]}
    assert by["A"] == pytest.approx(0.5)
    assert by["B"] == pytest.approx(0.5)


def test_record_mirrors_into_metrics():
    m = MetricsRegistry()
    a = costattr.CostAttribution(metrics=m)
    a.record("K8sThing", "webhook", "dispatch", 0.25, rows=10)
    assert m.get_counter(M.CONSTRAINT_EVAL, {
        "template": "K8sThing", "enforcement_point": "webhook",
        "phase": "dispatch"}) == pytest.approx(0.25)


def test_table_renders():
    a = costattr.CostAttribution()
    assert "no passes" in a.table()
    a.record("K8sX", "audit", "dispatch", 0.5, rows=3)
    out = a.table()
    assert "K8sX" in out and "dispatch=0.500" in out


# --- the sweep closure (acceptance criterion) ------------------------------

@pytest.fixture(scope="module")
def library_sweep():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client)
    objects = make_cluster_objects(120, seed=11)
    mgr = AuditManager(
        client, lister=lambda: iter(objects),
        config=AuditConfig(chunk_size=48, exact_totals=False,
                           pipeline="off"),
        evaluator=ShardedEvaluator(tpu, make_mesh(),
                                   violations_limit=20),
    )
    return mgr


def test_sweep_dispatch_attribution_closes_to_span_wall(library_sweep):
    """THE closure: per-template gatekeeper_constraint_eval_seconds
    (phase=dispatch) summed over a library-corpus sweep reproduces the
    parent device.sweep_dispatch spans' total wall time within 5%."""
    mgr = library_sweep
    mgr.audit()  # warmup compile OUTSIDE the attributed run
    attr = costattr.CostAttribution()
    tracer = tracing.Tracer(seed=0, ring_capacity=64)
    with costattr.activate(attr), tracing.activate(tracer):
        run = mgr.audit()
    assert sum(run.total_violations.values()) > 0  # non-vacuous
    span_wall = sum(
        s["duration_s"]
        for tr in tracer.traces() for s in tr["spans"]
        if s["name"] == "device.sweep_dispatch")
    assert span_wall > 0
    attributed = attr.total_seconds(costattr.EP_AUDIT,
                                    costattr.PHASE_DISPATCH)
    assert attributed == pytest.approx(span_wall, rel=0.05)
    # flatten and render phases attributed too (the /debug/cost view is
    # the whole host+device story, not just dispatch)
    assert attr.total_seconds(costattr.EP_AUDIT,
                              costattr.PHASE_FLATTEN) > 0
    assert attr.total_seconds(costattr.EP_AUDIT,
                              costattr.PHASE_RENDER) > 0
    # every top entry is a real template kind of the library
    kinds = {c.kind for c in mgr.client.constraints()}
    for entry in attr.snapshot()["top"]:
        assert entry["template"] in kinds


def test_reduced_collect_occupancy_matches_masks_lane(library_sweep):
    """--collect=reduced closure satellite: the reduced lane attributes
    the dispatch wall from the ON-DEVICE occupancy counts (the host
    never materializes the masks) — the accumulated per-template row
    occupancy must equal the masks lane's host-side mask sums exactly,
    and the closure to the dispatch span wall must hold on both lanes."""
    mgr = library_sweep
    assert mgr.evaluator.collect == "reduced"  # the default lane
    mgr_masks = AuditManager(
        mgr.client, lister=mgr.lister, config=mgr.config,
        evaluator=ShardedEvaluator(mgr.evaluator.driver, make_mesh(),
                                   violations_limit=20, collect="masks"))
    mgr.audit()  # compile both lanes OUTSIDE the attributed runs
    mgr_masks.audit()

    def dispatch_rows(m):
        attr = costattr.CostAttribution()
        tracer = tracing.Tracer(seed=0, ring_capacity=64)
        with costattr.activate(attr), tracing.activate(tracer):
            m.audit()
        span_wall = sum(
            s["duration_s"]
            for tr in tracer.traces() for s in tr["spans"]
            if s["name"] == "device.sweep_dispatch")
        attributed = attr.total_seconds(costattr.EP_AUDIT,
                                        costattr.PHASE_DISPATCH)
        assert attributed == pytest.approx(span_wall, rel=0.05)
        return {t: cell[2] for (t, ep, ph), cell in attr._cells.items()
                if ep == costattr.EP_AUDIT
                and ph == costattr.PHASE_DISPATCH}

    assert dispatch_rows(mgr) == dispatch_rows(mgr_masks)


def test_attribution_off_adds_no_cells(library_sweep):
    mgr = library_sweep
    assert costattr.active() is None
    mgr.audit()
    # nothing installed: the sweep ran clean with no attribution seam
    a = costattr.CostAttribution()
    assert a.snapshot()["top"] == []


# --- the webhook path ------------------------------------------------------

def test_query_batch_attributes_webhook_ep(library_sweep):
    from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
    from gatekeeper_tpu.target.review import AugmentedUnstructured

    mgr = library_sweep
    client = mgr.client
    reviews = [AugmentedUnstructured(object=o, source=SOURCE_ORIGINAL)
               for o in make_cluster_objects(24, seed=3)]
    attr = costattr.CostAttribution()
    with costattr.activate(attr):
        client.review_batch(reviews)
    assert attr.total_seconds(costattr.EP_WEBHOOK) > 0
    cells = attr.snapshot()["cells"]
    assert any(c["enforcement_point"] == "webhook" and
               c["phase"] == "dispatch" for c in cells)


# --- /debug/cost -----------------------------------------------------------

def test_debug_cost_endpoint():
    attr = costattr.CostAttribution()
    attr.record("K8sHot", "audit", "dispatch", 1.25, rows=99)
    srv = WebhookServer(port=0, cost_attribution=attr).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/cost") as r:
            doc = json.loads(r.read())
        assert doc["top"][0]["template"] == "K8sHot"
        assert doc["top"][0]["seconds"] == pytest.approx(1.25)
    finally:
        srv.stop()


def test_gator_bench_attribution_table(capsys):
    """`gator bench --attribution` prints the per-template cost table
    (the /debug/cost view, offline)."""
    from gatekeeper_tpu.gator.bench import run_cli

    lib = "/root/repo/library/general/allowedrepos"
    rc = run_cli(["-f", f"{lib}/template.yaml",
                  "-f", f"{lib}/samples/constraint.yaml",
                  "-f", f"{lib}/samples/example_disallowed.yaml",
                  "--engine", "tpu", "-n", "2", "--attribution"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "cost attribution" in out
    assert "K8sAllowedRepos" in out
    assert "dispatch=" in out


def test_debug_cost_404_when_off():
    srv = WebhookServer(port=0).start()
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/debug/cost")
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert "cost attribution" in json.loads(e.read())["error"]
    finally:
        srv.stop()
