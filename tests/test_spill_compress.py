"""Snapshot spill compression (ISSUE 14 satellite): the zlib codec
round-trips the exact state the 'none' codec does, the header records
the codec (loader auto-detects either), default 'none' stays
byte-identical to the pre-codec format, and miss-reason accounting is
unchanged (a truncated compressed section is MISS_CORRUPT, an unknown
codec MISS_VERSION).

Fake-snapshot level on purpose: SnapshotSpill's contract with the
snapshot is four calls (export_state / evaluator.driver.vocab /
_cons_digest / adopt_spill); the full-stack spill round-trip including
worker-flattened rows is tests/test_snapshot_persist.py's job.
"""

import json
import os
import zlib

import pytest

from gatekeeper_tpu.ops.flatten import Vocab
from gatekeeper_tpu.snapshot.persist import (HEADER, MISS_CORRUPT,
                                             MISS_VERSION, SnapshotSpill)

_STATE = {"rows": 3, "digest": "d1",
          "payload": list(range(200)) * 50}  # compressible


class _FakeDriver:
    def __init__(self):
        self.vocab = Vocab()


class _FakeEvaluator:
    def __init__(self):
        self.driver = _FakeDriver()


class _FakeSnapshot:
    def __init__(self):
        self.evaluator = _FakeEvaluator()
        self.adopted = None

    def export_state(self):
        return dict(_STATE)

    def _cons_digest(self, constraints):
        return "d1"

    def adopt_spill(self, constraints, state):
        self.adopted = state
        return state["rows"]


def _spill_dir(tmp_path, name):
    return str(tmp_path / name)


def test_unknown_codec_rejected_at_construction(tmp_path):
    with pytest.raises(ValueError):
        SnapshotSpill(_spill_dir(tmp_path, "x"), compress="lz4")


def test_none_codec_header_is_pre_codec_format(tmp_path):
    snap = _FakeSnapshot()
    spill = SnapshotSpill(_spill_dir(tmp_path, "none"))
    assert spill.save(snap)["ok"]
    with open(os.path.join(spill.root, HEADER)) as f:
        header = json.load(f)
    assert "codec" not in header  # old loaders keep reading new spills
    # sections are plain pickles (magic byte), not zlib streams
    with open(os.path.join(spill.root, "snapshot.rows.pkl"), "rb") as f:
        assert f.read(1) == b"\x80"


def test_zlib_round_trip_identical_state_and_smaller(tmp_path):
    snap_a, snap_b = _FakeSnapshot(), _FakeSnapshot()
    plain = SnapshotSpill(_spill_dir(tmp_path, "plain"))
    packed = SnapshotSpill(_spill_dir(tmp_path, "packed"), compress="zlib")
    r_plain = plain.save(snap_a)
    r_packed = packed.save(snap_b)
    assert r_plain["ok"] and r_packed["ok"]
    assert r_packed["bytes"] < r_plain["bytes"]  # it actually compressed
    with open(os.path.join(packed.root, HEADER)) as f:
        assert json.load(f)["codec"] == "zlib"

    loaded = packed.load(_FakeSnapshot2 := _FakeSnapshot(), [])
    assert loaded is not None and loaded["rows"] == 3
    assert _FakeSnapshot2.adopted == _STATE
    assert packed.load_hits == 1 and packed.load_misses == 0


def test_loader_autodetects_either_codec_regardless_of_flag(tmp_path):
    # written compressed, loaded by a 'none'-configured spill (the
    # flag never strands an existing spill) — and vice versa
    d = _spill_dir(tmp_path, "auto")
    SnapshotSpill(d, compress="zlib").save(_FakeSnapshot())
    rd = SnapshotSpill(d)  # compress='none'
    assert rd.load(_FakeSnapshot(), []) is not None

    d2 = _spill_dir(tmp_path, "auto2")
    SnapshotSpill(d2).save(_FakeSnapshot())
    rd2 = SnapshotSpill(d2, compress="zlib")
    assert rd2.load(_FakeSnapshot(), []) is not None


def test_corrupt_compressed_section_is_miss_corrupt(tmp_path):
    d = _spill_dir(tmp_path, "corrupt")
    spill = SnapshotSpill(d, compress="zlib")
    assert spill.save(_FakeSnapshot())["ok"]
    # valid zlib bytes that are NOT the recorded section: sha mismatch
    # path is already covered; here the sha matches but inflate fails —
    # rewrite section AND its recorded sha with a truncated stream
    path = os.path.join(d, "snapshot.rows.pkl")
    with open(path, "rb") as f:
        raw = f.read()
    bad = raw[: len(raw) // 2]
    with open(path, "wb") as f:
        f.write(bad)
    import hashlib

    with open(os.path.join(d, HEADER)) as f:
        header = json.load(f)
    header["sections"]["snapshot.rows.pkl"]["sha256"] = \
        hashlib.sha256(bad).hexdigest()
    with open(os.path.join(d, HEADER), "w") as f:
        json.dump(header, f)
    assert spill.load(_FakeSnapshot(), []) is None
    assert spill.miss_reasons == {MISS_CORRUPT: 1}
    # rejected spills are deleted, never half-served
    assert not os.path.exists(os.path.join(d, HEADER))


def test_unknown_codec_in_header_is_version_drift(tmp_path):
    d = _spill_dir(tmp_path, "future")
    spill = SnapshotSpill(d)
    assert spill.save(_FakeSnapshot())["ok"]
    with open(os.path.join(d, HEADER)) as f:
        header = json.load(f)
    header["codec"] = "zstd-9000"
    with open(os.path.join(d, HEADER), "w") as f:
        json.dump(header, f)
    assert spill.load(_FakeSnapshot(), []) is None
    assert spill.miss_reasons == {MISS_VERSION: 1}
