"""Batched mutation lane (ISSUE 7): the differential harness + /v1/mutate.

The load-bearing pin: batched mutate-then-validate must equal the
per-object reference path BIT-IDENTICALLY — patches, converged objects,
error outcomes, and downstream sweep verdicts — over the library corpus,
with a MIXED registry (lowered Assign/AssignMetadata + host-only
ModifySet/assignIf) so host-fallback batches are inside the covered set.

Also pinned here:
- the compiled-lane cache keys on the registry revision (mutator churn
  recompiles; the revision is initialized, not conjured);
- `mutation.batch` chaos routes the WHOLE batch to the authoritative
  host walk — graceful fallback, never a lost or diverging mutation;
- `/v1/mutate` through the batched handler + microbatcher: patches,
  DELETE passthrough, excluded namespaces, overload shed under both
  failurePolicies (Ignore = admit unmutated + warning, Fail = 429 +
  Retry-After), and the HTTP header emission;
- `gator bench --engine mutate` and the bench script's smoke lane.
"""

import copy
import http.client
import json
import random
import threading

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.mutation.system import MutationSystem
from gatekeeper_tpu.mutlane import (BatchedMutationHandler, MutationBatcher,
                                    MutationDifferentialError, MutationLane)
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.resilience.overload import Shed
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects
from gatekeeper_tpu.webhook.server import WebhookServer


def _assign(name, location, value, extra=None, kinds=("Pod",)):
    params = {"assign": {"value": value}}
    params.update(extra or {})
    return {
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign", "metadata": {"name": name},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": list(kinds)}],
                 "location": location, "parameters": params},
    }


def _assign_meta(name, location, value):
    return {
        "apiVersion": "mutations.gatekeeper.sh/v1beta1",
        "kind": "AssignMetadata", "metadata": {"name": name},
        "spec": {"location": location,
                 "parameters": {"assign": {"value": value}}},
    }


def _mixed_registry():
    """6 lowered + 2 host-only mutators (the bench registry): the
    batched fragment AND the fallback path both live in every burst."""
    return [
        _assign("pull-policy",
                "spec.containers[name: *].imagePullPolicy", "Always"),
        _assign("host-network", "spec.hostNetwork", False),
        _assign("run-as-nonroot",
                "spec.securityContext.runAsNonRoot", True),
        _assign("priority", "spec.priority", 100),
        _assign_meta("owner-label", "metadata.labels.owner",
                     "platform-team"),
        _assign_meta("audit-ann", "metadata.annotations.audited", "true"),
        # host-only: ModifySet and assignIf are outside the fragment
        {
            "apiVersion": "mutations.gatekeeper.sh/v1",
            "kind": "ModifySet", "metadata": {"name": "topo-keys"},
            "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                                  "kinds": ["Service"]}],
                     "location": "spec.topologyKeys",
                     "parameters": {"operation": "merge",
                                    "values": {"fromList": ["zone"]}}},
        },
        _assign("dns-policy-cond", "spec.dnsPolicy", "ClusterFirst",
                extra={"assignIf": {"in": ["Default"]}}),
    ]


def _system(mutators=None):
    system = MutationSystem()
    for m in mutators if mutators is not None else _mixed_registry():
        system.upsert_unstructured(m)
    return system


def _weird_obj(rng, i):
    """Objects whose shapes force walk errors and error-parity routing
    (containers that are not lists, securityContext scalars, ...)."""
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": f"weird-{i}"}}
    spec = {}
    r = rng.random()
    if r < 0.4:
        spec["containers"] = rng.choice(
            ["notalist", {"a": {}}, 5,
             [{"name": "app", "imagePullPolicy": 7}]])
    elif r < 0.7:
        spec["securityContext"] = rng.choice(["bogus", 3, []])
    else:
        spec["priority"] = rng.choice(["100", True])
        obj["metadata"]["labels"] = "notadict"
    obj["spec"] = spec
    return obj


def _corpus(n=200, seed=29, weird=24):
    rng = random.Random(seed)
    objects = make_cluster_objects(n, seed=seed)
    objects += [_weird_obj(rng, i) for i in range(weird)]
    rng.shuffle(objects)
    return objects


def _outcome_sig(o):
    return (o.changed, o.patch, o.error is None, o.obj)


# --- THE differential: batched == reference over the library corpus -------

def test_batched_lane_bit_identical_to_reference():
    """Patches, converged objects, and error outcomes equal the
    per-object reference path over a mixed corpus, and every outcome
    lane (noop/device/solo/multi/host) is actually exercised."""
    metrics = MetricsRegistry()
    lane = MutationLane(_system(), metrics=metrics)
    objects = _corpus()
    # steady-state admissions arrive already converged (the webhook
    # reality): pre-converge a slice so the noop fast path is covered
    objects += [lane.reference_outcome(o).obj
                for o in make_cluster_objects(24, seed=91)]
    outcomes = lane.mutate_objects(objects, want_objects=True)
    lanes_seen = set()
    for obj, got in zip(objects, outcomes):
        want = lane.reference_outcome(obj)
        lanes_seen.add(got.lane)
        assert got.patch == want.patch, (got.lane, obj, got.patch,
                                         want.patch)
        assert got.changed == want.changed, (got.lane, obj)
        assert (got.error is None) == (want.error is None), (
            got.lane, obj, got.error, want.error)
        if got.error is None:
            assert got.obj == want.obj, (got.lane, obj)
        else:
            # the host path reproduced the reference's exact message
            assert got.error == want.error
    # the corpus must exercise the fragment AND the fallbacks
    assert "device" in lanes_seen or "multi" in lanes_seen, lanes_seen
    assert "host" in lanes_seen, lanes_seen
    assert "noop" in lanes_seen, lanes_seen
    assert metrics.get_counter(M.MUTATION_BATCH) >= 1
    fallback = sum(1 for o in outcomes if o.lane == "host")
    total_fb = sum(
        metrics.get_counter(M.MUTATION_FALLBACK, {"reason": r})
        for r in ("host_mutator", "multi", "interacting", "error",
                  "match", "chaos"))
    assert total_fb == fallback
    ops = sum(len(o.patch) for o in outcomes if o.patch)
    assert metrics.get_counter(M.MUTATION_PATCH_OPS) == ops > 0


def test_differential_mode_is_silent_on_agreement():
    lane = MutationLane(_system(), differential=True)
    lane.mutate_objects(_corpus(n=60, seed=5, weird=8),
                        want_objects=True)  # no raise


def test_differential_mode_catches_divergence(monkeypatch):
    """Corrupt the device patch emission: the differential harness must
    flag it (proves the harness can actually fail)."""
    lane = MutationLane(
        _system([_assign("host-network", "spec.hostNetwork", False)]),
        differential=True)
    orig = MutationLane._emit_scalar

    def corrupted(self, m, batch, oi, obj, want_objects):
        out = orig(self, m, batch, oi, obj, want_objects)
        if out.patch:
            out.patch = [dict(out.patch[0], value="WRONG")]
        return out

    monkeypatch.setattr(MutationLane, "_emit_scalar", corrupted)
    with pytest.raises(MutationDifferentialError):
        lane.mutate_objects([{"apiVersion": "v1", "kind": "Pod",
                              "metadata": {"name": "p"}, "spec": {}}])


def test_mutate_then_validate_verdicts_identical():
    """Downstream verdicts: an audit sweep over the batched lane's
    converged corpus equals the sweep over the reference path's
    converged corpus — the full mutate-then-validate composition."""
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client)
    lane = MutationLane(_system())
    objects = make_cluster_objects(120, seed=37)

    batched = [o.obj for o in lane.mutate_objects(objects,
                                                  want_objects=True)]
    reference = [lane.reference_outcome(o).obj for o in objects]

    def sweep(objs):
        run = AuditManager(
            client, lister=lambda: iter(copy.deepcopy(objs)),
            config=AuditConfig(chunk_size=64, exact_totals=False,
                               pipeline="off"),
            evaluator=ShardedEvaluator(tpu, make_mesh(),
                                       violations_limit=20),
        ).audit()
        return (run.total_violations,
                {k: [(v.message, v.kind, v.name, v.namespace,
                      v.enforcement_action) for v in vs]
                 for k, vs in run.kept.items()})

    sig_batched = sweep(batched)
    sig_reference = sweep(reference)
    assert sum(sig_batched[0].values()) > 0, "corpus produced no verdicts"
    assert sig_batched == sig_reference


# --- compile cache keyed on the registry revision -------------------------

def test_revision_initialized_and_bumped():
    system = MutationSystem()
    assert system.revision() == 0  # initialized in __init__, not conjured
    system.upsert_unstructured(_assign("a", "spec.hostNetwork", False))
    assert system.revision() == 1
    system.remove(next(iter(system.mutators())).id)
    assert system.revision() == 2


def test_mutator_churn_invalidates_compiled_lane():
    system = _system([_assign("host-network", "spec.hostNetwork", False)])
    lane = MutationLane(system)
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "p"}, "spec": {}}
    first = lane.compiled()
    assert lane.compiled() is first  # cached while the registry is quiet
    assert lane.mutate_objects([pod])[0].patch == [
        {"op": "add", "path": "/spec/hostNetwork", "value": False}]
    # in-place churn: same id, different value — MUST recompile
    system.upsert_unstructured(_assign("host-network",
                                       "spec.hostNetwork", True))
    second = lane.compiled()
    assert second is not first
    assert second.revision > first.revision
    assert lane.mutate_objects([pod])[0].patch == [
        {"op": "add", "path": "/spec/hostNetwork", "value": True}]


# --- chaos: the batched program is "down" ---------------------------------

def test_chaos_batch_fault_routes_to_host_identically():
    metrics = MetricsRegistry()
    lane = MutationLane(_system(), metrics=metrics)
    objects = _corpus(n=40, seed=3, weird=6)
    want = [lane.reference_outcome(o) for o in objects]
    plan = FaultPlan([{"site": "mutation.batch", "mode": "error"}])
    with inject(plan):
        outcomes = lane.mutate_objects(objects, want_objects=True)
    assert all(o.lane == "host" for o in outcomes)
    assert metrics.get_counter(M.MUTATION_FALLBACK,
                               {"reason": "chaos"}) == len(objects)
    for got, ref in zip(outcomes, want):
        assert got.patch == ref.patch
        assert (got.error is None) == (ref.error is None)
    # chaos lifted: the lane classifies again (not stuck on host)
    normal = lane.mutate_objects(objects[:8])
    assert any(o.lane != "host" for o in normal)


# --- /v1/mutate serving ---------------------------------------------------

def _review(uid, obj, operation="CREATE", namespace=""):
    req = {"uid": uid, "operation": operation,
           "kind": {"group": "", "version": "v1",
                    "kind": obj.get("kind", "Pod")},
           "userInfo": {"username": "t"}, "object": obj}
    if namespace:
        req["namespace"] = namespace
    return {"apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview", "request": req}


POD = {"apiVersion": "v1", "kind": "Pod",
       "metadata": {"name": "p"}, "spec": {}}


def test_handler_patch_delete_and_exclusion():
    class _Excluder:
        def is_excluded(self, process, namespace):
            return namespace == "kube-system"

    h = BatchedMutationHandler(_system(), process_excluder=_Excluder())
    r = h.handle(_review("u1", copy.deepcopy(POD)))
    assert r.allowed and r.patch, r
    ref = MutationLane(_system()).reference_outcome(copy.deepcopy(POD))
    assert r.patch == ref.patch
    # DELETE passes through unmutated (reference: CREATE/UPDATE only)
    r = h.handle(_review("u2", copy.deepcopy(POD), operation="DELETE"))
    assert r.allowed and r.patch is None
    # excluded namespace passes through
    r = h.handle(_review("u3", copy.deepcopy(POD),
                         namespace="kube-system"))
    assert r.allowed and r.patch is None


def test_handler_error_answers_allowed_with_message():
    h = BatchedMutationHandler(_system())
    bad = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "bad"},
           "spec": {"containers": "notalist"}}
    want = MutationLane(_system()).reference_outcome(copy.deepcopy(bad))
    assert want.error is not None  # the corpus shape really errors
    r = h.handle(_review("u1", bad))
    assert r.allowed and r.patch is None
    assert r.message == want.error


class _ShedGate:
    """OverloadController stand-in whose admit always sheds."""

    def __init__(self, reason="queue_full", retry_after_s=2.0):
        self.reason = reason
        self.retry_after_s = retry_after_s

    def admit(self, cost):
        raise Shed(self.reason, self.retry_after_s)


def test_shed_failure_policy_ignore_admits_unmutated():
    h = BatchedMutationHandler(_system(), overload=_ShedGate(),
                               failure_policy="ignore")
    r = h.handle(_review("u1", copy.deepcopy(POD)))
    assert r.allowed and r.patch is None
    assert r.warnings and "shed" in r.warnings[0]


def test_shed_failure_policy_fail_429_retry_after():
    h = BatchedMutationHandler(_system(), overload=_ShedGate(),
                               failure_policy="fail")
    r = h.handle(_review("u1", copy.deepcopy(POD)))
    assert not r.allowed
    assert r.code == 429
    assert r.retry_after_s == pytest.approx(2.0)


def test_server_mutate_endpoint_emits_retry_after_header():
    h = BatchedMutationHandler(_system(), overload=_ShedGate(),
                               failure_policy="fail")
    srv = WebhookServer(mutation_handler=h, port=0).start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        c.request("POST", "/v1/mutate",
                  json.dumps(_review("u1", POD)).encode(),
                  {"Content-Type": "application/json"})
        resp = c.getresponse()
        doc = json.loads(resp.read())
        c.close()
        assert resp.getheader("Retry-After") == "2"
        assert doc["response"]["allowed"] is False
        assert doc["response"]["status"]["code"] == 429
    finally:
        srv.stop(drain_timeout=2)


def test_server_mutate_endpoint_patch_roundtrip():
    """The full wire path: POST /v1/mutate through the microbatcher,
    base64 JSONPatch in the response, bit-identical to the reference."""
    import base64

    system = _system()
    lane = MutationLane(system)
    batcher = MutationBatcher(lane).start()
    h = BatchedMutationHandler(system, lane=lane, batcher=batcher)
    srv = WebhookServer(mutation_handler=h, port=0,
                        mutation_batcher=batcher).start()
    try:
        want = MutationLane(_system()).reference_outcome(
            copy.deepcopy(POD))
        results = {}
        lock = threading.Lock()

        def post(i):
            c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                           timeout=10)
            c.request("POST", "/v1/mutate",
                      json.dumps(_review(f"u{i}", POD)).encode(),
                      {"Content-Type": "application/json"})
            doc = json.loads(c.getresponse().read())
            with lock:
                results[f"u{i}"] = doc["response"]
            c.close()

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(results) == 8
        for uid, resp in results.items():
            assert resp["uid"] == uid
            assert resp["allowed"] is True
            assert resp["patchType"] == "JSONPatch"
            patch = json.loads(base64.b64decode(resp["patch"]))
            assert patch == want.patch
    finally:
        srv.stop(drain_timeout=5)
        batcher.stop()


def test_mutation_batcher_stop_drains_queue():
    """Reviews queued in the mutate batcher at stop() time still answer
    (zero-loss drain covers /v1/mutate)."""
    lane = MutationLane(_system())
    b = MutationBatcher(lane, max_batch=2).start()
    plan = FaultPlan([{"site": "mutation.batch", "mode": "sleep",
                       "delay_s": 0.05}])
    results, errors = {}, {}

    def one(i):
        try:
            results[i] = b.mutate({"apiVersion": "v1", "kind": "Pod",
                                   "metadata": {"name": f"p{i}"},
                                   "spec": {}}, None)
        except Exception as e:  # noqa: BLE001
            errors[i] = e

    with inject(plan):
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(10)]
        for t in threads:
            t.start()
        drained = b.stop(timeout=15)
        for t in threads:
            t.join(15)
    assert drained
    assert errors == {}
    assert len(results) == 10
    assert b.queue_depth() == 0
    # chaos error mode routed to host: the verdicts are still correct
    for out in results.values():
        assert out.patch  # every empty pod gets mutated


# --- gator bench + the bench script ---------------------------------------

def test_gator_bench_mutate_engine():
    from gatekeeper_tpu.gator.bench import run_bench

    objs = _mixed_registry() + make_cluster_objects(40, seed=17)
    r = run_bench(objs, "mutate", iterations=2)
    assert r.engine == "mutate"
    assert r.reviews_per_sec > 0
    lo = r.lowering
    assert lo["lowered_mutators"] == 6
    assert lo["host_only_mutators"] == 2
    assert lo["host_objs_per_sec"] > 0
    assert sum(lo["lanes"].values()) == r.objects


@pytest.mark.slow
def test_bench_mutation_smoke():
    """tools/bench_mutation.py --smoke runs green (the script embeds a
    differential spot check, so a diverging lane fails here too)."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "bench_mutation.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=root)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout)
    assert rec["batched_objs_per_sec"] > 0
    assert rec["host_objs_per_sec"] > 0
    assert rec["lanes"]
