"""Snapshot spill persistence: cold-start-free restarts.

1. THE restart differential: build → churn → tick → spill → "restart"
   into a FRESH driver/vocab/evaluator (compile cache warm) → load →
   tick, pinned bit-identical to a fresh relist with ZERO list calls,
   ZERO flatten, ZERO lowerings and ZERO fused-sweep retraces.
2. Torn/corrupt/stale spills: truncated section, flipped byte,
   schema-version drift, constraint-set drift — each a counted miss,
   deleted, and the boot falls back to a clean relist.
3. Stale-spill recovery: the cluster changed while the process was
   down — the warm resubscription's replay/diff (synthetic DELETEDs off
   the spilled key set) reconciles, tick equals a fresh relist.
4. The kube watch seam: ``from_rv`` resume makes zero list calls; an rv
   compacted past the spill 410s into the standard relist recovery.
5. Drain flush, extdata column TTL spill, and the QoS ledger's
   slo-window decay satellite.

Wall-budget note: one module-scoped corpus (8-template library slice,
120 objects) and a shared on-disk compile cache keep the fresh-client
restart test cheap (tier-1 runs ~35s under its timeout).
"""

from __future__ import annotations

import copy
import glob
import os
import shutil
import threading
import time

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.generation import CompileCache, WarmStateCache
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.ops.flatten import Flattener, RowIdMap
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.snapshot import (ClusterSnapshot, SnapshotConfig,
                                     SnapshotSpill, SnapshotSpiller,
                                     WatchIngester, gvks_of,
                                     templates_digest)
from gatekeeper_tpu.snapshot.persist import (HEADER, MISS_COLD,
                                             MISS_CORRUPT, MISS_PLAN,
                                             MISS_VERSION)
from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig
from gatekeeper_tpu.sync.mock_apiserver import MockApiServer
from gatekeeper_tpu.sync.source import ADDED, DELETED, FakeCluster
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import (library_dir, load_library,
                                            make_cluster_objects)
from gatekeeper_tpu.utils.unstructured import load_yaml_file

POD_GVK = ("", "v1", "Pod")


def _all_kinds():
    paths = sorted(
        glob.glob(os.path.join(library_dir(), "general", "*",
                               "template.yaml")) +
        glob.glob(os.path.join(library_dir(), "pod-security-policy", "*",
                               "template.yaml")))
    return [load_yaml_file(p)[0]["spec"]["crd"]["spec"]["names"]["kind"]
            for p in paths]


_KEEP = 8  # template-subset client: bounded compile+trace wall (tier-1)


def _make_client(cache_dir):
    skip = tuple(_all_kinds()[_KEEP:])
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel,
                    compile_cache=CompileCache(str(cache_dir)))
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client, skip_kinds=skip)
    return client, tpu


def _snap_manager(client, evaluator, lister, snapshot, spiller=None):
    return AuditManager(
        client, lister=lister,
        config=AuditConfig(audit_source="snapshot", chunk_size=64,
                           exact_totals=False, pipeline="off"),
        evaluator=evaluator, snapshot=snapshot, spiller=spiller)


def _relist_reference(client, evaluator, lister):
    return AuditManager(
        client, lister=lister,
        config=AuditConfig(chunk_size=64, exact_totals=False,
                           pipeline="off"),
        evaluator=evaluator).audit()


def _assert_identical(run_a, run_b, limit=20):
    diff = AuditManager._verdicts_differ_canonical(
        run_a.kept, run_a.total_violations,
        run_b.kept, run_b.total_violations, limit)
    assert diff is None, diff


def _churn_labels(cluster, objects, tag, n=10):
    """Modify the SAME first n objects (layouts repeat across rounds —
    the zero-retrace pin's precondition)."""
    for j in range(n):
        o = copy.deepcopy(objects[j])
        o.setdefault("metadata", {}).setdefault("labels", {})["churn"] = \
            tag
        cluster.apply(o)


def wait_for(pred, timeout=10.0):
    end = time.time() + timeout
    while time.time() < end:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """client1 + warm pre-restart state: full pass, one churn tick, the
    spill and warm state saved to module-scoped dirs."""
    cache_dir = tmp_path_factory.mktemp("compile-cache")
    spill_dir = tmp_path_factory.mktemp("spill")
    client, tpu = _make_client(cache_dir)
    objects = make_cluster_objects(120, seed=13)
    cluster = FakeCluster()
    for o in objects:
        cluster.apply(copy.deepcopy(o))

    def lister():
        return iter(cluster.list())

    evaluator = ShardedEvaluator(tpu, make_mesh(), violations_limit=20)
    snapshot = ClusterSnapshot(evaluator, SnapshotConfig())
    mgr = _snap_manager(client, evaluator, lister, snapshot)
    ingester = WatchIngester(snapshot, cluster,
                             gvks_of(cluster.list())).start()
    mgr.audit()
    _churn_labels(cluster, objects, "r0")
    ingester.pump()
    tick_run = mgr.audit_tick()
    spill = SnapshotSpill(str(spill_dir))
    wrote = spill.save(snapshot, rvs=dict(ingester.rvs),
                       templates=templates_digest(client))
    assert wrote["ok"] and wrote["rows"] == 120
    assert WarmStateCache(str(cache_dir)).save(tpu, evaluator)
    ctx = {
        "client": client, "tpu": tpu, "objects": objects,
        "cluster": cluster, "lister": lister, "evaluator": evaluator,
        "snapshot": snapshot, "mgr": mgr, "ingester": ingester,
        "cache_dir": str(cache_dir), "spill_dir": str(spill_dir),
        "cons": [c for c in client.constraints()
                 if c.actions_for(AUDIT_EP)],
        "tdig": templates_digest(client),
        "tick_run": tick_run,
    }
    yield ctx
    ingester.stop()


# --- 0. unit: identity + cold miss -----------------------------------------

def test_rowid_export_restore_keeps_high_water():
    ids = RowIdMap()
    a, _ = ids.assign(("k", "ns", "a"))
    b, _ = ids.assign(("k", "ns", "b"))
    ids.forget(("k", "ns", "a"))  # retired, never reissued
    state = ids.export_state()
    fresh = RowIdMap()
    fresh.restore(state)
    assert fresh.get(("k", "ns", "b")) == b
    assert fresh.get(("k", "ns", "a")) is None
    nid, created = fresh.assign(("k", "ns", "c"))
    assert created and nid > max(a, b)  # above every id EVER issued


def test_spill_cold_miss_counted(corpus, tmp_path):
    spill = SnapshotSpill(str(tmp_path / "empty"))
    snap = ClusterSnapshot(corpus["evaluator"], SnapshotConfig())
    assert spill.load(snap, corpus["cons"],
                      templates=corpus["tdig"]) is None
    assert spill.miss_reasons == {MISS_COLD: 1}
    assert snap.stale  # untouched on a miss


# --- 1. THE restart differential ------------------------------------------

def test_restart_roundtrip_cold_start_free(corpus):
    """Fresh driver/vocab/evaluator (the real restart shape, compile
    cache warm): spill load + warm-state replay serve the first tick
    with zero list calls, zero flatten, zero lowerings, zero fused
    retraces — verdicts and row ids bit-identical to the pre-restart
    state and to a fresh relist of the same cluster."""
    client2, tpu2 = _make_client(corpus["cache_dir"])
    assert tpu2._compile_cache.misses == 0  # boot answered from disk
    ev2 = ShardedEvaluator(tpu2, make_mesh(), violations_limit=20)
    rep = WarmStateCache(corpus["cache_dir"]).replay(tpu2, ev2)
    assert rep["hit"] and rep["sweep_traces"] > 0
    snap2 = ClusterSnapshot(ev2, SnapshotConfig())
    cons2 = [c for c in client2.constraints() if c.actions_for(AUDIT_EP)]
    spill = SnapshotSpill(corpus["spill_dir"])
    loaded = spill.load(snap2, cons2, templates=templates_digest(client2))
    assert loaded is not None and loaded["rows"] == 120
    assert not snap2.stale and snap2.warm_loaded
    # row ids survived the restart exactly (gid-keyed verdicts depend
    # on it)
    assert dict(snap2.ids._ids) == dict(corpus["snapshot"].ids._ids)

    cluster, objects = corpus["cluster"], corpus["objects"]
    calls = [0]

    def counting_lister():
        calls[0] += 1
        return iter(cluster.list())

    mgr2 = _snap_manager(client2, ev2, counting_lister, snap2)
    ing2 = WatchIngester(snap2, cluster, gvks_of(cluster.list()),
                         from_rvs=loaded["rvs"]).start()
    try:
        # first tick: NOTHING changed since the spill — zero list, zero
        # flatten, zero rows evaluated (replay churn absorbs as no-op)
        flattens = [0]
        orig_flatten = Flattener.flatten

        def counting_flatten(self, *a, **k):
            flattens[0] += 1
            return orig_flatten(self, *a, **k)

        Flattener.flatten = counting_flatten
        try:
            tick0 = mgr2.audit_tick()
        finally:
            Flattener.flatten = orig_flatten
        assert calls[0] == 0, "warm boot paid a list call"
        assert flattens[0] == 0, "warm boot paid a flatten"
        assert mgr2.perf.get("snapshot_rows_evaluated", 0) == 0
        _assert_identical(tick0, corpus["tick_run"])
        # churn the SAME objects the pre-restart process churned: the
        # tick's layouts repeat, so the replayed traces must absorb it
        tc0, miss0 = ev2.trace_count, tpu2._compile_cache.misses
        _churn_labels(cluster, objects, "r1")
        ing2.pump()
        tick1 = mgr2.audit_tick()
        assert calls[0] == 0
        assert ev2.trace_count == tc0, "post-restart tick retraced"
        assert tpu2._compile_cache.misses == miss0
        relist = _relist_reference(client2, ev2, corpus["lister"])
        _assert_identical(tick1, relist)
        # columns/vocab prove out row by row (the resync differential)
        assert snap2.resync_differential(
            lambda: iter(cluster.list())) is None
    finally:
        ing2.stop()


# --- 2. stale spill: the cluster moved while the process was down ----------
# (runs BEFORE the corrupt-spill rebuild below: the rebuild interns the
# later churn's strings into client1's vocab, after which the pristine
# spill's vocab is no longer a prefix and would legitimately miss)


def test_stale_spill_reconciles_through_replay_diff(corpus):
    """Load the spill against a cluster that changed since it was
    written (delete + modify + add): the warm resubscription's replay
    plus the synthetic-DELETE diff off the spilled key set reconcile
    the resident rows, and the first tick equals a fresh relist — no
    verdict divergence, no relist boot."""
    objects = corpus["objects"]
    c2 = FakeCluster()
    for o in corpus["cluster"].list():
        c2.apply(copy.deepcopy(o))
    gone = copy.deepcopy(objects[20])
    c2.delete(gone)
    changed = copy.deepcopy(objects[21])
    changed.setdefault("metadata", {}).setdefault(
        "labels", {})["churn"] = "while-down"
    c2.apply(changed)
    newobj = copy.deepcopy(objects[22])
    newobj["metadata"]["name"] = objects[22]["metadata"]["name"] + "-new"
    c2.apply(newobj)

    snapX = ClusterSnapshot(corpus["evaluator"], SnapshotConfig())
    spill = SnapshotSpill(corpus["spill_dir"])
    loaded = spill.load(snapX, corpus["cons"], templates=corpus["tdig"])
    assert loaded is not None

    def lister():
        return iter(c2.list())

    ing = WatchIngester(snapX, c2, gvks_of(c2.list()),
                        from_rvs=loaded["rvs"]).start()
    try:
        mgrX = _snap_manager(corpus["client"], corpus["evaluator"],
                             lister, snapX)
        tick = mgrX.audit_tick()
        relist = _relist_reference(corpus["client"], corpus["evaluator"],
                                   lister)
        _assert_identical(tick, relist)
        # the vanished object's row is gone (synthetic DELETED landed)
        from gatekeeper_tpu.snapshot import obj_key

        assert snapX.ids.get(obj_key(gone)) is None
        assert snapX.resync_differential(lambda: iter(c2.list())) is None
    finally:
        ing.stop()


# --- 2b. delta spills: per-group sections, only-dirty rewrites --------------
# (placed after the pristine-spill loads above: these churn the module
# cluster, interning fresh strings; gate-order misses in section 3 fire
# before the vocab check, so they stay unaffected)


def _group_marks(snapshot):
    return {"|".join(sorted(st.group)): st.mutations
            for st in snapshot._groups.values()}


def test_delta_spill_reuses_clean_groups_and_roundtrips(corpus, tmp_path):
    snap, cluster = corpus["snapshot"], corpus["cluster"]
    d = str(tmp_path / "delta")
    spill = SnapshotSpill(d, delta=True, full_every=100)
    w0 = spill.save(snap, templates=corpus["tdig"])
    assert w0["ok"]
    gfiles = sorted(glob.glob(os.path.join(d, "snapshot.group-*.pkl")))
    assert len(gfiles) == len(snap._groups)  # first spill is full
    assert spill.groups_skipped == 0
    bytes0 = {p: open(p, "rb").read() for p in gfiles}

    # no churn: the second spill reuses EVERY group section, and the
    # written payload collapses to the slim manifest + vocab + aux
    w1 = spill.save(snap, templates=corpus["tdig"])
    assert w1["ok"] and w1["bytes"] < w0["bytes"]
    assert spill.delta_spills == 1
    assert spill.groups_skipped == len(gfiles)
    for p in gfiles:
        assert open(p, "rb").read() == bytes0[p]  # untouched on disk

    # churn a few rows: ONLY the stores whose mutation mark moved
    # rewrite their section
    marks0 = _group_marks(snap)
    _churn_labels(cluster, corpus["objects"], "r1", n=6)
    corpus["ingester"].pump()
    corpus["mgr"].audit_tick()
    marks1 = _group_marks(snap)
    dirty = {k for k, m in marks1.items() if marks0.get(k) != m}
    assert dirty and len(dirty) < len(marks1)
    skipped0 = spill.groups_skipped
    w2 = spill.save(snap, templates=corpus["tdig"])
    assert w2["ok"]
    assert spill.groups_skipped - skipped0 == len(marks1) - len(dirty)

    # round-trip: a fresh snapshot adopts the reassembled groups and
    # proves out row by row against a fresh relist
    snap2 = ClusterSnapshot(corpus["evaluator"], SnapshotConfig())
    out = spill.load(snap2, corpus["cons"], templates=corpus["tdig"])
    assert out is not None and out["rows"] == snap.live_count()
    assert dict(snap2.ids._ids) == dict(snap.ids._ids)
    assert snap2.resync_differential(
        lambda: iter(cluster.list())) is None


def test_delta_spill_full_every_rewrite_prunes_orphans(corpus, tmp_path):
    snap = corpus["snapshot"]
    d = str(tmp_path / "delta-full")
    spill = SnapshotSpill(d, delta=True, full_every=2)
    assert spill.save(snap, templates=corpus["tdig"])["ok"]  # full
    n = len(glob.glob(os.path.join(d, "snapshot.group-*.pkl")))
    assert spill.save(snap, templates=corpus["tdig"])["ok"]  # delta
    assert spill.groups_skipped == n
    # plant an orphan (a deleted group's leftover section): the next
    # spill is the full_every'th — a full rewrite that prunes it
    orphan = os.path.join(d, "snapshot.group-deadbeefdead.pkl")
    with open(orphan, "wb") as f:
        f.write(b"stale")
    assert spill.save(snap, templates=corpus["tdig"])["ok"]  # full again
    assert spill.groups_skipped == n  # nothing reused on the full
    assert not os.path.exists(orphan)
    # loadable after the cycle
    snapF = ClusterSnapshot(corpus["evaluator"], SnapshotConfig())
    assert spill.load(snapF, corpus["cons"],
                      templates=corpus["tdig"]) is not None


def test_delta_spill_corrupt_group_section_rejected(corpus, tmp_path):
    snap = corpus["snapshot"]
    d = str(tmp_path / "delta-corrupt")
    spill = SnapshotSpill(d, delta=True)
    assert spill.save(snap, templates=corpus["tdig"])["ok"]
    gfile = sorted(glob.glob(os.path.join(d, "snapshot.group-*.pkl")))[0]
    with open(gfile, "r+b") as f:
        f.seek(os.path.getsize(gfile) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    fresh = SnapshotSpill(d, delta=True)
    snapC = ClusterSnapshot(corpus["evaluator"], SnapshotConfig())
    assert fresh.load(snapC, corpus["cons"],
                      templates=corpus["tdig"]) is None
    assert fresh.miss_reasons == {MISS_CORRUPT: 1}
    # the reject deleted the WHOLE spill, group sections included
    assert glob.glob(os.path.join(d, "snapshot.group-*.pkl")) == []
    assert not os.path.exists(os.path.join(d, HEADER))
    # ...and the original writer fails CLOSED (its stubs reference the
    # deleted sections), then recovers with a forced-full spill
    assert not spill.save(snap, templates=corpus["tdig"])["ok"]
    assert spill.save(snap, templates=corpus["tdig"])["ok"]
    snapR = ClusterSnapshot(corpus["evaluator"], SnapshotConfig())
    assert spill.load(snapR, corpus["cons"],
                      templates=corpus["tdig"]) is not None


def test_non_delta_spill_format_unchanged(corpus, tmp_path):
    """delta=False keeps the PR 13/14 inline single-section layout: no
    group files, no manifest key in rows.pkl."""
    import pickle

    d = str(tmp_path / "classic")
    spill = SnapshotSpill(d)
    assert spill.save(corpus["snapshot"],
                      templates=corpus["tdig"])["ok"]
    assert glob.glob(os.path.join(d, "snapshot.group-*.pkl")) == []
    with open(os.path.join(d, "snapshot.rows.pkl"), "rb") as f:
        state = pickle.load(f)
    assert "group_files" not in state and "groups" in state


# --- 3. torn / corrupt / drifted spills ------------------------------------

def _copy_spill(corpus, tmp_path):
    dst = tmp_path / "spill-copy"
    shutil.copytree(corpus["spill_dir"], dst)
    return str(dst)


def _load_into_fresh(corpus, spill_dir):
    spill = SnapshotSpill(spill_dir)
    snap = ClusterSnapshot(corpus["evaluator"], SnapshotConfig())
    out = spill.load(snap, corpus["cons"], templates=corpus["tdig"])
    return spill, snap, out


def test_spill_truncated_section_falls_back_to_relist(corpus, tmp_path):
    d = _copy_spill(corpus, tmp_path)
    rows_p = os.path.join(d, "snapshot.rows.pkl")
    with open(rows_p, "r+b") as f:
        f.truncate(os.path.getsize(rows_p) // 2)
    spill, snap, out = _load_into_fresh(corpus, d)
    assert out is None
    assert spill.miss_reasons == {MISS_CORRUPT: 1}
    assert not os.path.exists(os.path.join(d, HEADER))  # deleted
    # the fallback: a clean relist boot, verdicts identical to relist
    mgr = _snap_manager(corpus["client"], corpus["evaluator"],
                        corpus["lister"], snap)
    run = mgr.audit()  # stale snapshot -> rebuild (the relist path)
    relist = _relist_reference(corpus["client"], corpus["evaluator"],
                               corpus["lister"])
    _assert_identical(run, relist)


def test_spill_flipped_byte_in_column_section_rejected(corpus, tmp_path):
    d = _copy_spill(corpus, tmp_path)
    rows_p = os.path.join(d, "snapshot.rows.pkl")
    with open(rows_p, "r+b") as f:
        f.seek(os.path.getsize(rows_p) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    spill, snap, out = _load_into_fresh(corpus, d)
    assert out is None
    assert spill.miss_reasons == {MISS_CORRUPT: 1}
    assert snap.stale


def test_spill_schema_version_drift_rejected(corpus, tmp_path):
    import json

    d = _copy_spill(corpus, tmp_path)
    hp = os.path.join(d, HEADER)
    with open(hp) as f:
        header = json.load(f)
    header["flatten_schema_version"] += 1
    with open(hp, "w") as f:
        json.dump(header, f)
    spill, snap, out = _load_into_fresh(corpus, d)
    assert out is None
    assert spill.miss_reasons == {MISS_VERSION: 1}
    assert not os.path.exists(hp)


def test_spill_constraint_drift_rejected(corpus, tmp_path):
    d = _copy_spill(corpus, tmp_path)
    spill = SnapshotSpill(d)
    snap = ClusterSnapshot(corpus["evaluator"], SnapshotConfig())
    out = spill.load(snap, corpus["cons"][:-1],  # one constraint gone
                     templates=corpus["tdig"])
    assert out is None
    assert spill.miss_reasons == {MISS_PLAN: 1}


# --- 4. the kube watch seam: rv resume + 410 fallback ----------------------

@pytest.fixture()
def server():
    srv = MockApiServer().start()
    yield srv
    srv.stop()


def _pod(name):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "img"}]}}


def test_kube_warm_resume_makes_zero_list_calls(server):
    kube = KubeCluster(KubeConfig(server=server.url), page_limit=50,
                       watch_backoff_s=0.05, watch_timeout_s=20.0)
    try:
        server.put_object(_pod("a"))
        server.put_object(_pod("b"))
        _objs, rv = kube._list_paged(POD_GVK)  # the "spilled" rv
        lists = [0]
        orig = kube._list_paged

        def counting(gvk):
            lists[0] += 1
            return orig(gvk)

        kube._list_paged = counting
        events = []
        cancel = kube.subscribe(
            POD_GVK, events.append, replay=True, from_rv=rv,
            seed_known=[("default", "a"), ("default", "b")])
        try:
            server.put_object(_pod("new1"))
            assert wait_for(lambda: any(
                e.obj["metadata"]["name"] == "new1" for e in events))
            assert lists[0] == 0, "warm resume paid a list call"
            # nothing replayed the world: only the missed event arrived
            assert all(e.obj["metadata"]["name"] == "new1"
                       for e in events)
        finally:
            cancel()
    finally:
        kube.close()


def test_kube_stale_rv_410_falls_back_to_relist_with_diff(server):
    kube = KubeCluster(KubeConfig(server=server.url), page_limit=50,
                       watch_backoff_s=0.05, watch_timeout_s=20.0)
    try:
        server.put_object(_pod("stay"))
        server.put_object(_pod("goner"))
        _objs, rv = kube._list_paged(POD_GVK)
        # while "down": goner vanishes, history compacts past our rv
        with server._lock:
            server._objects.pop(("Pod", "default", "goner"))
        server.put_object(_pod("later"))
        server.compact()
        events = []
        cancel = kube.subscribe(
            POD_GVK, events.append, replay=True, from_rv=rv,
            seed_known=[("default", "stay"), ("default", "goner")])
        try:
            # 410 -> relist recovery: synthetic DELETED for the spilled
            # key the fresh list no longer carries, MODIFIED/ADDED churn
            # for the rest
            assert wait_for(lambda: any(
                e.type == DELETED
                and e.obj["metadata"]["name"] == "goner"
                for e in events))
            assert wait_for(lambda: any(
                e.type == ADDED
                and e.obj["metadata"]["name"] == "later"
                for e in events))
        finally:
            cancel()
    finally:
        kube.close()


# --- 5. drain flush + spiller ----------------------------------------------

def test_drain_flushes_final_spill(corpus, tmp_path):
    spill = SnapshotSpill(str(tmp_path / "drain-spill"))
    spiller = SnapshotSpiller(spill, corpus["snapshot"],
                              templates_fn=lambda: corpus["tdig"])
    mgr = _snap_manager(corpus["client"], corpus["evaluator"],
                        corpus["lister"], corpus["snapshot"],
                        spiller=spiller)
    mgr.config.interval_s = 30.0
    # the resident snapshot is already evaluated (rows clean, verdicts
    # stored) — boot it warm so run_forever's first pass is a cheap
    # tick, not a second full evaluation (tier-1 wall budget)
    corpus["snapshot"].warm_loaded = True
    t = threading.Thread(target=mgr.run_forever, daemon=True)
    t.start()
    try:
        assert wait_for(lambda: not corpus["snapshot"].stale,
                        timeout=30.0)
    finally:
        mgr.stop()
        t.join(timeout=30.0)
    assert not t.is_alive()
    # run_forever's exit flushed the resident state to disk
    assert os.path.exists(os.path.join(spill.root, HEADER))
    assert spiller.last_result and spiller.last_result["ok"]
    assert spiller.last_result["rows"] == \
        corpus["snapshot"].live_count()
    # a background request coalesces + lands too
    spiller.request(wait=True)
    assert spiller.last_result["ok"]
    spiller.stop(flush=False)


# --- 6. extdata column spill (per-key TTL) ----------------------------------

def test_extdata_column_spill_drops_expired_keys():
    from gatekeeper_tpu.extdata.lane import ExtDataLane
    from gatekeeper_tpu.externaldata.providers import ProviderCache

    clock = [1000.0]
    lane = ExtDataLane(ProviderCache(), clock=lambda: clock[0])
    col = lane.column("prov")
    col.land({"k-fresh": ("v1", None), "k-err": (None, "boom")})
    clock[0] += col.ttl_s * 0.6
    col.land({"k-young": ("v2", None)})
    payload = lane.export_columns()
    # "restart" on a new clock epoch after half a TTL of downtime: the
    # older keys (0.6 TTL consumed at spill + 0.5 down > 1.0) expired
    clock2 = [5000.0]
    lane2 = ExtDataLane(ProviderCache(), clock=lambda: clock2[0])
    landed = lane2.import_columns(payload, elapsed_s=col.ttl_s * 0.5)
    col2 = lane2.column("prov")
    assert landed == 1
    assert col2.get("k-young") == ("v2", None)
    assert col2.missing(["k-fresh", "k-err", "k-young"]) == \
        ["k-fresh", "k-err"]


# --- 7. QoS ledger decay: slo-window satellite ------------------------------

def test_qos_ledger_event_decay_bit_identical_when_unarmed():
    from gatekeeper_tpu.resilience.qos import TenantCostLedger

    a = TenantCostLedger(half_every=4)
    b = TenantCostLedger(half_every=4)
    b.set_clock(None, 0.0)  # explicit disarm == default
    for i in range(13):
        a.charge(f"t{i % 3}", 100.0 + i)
        b.charge(f"t{i % 3}", 100.0 + i)
    assert a.totals() == b.totals()


def test_qos_ledger_slo_window_decay_halves_per_window():
    from gatekeeper_tpu.resilience.qos import TenantCostLedger

    clock = [0.0]
    led = TenantCostLedger(half_every=4)
    led.set_clock(lambda: clock[0], 300.0)
    for _ in range(8):  # event count alone must NOT decay any more
        led.charge("noisy", 100.0)
    assert led.heaviness("noisy") == 800.0
    clock[0] = 301.0
    led.charge("noisy", 0.0)  # one window elapsed: halve once
    assert led.heaviness("noisy") == 400.0
    clock[0] = 1000.0  # two more windows
    led.charge("quiet", 10.0)
    assert led.heaviness("noisy") == 100.0
    assert led.heaviness("quiet") == 10.0


def test_overload_controller_wires_ledger_clock():
    from gatekeeper_tpu.resilience.overload import (OverloadConfig,
                                                    OverloadController)
    from gatekeeper_tpu.resilience.qos import QoSConfig

    ctl = OverloadController(OverloadConfig(qos=QoSConfig()))
    clock = [0.0]
    ctl.set_qos_ledger_clock(lambda: clock[0], 100.0)
    ctl._ledger_qos.charge("t", 64.0)
    clock[0] = 101.0
    ctl._ledger_qos.charge("t", 0.0)
    assert ctl._ledger_qos.heaviness("t") == 32.0
    # disarm restores event-count behavior (the default path)
    ctl.set_qos_ledger_clock(None, 0.0)
    assert ctl._ledger_qos._clock is None


# --- 6. worker-flattened rows: stable ids + compressed spill (ISSUE 14) ----
# Appended LAST on purpose: this test interns fresh strings through the
# module corpus's shared vocab, which would make the module-scoped spill
# un-loadable (miss reason `vocab`) for any spill-loading test after it.

def test_worker_flattened_rows_stable_ids_and_zlib_spill(corpus, tmp_path):
    """The snapshot patch-lane x flatten-workers interaction pin:
    rows columnized through the multiprocess worker pool keep stable
    RowIdMap ids, verdicts equal a fresh relist, and the state round-
    trips through a zlib-compressed spill bit-identically."""
    from gatekeeper_tpu.ops.flatten import shutdown_flatten_pools
    from gatekeeper_tpu.utils.rawjson import as_raw

    client, tpu = corpus["client"], corpus["tpu"]
    evaluator = ShardedEvaluator(tpu, make_mesh(), violations_limit=20,
                                 flatten_workers=2)
    objects = make_cluster_objects(250, seed=29)
    cluster = FakeCluster()
    for o in objects:
        cluster.apply(copy.deepcopy(o))

    def lister():
        # RawJSON input — the worker pool's lane (bytes cross the
        # process boundary, never a DOM)
        return (as_raw(copy.deepcopy(o)) for o in cluster.list())

    try:
        snapshot = ClusterSnapshot(evaluator, SnapshotConfig())
        mgr = _snap_manager(client, evaluator, lister, snapshot)
        run = mgr.audit()  # rebuild: the Pod group flattens >128 rows
        assert sum(run.total_violations.values()) > 0
        # the pool actually columnized some group's resident rows
        assert any(getattr(st.flattener, "last_workers_used", 0) == 2
                   for st in snapshot._groups.values()
                   if st.flattener is not None)
        # row ids assigned before/independent of the worker flatten
        gids0 = {uid: snapshot.ids.get(uid) for uid in snapshot.ids.uids()}
        assert len(gids0) == 250

        # verdict parity with a fresh relist through the same evaluator
        _assert_identical(run, _relist_reference(client, evaluator,
                                                 lister))

        # zlib spill round-trip: fresh snapshot adopts the exact state
        spill = SnapshotSpill(str(tmp_path / "wspill"), compress="zlib")
        wrote = spill.save(snapshot, templates=corpus["tdig"])
        assert wrote["ok"] and wrote["rows"] == 250
        snap2 = ClusterSnapshot(evaluator, SnapshotConfig())
        out = spill.load(snap2, corpus["cons"], templates=corpus["tdig"])
        assert out is not None and out["rows"] == 250
        gids1 = {uid: snap2.ids.get(uid) for uid in snap2.ids.uids()}
        assert gids1 == gids0  # stable ids across the compressed spill
        run2 = _snap_manager(client, evaluator, lister, snap2).audit_tick()
        _assert_identical(run2, run)
    finally:
        shutdown_flatten_pools()
