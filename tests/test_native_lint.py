"""Native-kernel gates: warning-clean strict compiles in tier-1, the
ASan/UBSan corpus run slow-marked, and the ops/native.py flag-digest
rebuild semantics (a compile-flag change must never silently reuse the
previous binary)."""

import os
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import lint_native  # noqa: E402
from gatekeeper_tpu.ops import native  # noqa: E402


@pytest.mark.parametrize("src", lint_native.SOURCES)
def test_native_warning_clean(src):
    ok, out = lint_native.compile_strict(src)
    assert ok, f"native/{src} fails -Wall -Wextra -Werror:\n{out}"


@pytest.mark.slow
def test_native_asan_corpus():
    """The flatten unit corpus under an ASan+UBSan build of both
    modules: memory errors / UB in the threaded kernel fail here
    before they can corrupt a sweep."""
    ok, out = lint_native.asan_corpus_run()
    assert ok, f"sanitizer corpus run failed:\n{out}"


# --- flag-digest rebuild semantics (ops/native._build) -----------------

_TRIVIAL_MOD = textwrap.dedent("""\
    #define PY_SSIZE_T_CLEAN
    #include <Python.h>
    static struct PyModuleDef d = {
        PyModuleDef_HEAD_INIT, "%(name)s", NULL, -1, NULL,
        NULL, NULL, NULL, NULL,
    };
    PyMODINIT_FUNC
    PyInit_%(name)s(void)
    {
        return PyModule_Create(&d);
    }
""")


def _expected_out(name):
    import sysconfig

    ext = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(
        os.path.abspath(native._BUILD_DIR),
        native._flag_digest(native._build_flags()), name + ext)


@pytest.fixture
def build_env(tmp_path, monkeypatch):
    monkeypatch.setattr(native, "_NATIVE_DIR", str(tmp_path / "src"))
    monkeypatch.setattr(native, "_BUILD_DIR", str(tmp_path / "build"))
    os.makedirs(tmp_path / "src")
    monkeypatch.delenv("GTPU_NATIVE_CFLAGS", raising=False)

    def write_mod(name):
        path = tmp_path / "src" / f"{name}.c"
        path.write_text(_TRIVIAL_MOD % {"name": name})
        return f"{name}.c"

    return write_mod


def test_build_reuses_fresh_binary(build_env):
    src = build_env("gtpu_lint_t1")
    native._build("gtpu_lint_t1", src)
    out = _expected_out("gtpu_lint_t1")
    assert os.path.exists(out)
    mtime = os.path.getmtime(out)
    native._build("gtpu_lint_t1", src)  # unchanged source + flags
    assert os.path.getmtime(out) == mtime, "fresh binary was recompiled"


def test_build_flag_drift_lands_in_new_dir(build_env, monkeypatch):
    """The regression this guards: _build used to compare source mtime
    only, so an edited flag set silently reused the stale binary.  The
    flag digest is part of the output path — drift compiles fresh."""
    src = build_env("gtpu_lint_t2")
    native._build("gtpu_lint_t2", src)
    plain_out = _expected_out("gtpu_lint_t2")
    assert os.path.exists(plain_out)
    monkeypatch.setenv("GTPU_NATIVE_CFLAGS", "-DGTPU_LINT_DRIFT=1")
    drift_out = _expected_out("gtpu_lint_t2")
    assert os.path.dirname(drift_out) != os.path.dirname(plain_out)
    assert not os.path.exists(drift_out)
    native._build("gtpu_lint_t2", src)
    assert os.path.exists(drift_out), "flag drift did not rebuild"
    assert os.path.exists(plain_out), "drift build clobbered the original"


def test_flag_digest_depends_on_flags():
    a = native._flag_digest(["cc", "-O3"])
    b = native._flag_digest(["cc", "-O3", "-DX"])
    assert a != b
    assert native._flag_digest(["cc", "-O3"]) == a
