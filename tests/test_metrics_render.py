"""Metrics registry exposition format: TYPE lines, label escaping,
quantile label ordering, numeric formatting (the scrape must parse)."""

from gatekeeper_tpu.metrics.registry import (MetricsRegistry, PREFIX, _fmt,
                                             _num)


def test_counter_gauge_summary_type_lines():
    reg = MetricsRegistry()
    reg.inc_counter("requests_count", {"status": "allow"})
    reg.inc_counter("requests_count", {"status": "deny"}, value=2)
    reg.set_gauge("depth", 3)
    reg.observe("latency_seconds", 0.5)
    out = reg.render()
    lines = out.splitlines()
    assert f"# TYPE {PREFIX}requests_count counter" in lines
    assert f"# TYPE {PREFIX}depth gauge" in lines
    assert f"# TYPE {PREFIX}latency_seconds histogram" in lines
    # exactly ONE TYPE line per metric name, before its first sample
    assert sum(1 for ln in lines if ln.startswith("# TYPE")) == 3
    assert f'{PREFIX}requests_count{{status="allow"}} 1' in lines
    assert f'{PREFIX}requests_count{{status="deny"}} 2' in lines
    assert f"{PREFIX}depth 3" in lines
    assert out.endswith("\n")


def test_histogram_count_sum_and_quantile_label_ordering():
    reg = MetricsRegistry()
    for v in (0.1, 0.2, 0.3, 0.4, 1.0):
        reg.observe("dur_seconds", v, {"stage": "flatten"})
    lines = reg.render().splitlines()
    assert f'{PREFIX}dur_seconds_count{{stage="flatten"}} 5' in lines
    assert f'{PREFIX}dur_seconds_sum{{stage="flatten"}} 2' in lines
    # bucketed histogram: cumulative le series incl. +Inf
    assert any(ln.startswith(f'{PREFIX}dur_seconds_bucket'
                             f'{{stage="flatten",le="0.1"}} ')
               for ln in lines), lines
    assert f'{PREFIX}dur_seconds_bucket{{stage="flatten",le="+Inf"}} 5' \
        in lines
    # compat shim: the summary-era quantile series still render, LAST
    # after the sorted user labels, now estimated from lifetime buckets
    for q in ("0.5", "0.9", "0.99"):
        assert any(
            ln.startswith(f'{PREFIX}dur_seconds{{stage="flatten",'
                          f'quantile="{q}"}} ')
            for ln in lines), (q, lines)


def test_label_values_are_escaped():
    reg = MetricsRegistry()
    reg.inc_counter("errs_count", {"msg": 'say "hi"\nback\\slash'})
    out = reg.render()
    line = next(ln for ln in out.splitlines()
                if ln.startswith(f"{PREFIX}errs_count"))
    # exposition-format escapes: \\ then \" then \n — and the rendered
    # page must not contain a raw newline inside a label value
    assert '\\"hi\\"' in line
    assert "\\n" in line and "\nback" not in line
    assert "back\\\\slash" in line
    # every sample line still has the NAME{LABELS} VALUE shape
    for ln in out.splitlines():
        if not ln.startswith("#"):
            assert ln.rsplit(" ", 1)[1] != ""


def test_fmt_and_num_formatting():
    assert _fmt(()) == ""
    assert _fmt((("a", "x"),)) == '{a="x"}'
    assert _fmt((("a", 'q"u'), ("b", "c\\d"))) == \
        '{a="q\\"u",b="c\\\\d"}'
    # integral floats render as integers, true floats as repr
    assert _num(3.0) == "3"
    assert _num(0) == "0"
    assert _num(0.5) == "0.5"
    assert _num(1e-9) == "1e-09"


def test_counter_total_and_get_helpers():
    reg = MetricsRegistry()
    reg.inc_counter("c", {"k": "a"})
    reg.inc_counter("c", {"k": "b"}, value=4)
    assert reg.counter_total("c") == 5
    assert reg.get_counter("c", {"k": "a"}) == 1
    assert reg.get_gauge("missing") is None
