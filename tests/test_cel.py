"""CEL engine + driver tests (reference fixtures: the bats CEL template and
gator bench cel fixtures)."""

import pytest
import yaml

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELCompileError, CELDriver
from gatekeeper_tpu.drivers.rego_driver import RegoDriver
from gatekeeper_tpu.lang.cel.cel import CelError, Program
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget


def ev(src, **bindings):
    return Program(src).eval(bindings)


def test_cel_basics():
    assert ev("1 + 2 * 3") == 7
    assert ev("(1 + 2) * 3") == 9
    assert ev('"a" + "b"') == "ab"
    assert ev("[1, 2] + [3]") == [1, 2, 3]
    assert ev("7 / 2") == 3  # int division truncates
    assert ev("-7 / 2") == -3  # toward zero
    assert ev("-7 % 2") == -1
    assert ev("7.0 / 2") == 3.5
    assert ev("true ? 1 : 2") == 1
    assert ev('size("abc")') == 3
    assert ev('"abc".contains("b")')
    assert ev('"v1.2".matches("^v[0-9]+")')
    assert ev('"a,b,c".split(",", 2)') == ["a", "b,c"]
    assert ev('"a,b,c".split(",", 0)') == []
    assert ev('string(42)') == "42"
    assert ev('int("42")') == 42
    assert ev('type(1)') == "int"


def test_cel_collections():
    assert ev("[1,2,3].all(x, x > 0)")
    assert not ev("[1,-2,3].all(x, x > 0)")
    assert ev("[1,2,3].exists(x, x == 2)")
    assert ev("[1,2,3].exists_one(x, x > 2)")
    assert ev("[1,2,3].filter(x, x > 1)") == [2, 3]
    assert ev("[1,2,3].map(x, x * 2)") == [2, 4, 6]
    assert ev('{"a": 1, "b": 2}.all(k, k != "c")')
    assert ev('"b" in {"a": 1, "b": 2}')
    assert ev("2 in [1, 2]")
    assert ev('{"a": 1}["a"]') == 1


def test_cel_has_and_errors():
    obj = {"spec": {"x": 1}}
    assert ev("has(object.spec)", object=obj)
    assert not ev("has(object.status)", object=obj)
    # cel-go: a missing INTERMEDIATE key errors — hence the chained
    # has(a.b) && has(a.b.c) idiom in VAP templates
    with pytest.raises(CelError):
        ev("has(object.status.phase)", object=obj)
    assert not ev(
        "has(object.status) && has(object.status.phase)", object=obj)
    with pytest.raises(CelError):
        ev("object.status.phase", object=obj)
    # || absorbs an error when the other side decides
    assert ev("true || object.a.b", object={})
    assert ev("object.a.b || true", object={})
    with pytest.raises(CelError):
        ev("false || object.a.b", object={})
    # && likewise
    assert ev("false && object.a.b", object={}) is False
    # macro error absorption: exists decided by another element
    assert ev("[{}, {'privileged': true}].exists(c, c.privileged)")


def test_cel_equality_semantics():
    assert ev("1 == 1.0")
    assert not ev("1 == true")
    assert not ev('1 == "1"')
    assert ev("null == null")
    assert ev("[1, [2]] == [1, [2]]")


CEL_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8scelrequiredlabels"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sCelRequiredLabels"},
                         "validation": {"openAPIV3Schema": {
                             "type": "object"}}}},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "code": [{
                "engine": "K8sNativeValidation",
                "source": {
                    "variables": [
                        {"name": "missing",
                         "expression": (
                             "variables.params.labels.filter(l, "
                             "!has(object.metadata.labels) || "
                             "!(l in object.metadata.labels))"
                         )},
                    ],
                    "validations": [{
                        "expression": "size(variables.missing) == 0",
                        "messageExpression": (
                            '"missing required labels: " + '
                            'variables.missing.join(", ")'
                        ),
                    }],
                },
            }],
        }],
    },
}


def make_client():
    # driver priority: CEL first so CEL-sourced templates land there,
    # mirroring gator's WithK8sCEL registration
    return Client(
        target=K8sValidationTarget(),
        drivers=[RegoDriver(), CELDriver()],
        enforcement_points=["gator.gatekeeper.sh"],
    )


def test_cel_driver_end_to_end():
    client = make_client()
    client.add_template(CEL_TEMPLATE)
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sCelRequiredLabels",
        "metadata": {"name": "need-owner-team"},
        "spec": {"parameters": {"labels": ["owner", "team"]}},
    })
    bad = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "p", "labels": {"owner": "x"}}}
    resp = client.review(AugmentedUnstructured(object=bad),
                         enforcement_point="gator.gatekeeper.sh")
    results = resp.results()
    assert len(results) == 1
    assert results[0].msg == "missing required labels: team"
    good = {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "labels": {"owner": "x", "team": "y"}}}
    resp = client.review(AugmentedUnstructured(object=good),
                         enforcement_point="gator.gatekeeper.sh")
    assert resp.results() == []


def test_cel_reference_bats_template():
    """The reference's namespaceObject CEL template, evaluated verbatim."""
    t = yaml.safe_load(open(
        "/root/reference/test/bats/tests/templates/"
        "k8snamespacelabelcheck_template_cel.yaml"))
    client = make_client()
    client.add_template(t)
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": t["spec"]["crd"]["spec"]["names"]["kind"],
        "metadata": {"name": "ns-check"},
        "spec": {"parameters": {"requiredLabel": "team"}},
    })
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "p", "namespace": "ns1"}}
    ns_with = {"apiVersion": "v1", "kind": "Namespace",
               "metadata": {"name": "ns1", "labels": {"team": "a"}}}
    ns_without = {"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "ns1"}}
    ok = client.review(
        AugmentedUnstructured(object=pod, namespace=ns_with),
        enforcement_point="gator.gatekeeper.sh")
    assert ok.results() == []
    bad = client.review(
        AugmentedUnstructured(object=pod, namespace=ns_without),
        enforcement_point="gator.gatekeeper.sh")
    assert len(bad.results()) == 1
    assert "does not have required label: team" in bad.results()[0].msg


def test_cel_match_conditions_and_failure_policy():
    template = {
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8scelmc"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sCelMc"}}},
            "targets": [{
                "target": "admission.k8s.gatekeeper.sh",
                "code": [{"engine": "K8sNativeValidation", "source": {
                    "matchCondition": [
                        {"name": "only-pods",
                         "expression": 'request.kind.kind == "Pod"'},
                    ],
                    "validations": [
                        {"expression": "false", "message": "always denied"},
                    ],
                }}],
            }],
        },
    }
    client = make_client()
    client.add_template(template)
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sCelMc", "metadata": {"name": "mc"}, "spec": {},
    })
    pod = {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p"}}
    svc = {"apiVersion": "v1", "kind": "Service", "metadata": {"name": "s"}}
    assert len(client.review(
        AugmentedUnstructured(object=pod),
        enforcement_point="gator.gatekeeper.sh").results()) == 1
    assert client.review(
        AugmentedUnstructured(object=svc),
        enforcement_point="gator.gatekeeper.sh").results() == []


def test_cel_delete_normalization():
    """driver.go:184-186: object is null on DELETE for CEL."""
    template = {
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sceldel"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sCelDel"}}},
            "targets": [{
                "target": "admission.k8s.gatekeeper.sh",
                "code": [{"engine": "K8sNativeValidation", "source": {
                    "validations": [
                        {"expression": "object != null",
                         "message": "object is null on delete"},
                    ],
                }}],
            }],
        },
    }
    client = make_client()
    client.add_template(template)
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sCelDel", "metadata": {"name": "d"}, "spec": {},
    })
    from gatekeeper_tpu.target.review import AdmissionRequest

    req = AdmissionRequest(
        kind={"group": "", "version": "v1", "kind": "Pod"},
        operation="DELETE",
        old_object={"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": "p"}},
    )
    resp = client.review(req, enforcement_point="gator.gatekeeper.sh")
    assert len(resp.results()) == 1
    assert resp.results()[0].msg == "object is null on delete"


def test_reserved_prefix_rejected():
    t = {
        "apiVersion": "templates.gatekeeper.sh/v1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8scelbad"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sCelBad"}}},
            "targets": [{
                "target": "admission.k8s.gatekeeper.sh",
                "code": [{"engine": "K8sNativeValidation", "source": {
                    "variables": [{"name": "gatekeeper_internal_x",
                                   "expression": "1"}],
                    "validations": [{"expression": "true"}],
                }}],
            }],
        },
    }
    with pytest.raises(CELCompileError):
        CELDriver().add_template(ConstraintTemplate.from_unstructured(t))


def test_vap_codegen():
    driver = CELDriver()
    t = ConstraintTemplate.from_unstructured(CEL_TEMPLATE)
    driver.add_template(t)
    vap = driver.template_to_vap(t)
    assert vap["kind"] == "ValidatingAdmissionPolicy"
    assert vap["spec"]["validations"][0]["expression"] == (
        "size(variables.missing) == 0")
    assert any(v["name"] == "params" for v in vap["spec"]["variables"])
    con = Constraint.from_unstructured({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sCelRequiredLabels",
        "metadata": {"name": "c1"}, "spec": {}})
    vapb = driver.constraint_to_vap_binding(con, t)
    assert vapb["spec"]["policyName"] == "gatekeeper-k8scelrequiredlabels"


def test_static_checker_rejects_bad_templates_at_add():
    """Unknown functions / undeclared identifiers fail at AddTemplate
    (reference: cel-go type checking in the k8scel driver), not at eval."""
    import pytest

    from gatekeeper_tpu.drivers.cel_driver import CELCompileError, CELDriver

    def tmpl(expr):
        return ConstraintTemplate.from_unstructured({
            "apiVersion": "templates.gatekeeper.sh/v1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": "k8scelbad"},
            "spec": {
                "crd": {"spec": {"names": {"kind": "K8sCelBad"}}},
                "targets": [{
                    "target": "admission.k8s.gatekeeper.sh",
                    "code": [{"engine": "K8sNativeValidation",
                              "source": {"validations": [
                                  {"expression": expr, "message": "m"}]}}],
                }],
            },
        })

    d = CELDriver()
    for bad in ("frobnicate(object)",
                "object.metadata.name.fliptwist()",
                "unknownvar.spec.x == 1",
                "size(object, params) > 0"):
        with pytest.raises(CELCompileError):
            d.add_template(tmpl(bad))
    # good templates still admit
    d.add_template(tmpl("object.metadata.name == params.name"))
    assert "K8sCelBad" in [k for k in d._templates]


def test_k8s_extension_libraries():
    """quantity / ip / cidr / url extension functions (reference: the
    cel-go k8s libraries in the k8scel driver env)."""
    from gatekeeper_tpu.lang.cel.cel import CelError, Env, Program

    def ev(expr, **vars_):
        return Program(expr).eval(Env(vars_))

    assert ev('quantity("1Gi").isGreaterThan(quantity("900Mi"))') is True
    assert ev('quantity("100m").asApproximateFloat()') == 0.1
    assert ev('quantity("2Ki").asInteger()') == 2048
    assert ev('quantity("1.5").isInteger()') is False
    assert ev('quantity("-3").sign()') == -1
    assert ev('quantity("1Gi").compareTo(quantity("1024Mi"))') == 0
    assert ev('quantity("1Gi").add(quantity("1Gi")).asInteger()') == 2**31
    assert ev('isQuantity("10Wi")') is False
    assert ev('isQuantity("150Mi")') is True

    assert ev('ip("127.0.0.1").isLoopback()') is True
    assert ev('ip("::1").family()') == 6
    assert ev('isIP("999.1.1.1")') is False
    assert ev('cidr("10.0.0.0/8").containsIP("10.1.2.3")') is True
    assert ev('cidr("10.0.0.0/8").containsIP(ip("11.1.2.3"))') is False
    assert ev('cidr("10.0.0.0/8").containsCIDR("10.2.0.0/16")') is True
    assert ev('cidr("10.0.0.0/8").prefixLength()') == 8
    assert ev('isCIDR("10.0.0.0/33")') is False

    assert ev('url("https://example.com:8443/x").getScheme()') == "https"
    assert ev('url("https://example.com:8443/x").getPort()') == "8443"
    assert ev('url("https://example.com:8443/x").getHostname()') == \
        "example.com"
    assert ev('isURL("not a url")') is False

    # errors are CelErrors (absorbed by || / failurePolicy like any other)
    import pytest
    with pytest.raises(CelError):
        ev('quantity("10Wi")')
    with pytest.raises(CelError):
        ev('quantity("100m").asInteger() == 1')  # 0.1 is not integral
