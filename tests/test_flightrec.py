"""Admission flight recorder: ring semantics, JSONL sink, the
ValidationHandler / mutation-handler wiring (allow/deny/shed decisions
with overload state + trace id), and /debug/decisions?uid= lookup."""

import json
import urllib.request

import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.observability import flightrec, tracing
from gatekeeper_tpu.resilience import overload as ovl
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.unstructured import load_yaml_file
from gatekeeper_tpu.webhook.policy import ValidationHandler
from gatekeeper_tpu.webhook.server import WebhookServer

LIB = "/root/repo/library/general"


# --- recorder unit ---------------------------------------------------------

def test_ring_bounds_and_uid_lookup():
    rec = flightrec.FlightRecorder(capacity=3)
    for i in range(5):
        rec.record("validate", "allow", uid=f"u{i}")
    assert rec.recorded == 5
    snap = rec.snapshot()
    assert [e["uid"] for e in snap["decisions"]] == ["u4", "u3", "u2"]
    assert rec.by_uid("u0") == []  # evicted by the bound
    assert rec.by_uid("u4")[0]["decision"] == "allow"
    assert rec.snapshot(uid="u3")["decisions"][0]["uid"] == "u3"


def test_message_truncation_and_no_object_body():
    rec = flightrec.FlightRecorder(max_message=16)
    rec.record("validate", "deny", uid="u", message="x" * 100,
               obj_kind="Pod", name="p", namespace="ns")
    e = rec.by_uid("u")[0]
    assert len(e["message"]) == 16
    assert "object" not in e  # metadata only, never the body


def test_jsonl_sink(tmp_path):
    path = tmp_path / "decisions.jsonl"
    rec = flightrec.FlightRecorder(capacity=8, sink_path=str(path))
    rec.record("validate", "allow", uid="a")
    rec.record("mutate", "shed", uid="b", reason="queue_full")
    rec.close()
    lines = [json.loads(ln) for ln in
             path.read_text().strip().splitlines()]
    assert [e["uid"] for e in lines] == ["a", "b"]
    assert lines[1]["reason"] == "queue_full"


def test_trace_id_and_overload_state_captured():
    ctl = ovl.OverloadController(ovl.OverloadConfig())
    rec = flightrec.FlightRecorder()
    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        with tracing.span("webhook.request") as sp:
            rec.record("validate", "shed", uid="u", reason="chaos",
                       overload=ctl)
            tid = sp.trace_id
    e = rec.by_uid("u")[0]
    assert e["trace_id"] == tid
    assert e["overload"]["brownout"] == 0
    assert e["overload"]["inflight_limit"] >= 1


def test_metrics_counter():
    m = MetricsRegistry()
    rec = flightrec.FlightRecorder(metrics=m)
    rec.record("validate", "allow")
    rec.record("validate", "deny")
    rec.record("validate", "deny")
    assert m.get_counter(M.FLIGHTREC_DECISIONS,
                         {"decision": "deny"}) == 2


# --- handler wiring --------------------------------------------------------

@pytest.fixture(scope="module")
def handler_client():
    client = Client(target=K8sValidationTarget(), drivers=[TpuDriver()],
                    enforcement_points=["validation.gatekeeper.sh"])
    client.add_template(load_yaml_file(
        f"{LIB}/requiredlabels/template.yaml")[0])
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "ns-must-have-gk"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Namespace"]}]},
                 "parameters": {"labels": [{"key": "gatekeeper"}]}},
    })
    return client


def _body(uid, labeled):
    meta = {"name": "n"}
    if labeled:
        meta["labels"] = {"gatekeeper": "yes"}
    return {
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": uid, "operation": "CREATE",
            "kind": {"group": "", "version": "v1", "kind": "Namespace"},
            "name": "n", "namespace": "",
            "userInfo": {"username": "alice"},
            "object": {"apiVersion": "v1", "kind": "Namespace",
                       "metadata": meta},
        },
    }


def test_validation_decisions_recorded(handler_client):
    rec = flightrec.FlightRecorder()
    h = ValidationHandler(handler_client)
    with flightrec.activate(rec):
        h.handle(_body("ok-1", labeled=True))
        h.handle(_body("bad-1", labeled=False))
    allow = rec.by_uid("ok-1")[0]
    deny = rec.by_uid("bad-1")[0]
    assert allow["decision"] == "allow" and allow["kind"] == "Namespace"
    assert deny["decision"] == "deny" and deny["code"] == 403
    assert "you must provide labels" in deny["message"]


def test_shed_decision_recorded_with_overload_state(handler_client):
    """The "why was THIS request shed at 14:02" answer: a chaos-forced
    shed lands in the recorder with its reason, cost, and the overload
    state at decision time."""
    rec = flightrec.FlightRecorder()
    ctl = ovl.OverloadController(ovl.OverloadConfig())
    h = ValidationHandler(handler_client, overload=ctl,
                          failure_policy="fail")
    plan = FaultPlan([{"site": "webhook.overload", "mode": "error",
                       "times": 1}])
    with flightrec.activate(rec), inject(plan), ovl.activate(ctl):
        shed = h.handle(_body("shed-1", labeled=True))
        ok = h.handle(_body("ok-2", labeled=True))
    assert shed.code == 429 and ok.allowed
    e = rec.by_uid("shed-1")[0]
    assert e["decision"] == "shed"
    assert e["reason"] == "chaos"
    assert e["cost"] > 0
    assert e["overload"]["inflight_limit"] >= 1
    assert rec.by_uid("ok-2")[0]["decision"] == "allow"


def test_mutate_decision_recorded():
    from gatekeeper_tpu.mutation.system import MutationSystem
    from gatekeeper_tpu.mutlane import BatchedMutationHandler

    system = MutationSystem()
    system.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1",
        "kind": "Assign",
        "metadata": {"name": "set-policy"},
        "spec": {
            "applyTo": [{"groups": [""], "versions": ["v1"],
                         "kinds": ["Pod"]}],
            "location": "spec.priorityClassName",
            "parameters": {"assign": {"value": "low"}},
        },
    })
    m = MetricsRegistry()
    h = BatchedMutationHandler(system, metrics=m)
    rec = flightrec.FlightRecorder()
    body = {
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": "mu-1", "operation": "CREATE",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": "p", "namespace": "default",
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "p",
                                    "namespace": "default"},
                       "spec": {"containers": [
                           {"name": "c", "image": "i"}]}},
        },
    }
    with flightrec.activate(rec):
        resp = h.handle(body)
    assert resp.allowed and resp.patch
    e = rec.by_uid("mu-1")[0]
    assert e["endpoint"] == "mutate"
    assert e["decision"] == "allow"
    assert e["lane"] in ("device", "solo", "host")
    assert e["patch_ops"] == len(resp.patch)
    # the new mutate-latency histogram observed the request
    assert m.get_histogram(M.MUTATION_REQUEST_DURATION)["count"] == 1


# --- /debug/decisions ------------------------------------------------------

def test_debug_decisions_endpoint():
    rec = flightrec.FlightRecorder()
    rec.record("validate", "shed", uid="target-uid", reason="queue_full")
    rec.record("validate", "allow", uid="other")
    srv = WebhookServer(port=0, flight_recorder=rec).start()
    try:
        base = f"http://127.0.0.1:{srv.port}/debug/decisions"
        with urllib.request.urlopen(base) as r:
            doc = json.loads(r.read())
        assert doc["recorded"] == 2
        assert len(doc["decisions"]) == 2
        with urllib.request.urlopen(f"{base}?uid=target-uid") as r:
            doc = json.loads(r.read())
        assert len(doc["decisions"]) == 1
        assert doc["decisions"][0]["reason"] == "queue_full"
    finally:
        srv.stop()


def test_debug_decisions_404_when_off():
    srv = WebhookServer(port=0).start()
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/decisions")
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404
    finally:
        srv.stop()


# --- time-range + decision-kind filters ------------------------------------

def test_snapshot_time_range_and_kind_filters():
    clock = [1000.0]
    rec = flightrec.FlightRecorder(wall=lambda: clock[0])
    for i, decision in enumerate(
            ["allow", "shed", "deny", "shed", "allow"]):
        clock[0] = 1000.0 + i
        rec.record("validate", decision, uid=f"u{i}")
    # half-open [since, until): 1001 and 1002 only
    snap = rec.snapshot(since=1001.0, until=1003.0)
    assert [e["uid"] for e in snap["decisions"]] == ["u2", "u1"]
    assert snap["matched"] == 2
    # decision-kind filter composes with the range
    snap = rec.snapshot(since=1001.0, kinds={"shed"})
    assert [e["uid"] for e in snap["decisions"]] == ["u3", "u1"]
    # kinds alone
    snap = rec.snapshot(kinds={"allow", "deny"})
    assert [e["decision"] for e in snap["decisions"]] == \
        ["allow", "deny", "allow"]
    # uid composes with filters
    snap = rec.snapshot(uid="u1", kinds={"shed"})
    assert len(snap["decisions"]) == 1
    assert rec.snapshot(uid="u1", kinds={"allow"})["decisions"] == []


def test_debug_decisions_endpoint_filters():
    clock = [2000.0]
    rec = flightrec.FlightRecorder(wall=lambda: clock[0])
    for i, decision in enumerate(["allow", "shed", "deny", "shed"]):
        clock[0] = 2000.0 + i
        rec.record("validate", decision, uid=f"u{i}")
    srv = WebhookServer(port=0, flight_recorder=rec).start()
    try:
        base = f"http://127.0.0.1:{srv.port}/debug/decisions"
        with urllib.request.urlopen(
                f"{base}?since=2001&until=2003") as r:
            doc = json.loads(r.read())
        assert [e["uid"] for e in doc["decisions"]] == ["u2", "u1"]
        with urllib.request.urlopen(f"{base}?decision=shed") as r:
            doc = json.loads(r.read())
        assert [e["uid"] for e in doc["decisions"]] == ["u3", "u1"]
        # comma-list and repeated params both parse
        with urllib.request.urlopen(f"{base}?decision=deny,shed") as r:
            doc = json.loads(r.read())
        assert doc["matched"] == 3
        with urllib.request.urlopen(
                f"{base}?decision=deny&decision=shed&since=2002") as r:
            doc = json.loads(r.read())
        assert [e["uid"] for e in doc["decisions"]] == ["u3", "u2"]
        try:
            urllib.request.urlopen(f"{base}?since=notanumber")
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv.stop()


# --- sink rotation (ISSUE 18 satellite) ------------------------------------

def test_sink_rotation_shifts_and_caps(tmp_path):
    """Past sink_max_bytes the sink rotates path -> path.1 -> path.2;
    only sink_keep rotated files are retained (oldest dropped); every
    recorded line survives somewhere in the retained set until the cap
    forces the oldest out."""
    path = tmp_path / "d.jsonl"
    rec = flightrec.FlightRecorder(
        sink_path=str(path), sink_max_bytes=200, sink_keep=2)
    for i in range(40):
        rec.record("validate", "allow", uid=f"u{i:03d}")
    rec.close()
    assert rec.rotations > 2
    paths = flightrec.rotated_paths(str(path))
    assert str(path) in paths
    assert len(paths) <= 3  # live + sink_keep rotated
    # oldest-first ordering: uids increase monotonically across the set
    uids = []
    for p in paths:
        with open(p) as f:
            uids += [json.loads(ln)["uid"] for ln in f if ln.strip()]
    assert uids == sorted(uids)
    assert uids[-1] == "u039"  # the newest record is in the live sink


def test_rotated_set_reads_as_one_stream(tmp_path):
    """gator decisions reads a rotated sink set transparently —
    filters, ordering and counts behave as if it were one file."""
    from gatekeeper_tpu.gator.decisions_cmd import read_decisions

    path = tmp_path / "d.jsonl"
    clock = [1000.0]
    rec = flightrec.FlightRecorder(
        wall=lambda: clock[0], sink_path=str(path),
        sink_max_bytes=150, sink_keep=8)
    for i in range(12):
        clock[0] = 1000.0 + i
        rec.record("validate", "shed" if i % 3 == 0 else "allow",
                   uid=f"u{i}", tenant="t-a" if i % 2 == 0 else "t-b")
    rec.close()
    assert rec.rotations > 0
    doc = read_decisions(str(path))
    assert doc["recorded"] == 12
    assert doc.get("rotated_files", 1) > 1
    assert doc["decisions"][0]["uid"] == "u11"  # most recent first
    sheds = read_decisions(str(path), kinds={"shed"})
    assert [e["uid"] for e in sheds["decisions"]] == \
        ["u9", "u6", "u3", "u0"]
    both = read_decisions(str(path), kinds={"shed"}, tenant="t-a")
    assert [e["uid"] for e in both["decisions"]] == ["u6", "u0"]


def test_torn_tail_repair_across_rotation(tmp_path):
    """A crash-torn tail in a ROTATED file is confined to its own file:
    the reader counts one truncated record there and every other line
    in the set still parses; reopening the live sink still repairs its
    own tail independently."""
    from gatekeeper_tpu.gator.decisions_cmd import read_decisions

    path = tmp_path / "d.jsonl"
    rec = flightrec.FlightRecorder(
        sink_path=str(path), sink_max_bytes=120, sink_keep=3)
    for i in range(10):
        rec.record("validate", "allow", uid=f"r{i}")
    rec.close()
    rotated = flightrec.rotated_paths(str(path))
    assert len(rotated) > 2
    # tear the tail of the OLDEST rotated file (simulated crash before
    # this rotation happened)
    with open(rotated[0], "a") as f:
        f.write('{"ts": 1.0, "uid": "torn')
    doc = read_decisions(str(path))
    assert doc["truncated"] == 1
    assert all(e["uid"].startswith("r") for e in doc["decisions"])
    # the live sink's own torn tail still repairs on reopen: the
    # separating newline confines the fragment to ONE lost line (now a
    # complete-but-malformed line, counted apart from the torn tail)
    with open(path, "a") as f:
        f.write('{"partial')
    rec2 = flightrec.FlightRecorder(sink_path=str(path))
    rec2.record("validate", "deny", uid="after-torn")
    rec2.close()
    doc = read_decisions(str(path))
    assert doc["truncated"] == 1
    assert doc["malformed"] == 1
    assert doc["decisions"][0]["uid"] == "after-torn"
