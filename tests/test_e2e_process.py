"""End-to-end process tests: drive `python -m gatekeeper_tpu` as a real
subprocess (the reference's bats e2e suite shape, test/bats/test.bats) —
audit --once output, the served webhook admit path, and SIGTERM shutdown.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")

MANIFESTS = """\
apiVersion: templates.gatekeeper.sh/v1
kind: ConstraintTemplate
metadata:
  name: k8spsphostnamespace
spec:
  crd:
    spec:
      names:
        kind: K8sPSPHostNamespace
  targets:
    - target: admission.k8s.gatekeeper.sh
      rego: |
        package k8spsphostnamespace

        violation[{"msg": "host namespace"}] {
          input.review.object.spec.hostPID
        }
---
apiVersion: constraints.gatekeeper.sh/v1beta1
kind: K8sPSPHostNamespace
metadata:
  name: no-host-ns
spec: {}
---
apiVersion: v1
kind: Pod
metadata:
  name: bad-pod
  namespace: default
spec:
  hostPID: true
---
apiVersion: v1
kind: Pod
metadata:
  name: good-pod
  namespace: default
spec:
  hostPID: false
"""


@pytest.fixture()
def manifest_dir(tmp_path):
    d = tmp_path / "manifests"
    d.mkdir()
    (d / "all.yaml").write_text(MANIFESTS)
    return str(d)


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return env


def test_audit_once_end_to_end(manifest_dir):
    proc = subprocess.run(
        [sys.executable, "-m", "gatekeeper_tpu", "--manifests", manifest_dir,
         "--once"],
        capture_output=True, text=True, timeout=180, cwd=REPO, env=_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "1 violations" in proc.stderr or ", 1 violations" in proc.stderr, \
        proc.stderr[-500:]
    assert "bad-pod" in proc.stdout and "host namespace" in proc.stdout
    assert "good-pod" not in proc.stdout


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_webhook_serve_admit_and_sigterm(manifest_dir):
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "gatekeeper_tpu", "--manifests", manifest_dir,
         "--operation", "webhook", "--port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=_env(),
    )
    try:
        deadline = time.time() + 120
        up = False
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=2) as r:
                    up = r.status == 200
                    break
            except Exception:
                if proc.poll() is not None:
                    raise AssertionError(proc.stderr.read()[-2000:])
                time.sleep(0.5)
        assert up, "webhook never became ready"

        review = {"request": {
            "uid": "u1", "operation": "CREATE",
            "kind": {"kind": "Pod", "version": "v1"},
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "p"},
                       "spec": {"hostPID": True}},
        }}
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/admit",
            data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            body = json.load(r)
        resp = body["response"]
        assert resp["allowed"] is False
        assert "host namespace" in resp["status"]["message"]

        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
        assert proc.returncode == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
