"""Differential test: the native flattener must produce bit-identical columns
(and identical vocab interning) to the Python reference implementation."""

import random

import numpy as np
import pytest

from gatekeeper_tpu.ops import native
from gatekeeper_tpu.ops.flatten import (
    Axis,
    Flattener,
    KeySetCol,
    RaggedCol,
    ScalarCol,
    Schema,
    Vocab,
)


def make_schema():
    containers = Axis(((("spec", "containers"),),
                       (("spec", "initContainers"),)))
    ports = Axis(((("spec", "containers"), ("ports",)),
                  (("spec", "initContainers"), ("ports",))))
    s = Schema()
    s.scalars = [ScalarCol(("spec", "hostNetwork")),
                 ScalarCol(("spec", "priority")),
                 ScalarCol(("metadata", "name"))]
    s.raggeds = [RaggedCol(containers, ("securityContext", "privileged")),
                 RaggedCol(containers, ("name",)),
                 RaggedCol(containers, ()),
                 RaggedCol(ports, ("hostPort",))]
    s.keysets = [KeySetCol(("metadata", "labels"))]
    return s


def make_objects(n, seed=0):
    rng = random.Random(seed)
    objs = []
    for i in range(n):
        containers = []
        for j in range(rng.randint(0, 4)):
            c = {"name": f"c{j}"}
            if rng.random() < 0.5:
                c["securityContext"] = {"privileged": rng.choice(
                    [True, False, "x", 1, None])}
            if rng.random() < 0.4:
                c["ports"] = [{"hostPort": rng.randint(1, 70000)}
                              for _ in range(rng.randint(0, 3))]
            containers.append(c)
        obj = {
            "apiVersion": rng.choice(["v1", "apps/v1", "batch/v1"]),
            "kind": rng.choice(["Pod", "Deployment"]),
            "metadata": {
                "name": f"o{i}",
                "namespace": rng.choice(["default", "kube-system", ""]),
            },
            "spec": {"containers": containers},
        }
        if rng.random() < 0.3:
            obj["metadata"]["labels"] = {
                f"k{x}": f"v{x}" for x in range(rng.randint(1, 4))
            }
        if rng.random() < 0.3:
            obj["spec"]["hostNetwork"] = rng.choice([True, False, "maybe"])
        if rng.random() < 0.3:
            obj["spec"]["priority"] = rng.choice([1, 2.5, -3, "high"])
        if rng.random() < 0.2:
            obj["spec"]["initContainers"] = [{"name": "init"}]
        objs.append(obj)
    return objs


@pytest.mark.skipif(native.load() is None, reason="native build unavailable")
def test_native_matches_python():
    schema = make_schema()
    objs = make_objects(300)
    v_py, v_c = Vocab(), Vocab()
    py = Flattener(schema, v_py, use_native=False).flatten(objs, pad_n=320)
    nat = Flattener(schema, v_c, use_native=True)._flatten_native(
        native.load(), objs, 320)

    assert v_py._to_str == v_c._to_str  # identical interning order
    np.testing.assert_array_equal(py.group_sid, nat.group_sid)
    np.testing.assert_array_equal(py.kind_sid, nat.kind_sid)
    np.testing.assert_array_equal(py.ns_sid, nat.ns_sid)
    np.testing.assert_array_equal(py.name_sid, nat.name_sid)
    for spec in schema.scalars:
        np.testing.assert_array_equal(py.scalars[spec].kind,
                                      nat.scalars[spec].kind, err_msg=str(spec))
        np.testing.assert_array_equal(py.scalars[spec].num,
                                      nat.scalars[spec].num)
        np.testing.assert_array_equal(py.scalars[spec].sid,
                                      nat.scalars[spec].sid)
    for axis in schema.axes():
        np.testing.assert_array_equal(py.axis_counts[axis],
                                      nat.axis_counts[axis])
    for spec in schema.raggeds:
        np.testing.assert_array_equal(py.raggeds[spec].kind,
                                      nat.raggeds[spec].kind, err_msg=str(spec))
        np.testing.assert_array_equal(py.raggeds[spec].num,
                                      nat.raggeds[spec].num)
        np.testing.assert_array_equal(py.raggeds[spec].sid,
                                      nat.raggeds[spec].sid)
    for spec in schema.keysets:
        np.testing.assert_array_equal(py.keysets[spec].sid,
                                      nat.keysets[spec].sid)
        np.testing.assert_array_equal(py.keysets[spec].count,
                                      nat.keysets[spec].count)


@pytest.mark.skipif(native.load() is None, reason="native build unavailable")
def test_native_empty_and_weird_inputs():
    schema = make_schema()
    mod = native.load()
    for objs in ([], [{}], [{"spec": None}], [{"spec": {"containers": "x"}}]):
        v1, v2 = Vocab(), Vocab()
        py = Flattener(schema, v1, use_native=False).flatten(objs, pad_n=8)
        nat = Flattener(schema, v2, use_native=True)._flatten_native(
            mod, objs, 8)
        for axis in schema.axes():
            np.testing.assert_array_equal(py.axis_counts[axis],
                                          nat.axis_counts[axis])
        for spec in schema.scalars:
            np.testing.assert_array_equal(py.scalars[spec].kind,
                                          nat.scalars[spec].kind)


@pytest.mark.skipif(native.load() is None, reason="native build unavailable")
def test_native_huge_int_saturates_no_pending_exception():
    # ADVICE r1: PyLong_AsDouble overflow must not leave a pending exception;
    # both flatteners saturate to +/-inf with the right sign
    schema = make_schema()
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "big"},
             "spec": {"priority": 10 ** 400}},
            {"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "neg"},
             "spec": {"priority": -(10 ** 400)}}]
    v1, v2 = Vocab(), Vocab()
    py = Flattener(schema, v1, use_native=False).flatten(objs, pad_n=4)
    nat = Flattener(schema, v2, use_native=True)._flatten_native(
        native.load(), objs, 4)
    spec = schema.scalars[1]  # spec.priority
    np.testing.assert_array_equal(py.scalars[spec].num, nat.scalars[spec].num)
    assert np.isposinf(nat.scalars[spec].num[0])
    assert np.isneginf(nat.scalars[spec].num[1])
    # no pending exception corrupts the next unrelated call
    assert 1 + 1 == 2


@pytest.mark.skipif(native.load() is None, reason="native build unavailable")
def test_native_extract_extras_matches_python():
    """parent-idx and ragged-keyset columns: C extract_extras vs the Python
    loops, bit-identical (incl. vocab interning order)."""
    from gatekeeper_tpu.ops.flatten import ParentIdxCol, RaggedKeySetCol

    containers = Axis(((("spec", "containers"),),
                       (("spec", "initContainers"),)))
    drops = Axis(((("spec", "containers"),
                   ("securityContext", "capabilities", "drop")),
                  (("spec", "initContainers"),
                   ("securityContext", "capabilities", "drop"))))
    s = Schema()
    s.raggeds = [RaggedCol(containers, ("name",)),
                 RaggedCol(drops, ())]
    s.parent_idx = [ParentIdxCol(axis=drops, parent=containers)]
    s.ragged_keysets = [RaggedKeySetCol(axis=containers, subpath=())]

    rng = random.Random(5)
    objs = []
    for i in range(200):
        cs = []
        for j in range(rng.randint(0, 4)):
            c = {"name": f"c{j}"}
            if rng.random() < 0.6:
                c["securityContext"] = {"capabilities": {
                    "drop": [rng.choice(["ALL", "NET_RAW", "KILL"])
                             for _ in range(rng.randint(0, 3))]}}
            if rng.random() < 0.3:
                c["livenessProbe"] = {"tcpSocket": {}}
            if rng.random() < 0.2:
                c["extra"] = False  # truthy-key filter
            cs.append(c)
        spec = {"containers": cs}
        if rng.random() < 0.3:
            spec["initContainers"] = [{"name": "i", "securityContext": {
                "capabilities": {"drop": ["X"]}}}]
        objs.append({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": f"p{i}"}, "spec": spec})

    v_py, v_c = Vocab(), Vocab()
    py = Flattener(s, v_py, use_native=False).flatten(objs, pad_n=256)
    nat = Flattener(s, v_c, use_native=True).flatten(objs, pad_n=256)
    assert v_py._to_str == v_c._to_str
    for spec_ in s.parent_idx:
        np.testing.assert_array_equal(py.parent_idx[spec_].idx,
                                      nat.parent_idx[spec_].idx)
    for spec_ in s.ragged_keysets:
        np.testing.assert_array_equal(py.ragged_keysets[spec_].sid,
                                      nat.ragged_keysets[spec_].sid)
        np.testing.assert_array_equal(py.ragged_keysets[spec_].count,
                                      nat.ragged_keysets[spec_].count)
