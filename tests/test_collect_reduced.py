"""Device-side verdict reduction (ISSUE 9): ``--collect=reduced`` must
be bit-identical to the host-fold masks lane over the library corpus —
violation totals, canonical kept selections (including capped-selection
and the exact-engine fallback merge), snapshot tick/resync results —
while transferring O(kept/violations) device->host bytes instead of the
O(objects x constraints) grid.  The ``differential`` lane asserts the
same per chunk inside the evaluator, and the complete-hits overflow
path must fall back to the masks lane without changing a single
verdict."""

import copy

import numpy as np
import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.parallel.sharded import (HitRows, ShardedEvaluator,
                                             hit_bucket, make_mesh,
                                             violation_rows)
from gatekeeper_tpu.snapshot import ClusterSnapshot, SnapshotConfig
from gatekeeper_tpu.sync.source import FakeCluster
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import (load_library,
                                            make_cluster_objects)


# --- units -----------------------------------------------------------------

def test_hit_bucket_ladder():
    assert hit_bucket(0, 920) == 0
    assert hit_bucket(1, 920) == 16
    assert hit_bucket(17, 920) == 64
    assert hit_bucket(64, 920) == 64
    assert hit_bucket(65, 920) == 256
    assert hit_bucket(257, 920) == 920  # full per-chunk kept capacity
    # a tiny constraint set never allocates past its exhaustive bound
    assert hit_bucket(300, 40) == 40


def test_hitrows_matches_unpackbits():
    rng = np.random.default_rng(3)
    pad_n, n, c = 64, 50, 5
    grid = rng.random((c, pad_n)) < 0.2
    grid[:, n:] = False
    flat = np.nonzero(grid.reshape(-1))[0].astype(np.int64)
    hr = HitRows(flat, pad_n, n, c)
    bits = np.packbits(grid, axis=1)
    for ci in range(c):
        assert np.array_equal(violation_rows(hr, ci, n),
                              violation_rows(bits, ci, n))


# --- library-corpus fixtures ----------------------------------------------

@pytest.fixture(scope="module")
def world():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    load_library(client)
    objects = make_cluster_objects(150, seed=7)
    return client, tpu, objects


def _mgr(client, tpu, objects, collect, **cfg_kw):
    cfg_kw.setdefault("exact_totals", False)
    cfg_kw.setdefault("chunk_size", 48)
    cfg_kw.setdefault("pipeline", "off")
    limit = cfg_kw.setdefault("violations_limit", 20)
    ev = ShardedEvaluator(tpu, make_mesh(), violations_limit=limit,
                          collect=collect)
    return AuditManager(client, lister=lambda: iter(objects),
                        config=AuditConfig(**cfg_kw), evaluator=ev), ev


def _assert_runs_identical(a, b):
    diff = AuditManager._schedules_differ(
        a.kept, a.total_violations, b.kept, b.total_violations)
    assert diff is None, diff


# --- relist sweep: reduced == masks ---------------------------------------

@pytest.mark.slow  # tier-1 wall budget (PR 16): 24s; the exact-totals
# variant below pins the same reduced==masks equivalence in tier 1.
def test_reduced_matches_masks_nonexact(world):
    client, tpu, objects = world
    mgr_m, ev_m = _mgr(client, tpu, objects, "masks")
    mgr_r, ev_r = _mgr(client, tpu, objects, "reduced")
    run_m = mgr_m.audit()
    run_r = mgr_r.audit()
    assert sum(run_m.total_violations.values()) > 0
    _assert_runs_identical(run_m, run_r)
    # the acceptance signal: the reduced lane moved fewer bytes off the
    # device at equal verdicts
    assert ev_r.perf["d2h_bytes"] < ev_m.perf["d2h_bytes"]
    assert ev_r.perf.get("collect_fallbacks", 0) == 0


def test_reduced_matches_masks_exact_totals(world):
    """Exact-totals parity: totals count RESULTS (a pod with two bad
    containers contributes 2), which renders every hit — the reduced
    lane ships the complete hit-coordinate list instead of the bit
    grid, and the exact-engine fallback kinds (CEL templates, inventory
    -inexact referential kinds) merge through their own drivers on both
    lanes."""
    client, tpu, objects = world
    corpus = objects[:60]
    mgr_m, ev_m = _mgr(client, tpu, corpus, "masks", exact_totals=True,
                       chunk_size=24)
    mgr_r, ev_r = _mgr(client, tpu, corpus, "reduced", exact_totals=True,
                       chunk_size=24)
    run_m = mgr_m.audit()
    run_r = mgr_r.audit()
    assert sum(run_m.total_violations.values()) > 0
    _assert_runs_identical(run_m, run_r)
    assert ev_r.perf["d2h_bytes"] < ev_m.perf["d2h_bytes"]


def test_reduced_capped_selection(world):
    """Capped selection: far more violations than the render cap — the
    device top-k under the budget must keep exactly the first-k
    canonical hits the masks fold keeps, and later chunks (budget
    drained) ship zero kept coordinates."""
    client, tpu, objects = world
    mgr_m, _ = _mgr(client, tpu, objects, "masks", violations_limit=3,
                    chunk_size=32)
    mgr_r, ev_r = _mgr(client, tpu, objects, "reduced",
                       violations_limit=3, chunk_size=32)
    run_m = mgr_m.audit()
    run_r = mgr_r.audit()
    _assert_runs_identical(run_m, run_r)
    capped = [k for k, v in run_m.kept.items() if len(v) == 3]
    assert capped, "corpus must cap at least one constraint"


# --- the differential lane -------------------------------------------------

@pytest.mark.slow  # tier-1 wall budget (PR 16): 37s; the exact-totals
# differential-lane test below keeps the identity pin in tier 1.
def test_differential_lane_proves_identity(world):
    client, tpu, objects = world
    mgr_m, _ = _mgr(client, tpu, objects, "masks")
    mgr_d, ev_d = _mgr(client, tpu, objects, "differential")
    run_m = mgr_m.audit()
    run_d = mgr_d.audit()
    assert not run_d.incomplete
    assert ev_d.perf.get("collect_differential_ok", 0) > 0
    _assert_runs_identical(run_m, run_d)


def test_differential_lane_exact(world):
    client, tpu, objects = world
    corpus = objects[:48]
    mgr_m, _ = _mgr(client, tpu, corpus, "masks", exact_totals=True,
                    chunk_size=24)
    mgr_d, ev_d = _mgr(client, tpu, corpus, "differential",
                       exact_totals=True, chunk_size=24)
    run_m = mgr_m.audit()
    run_d = mgr_d.audit()
    assert not run_d.incomplete
    assert ev_d.perf.get("collect_differential_ok", 0) > 0
    _assert_runs_identical(run_m, run_d)


# --- snapshot lane: tick + resync through reduced collect ------------------

@pytest.mark.slow  # tier-1 wall budget (PR 16): 27s; snapshot tick +
# resync semantics are pinned extensively in tests/test_snapshot.py.
def test_snapshot_reduced_tick_and_resync(world):
    client, tpu, objects = world
    cluster = FakeCluster()
    for o in objects:
        cluster.apply(copy.deepcopy(o))

    def lister():
        return iter(cluster.list())

    def managers(collect):
        ev = ShardedEvaluator(tpu, make_mesh(), violations_limit=20,
                              collect=collect)
        snapshot = ClusterSnapshot(ev, SnapshotConfig())
        snap_mgr = AuditManager(
            client, lister=lister,
            config=AuditConfig(audit_source="snapshot", pipeline="off",
                               exact_totals=False, chunk_size=48),
            evaluator=ev, snapshot=snapshot)
        return ev, snapshot, snap_mgr

    ev_r, snapshot, snap_mgr = managers("reduced")
    _, _, masks_mgr = managers("masks")
    run_r = snap_mgr.audit()  # full pass builds + evaluates the snapshot
    run_m = masks_mgr.audit()
    _assert_runs_identical(run_m, run_r)
    # dirty a few rows through the watch seam and tick: per-row verdict
    # persistence keyed by the returned hit indices, O(churn) evaluated
    changed = copy.deepcopy(objects[3])
    changed["metadata"]["labels"] = {"app": "patched"}
    cluster.apply(changed)
    snapshot.enqueue("MODIFIED", changed)
    tick = snap_mgr.audit_tick()
    assert not tick.incomplete
    # resync differential: fresh relist + host-fold reference sweep must
    # equal the patch-maintained snapshot (columns, vocab, verdicts)
    resync = snap_mgr.audit_resync()
    assert snap_mgr.last_resync_diff is None, snap_mgr.last_resync_diff
    assert not resync.incomplete
    assert snap_mgr.perf.get("resync_ok") == 1.0


# --- complete-hits overflow: masks fallback + adaptive buffer --------------

def test_complete_overflow_falls_back_bit_identically(world):
    client, tpu, objects = world
    corpus = objects[:96]
    mgr_m, _ = _mgr(client, tpu, corpus, "masks", exact_totals=True,
                    chunk_size=48)
    mgr_r, ev_r = _mgr(client, tpu, corpus, "reduced", exact_totals=True,
                       chunk_size=48)
    # force a tiny complete-hits buffer so dense chunks overflow: the
    # collect must re-dispatch those chunks through the masks lane and
    # escalate (or pin) the shape's buffer — verdicts never change
    state = {"cap": 8, "low": 0, "pinned": False, "blast": None}
    ev_r._hit_state_for = lambda kinds, pad_n: state
    run_m = mgr_m.audit()
    run_r = mgr_r.audit()
    _assert_runs_identical(run_m, run_r)
    assert ev_r.perf.get("collect_fallbacks", 0) > 0
    assert state["pinned"] or state["cap"] > 8
