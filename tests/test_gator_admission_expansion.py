"""gator test expands AdmissionReview-embedded objects (reference
test.go:125 expands EVERY reviewed object): a Deployment arriving inside
an AdmissionReview fixture produces its implied Pod, and violations on
the implied Pod surface with the [Implied by] prefix."""

import copy

from gatekeeper_tpu.gator.test import test as gator_test

TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8srequiredprivdeny"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sRequiredPrivDeny"}}},
        "targets": [{
            "target": "admission.k8s.io",
            "rego": """
package k8srequiredprivdeny

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  c.securityContext.privileged
  msg := sprintf("privileged container %v", [c.name])
}
""",
        }],
    },
}

CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sRequiredPrivDeny",
    "metadata": {"name": "no-priv"},
    "spec": {"match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]}},
}

EXPANSION = {
    "apiVersion": "expansion.gatekeeper.sh/v1alpha1",
    "kind": "ExpansionTemplate",
    "metadata": {"name": "expand-deployments"},
    "spec": {
        "applyTo": [{"groups": ["apps"], "versions": ["v1"],
                     "kinds": ["Deployment"]}],
        "templateSource": "spec.template",
        "generatedGVK": {"group": "", "version": "v1", "kind": "Pod"},
    },
}

DEPLOYMENT = {
    "apiVersion": "apps/v1",
    "kind": "Deployment",
    "metadata": {"name": "web", "namespace": "default"},
    "spec": {
        "template": {
            "metadata": {"labels": {"app": "web"}},
            "spec": {"containers": [{
                "name": "evil",
                "securityContext": {"privileged": True},
            }]},
        },
    },
}


def _admission_review(obj):
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": "fixture-1", "operation": "CREATE",
            "kind": {"group": "apps", "version": "v1",
                     "kind": "Deployment"},
            "userInfo": {"username": "dev"},
            "object": obj,
        },
    }


def test_admission_review_fixture_expands_implied_pod():
    fixtures = [TEMPLATE, CONSTRAINT, EXPANSION,
                _admission_review(copy.deepcopy(DEPLOYMENT))]
    responses = gator_test(fixtures, include_cel=False)
    results = responses.results()
    msgs = [r.msg for r in results]
    assert any("privileged container evil" in m for m in msgs), msgs
    # the violation came from the IMPLIED Pod (expansion aggregation
    # prefixes the resultant's messages with the template name)
    assert any("expand-deployments" in m and "Implied" in m
               for m in msgs), msgs


def test_bare_object_expansion_unchanged():
    """The bare-Deployment path (pre-existing behavior) reports the same
    implied-Pod violation — the fixture lanes agree."""
    bare = gator_test([TEMPLATE, CONSTRAINT, EXPANSION,
                       copy.deepcopy(DEPLOYMENT)], include_cel=False)
    via_review = gator_test(
        [TEMPLATE, CONSTRAINT, EXPANSION,
         _admission_review(copy.deepcopy(DEPLOYMENT))],
        include_cel=False)
    assert sorted(r.msg for r in bare.results()) == \
        sorted(r.msg for r in via_review.results())


def test_admission_review_without_object_does_not_expand():
    """DELETE-shaped fixtures (oldObject only) review fine and skip
    expansion — no resultant, no crash."""
    ar = _admission_review(copy.deepcopy(DEPLOYMENT))
    ar["request"]["operation"] = "DELETE"
    ar["request"]["oldObject"] = ar["request"].pop("object")
    responses = gator_test([TEMPLATE, CONSTRAINT, EXPANSION, ar],
                           include_cel=False)
    assert all("Implied" not in r.msg for r in responses.results())
