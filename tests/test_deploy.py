"""Deployment packaging smoke tests (VERDICT r2 #8): the shipped
manifests apply cleanly against the envtest-equivalent mock apiserver,
and their cross-references (service <-> webhook config <-> deployment
labels <-> sidecar ports <-> CRD groups) are mutually consistent with
the code's GVK constants.  Reference shape:
/root/reference/deploy/gatekeeper.yaml:5744,5852 (two-pod --operation
split) — ours adds the device-owning Evaluate sidecar container."""

import os

import pytest
import yaml

from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig
from gatekeeper_tpu.sync.mock_apiserver import MockApiServer

DEPLOY = os.path.join(os.path.dirname(__file__), "..", "deploy",
                      "gatekeeper-tpu.yaml")


@pytest.fixture(scope="module")
def docs():
    with open(DEPLOY) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def by_kind(docs, kind):
    return [d for d in docs if d.get("kind") == kind]


def test_manifests_apply_against_mock_apiserver(docs):
    srv = MockApiServer().start()
    try:
        kc = KubeCluster(KubeConfig(server=srv.url))
        try:
            for doc in docs:
                kc.apply(doc)
            # everything readable back by name
            for doc in docs:
                gvk = doc["apiVersion"], doc["kind"]
                got = kc.get(
                    (gvk[0].rsplit("/", 1)[0] if "/" in gvk[0] else "",
                     gvk[0].rsplit("/", 1)[-1], doc["kind"]),
                    (doc["metadata"].get("namespace") or ""),
                    doc["metadata"]["name"])
                assert got is not None, doc["metadata"]["name"]
        finally:
            kc.close()
    finally:
        srv.stop()


def test_two_pod_operation_split(docs):
    deps = {d["metadata"]["name"]: d for d in by_kind(docs, "Deployment")}
    assert set(deps) == {"gatekeeper-controller-manager",
                         "gatekeeper-audit"}
    cm = deps["gatekeeper-controller-manager"]
    audit = deps["gatekeeper-audit"]

    def container(dep, name):
        cs = dep["spec"]["template"]["spec"]["containers"]
        return next(c for c in cs if c["name"] == name)

    cm_args = container(cm, "manager")["args"]
    audit_args = container(audit, "manager")["args"]
    assert "--operation=webhook" in cm_args
    assert "--operation=audit" not in cm_args
    assert "--operation=audit" in audit_args
    assert not any(a.startswith("--operation=webhook")
                   for a in audit_args)
    # each pod carries the device-owning sidecar, and the manager's
    # --evaluate-sidecar address matches the sidecar's bound port
    for dep in (cm, audit):
        side = container(dep, "evaluate-sidecar")
        port = next(a.split("=", 1)[1] for a in side["args"]
                    if a.startswith("--port="))
        mgr_args = container(dep, "manager")["args"]
        addr = next(a.split("=", 1)[1] for a in mgr_args
                    if a.startswith("--evaluate-sidecar="))
        assert addr.endswith(f":{port}"), (dep["metadata"]["name"],
                                           addr, port)
        # control-plane container stays off the device
        env = {e["name"]: e.get("value")
               for e in container(dep, "manager").get("env", [])}
        assert env.get("JAX_PLATFORMS") == "cpu"
        # the sidecar is the only container requesting the accelerator
        assert "google.com/tpu" in side["resources"]["limits"]
        assert "google.com/tpu" not in (
            container(dep, "manager")["resources"].get("limits") or {})


def test_service_routes_to_webhook_pods(docs):
    svc = by_kind(docs, "Service")[0]
    cm = next(d for d in by_kind(docs, "Deployment")
              if d["metadata"]["name"] == "gatekeeper-controller-manager")
    pod_labels = cm["spec"]["template"]["metadata"]["labels"]
    for k, v in svc["spec"]["selector"].items():
        assert pod_labels.get(k) == v, (k, v)
    # the audit pod must NOT match the service selector
    audit = next(d for d in by_kind(docs, "Deployment")
                 if d["metadata"]["name"] == "gatekeeper-audit")
    audit_labels = audit["spec"]["template"]["metadata"]["labels"]
    assert any(audit_labels.get(k) != v
               for k, v in svc["spec"]["selector"].items())


def test_webhook_configs_point_at_service_paths(docs):
    svc = by_kind(docs, "Service")[0]
    vwc = by_kind(docs, "ValidatingWebhookConfiguration")[0]
    mwc = by_kind(docs, "MutatingWebhookConfiguration")[0]
    paths = {}
    for wh in vwc["webhooks"] + mwc["webhooks"]:
        ref = wh["clientConfig"]["service"]
        assert ref["name"] == svc["metadata"]["name"]
        assert ref["namespace"] == svc["metadata"]["namespace"]
        paths[wh["name"]] = ref["path"]
    # the served paths of webhook/server.py
    assert paths["validation.gatekeeper.sh"] == "/v1/admit"
    assert paths["mutation.gatekeeper.sh"] == "/v1/mutate"
    assert paths["check-ignore-label.gatekeeper.sh"] == "/v1/admitlabel"
    # fail-open default for the policy webhook (reference policy.go:83),
    # fail-closed for the ns-label exemption guard
    fps = {wh["name"]: wh["failurePolicy"] for wh in vwc["webhooks"]}
    assert fps["validation.gatekeeper.sh"] == "Ignore"
    assert fps["check-ignore-label.gatekeeper.sh"] == "Fail"


def test_crds_cover_every_reconciled_group(docs):
    from gatekeeper_tpu.controller.manager import (
        CONFIG_GVK, CONNECTION_GVK, EXPANSION_GVK, PROVIDER_GVK,
        STATUS_GROUP, STATUS_KIND_FOR, SYNCSET_GVK, TEMPLATES_GVK)
    from gatekeeper_tpu.mutation.mutators import MUTATOR_KINDS

    crds = by_kind(docs, "CustomResourceDefinition")
    served = {(c["spec"]["group"], c["spec"]["names"]["kind"]):
              {v["name"] for v in c["spec"]["versions"] if v["served"]}
              for c in crds}
    for group, version, kind in (TEMPLATES_GVK, CONFIG_GVK, SYNCSET_GVK,
                                 EXPANSION_GVK, PROVIDER_GVK,
                                 CONNECTION_GVK):
        assert version in served.get((group, kind), set()), (group, kind)
    for mk in MUTATOR_KINDS:
        assert ("mutations.gatekeeper.sh", mk) in served, mk
    for sk in set(STATUS_KIND_FOR.values()):
        assert "v1beta1" in served.get((STATUS_GROUP, sk), set()), sk


def test_namespace_self_exemption_label(docs):
    ns = by_kind(docs, "Namespace")[0]
    # the exemption label that the ns-label webhook guards
    # (reference deploy sets it so gatekeeper never blocks itself)
    assert ns["metadata"]["labels"][
        "admission.gatekeeper.sh/ignore"] == "no-self-managing"
    cm = next(d for d in by_kind(docs, "Deployment")
              if d["metadata"]["name"] == "gatekeeper-controller-manager")
    args = [c for c in cm["spec"]["template"]["spec"]["containers"]
            if c["name"] == "manager"][0]["args"]
    assert "--exempt-namespace=gatekeeper-system" in args


def test_cluster_cert_bootstrap_and_ca_injection(tmp_path):
    """ensure_cluster_certs (cert-controller equivalent): the first
    replica generates + publishes the Secret and injects caBundle into
    the shipped webhook configurations; a second replica consumes the
    SAME stored chain (one consistent CA across replicas); a read-only
    certs dir falls back to a scratch dir."""
    import base64

    from gatekeeper_tpu.webhook.certs import ensure_cluster_certs

    with open(DEPLOY) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    srv = MockApiServer().start()
    try:
        kc = KubeCluster(KubeConfig(server=srv.url))
        try:
            for doc in docs:
                kc.apply(doc)
            d1 = tmp_path / "replica1"
            crt1, key1 = ensure_cluster_certs(kc, str(d1))
            assert crt1.endswith("tls.crt") and os.path.exists(crt1)
            sec = kc.get(("", "v1", "Secret"), "gatekeeper-system",
                         "gatekeeper-webhook-server-cert")
            assert sec["data"]["tls.crt"]
            ca = sec["data"]["ca.crt"]
            # caBundle injected into every webhook of both configs
            for kind, name in (
                    ("ValidatingWebhookConfiguration",
                     "gatekeeper-validating-webhook-configuration"),
                    ("MutatingWebhookConfiguration",
                     "gatekeeper-mutating-webhook-configuration")):
                cfg = kc.get(("admissionregistration.k8s.io", "v1", kind),
                             "", name)
                for wh in cfg["webhooks"]:
                    assert wh["clientConfig"]["caBundle"] == ca
            # replica 2: consumes the stored chain, no regeneration
            d2 = tmp_path / "replica2"
            crt2, _ = ensure_cluster_certs(kc, str(d2))
            with open(crt1, "rb") as f1, open(crt2, "rb") as f2:
                assert f1.read() == f2.read()
            assert base64.b64decode(sec["data"]["tls.crt"]) == \
                open(crt1, "rb").read()
            # unwritable certs dir (chmod can't stop a root test runner:
            # use a path under a regular FILE so makedirs raises):
            # scratch-dir fallback
            blocker = tmp_path / "blocker"
            blocker.write_text("")
            ro = blocker / "certs"
            crt3, _ = ensure_cluster_certs(kc, str(ro))
            assert not crt3.startswith(str(ro))
            assert os.path.exists(crt3)
        finally:
            kc.close()
    finally:
        srv.stop()
