"""End-to-end tracing: one timeline from AdmissionReview to XLA
dispatch.

Covers the webhook HTTP path (traceparent ingest/emit, request →
review → batcher enqueue/flush → device.query_batch), the audit sweep
(chunk-scoped pipeline stage spans, serial-schedule chunk spans, sweep
root attributes), the /debug/traces ring-buffer endpoint, resilience
events landing on spans under chaos, and the tracer-on vs tracer-off
verdict differential over the library corpus (tracing must be
zero-cost to verdicts — the chaos-differential discipline applied to
observability)."""

import json
import urllib.request

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.observability import export, tracing
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects
from gatekeeper_tpu.utils.unstructured import load_yaml_file
from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
from gatekeeper_tpu.webhook.server import WebhookServer

LIB = "/root/repo/library/general"


# --- webhook plane --------------------------------------------------------

def _webhook_client():
    client = Client(target=K8sValidationTarget(), drivers=[TpuDriver()],
                    enforcement_points=["validation.gatekeeper.sh"])
    client.add_template(load_yaml_file(
        f"{LIB}/requiredlabels/template.yaml")[0])
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "ns-must-have-gk"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Namespace"]}]},
                 "parameters": {"labels": [{"key": "gatekeeper"}]}},
    })
    return client


def _review_body(uid="trace-u1"):
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": uid,
            "kind": {"group": "", "version": "v1", "kind": "Namespace"},
            "name": "bad", "namespace": "", "operation": "CREATE",
            "userInfo": {"username": "alice"},
            "object": {"apiVersion": "v1", "kind": "Namespace",
                       "metadata": {"name": "bad"}},
        },
    }


@pytest.fixture(scope="module")
def traced_server():
    client = _webhook_client()
    # small_batch=0: every admission takes the device verdict-grid lane,
    # so the timeline reaches device.query_batch deterministically
    batcher = Batcher(client, small_batch=0).start()
    srv = WebhookServer(
        validation_handler=ValidationHandler(client, batcher=batcher),
        port=0,
    ).start()
    yield srv
    srv.stop()
    batcher.stop()


def _post(port, path, body, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def test_webhook_timeline_and_traceparent_roundtrip(traced_server):
    remote_trace = "a" * 32
    header = f"00-{remote_trace}-{'b' * 16}-01"
    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        out, resp_headers = _post(
            traced_server.port, "/v1/admit", _review_body(),
            headers={"traceparent": header})
    assert out["response"]["allowed"] is False
    traces = tracer.traces()
    assert len(traces) == 1
    tr = traces[0]
    # ingest: the request span joined the caller's trace
    assert tr["trace_id"] == remote_trace
    by_name = {s["name"]: s for s in tr["spans"]}
    root = by_name["webhook.request"]
    assert root["parent_id"] == "b" * 16  # remote parent link
    assert root["attributes"]["path"] == "/v1/admit"
    assert root["attributes"]["uid"] == "trace-u1"
    assert root["attributes"]["http.status"] == 200
    # the full lane: request -> review -> batcher enqueue/flush -> device
    for name in ("webhook.review", "webhook.batcher.enqueue",
                 "webhook.batcher.flush", "device.query_batch"):
        assert name in by_name, (name, sorted(by_name))
    assert by_name["webhook.review"]["parent_id"] == root["span_id"]
    enq = by_name["webhook.batcher.enqueue"]
    assert enq["parent_id"] == by_name["webhook.review"]["span_id"]
    flush = by_name["webhook.batcher.flush"]
    assert flush["parent_id"] == enq["span_id"]  # cross-thread link
    assert flush["attributes"]["lane"] == "grid"
    assert flush["attributes"]["batch_size"] == 1
    assert by_name["device.query_batch"]["parent_id"] == flush["span_id"]
    # emit: the response carries the request span's traceparent
    tp = resp_headers.get("traceparent", "")
    assert tp.startswith(f"00-{remote_trace}-")
    assert tp.split("-")[2] == root["span_id"]


def test_webhook_without_traceparent_starts_fresh_trace(traced_server):
    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        _post(traced_server.port, "/v1/admit", _review_body("u2"))
    tr = tracer.traces()[0]
    root = next(s for s in tr["spans"] if s["name"] == "webhook.request")
    assert root["parent_id"] is None
    assert len(tr["trace_id"]) == 32


def test_debug_traces_endpoint(traced_server):
    url = f"http://127.0.0.1:{traced_server.port}/debug/traces"
    # no tracer installed -> 404 with a hint
    try:
        urllib.request.urlopen(url)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404
    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        _post(traced_server.port, "/v1/admit", _review_body("u3"))
        with urllib.request.urlopen(url) as resp:
            doc = json.loads(resp.read())
    assert doc["kept"] >= 1
    assert doc["traces"][0]["spans"]
    names = {s["name"] for tr in doc["traces"] for s in tr["spans"]}
    assert "webhook.request" in names


# --- audit sweep ----------------------------------------------------------

def _library_mgr(objects, **cfg_kw):
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    load_library(client)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
    cfg_kw.setdefault("exact_totals", False)
    cfg = AuditConfig(chunk_size=48, **cfg_kw)
    return AuditManager(
        client, lister=lambda: iter(objects), config=cfg,
        evaluator=ShardedEvaluator(tpu, make_mesh(), violations_limit=20),
    )


def _kept_signature(run):
    return {
        k: [(v.message, v.kind, v.name, v.namespace, v.enforcement_action)
            for v in vs]
        for k, vs in run.kept.items()
    }


def test_pipelined_sweep_emits_chunk_scoped_stage_spans(tmp_path):
    objects = make_cluster_objects(120, seed=17)
    mgr = _library_mgr(objects, pipeline="on")
    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        run = mgr.audit()
    assert mgr.perf["pipelined"] == 1.0
    traces = tracer.traces()
    assert len(traces) == 1
    tr = traces[0]
    spans = tr["spans"]
    root = next(s for s in spans if s["name"] == "audit.sweep")
    # the ROADMAP's bench-JSON numbers ride the sweep root span
    assert root["attributes"]["objects"] == run.total_objects == 120
    assert root["attributes"]["violations"] == \
        sum(run.total_violations.values()) > 0
    assert root["attributes"]["stage_busy_sum_s"] == \
        mgr.pipe_stats["stage_busy_sum_s"]
    assert root["attributes"]["device_idle_fraction"] == \
        mgr.pipe_stats["device_idle_fraction"]
    # chunk-scoped stage spans, parented under the sweep root
    for stage in ("flatten", "dispatch", "collect", "fold_render"):
        st = [s for s in spans if s["name"] == f"pipeline.stage.{stage}"]
        assert st, stage
        assert all(s["parent_id"] == root["span_id"] for s in st)
        chunks = sorted(s["attributes"]["chunk"] for s in st)
        assert chunks == list(range(len(st))), (stage, chunks)
    n_chunks = mgr.pipe_stats["stages"]["flatten"]["items"]
    assert len([s for s in spans
                if s["name"] == "pipeline.stage.flatten"]) == n_chunks
    # the device lane is visible inside the dispatch/collect stages
    disp = [s for s in spans if s["name"] == "device.sweep_dispatch"]
    assert disp
    disp_parents = {s["parent_id"] for s in disp}
    stage_ids = {s["span_id"] for s in spans
                 if s["name"] == "pipeline.stage.dispatch"}
    assert disp_parents <= stage_ids
    assert any(s["name"] == "device.sweep_collect" for s in spans)

    # Chrome export of this sweep is a valid trace-event file with the
    # chunk indices riding the args (the bench.py --trace artifact shape)
    path = tmp_path / "sweep_trace.json"
    export.write_chrome_trace(str(path), tracer)
    doc = json.loads(path.read_text())
    evs = doc["traceEvents"]
    assert any(e["ph"] == "X" and e["name"].startswith("pipeline.stage.")
               and "chunk" in e["args"] for e in evs)
    assert any(e["name"] == "device.sweep_dispatch" for e in evs)
    assert any(e["name"] == "audit.sweep" for e in evs)


def test_serial_sweep_emits_chunk_spans():
    objects = make_cluster_objects(100, seed=19)
    mgr = _library_mgr(objects, pipeline="off")
    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        mgr.audit()
    spans = tracer.traces()[0]["spans"]
    subs = [s for s in spans if s["name"] == "audit.chunk.submit"]
    folds = [s for s in spans if s["name"] == "audit.chunk.collect_fold"]
    assert subs and len(folds) == len(subs)
    assert sorted(s["attributes"]["chunk"] for s in subs) == \
        list(range(len(subs)))
    root = next(s for s in spans if s["name"] == "audit.sweep")
    assert all(s["parent_id"] == root["span_id"] for s in subs)


@pytest.mark.slow  # tier-1 wall budget (PR 15): observability
# on-vs-off bit-identity stays pinned in tier-1 by
# test_obs_integration.py::test_observability_on_vs_off_bit_identical;
# this tracing-scoped twin rides the slow lane
def test_tracing_differential_verdicts_bit_identical():
    """Acceptance: tracer-on vs tracer-off (and the empty sampler) are
    bit-identical on totals AND rendered kept messages over the library
    corpus."""
    objects = make_cluster_objects(150, seed=23)
    run_off = _library_mgr(objects, pipeline="on").audit()

    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        run_on = _library_mgr(objects, pipeline="on").audit()
    assert len(tracer.traces()) == 1  # tracing actually ran

    empty = tracing.Tracer(seed=0, sample_rate=0.0)
    with tracing.activate(empty):
        run_empty = _library_mgr(objects, pipeline="on").audit()
    assert empty.traces() == [] and empty.span_count > 0

    assert run_off.total_violations == run_on.total_violations \
        == run_empty.total_violations
    assert _kept_signature(run_off) == _kept_signature(run_on) \
        == _kept_signature(run_empty)
    assert sum(run_off.total_violations.values()) > 0  # non-vacuous


# --- resilience events on spans ------------------------------------------

def test_chaos_fault_lands_as_span_event():
    """--chaos + --trace: the injected fault is an event on the exact
    span it hit, and the stage retry rides the same span."""
    from gatekeeper_tpu.resilience.faults import FaultPlan, inject

    objects = make_cluster_objects(100, seed=29)
    mgr = _library_mgr(objects, pipeline="on")
    tracer = tracing.Tracer(seed=0)
    plan = FaultPlan([{"site": "pipeline.stage.flatten", "mode": "error",
                       "times": 1}])
    with tracing.activate(tracer), inject(plan):
        run = mgr.audit()
    assert plan.fired() == 1
    spans = tracer.traces()[0]["spans"]
    flat = [s for s in spans if s["name"] == "pipeline.stage.flatten"]
    faulted = [s for s in flat
               if any(e["name"] == "fault_injected" for e in s["events"])]
    assert len(faulted) == 1
    ev = {e["name"]: e for e in faulted[0]["events"]}
    assert ev["fault_injected"]["attrs"] == {
        "site": "pipeline.stage.flatten", "mode": "error"}
    assert ev["stage_retry"]["attrs"]["attempt"] == 1
    # the retried stage still produced bit-identical output
    clean = _library_mgr(objects, pipeline="off").audit()
    assert run.total_violations == clean.total_violations


def test_gator_bench_prints_span_summary(tmp_path, capsys):
    """Satellite: one-line top-3-by-self-time span summary after each
    engine run."""
    import shutil

    from gatekeeper_tpu.gator import bench as gbench

    shutil.copy(f"{LIB}/requiredlabels/template.yaml", tmp_path)
    shutil.copy(f"{LIB}/requiredlabels/samples/constraint.yaml", tmp_path)
    (tmp_path / "data.yaml").write_text(
        "apiVersion: v1\nkind: Namespace\nmetadata:\n  name: no-owner\n")
    trace_out = tmp_path / "trace.json"
    rc = gbench.run_cli(["-f", str(tmp_path), "--engine", "rego", "-n",
                         "2", "--trace", str(trace_out)])
    assert rc == 0
    err = capsys.readouterr().err
    line = next(ln for ln in err.splitlines() if ln.startswith("[rego]"))
    assert "spans (top self-time):" in line
    assert "gator.bench.pass" in line
    doc = json.loads(trace_out.read_text())
    assert any(e.get("name") == "gator.bench.pass"
               for e in doc["traceEvents"])
    # the bench-scoped tracer did not leak into the process
    assert tracing.active_tracer() is None


@pytest.mark.slow
def test_bench_py_trace_artifact(tmp_path):
    """Acceptance: ``bench.py --trace out.json`` over the library corpus
    writes a valid Chrome trace-event file with pipeline stage spans
    (chunk indices) and device dispatch spans."""
    import os
    import subprocess
    import sys

    out = tmp_path / "trace.json"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--pipeline=on", f"--trace={out}",
         "800", "256"],
        cwd="/root/repo", timeout=560, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(out.read_text())
    evs = doc["traceEvents"]
    assert any(e["ph"] == "X" and e["name"].startswith("pipeline.stage.")
               and "chunk" in e["args"] for e in evs)
    assert any(e.get("name") == "device.sweep_dispatch" for e in evs)
    assert any(e.get("name") == "audit.sweep"
               and "stage_busy_sum_s" in e["args"] for e in evs)


def test_retry_and_breaker_events_ride_the_ambient_span():
    from gatekeeper_tpu.resilience.policy import CircuitBreaker, RetryPolicy

    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        with tracing.span("op"):
            calls = [0]

            def flaky():
                calls[0] += 1
                if calls[0] < 3:
                    raise OSError("transient")
                return "ok"

            rp = RetryPolicy(attempts=3, base_s=0.0, cap_s=0.0,
                             dependency="dep", sleep=lambda _s: None)
            assert rp.call(flaky) == "ok"
            br = CircuitBreaker("dep2", failure_threshold=1,
                                clock=lambda: 0.0)
            br.record_failure()
    sp = tracer.traces()[0]["spans"][0]
    events = [(e["name"], e["attrs"]) for e in sp["events"]]
    retries = [a for n, a in events if n == "retry"]
    assert [a["attempt"] for a in retries] == [1, 2]
    assert all(a["dependency"] == "dep" for a in retries)
    transitions = [a for n, a in events if n == "breaker_transition"]
    assert transitions == [{"dependency": "dep2", "breaker_from": "closed",
                            "breaker_to": "open"}]
