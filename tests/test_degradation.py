"""Targeted SLO degradation maps (ISSUE 18): DegradationRegistry
semantics (refcounted activation, cluster scoping, gauge export),
engine edges (rising-edge activate, hold-based escalation, falling-edge
release in reverse), deterministic trajectory replay under an injected
clock, fleet-scoped breach isolation, consumer wiring (shed_harder
queue bounds), and --slo-config fail-fast validation."""

import pytest

from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.observability import slo
from gatekeeper_tpu.resilience import overload as ovl

STALE = {
    "name": "stale", "type": "staleness", "gauge": "last_end",
    "threshold": 60.0,
    "degradation": ["audit_yield_release", "resync_defer"],
}


def _engine(m, objectives, reg, hold=30.0):
    fake = {"t": 0.0, "w": 1_000_000.0}
    eng = slo.SLOEngine(m, objectives=list(objectives),
                        clock=lambda: fake["t"],
                        wall=lambda: fake["w"],
                        degradations=reg, escalate_hold_s=hold)
    return eng, fake


# --- registry semantics ----------------------------------------------------

def test_registry_refcounted_activation_and_gauge():
    m = MetricsRegistry()
    reg = ovl.DegradationRegistry(metrics=m)
    assert reg.activate(ovl.NS_CACHE_STALE, objective="a") is True
    # second holder: no new edge, still active
    assert reg.activate(ovl.NS_CACHE_STALE, objective="b") is False
    assert reg.is_active(ovl.NS_CACHE_STALE)
    assert m.get_gauge(M.SLO_DEGRADATION,
                       {"objective": "a",
                        "action": ovl.NS_CACHE_STALE}) == 1.0
    # releasing one holder keeps the action held by the other
    assert reg.release(ovl.NS_CACHE_STALE, objective="a") is False
    assert reg.is_active(ovl.NS_CACHE_STALE)
    assert reg.release(ovl.NS_CACHE_STALE, objective="b") is True
    assert not reg.is_active(ovl.NS_CACHE_STALE)
    assert m.get_gauge(M.SLO_DEGRADATION,
                       {"objective": "b",
                        "action": ovl.NS_CACHE_STALE}) == 0.0


def test_registry_unknown_action_rejected():
    reg = ovl.DegradationRegistry()
    with pytest.raises(ValueError, match="nope"):
        reg.activate("nope")
    with pytest.raises(ValueError, match="rogue"):
        reg.validate(["ns_cache_stale", "rogue"], where="objective 'x'")
    # custom actions register with a description and then validate
    reg.register("dim_the_lights", description="for tests")
    reg.validate(["dim_the_lights"])


def test_registry_cluster_scoping():
    reg = ovl.DegradationRegistry()
    reg.activate(ovl.NS_CACHE_STALE, objective="o@a", cluster="a")
    # cluster A's activation is invisible to B and to the global scope
    assert reg.is_active(ovl.NS_CACHE_STALE, cluster="a")
    assert not reg.is_active(ovl.NS_CACHE_STALE, cluster="b")
    assert not reg.is_active(ovl.NS_CACHE_STALE)
    # a GLOBAL activation is visible in every cluster scope
    reg.activate(ovl.EXTDATA_STALE, objective="g")
    assert reg.is_active(ovl.EXTDATA_STALE, cluster="a")
    assert reg.is_active(ovl.EXTDATA_STALE, cluster="b")
    names = reg.active_names()
    assert f"{ovl.NS_CACHE_STALE}@a" in names
    assert ovl.EXTDATA_STALE in names


def test_module_degradation_active_defaults_off():
    # no registry installed: every consumer check reads False — the
    # bit-identity guarantee of the un-armed build
    assert ovl.active_degradations() is None
    assert not ovl.degradation_active(ovl.SHED_HARDER)
    assert not ovl.degradation_active(ovl.NS_CACHE_STALE, "a")


# --- engine edges ----------------------------------------------------------

def _set_age(m, fake, age, labels=None):
    m.set_gauge("last_end", fake["w"] - age, labels)


def test_breach_activates_escalates_and_releases_in_reverse():
    m = MetricsRegistry()
    reg = ovl.DegradationRegistry(metrics=m)
    eng, fake = _engine(m, [STALE], reg, hold=30.0)

    _set_age(m, fake, 10.0)
    ev = eng.tick()["objectives"][0]
    assert not ev["breach"] and ev["degradation_active"] == []

    # breach: the first mapped action activates on the rising edge
    _set_age(m, fake, 120.0)
    fake["t"] = 10.0
    ev = eng.tick()["objectives"][0]
    assert ev["breach"]
    assert ev["degradation_active"] == ["audit_yield_release"]
    assert reg.is_active(ovl.AUDIT_YIELD_RELEASE)
    assert not reg.is_active(ovl.RESYNC_DEFER)

    # still breaching but inside the hold: no escalation yet
    fake["t"] = 25.0
    ev = eng.tick()["objectives"][0]
    assert ev["degradation_active"] == ["audit_yield_release"]

    # held past escalate_hold_s: the next action activates
    fake["t"] = 45.0
    ev = eng.tick()["objectives"][0]
    assert ev["degradation_active"] == ["audit_yield_release",
                                        "resync_defer"]
    assert reg.is_active(ovl.RESYNC_DEFER)

    # recovery: falling edge releases EVERYTHING, deepest-first
    _set_age(m, fake, 1.0)
    fake["t"] = 60.0
    ev = eng.tick()["objectives"][0]
    assert not ev["breach"] and ev["degradation_active"] == []
    assert not reg.is_active(ovl.AUDIT_YIELD_RELEASE)
    assert not reg.is_active(ovl.RESYNC_DEFER)
    events = [(e["action"], e["event"])
              for e in eng.degradation_trajectory]
    assert events == [
        ("audit_yield_release", "activate"),
        ("resync_defer", "activate"),
        ("resync_defer", "release"),       # reverse order on the way out
        ("audit_yield_release", "release"),
    ]


def _scripted_run():
    """One full breach/escalate/recover pass; returns the trajectory."""
    m = MetricsRegistry()
    reg = ovl.DegradationRegistry(metrics=m)
    eng, fake = _engine(m, [STALE], reg, hold=30.0)
    script = [(0.0, 10.0), (10.0, 120.0), (25.0, 130.0), (45.0, 140.0),
              (60.0, 150.0), (90.0, 1.0), (120.0, 5.0)]
    for t, age in script:
        fake["t"] = t
        _set_age(m, fake, age)
        eng.tick()
    return list(eng.degradation_trajectory)


def test_trajectory_replays_exactly():
    """Identical (config, injected clock, metric sequence) => identical
    activation/release trajectory — the determinism pin."""
    first = _scripted_run()
    second = _scripted_run()
    assert first == second
    assert first  # non-vacuous: the script really drives transitions
    assert any(e["event"] == "activate" for e in first)
    assert any(e["event"] == "release" for e in first)


# --- fleet-scoped isolation ------------------------------------------------

def test_cluster_breach_isolation():
    """Cluster A stale, cluster B fresh, one shared registry: A's
    objective breaches and degrades A only — B stays compliant and
    undegraded (the fleet isolation pin)."""
    m = MetricsRegistry()
    reg = ovl.DegradationRegistry(metrics=m)
    objectives = slo.per_cluster_objectives(["a", "b"], base=[STALE])
    eng, fake = _engine(m, objectives, reg)

    _set_age(m, fake, 900.0, {"cluster": "a"})   # A: very stale
    _set_age(m, fake, 2.0, {"cluster": "b"})     # B: fresh
    out = eng.tick()
    by_name = {ev["name"]: ev for ev in out["objectives"]}
    assert by_name["stale@a"]["breach"]
    assert by_name["stale@a"]["degradation_active"] == \
        ["audit_yield_release"]
    assert not by_name["stale@b"]["breach"]
    assert by_name["stale@b"]["degradation_active"] == []
    # the registry scopes the action: active for A, NOT for B, NOT
    # globally — cluster A's breach never degrades cluster B
    assert reg.is_active(ovl.AUDIT_YIELD_RELEASE, cluster="a")
    assert not reg.is_active(ovl.AUDIT_YIELD_RELEASE, cluster="b")
    assert not reg.is_active(ovl.AUDIT_YIELD_RELEASE)
    # the ?cluster= views split the same way
    snap_a = eng.snapshot(cluster="a")
    snap_b = eng.snapshot(cluster="b")
    assert [ev["name"] for ev in snap_a["objectives"]] == ["stale@a"]
    assert [ev["name"] for ev in snap_b["objectives"]] == ["stale@b"]
    assert eng.degraded() == {"stale@a": ["audit_yield_release"]}


# --- consumer wiring -------------------------------------------------------

def test_shed_harder_halves_queue_bounds():
    ctl = ovl.OverloadController(ovl.OverloadConfig(
        queue_depth=8, queue_cost=100.0))
    reg = ovl.DegradationRegistry()
    assert ctl._queue_bounds() == (8, 100.0)
    with ovl.activate_degradations(reg):
        assert ctl._queue_bounds() == (8, 100.0)  # armed but inactive
        reg.activate(ovl.SHED_HARDER, objective="o")
        assert ctl._queue_bounds() == (4, 50.0)
        reg.release(ovl.SHED_HARDER, objective="o")
        assert ctl._queue_bounds() == (8, 100.0)
    # degradations appear in the /debug/overload payload while held
    with ovl.activate_degradations(reg):
        reg.activate(ovl.SHED_HARDER, objective="o")
        snap = ctl.snapshot()
        assert snap["degraded"][0]["action"] == ovl.SHED_HARDER
        reg.release(ovl.SHED_HARDER, objective="o")


def test_audit_yield_release_skips_device_yield():
    ctl = ovl.OverloadController(ovl.OverloadConfig())
    reg = ovl.DegradationRegistry()
    with ovl.activate(ctl), ovl.activate_degradations(reg):
        ctl._brownout = 2  # deep brownout: audit normally yields
        assert ovl.yield_device_lane(max_wait_s=0.01, poll_s=0.005) \
            >= 0.0
        reg.activate(ovl.AUDIT_YIELD_RELEASE, objective="o")
        # released: the audit reclaims the lane instantly, no wait
        assert ovl.yield_device_lane(max_wait_s=5.0) == 0.0
        # cluster-scoped release only frees that cluster's audit
        reg.release(ovl.AUDIT_YIELD_RELEASE, objective="o")
        reg.activate(ovl.AUDIT_YIELD_RELEASE, objective="o@a",
                     cluster="a")
        assert ovl.yield_device_lane(max_wait_s=5.0, cluster="a") == 0.0


# --- config validation -----------------------------------------------------

def test_config_malformed_json_names_line(tmp_path):
    p = tmp_path / "slo.json"
    p.write_text('{"objectives": [\n  {"name": "x",}\n]}')
    with pytest.raises(slo.SLOConfigError) as ei:
        slo.load_config(str(p))
    msg = str(ei.value)
    assert str(p) in msg and "malformed JSON" in msg
    assert ":2:" in msg  # the offending line


def test_config_unknown_field_and_bad_types(tmp_path):
    p = tmp_path / "slo.json"
    p.write_text('{"objectives": [{"name": "x", "typo_field": 1}]}')
    with pytest.raises(slo.SLOConfigError, match=r"objectives\[0\].*"
                                                 r"typo_field"):
        slo.load_config(str(p))
    p.write_text('{"objectives": [{"name": "x", "target": "fast"}]}')
    with pytest.raises(slo.SLOConfigError, match="must be numbers"):
        slo.load_config(str(p))
    p.write_text('{"objectives": [], "tiers": [{"name": "t"}]}')
    with pytest.raises(slo.SLOConfigError, match="short_s"):
        slo.load_config(str(p))


def test_config_unknown_degradation_action(tmp_path):
    p = tmp_path / "slo.json"
    p.write_text('{"objectives": [{"name": "x", "type": "staleness", '
                 '"gauge": "g", "threshold": 5, '
                 '"degradation": ["warp_drive"]}]}')
    # without a registry the names pass through (inert maps)
    assert slo.load_config(str(p))["objectives"]
    with pytest.raises(slo.SLOConfigError, match="warp_drive"):
        slo.load_config(str(p), ovl.DegradationRegistry())
