"""Span tracer core: IDs, parent links, context propagation, W3C
traceparent interop, tail sampling, ring bounds, Chrome export."""

import json
import threading

from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.observability import export, tracing


# --- zero-cost disabled path ----------------------------------------------

def test_disabled_tracer_is_noop():
    assert tracing.active_tracer() is None
    assert not tracing.enabled()
    with tracing.span("anything", attr=1) as s:
        s.set_attribute("k", "v")
        s.add_event("ev", x=1)
        assert tracing.current_span() is None  # noop span is not ambient
    tracing.add_event("free-floating")  # must not raise
    assert tracing.format_traceparent() is None


# --- span structure -------------------------------------------------------

def test_parent_links_and_attributes():
    t = tracing.Tracer(seed=1)
    with tracing.activate(t):
        with tracing.span("root", lane="test") as r:
            assert tracing.current_span() is r
            with tracing.span("child", chunk=7) as c:
                c.add_event("retry", attempt=1)
            with tracing.span("child2"):
                pass
    traces = t.traces()
    assert len(traces) == 1
    tr = traces[0]
    assert tr["root"] == "root" and tr["n_spans"] == 3
    by_name = {s["name"]: s for s in tr["spans"]}
    root = by_name["root"]
    assert root["parent_id"] is None
    assert root["attributes"] == {"lane": "test"}
    assert by_name["child"]["parent_id"] == root["span_id"]
    assert by_name["child"]["attributes"]["chunk"] == 7
    assert by_name["child"]["events"][0]["name"] == "retry"
    assert by_name["child2"]["parent_id"] == root["span_id"]
    assert all(s["trace_id"] == tr["trace_id"] for s in tr["spans"])


def test_span_records_error_status():
    t = tracing.Tracer(seed=1)
    with tracing.activate(t):
        try:
            with tracing.span("boom"):
                raise ValueError("nope")
        except ValueError:
            pass
    sp = t.traces()[0]["spans"][0]
    assert sp["status"] == "error"
    assert "nope" in sp["error"]


def test_deterministic_ids_under_seed():
    def run(seed):
        t = tracing.Tracer(seed=seed)
        with tracing.activate(t):
            with tracing.span("a"):
                with tracing.span("b"):
                    pass
            with tracing.span("c"):
                pass
        return [(s["trace_id"], s["span_id"])
                for tr in t.traces() for s in tr["spans"]]

    assert run(42) == run(42)
    assert run(42) != run(43)


def test_explicit_parent_crosses_threads():
    t = tracing.Tracer(seed=2)
    got = {}
    with tracing.activate(t):
        with tracing.span("request") as req:
            def worker():
                # contextvars do not cross threads: the parent must ride
                # explicitly (the batcher / pipeline-stage pattern)
                assert tracing.current_span() is None
                with tracing.use_span(req):
                    with tracing.span("work") as w:
                        got["trace"] = w.trace_id
                        got["parent"] = w.parent_id
            th = threading.Thread(target=worker)
            th.start()
            th.join()
    assert got["trace"] == req.trace_id
    assert got["parent"] == req.span_id
    assert t.traces()[0]["n_spans"] == 2


# --- W3C traceparent ------------------------------------------------------

def test_traceparent_roundtrip():
    t = tracing.Tracer(seed=3)
    with tracing.activate(t):
        with tracing.span("out") as s:
            header = tracing.format_traceparent()
            assert header == f"00-{s.trace_id}-{s.span_id}-01"
    ctx = tracing.parse_traceparent(header)
    assert ctx.trace_id == s.trace_id and ctx.span_id == s.span_id
    # a remote parent joins the caller's trace but the local span is
    # still the LOCAL root (its end finalizes the trace)
    with tracing.activate(t):
        with tracing.span("ingest", parent=ctx):
            pass
    tr = t.traces()[-1]
    assert tr["trace_id"] == s.trace_id
    assert tr["spans"][0]["parent_id"] == s.span_id


def test_traceparent_rejects_malformed():
    bad = [
        None, "", "garbage", "00-abc-def-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # all-zero span
        "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",  # forbidden version
        "00-" + "g" * 32 + "-" + "b" * 16 + "-01",  # non-hex
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",  # short trace id
    ]
    for h in bad:
        assert tracing.parse_traceparent(h) is None, h


# --- tail sampling + ring bounds -----------------------------------------

def test_empty_sampler_retains_nothing():
    t = tracing.Tracer(seed=0, sample_rate=0.0)
    with tracing.activate(t):
        for _ in range(5):
            with tracing.span("r"):
                pass
    assert t.traces() == []
    assert t.sampled_out == 5 and t.kept == 0
    assert t.span_count == 5  # the machinery ran; nothing was retained


def test_slow_traces_always_kept():
    clock = [0.0]
    t = tracing.Tracer(seed=0, sample_rate=0.0, slow_threshold_s=1.0,
                       clock=lambda: clock[0])
    with tracing.activate(t):
        with tracing.span("fast"):
            clock[0] += 0.5
        with tracing.span("slow"):
            clock[0] += 2.0
    kept = t.traces()
    assert [tr["root"] for tr in kept] == ["slow"]
    assert t.sampled_out == 1


def test_probabilistic_sampling_is_seeded():
    def run():
        t = tracing.Tracer(seed=7, sample_rate=0.5)
        with tracing.activate(t):
            for i in range(40):
                with tracing.span(f"r{i}"):
                    pass
        return [tr["root"] for tr in t.traces()]

    first = run()
    assert run() == first  # same seed -> same keep/drop sequence
    assert 0 < len(first) < 40


def test_ring_buffer_is_bounded():
    t = tracing.Tracer(seed=0, ring_capacity=8)
    with tracing.activate(t):
        for i in range(30):
            with tracing.span(f"r{i}"):
                pass
    traces = t.traces()
    assert len(traces) == 8
    assert traces[-1]["root"] == "r29"  # most recent kept
    assert t.kept == 30  # kept counts all, the ring holds the tail


def test_sampler_outcomes_flow_into_metrics():
    from gatekeeper_tpu.metrics import registry as M

    reg = MetricsRegistry()
    t = tracing.Tracer(seed=0, sample_rate=0.0, slow_threshold_s=10.0,
                       metrics=reg)
    with tracing.activate(t):
        with tracing.span("r"):
            pass
    assert reg.counter_total(M.TRACE_SAMPLED_OUT) == 1
    assert reg.counter_total(M.TRACE_KEPT) == 0


# --- export ---------------------------------------------------------------

def test_chrome_trace_export(tmp_path):
    t = tracing.Tracer(seed=5)
    with tracing.activate(t):
        with tracing.span("root"):
            with tracing.span("stage", chunk=3) as s:
                s.add_event("fault_injected", site="x", mode="error")
    path = tmp_path / "out.json"
    n = export.write_chrome_trace(str(path), t)
    doc = json.loads(path.read_text())
    assert len(doc["traceEvents"]) == n
    complete = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"root", "stage"}
    stage = next(e for e in complete if e["name"] == "stage")
    assert stage["args"]["chunk"] == 3
    assert stage["args"]["parent_id"]
    assert stage["ts"] > 0 and stage["dur"] >= 0
    instant = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert instant[0]["name"] == "fault_injected"
    assert instant[0]["args"] == {"site": "x", "mode": "error"}
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert meta and meta[0]["name"] == "thread_name"


def test_self_time_summary_ranks_by_self_time():
    clock = [0.0]
    t = tracing.Tracer(seed=0, clock=lambda: clock[0])
    with tracing.activate(t):
        with tracing.span("outer"):
            clock[0] += 0.1  # outer self-time
            with tracing.span("inner"):
                clock[0] += 5.0  # inner dominates
    ranked = export.top_spans_by_self_time(t.traces(), top=3)
    assert ranked[0][0] == "inner"
    assert abs(ranked[0][1] - 5.0) < 1e-6
    assert abs(ranked[1][1] - 0.1) < 1e-6  # outer MINUS child time
    line = export.format_span_summary(t.traces())
    assert line.startswith("spans (top self-time): inner")
    assert export.format_span_summary([]) == "spans: (no traces kept)"
