"""The Pallas verdict-epilogue kernel must agree with the XLA twin
(parallel.sharded.topk_violations) under the valid-mask, for every grid
shape class the sweep produces.  Off-TPU the kernel runs in interpret
mode — same kernel logic, plain-JAX execution."""

import numpy as np
import jax.numpy as jnp

from gatekeeper_tpu.ops.pallas_topk import (fused_fold_pallas,
                                            topk_violations_counts_pallas,
                                            topk_violations_pallas)
from gatekeeper_tpu.parallel.sharded import topk_violations


def _agree(verdicts: np.ndarray, k: int):
    g = jnp.asarray(verdicts)
    xi, xv = topk_violations(g, k)
    pi, pv, pc = topk_violations_counts_pallas(g, k)
    xi, xv = np.asarray(xi), np.asarray(xv)
    pi, pv = np.asarray(pi), np.asarray(pv)
    assert np.array_equal(xv, pv), "valid masks differ"
    assert np.array_equal(np.where(xv, xi, -1), np.where(pv, pi, -1)), \
        "selected indices differ under the valid mask"
    # the kernel's fused count lane must be the exact row sums
    assert np.array_equal(np.asarray(pc), verdicts.sum(axis=1))


def test_dense_sparse_empty_rows():
    rng = np.random.default_rng(0)
    v = rng.random((46, 4096)) < 0.01      # sparse
    v[3] = False                            # empty row
    v[7] = True                             # full row
    v[11, -1] = True                        # lone hit at the tail
    _agree(v, 20)


def test_k_larger_than_hits_and_row():
    rng = np.random.default_rng(1)
    v = rng.random((5, 64)) < 0.2
    _agree(v, 20)   # k < n but > hits in most rows
    _agree(v, 64)   # k == n


def test_k_beyond_lane_tile_falls_back():
    rng = np.random.default_rng(3)
    v = rng.random((4, 512)) < 0.3
    _agree(v, 128)  # k >= _KPAD: routes through the XLA twin
    _agree(v, 200)


def test_row_padding_to_sublane_tile():
    rng = np.random.default_rng(2)
    for c in (1, 7, 8, 9, 46):
        v = rng.random((c, 512)) < 0.05
        _agree(v, 20)


def _fold_agree(grid_raw: np.ndarray, mask: np.ndarray, k: int):
    """fused_fold_pallas == XLA reference fold, bit for bit: top-k of
    the masked grid, masked row sums (violation totals), mask row sums
    (occupancy — the resident lane's device-vs-host mirror invariant)."""
    g, m = jnp.asarray(grid_raw), jnp.asarray(mask)
    masked = grid_raw & mask
    xi, xv = topk_violations(jnp.asarray(masked), min(k, masked.shape[1]))
    pi, pv, pc, po = fused_fold_pallas(g, m, k)
    xi, xv = np.asarray(xi), np.asarray(xv)
    pi, pv = np.asarray(pi), np.asarray(pv)
    assert np.array_equal(xv, pv), "valid masks differ"
    assert np.array_equal(np.where(xv, xi, -1), np.where(pv, pi, -1)), \
        "selected indices differ under the valid mask"
    assert np.array_equal(np.asarray(pc), masked.sum(axis=1))
    assert np.array_equal(np.asarray(po), mask.sum(axis=1))


def test_fused_fold_matches_xla_fold():
    rng = np.random.default_rng(4)
    grid = rng.random((46, 4096)) < 0.02   # raw verdicts (pre-mask)
    mask = rng.random((46, 4096)) < 0.7    # scope mask
    grid[5] = True                          # full row
    mask[9] = False                         # fully out-of-scope row
    grid[13] = False                        # clean row
    mask[21, :7] = True                     # sliver-scoped row
    _fold_agree(grid, mask, 20)


def test_fused_fold_shape_classes_and_k_edges():
    rng = np.random.default_rng(5)
    for c in (1, 7, 8, 46):
        grid = rng.random((c, 512)) < 0.1
        mask = rng.random((c, 512)) < 0.5
        _fold_agree(grid, mask, 20)
    grid = rng.random((4, 64)) < 0.3
    mask = rng.random((4, 64)) < 0.5
    _fold_agree(grid, mask, 64)    # k == n
    _fold_agree(grid, mask, 200)   # k > n: clamped


def test_fused_fold_k_beyond_lane_tile_falls_back():
    rng = np.random.default_rng(6)
    grid = rng.random((4, 512)) < 0.2
    mask = rng.random((4, 512)) < 0.6
    _fold_agree(grid, mask, 127)   # k == _KPAD - 1: XLA fallback
    _fold_agree(grid, mask, 300)


def test_first_k_are_lowest_indices():
    v = np.zeros((2, 256), bool)
    hits = [5, 17, 99, 100, 255]
    v[0, hits] = True
    idx, valid = topk_violations_pallas(jnp.asarray(v), 3)
    assert np.asarray(idx)[0, :3].tolist() == hits[:3]
    assert np.asarray(valid)[0].tolist() == [True, True, True]
    assert not np.asarray(valid)[1].any()
