"""The Pallas verdict-epilogue kernel must agree with the XLA twin
(parallel.sharded.topk_violations) under the valid-mask, for every grid
shape class the sweep produces.  Off-TPU the kernel runs in interpret
mode — same kernel logic, plain-JAX execution."""

import numpy as np
import jax.numpy as jnp

from gatekeeper_tpu.ops.pallas_topk import (topk_violations_counts_pallas,
                                            topk_violations_pallas)
from gatekeeper_tpu.parallel.sharded import topk_violations


def _agree(verdicts: np.ndarray, k: int):
    g = jnp.asarray(verdicts)
    xi, xv = topk_violations(g, k)
    pi, pv, pc = topk_violations_counts_pallas(g, k)
    xi, xv = np.asarray(xi), np.asarray(xv)
    pi, pv = np.asarray(pi), np.asarray(pv)
    assert np.array_equal(xv, pv), "valid masks differ"
    assert np.array_equal(np.where(xv, xi, -1), np.where(pv, pi, -1)), \
        "selected indices differ under the valid mask"
    # the kernel's fused count lane must be the exact row sums
    assert np.array_equal(np.asarray(pc), verdicts.sum(axis=1))


def test_dense_sparse_empty_rows():
    rng = np.random.default_rng(0)
    v = rng.random((46, 4096)) < 0.01      # sparse
    v[3] = False                            # empty row
    v[7] = True                             # full row
    v[11, -1] = True                        # lone hit at the tail
    _agree(v, 20)


def test_k_larger_than_hits_and_row():
    rng = np.random.default_rng(1)
    v = rng.random((5, 64)) < 0.2
    _agree(v, 20)   # k < n but > hits in most rows
    _agree(v, 64)   # k == n


def test_k_beyond_lane_tile_falls_back():
    rng = np.random.default_rng(3)
    v = rng.random((4, 512)) < 0.3
    _agree(v, 128)  # k >= _KPAD: routes through the XLA twin
    _agree(v, 200)


def test_row_padding_to_sublane_tile():
    rng = np.random.default_rng(2)
    for c in (1, 7, 8, 9, 46):
        v = rng.random((c, 512)) < 0.05
        _agree(v, 20)


def test_first_k_are_lowest_indices():
    v = np.zeros((2, 256), bool)
    hits = [5, 17, 99, 100, 255]
    v[0, hits] = True
    idx, valid = topk_violations_pallas(jnp.asarray(v), 3)
    assert np.asarray(idx)[0, :3].tolist() == hits[:3]
    assert np.asarray(valid)[0].tolist() == [True, True, True]
    assert not np.asarray(valid)[1].any()
