"""Kubernetes Events emission (reference --emit-admission-events,
pkg/webhook/policy.go:276-340; --emit-audit-events,
pkg/audit/manager.go:1247-1296): both sinks must POST real corev1 Event
objects through the apiserver client."""

import pytest

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.sync.events import (EventRecorder, admission_event_sink,
                                        audit_event_sink, violation_ref)
from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig
from gatekeeper_tpu.sync.mock_apiserver import MockApiServer
from gatekeeper_tpu.target.target import K8sValidationTarget

TARGET = "admission.k8s.gatekeeper.sh"

TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8sdenyall"},
    "spec": {"crd": {"spec": {"names": {"kind": "K8sDenyAll"}}},
             "targets": [{"target": TARGET, "rego": """
package k8sdenyall

violation[{"msg": msg}] {
  msg := sprintf("denied: %v", [input.review.object.metadata.name])
}
"""}]},
}


@pytest.fixture()
def server():
    srv = MockApiServer().start()
    yield srv
    srv.stop()


@pytest.fixture()
def cluster(server):
    kc = KubeCluster(KubeConfig(server=server.url))
    yield kc
    kc.close()


def _client():
    tpu = TpuDriver()
    client = Client(target=K8sValidationTarget(), drivers=[tpu],
                    enforcement_points=[
                        "validation.gatekeeper.sh", "audit.gatekeeper.sh"])
    client.add_template(TEMPLATE)
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sDenyAll", "metadata": {"name": "deny-everything"},
        "spec": {}})
    return client


def _events(cluster):
    return cluster.list(("", "v1", "Event"))


def test_violation_ref_reference_semantics():
    # default: gatekeeper namespace + synthetic aggregation UID
    ref = violation_ref("gatekeeper-system", "Pod", "p", "apps", "7", "u1",
                        "K8sDenyAll", "deny-everything", "", False)
    assert ref["namespace"] == "gatekeeper-system"
    assert ref["uid"] == "Pod/apps/p/K8sDenyAll//deny-everything"
    # involved-namespace: real uid/rv in the resource's own namespace
    ref = violation_ref("gatekeeper-system", "Pod", "p", "apps", "7", "u1",
                        "K8sDenyAll", "deny-everything", "", True)
    assert ref["namespace"] == "apps"
    assert ref["uid"] == "u1" and ref["resourceVersion"] == "7"


def test_admission_events_end_to_end(cluster):
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    rec = EventRecorder(cluster, "gatekeeper-webhook")
    handler = ValidationHandler(
        _client(), event_sink=admission_event_sink(rec),
    )
    resp = handler.handle({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": "req-1",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "operation": "CREATE", "name": "bad-pod", "namespace": "apps",
            "userInfo": {"username": "alice"},
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "bad-pod", "namespace": "apps",
                                    "uid": "u-1", "resourceVersion": "5"},
                       "spec": {"containers": []}},
        }})
    assert not resp.allowed
    rec.flush()
    evs = _events(cluster)
    assert len(evs) == 1
    ev = evs[0]
    assert ev["reason"] == "FailedAdmission"
    assert ev["type"] == "Warning"
    assert ev["source"]["component"] == "gatekeeper-webhook"
    assert ev["metadata"]["namespace"] == "gatekeeper-system"
    assert ev["involvedObject"]["kind"] == "Pod"
    assert ev["involvedObject"]["name"] == "bad-pod"
    assert "Constraint: deny-everything" in ev["message"]
    assert "denied request" in ev["message"]
    ann = ev["metadata"]["annotations"]
    assert ann["process"] == "admission"
    assert ann["event_type"] == "violation"
    assert ann["constraint_kind"] == "K8sDenyAll"
    assert ann["resource_namespace"] == "apps"
    assert ann["request_username"] == "alice"


def test_admission_events_involved_namespace(cluster):
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    rec = EventRecorder(cluster, "gatekeeper-webhook",
                        involved_namespace=True)
    handler = ValidationHandler(
        _client(), event_sink=admission_event_sink(rec),
    )
    handler.handle({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": "req-2",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "operation": "CREATE", "name": "bad-pod", "namespace": "apps",
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "bad-pod", "namespace": "apps",
                                    "uid": "u-1", "resourceVersion": "5"},
                       "spec": {"containers": []}},
        }})
    rec.flush()
    evs = _events(cluster)
    assert len(evs) == 1
    assert evs[0]["metadata"]["namespace"] == "apps"
    assert evs[0]["involvedObject"]["uid"] == "u-1"
    # involved-namespace message omits the namespace clause
    assert "Resource Namespace:" not in evs[0]["message"]


def test_audit_events_per_kept_violation(cluster):
    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager

    client = _client()
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": f"p{i}", "namespace": "apps"},
             "spec": {"containers": []}} for i in range(3)]
    rec = EventRecorder(cluster, "gatekeeper-audit")
    mgr = AuditManager(
        client, lister=lambda: iter(objs),
        config=AuditConfig(violations_limit=20),
        event_sink=audit_event_sink(rec),
    )
    run = mgr.audit()
    assert sum(run.total_violations.values()) == 3
    rec.flush()
    evs = _events(cluster)
    assert len(evs) == 3
    for ev in evs:
        assert ev["reason"] == "AuditViolation"
        assert ev["source"]["component"] == "gatekeeper-audit"
        assert ev["metadata"]["namespace"] == "gatekeeper-system"
        ann = ev["metadata"]["annotations"]
        assert ann["process"] == "audit"
        assert ann["event_type"] == "violation_audited"
        assert ann["auditTimestamp"] == run.timestamp
        assert ann["constraint_name"] == "deny-everything"
    assert sorted(e["involvedObject"]["name"] for e in evs) == \
        ["p0", "p1", "p2"]


def test_event_emit_failure_never_raises():
    class Boom:
        def create(self, obj):
            raise RuntimeError("apiserver down")

    errors = []
    rec = EventRecorder(Boom(), "gatekeeper-webhook",
                        on_error=errors.append)
    rec.annotated_event({"kind": "Pod", "name": "p",
                         "namespace": "gatekeeper-system"}, {},
                        "FailedAdmission", "msg")
    rec.flush()
    assert len(errors) == 1  # reported, not raised


def test_audit_events_aggregate_across_passes(cluster):
    """A violation persisting across audit intervals bumps count on the
    SAME Event object (record.EventRecorder series aggregation) instead of
    minting a new etcd object per pass."""
    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager

    client = _client()
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "p0", "namespace": "apps"},
             "spec": {"containers": []}}]
    rec = EventRecorder(cluster, "gatekeeper-audit")
    mgr = AuditManager(
        client, lister=lambda: iter(objs),
        config=AuditConfig(violations_limit=20),
        event_sink=audit_event_sink(rec),
    )
    mgr.audit()
    mgr.audit()
    rec.flush()
    evs = _events(cluster)
    assert len(evs) == 1
    assert evs[0]["count"] == 2


def test_aggregation_preserves_first_timestamp(cluster):
    rec = EventRecorder(cluster, "gatekeeper-audit")
    ref = violation_ref("gatekeeper-system", "Pod", "p0", "apps", "", "",
                        "K8sDenyAll", "c", "", False)
    rec.annotated_event(ref, {}, "AuditViolation", "m")
    rec.flush()
    first = _events(cluster)[0]["firstTimestamp"]
    rec.annotated_event(ref, {}, "AuditViolation", "m")
    rec.flush()
    ev = _events(cluster)[0]
    assert ev["count"] == 2
    assert ev["firstTimestamp"] == first


def test_sweep_ready_handles_rpc_futures():
    """RemoteEvaluator pendings are grpc futures: readiness must come from
    done(), never from treating the bound .result method as a jax array
    (which would force a blocking collect per submit — no pipelining)."""
    from gatekeeper_tpu.audit.manager import _sweep_ready

    class FakeFuture:
        def __init__(self, ready):
            self._ready = ready

        def done(self):
            return self._ready

        def result(self):
            return {}

    assert _sweep_ready(FakeFuture(True)) is True
    assert _sweep_ready(FakeFuture(False)) is False
    assert _sweep_ready({}) is True  # empty submit
