"""Interpreter tests built around the reference's own policy fixtures.

Expected violation messages are the byte-exact strings OPA would produce
(reference contract: demo/basic/templates + pkg/webhook/testdata PSP suite).
"""

import glob

import yaml

from gatekeeper_tpu.lang.rego.interp import Interpreter, compile_modules
from gatekeeper_tpu.lang.rego.value import RegoSet, UNDEFINED

REQ_LABELS = open(
    "/root/reference/demo/basic/templates/k8srequiredlabels_template.yaml"
).read()


def _rego_of(path):
    with open(path) as f:
        doc = yaml.safe_load(f)
    return doc["spec"]["targets"][0]["rego"]


def run_violations(rego, input_doc, data=None, libs=()):
    mods = compile_modules([rego, *libs])
    pkg = list(mods.by_pkg.keys())[0]
    interp = Interpreter(mods, data=data or {})
    return interp.query_set_rule(pkg, "violation", input_doc)


def test_required_labels_violation():
    rego = yaml.safe_load(REQ_LABELS)["spec"]["targets"][0]["rego"]
    input_doc = {
        "review": {"object": {"metadata": {"labels": {"app": "x"}}}},
        "parameters": {"labels": ["gatekeeper"]},
    }
    out = run_violations(rego, input_doc)
    assert len(out) == 1
    assert out[0]["msg"] == 'you must provide labels: {"gatekeeper"}'
    assert list(out[0]["details"]["missing_labels"]) == ["gatekeeper"]


def test_required_labels_ok():
    rego = yaml.safe_load(REQ_LABELS)["spec"]["targets"][0]["rego"]
    input_doc = {
        "review": {"object": {"metadata": {"labels": {"gatekeeper": "yes"}}}},
        "parameters": {"labels": ["gatekeeper"]},
    }
    assert run_violations(rego, input_doc) == []


def test_privileged_containers():
    rego = _rego_of(
        "/root/reference/pkg/webhook/testdata/psp-all-violations/"
        "psp-templates/privileged-containers-template.yaml"
    )
    input_doc = {
        "review": {
            "object": {
                "metadata": {"name": "nginx"},
                "spec": {
                    "containers": [
                        {"name": "nginx", "securityContext": {"privileged": True}},
                        {"name": "sidecar"},
                    ],
                    "initContainers": [
                        {"name": "init", "securityContext": {"privileged": True}}
                    ],
                },
            }
        },
        "parameters": {},
    }
    out = run_violations(rego, input_doc)
    msgs = sorted(v["msg"] for v in out)
    assert msgs == [
        "Privileged container is not allowed: init, securityContext: "
        '{"privileged": true}',
        "Privileged container is not allowed: nginx, securityContext: "
        '{"privileged": true}',
    ]


def test_host_network_ports():
    rego = _rego_of(
        "/root/reference/pkg/webhook/testdata/psp-all-violations/"
        "psp-templates/host-network-ports-template.yaml"
    )
    input_doc = {
        "review": {
            "object": {
                "metadata": {"name": "pod1"},
                "spec": {
                    "hostNetwork": True,
                    "containers": [
                        {"name": "c1", "ports": [{"hostPort": 80}]},
                    ],
                },
            }
        },
        "parameters": {"hostNetwork": False, "min": 1000, "max": 2000},
    }
    out = run_violations(rego, input_doc)
    assert len(out) == 1
    assert "The specified hostNetwork and hostPort are not allowed" in out[0]["msg"]
    # allowed case
    ok_doc = {
        "review": {
            "object": {
                "metadata": {"name": "pod1"},
                "spec": {"containers": [{"name": "c1", "ports": [{"hostPort": 1500}]}]},
            }
        },
        "parameters": {"hostNetwork": True, "min": 1000, "max": 2000},
    }
    assert run_violations(rego, ok_doc) == []


def test_unique_label_with_inventory():
    rego = _rego_of(
        "/root/reference/demo/basic/templates/k8suniquelabel_template.yaml"
    )
    inv = {
        "inventory": {
            "cluster": {
                "v1": {
                    "Namespace": {
                        "other": {
                            "apiVersion": "v1",
                            "kind": "Namespace",
                            "metadata": {"name": "other", "labels": {"team": "a"}},
                        }
                    }
                }
            },
            "namespace": {},
        }
    }
    input_doc = {
        "review": {
            "kind": {"group": "", "version": "v1", "kind": "Namespace"},
            "name": "mine",
            "object": {
                "apiVersion": "v1",
                "kind": "Namespace",
                "metadata": {"name": "mine", "labels": {"team": "a"}},
            },
        },
        "parameters": {"label": "team"},
    }
    out = run_violations(rego, input_doc, data=inv)
    assert len(out) == 1
    assert out[0]["msg"] == "label team has duplicate value a"
    # unique value: no violation
    input_doc["review"]["object"]["metadata"]["labels"]["team"] = "b"
    assert run_violations(rego, input_doc, data=inv) == []


def test_all_psp_templates_parse():
    for path in glob.glob(
        "/root/reference/pkg/webhook/testdata/psp-all-violations/psp-templates/*.yaml"
    ):
        rego = _rego_of(path)
        compile_modules([rego])


def test_else_and_default():
    rego = """
package t

default level = "none"

level = "high" {
  input.x > 10
} else = "low" {
  input.x > 0
}

violation[{"msg": msg}] {
  msg := sprintf("level is %v", [level])
}
"""
    out = run_violations(rego, {"x": 5})
    assert out[0]["msg"] == "level is low"
    out = run_violations(rego, {"x": 50})
    assert out[0]["msg"] == "level is high"
    out = run_violations(rego, {"x": -1})
    assert out[0]["msg"] == "level is none"


def test_comprehensions_and_sets():
    rego = """
package t

violation[{"msg": msg}] {
  names := {n | n := input.items[_].name}
  banned := {n | n := input.banned[_]}
  bad := names & banned
  count(bad) > 0
  msg := sprintf("banned: %v, total %d", [bad, count(names)])
}
"""
    doc = {
        "items": [{"name": "a"}, {"name": "b"}, {"name": "c"}],
        "banned": ["b", "z"],
    }
    out = run_violations(rego, doc)
    assert out[0]["msg"] == 'banned: {"b"}, total 3'


def test_functions_multiclause():
    rego = """
package t

fmt_av(kind) = av {
  kind.group != ""
  av := sprintf("%v/%v", [kind.group, kind.version])
}

fmt_av(kind) = av {
  kind.group == ""
  av := kind.version
}

violation[{"msg": fmt_av(input.kind)}] { true }
"""
    assert run_violations(rego, {"kind": {"group": "apps", "version": "v1"}})[0][
        "msg"
    ] == "apps/v1"
    assert run_violations(rego, {"kind": {"group": "", "version": "v1"}})[0][
        "msg"
    ] == "v1"


def test_not_and_walk():
    rego = """
package t

violation[{"msg": "no runAsNonRoot"}] {
  not input.review.object.spec.securityContext.runAsNonRoot
}
"""
    assert len(run_violations(rego, {"review": {"object": {}}})) == 1
    ok = {"review": {"object": {"spec": {"securityContext": {"runAsNonRoot": True}}}}}
    assert run_violations(rego, ok) == []


def test_startswith_arith_slicing():
    rego = """
package t

violation[{"msg": msg}] {
  some i
  c := input.containers[i]
  startswith(c.image, "bad/")
  msg := sprintf("container %d image %v", [i, c.image])
}
"""
    doc = {"containers": [{"image": "good/x"}, {"image": "bad/y"}]}
    out = run_violations(rego, doc)
    assert out == [{"msg": "container 1 image bad/y"}]
