"""Adversarial corpus + chaos soak harness (gatekeeper_tpu/fuzz/).

Tier-1 runs the property smoke (corpus determinism + one full-family
soak pass under chaos, every differential lane armed, serial drive —
the 1-core CI shape) and the two seeded-bug sensitivity checks: a soak
that cannot catch a planted divergence is worthless, so blindness here
is a test failure, not a shrug.  The multi-minute concurrent soak is
slow-marked (ROADMAP: deferred to multicore hosts).
"""

import json

import pytest

from gatekeeper_tpu.fuzz import corpus


@pytest.fixture(scope="module")
def cache_dir(tmp_path_factory):
    # one compile cache across every soak in this module: the harness
    # rebuilds per run, the lowered programs shouldn't
    return str(tmp_path_factory.mktemp("soak-cc"))


# --- corpus properties (no jax, no harness) -------------------------------

def test_corpus_deterministic_and_seed_sensitive():
    a = corpus.generate_all(seed=3, size=1)
    b = corpus.generate_all(seed=3, size=1)
    c = corpus.generate_all(seed=4, size=1)
    assert [x.family for x in a] == list(corpus.FAMILIES)
    key = lambda bs: json.dumps(
        [[x.objects, [d.decode() for d in x.raw_docs], x.mutators,
          x.match_specs, x.extdata_keys] for x in bs],
        sort_keys=True, default=str)
    assert key(a) == key(b), "same seed must replay bit-identically"
    assert key(a) != key(c), "different seed must differ"


def test_corpus_size_dial_and_stats():
    small = corpus.generate_all(seed=0, size=1)
    big = corpus.generate_all(seed=0, size=4)
    s_small = corpus.corpus_stats(small)
    s_big = corpus.corpus_stats(big)
    assert s_big["total"]["objects"] > s_small["total"]["objects"]
    assert s_big["total"]["object_bytes"] > s_small["total"]["object_bytes"]
    for fam in corpus.FAMILIES:
        assert fam in s_small["families"]
    # every raw byte doc is parseable JSON (dup keys and 256+ depth are
    # hostile to the C lane, not malformed)
    import sys
    old = sys.getrecursionlimit()
    sys.setrecursionlimit(5000)
    try:
        for b in small:
            for d in b.raw_docs:
                json.loads(d)
    finally:
        sys.setrecursionlimit(old)


def test_corpus_families_carry_their_weapons():
    bundles = {b.family: b for b in corpus.generate_all(seed=1, size=1)}
    assert len({o.get("kind") for o in
                bundles["crd_heavy"].objects}) >= 8
    assert any(len(json.dumps(o)) > 60000
               for o in bundles["megabyte_objects"].objects)
    assert any(d.count(b'{"n":') > 256
               for d in bundles["deep_nesting"].raw_docs)
    assert any("namespaceSelector" in s
               for s in map(json.dumps, bundles["selectors"].match_specs))
    assert len(bundles["alias_mutators"].mutators) >= 8
    assert bundles["expansion"].expansion_templates
    assert any("err-" in k for k in bundles["extdata_hostile"].extdata_keys)


def test_admission_bodies_shape():
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "p", "namespace": "default"}}]
    (body,) = corpus.admission_bodies(objs, seed=9, prefix="t")
    req = body["request"]
    assert body["kind"] == "AdmissionReview"
    assert req["uid"].startswith("t-9-")
    assert req["kind"]["kind"] == "Pod"
    assert req["object"]["metadata"]["name"] == "p"


# --- the soak: clean run + sensitivity ------------------------------------

def test_soak_smoke_all_families_all_lanes(cache_dir):
    """One full pass, every family, every differential lane armed,
    chaos on, serial drive: zero divergences, zero lost verdicts, zero
    crashes, clean drain — the PR's headline acceptance gate."""
    from gatekeeper_tpu.fuzz.soak import run_soak

    report = run_soak(seed=0, size=1, rounds=1, chaos=True,
                      cache_dir=cache_dir)
    assert report["ok"], report
    assert report["divergences"] == []
    assert report["crashes"] == []
    assert report["lost_verdicts"] == 0
    assert report["drain_ok"]
    assert report["requests"]["admit"] > 50
    assert report["requests"]["mutate"] > 20
    # the chaos plan actually fired, and the extdata differential
    # actually reached the hostile transport
    assert sum(report["faults_fired"].values()) > 0
    assert report["extdata_transport_calls"] > 0


def test_soak_resident_lane_armed(cache_dir):
    """residency="on" promotes the snapshot lane's columns to device
    mirrors; the per-round snapshot-vs-relist compare then runs
    HBM-resident ticks against the host reference under chaos — zero
    divergences, and the lane demonstrably uploaded."""
    from gatekeeper_tpu.fuzz.soak import run_soak

    report = run_soak(seed=0, size=1, families=["selectors"],
                      rounds=2, chaos=True, cache_dir=cache_dir,
                      residency="on")
    assert report["ok"], report
    assert report["residency"] == "on"
    assert report["resident_uploads"] > 0, \
        "resident lane never promoted — differential ran host-vs-host"


def test_soak_sensitivity_corrupted_mutation(cache_dir):
    """A corrupted batched patch (the lowered-program-corruption
    analogue) MUST surface as a mutate-lane divergence carrying the
    reproducing family + seed."""
    from gatekeeper_tpu.fuzz.soak import _repro_line, run_soak

    report = run_soak(seed=0, size=1, families=["alias_mutators"],
                      rounds=1, chaos=False,
                      inject_bug="mutate_program", cache_dir=cache_dir)
    assert not report["ok"]
    assert any(d["lane"] == "mutate" and d["family"] == "alias_mutators"
               for d in report["divergences"]), report["divergences"]
    line = _repro_line(report)
    assert "--seed 0" in line and "alias_mutators" in line


def test_soak_sensitivity_tampered_extdata_column(cache_dir):
    """A tampered resident provider column MUST surface as an
    extdata-lane divergence (batched join vs per-key reference)."""
    from gatekeeper_tpu.fuzz.soak import _repro_line, run_soak

    report = run_soak(seed=0, size=1, families=["extdata_hostile"],
                      rounds=1, chaos=False,
                      inject_bug="extdata_column", cache_dir=cache_dir)
    assert not report["ok"]
    assert any(d["lane"] == "extdata" and
               d["family"] == "extdata_hostile"
               for d in report["divergences"]), report["divergences"]
    assert "extdata_hostile" in _repro_line(report)


@pytest.mark.slow
def test_soak_minutes_concurrent(cache_dir):
    """The real soak: multi-minute clock, concurrent admit/mutate
    drive while the audit loop runs, bigger corpus.  Deferred out of
    tier-1 (1-core CI); run on multicore via tools/soak.py or -m slow."""
    from gatekeeper_tpu.fuzz.soak import run_soak

    report = run_soak(seed=0, size=4, duration_s=120.0, chaos=True,
                      concurrent=True, cache_dir=cache_dir)
    assert report["ok"], report
    assert report["rounds"] >= 2
